#!/usr/bin/env python
"""Summarize hw_sweep results (JSONL from scripts/hw_sweep*.sh):

* a markdown table (config, value, unit, MFU) ready for
  docs/performance.md,
* replication medians ± spread for any config family with reps
  (``<name>_rep<N>`` rows fold into one median row),
* the fp8-vs-bf16 ratio when both medians exist.

Usage: python scripts/summarize_sweep.py results.jsonl [more.jsonl ...]
"""

from __future__ import annotations

import json
import re
import statistics
import sys
from collections import defaultdict


def load(paths):
    rows = []
    for path in paths:
        with open(path) as f:
            for n, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    # A truncated line (sweep killed mid-write) must not
                    # take the whole summary down with it.
                    rows.append({"config": f"{path}:{n}", "result": None,
                                 "malformed": True})
    return rows


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__)
        return 2
    rows = load(sys.argv[1:])

    reps = defaultdict(list)
    singles = []
    for row in rows:
        config = row.get("config", "(unnamed)")
        r = row.get("result")
        # A row whose result lacks value/unit (a bench that died after
        # emitting a partial object) renders as one (malformed) line
        # instead of KeyError-ing the whole summary.
        if isinstance(r, dict) and r.get("value") is None:
            singles.append((config, "malformed"))
            continue
        if r is None:
            singles.append(
                (config, "malformed" if row.get("malformed") else None)
            )
            continue
        m = re.fullmatch(r"(.*)_rep\d+", config)
        if m:
            reps[m.group(1)].append(r)
        else:
            singles.append((config, r))

    print("| Config | value | unit | MFU |")
    print("|---|---|---|---|")
    for name, r in singles:
        if r == "malformed":
            print(f"| {name} | (malformed) | | |")
        elif r is None:
            print(f"| {name} | (no result) | | |")
        else:
            print(f"| {name} | {r['value']:,} | {r.get('unit', '')} "
                  f"| {r.get('mfu')} |")
    medians = {}
    for name, results in sorted(reps.items()):
        vals = [r["value"] for r in results]
        med = statistics.median(vals)
        medians[name] = med
        spread = (max(vals) - min(vals)) / med * 100 if med else 0
        mfus = [r["mfu"] for r in results if r.get("mfu") is not None]
        mfu = statistics.median(mfus) if mfus else ""
        print(f"| {name} (median of {len(vals)}) | {med:,} "
              f"| {results[0].get('unit', '')} ± {spread:.1f}% | {mfu} |")

    fp8 = next((v for k, v in medians.items() if "fp8" in k), None)
    bf16 = next((v for k, v in medians.items()
                 if "bf16" in k and "fp8" not in k), None)
    if fp8 and bf16:
        print(f"\nfp8 / bf16 median ratio: {fp8 / bf16:.4f} "
              f"({(fp8 / bf16 - 1) * 100:+.1f}%)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
