#!/usr/bin/env python
"""DEPRECATED (ISSUE 19): summarize_sweep.py read the ad-hoc JSONL the
retired hw_sweep*.sh scripts appended.  Sweeps are campaigns now — a
``campaign.json`` journal with per-point status/provenance — and the
report side lives in scripts/perf_report.py, which also renders the
full BENCH/MULTICHIP trajectory and the degraded-streak verdict.
"""

from __future__ import annotations

import sys


def main() -> int:
    print("scripts/summarize_sweep.py is deprecated; sweeps are "
          "resumable campaigns now:", file=sys.stderr)
    print("", file=sys.stderr)
    print("    python bench.py --campaign "
          "scripts/campaigns/hw_round.json", file=sys.stderr)
    print("    python scripts/perf_report.py   # trajectory + campaign "
          "verdict table", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
