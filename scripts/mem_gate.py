#!/usr/bin/env python
"""Memory budget gate: per-program device memory is a CI property.

The hlo_gate checks that every rank compiles the SAME program; this
gate checks what those programs COST.  It compiles the repo's
collective-bearing step programs on the 8-virtual-device CPU mesh —
the engine-style fused allreduce, the overlap train step in ``bucket``
and ``bucket+zero1`` modes, and the slot engine's full-pool decode
step — reads each artifact's ``memory_analysis()`` breakdown through
the memory plane's version-tolerant parser (obs/memplane.py), and
asserts:

* **budget** — every program's per-device footprint stays under the
  committed ceiling in ``memory_budget.json`` (regenerate with
  ``--write-budget`` when a deliberate change moves the numbers; the
  diff is then reviewable like any other contract change);
* **ZeRO-1** — the optimizer-state bytes resident per device under
  ``bucket+zero1`` are <= (1/world + eps) of the ``bucket`` mode's
  (PR 9's memory claim, asserted from the compiled programs' actual
  input buffers — the donated state the artifact executes on — not
  from the design doc).

Honest limits: on an interpreter whose executables expose no
``memory_analysis`` the budget half degrades to a loud skip (the
ZeRO-1 half still runs — it reads the input buffers), and the numbers
are CPU-mesh compiles: per-device *shapes* match a TPU's (SPMD
partitioning is platform-independent) but backend-specific temp sizes
may drift, which the budget headroom absorbs.

    python scripts/mem_gate.py                  # the gate (exit != 0 on violation)
    python scripts/mem_gate.py --seed-violation # self-test: a seeded 64x
        # oversized program MUST bust its budget (exit 0 iff it did)
    python scripts/mem_gate.py --write-budget   # re-measure and rewrite
        # memory_budget.json with standard headroom
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGET_PATH = os.path.join(REPO, "memory_budget.json")
BUDGET_SCHEMA = "hvdtpu-memory-budget-v1"
WORLD = 8          # the tier-1 virtual mesh
HEADROOM = 1.5     # budget = measured * HEADROOM (absorbs backend drift)
ZERO1_EPS = 0.03   # replicated scalar leaves (step counts) ride on top
                   # of the 1/world shard


def _setup_env() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={WORLD}"
        ).strip()
    sys.path.insert(0, REPO)


def _device_bytes(tree) -> int:
    """Per-device bytes of a pytree's leaves: the addressable-shard
    sizes of the arrays the compiled program actually takes (a ZeRO
    shard counts 1/world here; a replicated buffer counts whole)."""
    import jax  # noqa: PLC0415

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is not None:
            total += min(s.data.nbytes for s in shards)
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total


def measure(seed_violation: bool = False) -> dict:
    """Compile the gated programs and return
    ``{"programs": {name: breakdown}, "zero1": {...}}``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.obs import memplane
    from horovod_tpu.optim import overlap
    from horovod_tpu.ops.collectives import shard_map_compat

    mesh = Mesh(np.asarray(jax.devices(), dtype=object).reshape(WORLD),
                (hvd.DP_AXIS,))
    programs = {}

    # (1) engine-style fused allreduce (the device plane's schedule
    # shape: pre-scale, psum, post-scale over the staged buffer).  The
    # seeded violation inflates the buffer 64x — the budget MUST
    # reject it or the gate is decorative.
    n = (64 * 1024) * (64 if seed_violation else 1)

    def fused_allreduce(x):
        return lax.psum(x * (1.0 / WORLD), hvd.DP_AXIS)

    fn = jax.jit(shard_map_compat(
        fused_allreduce, mesh=mesh,
        in_specs=P(hvd.DP_AXIS), out_specs=P(),
    ))
    compiled = fn.lower(jnp.ones((WORLD, n), jnp.float32)).compile()
    programs["engine_allreduce"] = memplane.parse_memory_analysis(compiled)

    # (2)+(3) the overlap train step per mode — the same model shape
    # the hlo gate compiles, on the full 8-way mesh.
    def init_params(key):
        sizes = [64, 128, 128, 32]
        params = []
        for i in range(3):
            k, key = jax.random.split(key)
            params.append({
                "w": jax.random.normal(k, (sizes[i], sizes[i + 1])) * .1,
                "b": jnp.zeros(sizes[i + 1]),
            })
        return params

    def loss_fn(params, x, y):
        h = x
        for i, layer in enumerate(params):
            h = h @ layer["w"] + layer["b"]
            if i < 2:
                h = jax.nn.relu(h)
        return jnp.mean((h - y) ** 2)

    params = init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (WORLD * 2, 64))
    y = jax.random.normal(jax.random.PRNGKey(2), (WORLD * 2, 32))

    opt_dev_bytes = {}
    for mode, prog in (("bucket", "overlap_bucket"),
                       ("bucket+zero1", "overlap_zero1")):
        plan = overlap.OverlapPlan(params, optax.adamw(1e-3), mode=mode,
                                   mesh=mesh, bucket_mb=2 / 1024.0)
        spec = plan.state_spec()
        step = jax.jit(shard_map_compat(
            plan.local_step(loss_fn), mesh=mesh,
            in_specs=(spec, P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
            out_specs=(spec, P()),
        ), donate_argnums=(0,))
        state = plan.init(params)
        compiled = step.lower(state, x, y).compile()
        # Registration through the plan: the same call the production
        # compile sites make, so the gate exercises the real path.
        programs[prog] = plan.register_memory(compiled, program=prog)
        _, opt_state = state
        opt_dev_bytes[mode] = _device_bytes(opt_state)

    # (4) serve decode: the slot engine's full-pool decode step — its
    # own compile site registers the artifact (step_flops AOT handoff).
    from horovod_tpu.models.transformer import gpt
    from horovod_tpu.serve.engine import SlotEngine

    overrides = dict(num_layers=2, num_heads=4, emb_dim=64, max_len=128,
                     vocab_size=256, dtype=jnp.float32,
                     attention_impl="reference")
    model = gpt("nano", **overrides)
    sparams = model.init(jax.random.PRNGKey(3),
                         jnp.zeros((1, 8), jnp.int32))
    eng = SlotEngine(model.cfg, sparams, num_slots=4)
    eng.step_flops()  # compiles + registers serve.decode_step
    programs["serve_decode"] = memplane.program_report().get(
        "serve.decode_step", {"source": "unavailable"}
    )

    return {
        "programs": programs,
        "zero1": {
            "world": WORLD,
            "bucket_opt_bytes": opt_dev_bytes.get("bucket", 0),
            "zero1_opt_bytes": opt_dev_bytes.get("bucket+zero1", 0),
        },
    }


def write_budget(measured: dict) -> None:
    doc = {
        "schema": BUDGET_SCHEMA,
        "world": WORLD,
        "headroom": HEADROOM,
        "programs": {
            name: {
                "total_bytes_max": int(b.get("total_bytes", 0) * HEADROOM),
                "measured_total_bytes": int(b.get("total_bytes", 0)),
            }
            for name, b in measured["programs"].items()
            if b.get("source") == "memory_analysis"
        },
        "zero1": {
            "max_opt_ratio": round(1.0 / WORLD + ZERO1_EPS, 4),
        },
    }
    with open(BUDGET_PATH, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"mem gate: wrote {BUDGET_PATH}")


def check(measured: dict, budget: dict) -> int:
    failures = 0
    budgets = budget.get("programs") or {}
    any_available = False
    for name, b in sorted(measured["programs"].items()):
        if b.get("source") != "memory_analysis":
            print(f"mem gate: {name}: memory_analysis unavailable on "
                  f"this interpreter — budget check skipped")
            continue
        any_available = True
        total = b.get("total_bytes", 0)
        ceiling = (budgets.get(name) or {}).get("total_bytes_max")
        if ceiling is None:
            print(f"mem gate: {name}: no committed budget "
                  f"(measured {total}B) — add it via --write-budget")
            continue
        verdict = "OK" if total <= ceiling else "OVER BUDGET"
        print(f"mem gate: {name}: {total}B of {ceiling}B budget "
              f"(arg {b.get('argument_bytes', 0)} temp "
              f"{b.get('temp_bytes', 0)} out {b.get('output_bytes', 0)}) "
              f"{verdict}")
        if total > ceiling:
            failures += 1
    if not any_available:
        print("mem gate: NO program exposed memory_analysis — budget "
              "half skipped (version drift), ZeRO-1 half still gates")

    z = measured["zero1"]
    max_ratio = (budget.get("zero1") or {}).get(
        "max_opt_ratio", 1.0 / WORLD + ZERO1_EPS
    )
    if z["bucket_opt_bytes"] <= 0:
        print("mem gate: ZeRO-1 check could not measure the bucket-mode "
              "optimizer state", file=sys.stderr)
        failures += 1
    else:
        ratio = z["zero1_opt_bytes"] / z["bucket_opt_bytes"]
        ok = ratio <= max_ratio
        print(f"mem gate: zero1 optimizer-state per-device bytes "
              f"{z['zero1_opt_bytes']} / bucket {z['bucket_opt_bytes']} "
              f"= {ratio:.4f} (<= {max_ratio} = 1/{z['world']} + eps) "
              f"{'OK' if ok else 'VIOLATED'}")
        if not ok:
            failures += 1
    return failures


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--write-budget", action="store_true",
                        help="re-measure and rewrite memory_budget.json")
    parser.add_argument("--seed-violation", action="store_true",
                        help="self-test: a 64x oversized program must "
                             "bust its budget (exit 0 iff rejected)")
    args = parser.parse_args(argv)
    _setup_env()

    measured = measure(seed_violation=args.seed_violation)
    if args.write_budget:
        write_budget(measured)
        return 0
    if not os.path.exists(BUDGET_PATH):
        print(f"mem gate: {BUDGET_PATH} missing — run --write-budget "
              f"and commit it", file=sys.stderr)
        return 2
    with open(BUDGET_PATH) as f:
        budget = json.load(f)
    if budget.get("schema") != BUDGET_SCHEMA:
        print(f"mem gate: unexpected budget schema "
              f"{budget.get('schema')!r}", file=sys.stderr)
        return 2

    failures = check(measured, budget)
    if args.seed_violation:
        prog = measured["programs"].get("engine_allreduce", {})
        if prog.get("source") != "memory_analysis":
            # A blind checker must not pass its own blindness test
            # (the hlo_gate rule): no analysis means the violation was
            # never judged.
            print("mem gate SELF-TEST SKIPPED: memory_analysis "
                  "unavailable, nothing to seed against", file=sys.stderr)
            return 2
        # Judge the SEEDED program's own verdict, not the global
        # failure count: an unrelated failure (a drifted zero1
        # measurement) must not mask a budget check that silently
        # stopped rejecting anything.
        ceiling = ((budget.get("programs") or {}).get("engine_allreduce")
                   or {}).get("total_bytes_max")
        seeded_over = (ceiling is not None
                       and prog.get("total_bytes", 0) > ceiling)
        if not seeded_over:
            print("mem gate SELF-TEST FAILED: seeded 64x engine buffer "
                  "stayed under budget", file=sys.stderr)
            return 1
        print("mem gate self-test OK: seeded engine_allreduce rejected "
              f"({prog.get('total_bytes', 0)}B > {ceiling}B ceiling)")
        return 0
    if failures:
        print(f"mem gate FAILED: {failures} violation(s)",
              file=sys.stderr)
        return 1
    print(f"mem gate OK: {len(measured['programs'])} programs within "
          f"budget, zero1 ratio asserted at world {WORLD}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
