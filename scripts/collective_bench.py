#!/usr/bin/env python
"""Eager-engine collective microbenchmark: allreduce goodput vs world size
and message size (the in-tree analog of the reference's allreduce scaling
story, docs/benchmarks.rst:13-43 — the jit path's scaling rides XLA/ICI
and is exercised by the multichip dryrun instead).

Runs true multi-process worlds on localhost via the launcher (SURVEY §4
strategy) and prints a goodput table; `--engine native` exercises the C++
engine's poll-multiplexed coordinator, `--engine python` the symmetric
bit-vote controller.

    python scripts/collective_bench.py --engine native --np 2 4 8
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker(nbytes: int, iters: int):
    import time

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    x = np.ones(max(nbytes // 4, 1), np.float32)
    for _ in range(3):  # warm the cache fast path
        hvd.allreduce(x, op=hvd.Sum, name="warm")
    t0 = time.perf_counter()
    for i in range(iters):
        hvd.allreduce(x, op=hvd.Sum, name="bench")
    dt = time.perf_counter() - t0
    hvd.shutdown()
    # goodput: payload bytes reduced per second (one buffer per op)
    return nbytes * iters / dt


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--engine", default="python",
                        choices=["python", "native"])
    parser.add_argument("--np", type=int, nargs="+", default=[2, 4],
                        dest="worlds")
    parser.add_argument("--sizes-kb", type=int, nargs="+",
                        default=[4, 1024, 16384])
    parser.add_argument("--iters", type=int, default=50)
    args = parser.parse_args()

    import horovod_tpu.run as hvdrun
    from horovod_tpu.runtime.native import native_available

    if args.engine == "native" and not native_available():
        print("native engine not built (make -C cpp)", file=sys.stderr)
        return 1

    env = {"HVDTPU_EAGER_ENGINE": args.engine, "HVDTPU_CYCLE_TIME": "1"}
    print(f"# engine={args.engine} iters={args.iters} "
          "(goodput = payload bytes/sec, rank 0)")
    header = "size_kb " + " ".join(f"np={n:<12d}" for n in args.worlds)
    print(header)
    for kb in args.sizes_kb:
        row = [f"{kb:7d}"]
        for n in args.worlds:
            results = hvdrun.run(
                _worker, (kb * 1024, args.iters), np=n, use_cpu=True,
                timeout=600, env=env,
            )
            mbps = results[0] / 1e6
            row.append(f"{mbps:9.1f} MB/s")
        print(" ".join(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
