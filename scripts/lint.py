#!/usr/bin/env python
"""Dev-loop lint entry: the `make lint` equivalent.

Runs hvdtpu-lint over the files changed vs HEAD (plus untracked) so the
commit-time loop stays fast (<5 s on a typical diff); pass ``--all``
for the full configured surface (what the CI gate runs), or forward any
hvdtpu-lint flag verbatim (``--format json``, ``--rules HVD001``, ...).

    python scripts/lint.py            # changed files only
    python scripts/lint.py --all      # full surface, as CI runs it
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# Flags that consume the NEXT argument — their values must not be
# mistaken for path arguments when deciding whether to default to
# --changed ("--format json" carries no path).
_VALUE_FLAGS = {"--format", "--baseline", "--rules", "--root",
                "--write-baseline", "--jobs", "-j"}


def _has_explicit_paths(args: list) -> bool:
    skip_next = False
    for a in args:
        if skip_next:
            skip_next = False
            continue
        if a in _VALUE_FLAGS:
            skip_next = True
            continue
        if a.startswith("-"):
            continue  # covers --flag=value spellings too
        return True
    return False


def main(argv: list) -> int:
    args = list(argv)
    if "--all" in args:
        args.remove("--all")
    elif not _has_explicit_paths(args):
        # no explicit paths: default to the fast changed-files mode
        if "--changed" not in args:
            args.append("--changed")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.call(
        [sys.executable, "-m", "horovod_tpu.analysis", *args],
        cwd=REPO, env=env,
    )


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
