#!/usr/bin/env python
"""Bench regression sentinel over the BENCH_r*.json trajectory.

The driver's records tell a story nobody was reading: every record
since r02 is a degraded CPU fallback or a failed round, so the last
*real* perf number is ten rounds old and the trajectory "judged itself"
against placeholders.  This gate makes the trajectory machine-visible:

1. **Partition** every ``BENCH_r*.json`` into *real* (rc=0, a parsed
   measurement, not degraded), *degraded* (the explicit
   ``degraded: true`` stamp from bench.py — CPU fallbacks and give-up
   records), and *failed* (a nonzero rc with no measurement at all —
   the r03–r05 dark rounds), and print it.
2. **Baseline** per scenario ``(metric, device)``: the best value among
   real records only.  A degraded record is trajectory evidence, never
   a bar.  The audit also prints the degraded-streak verdict ("N
   consecutive records without a real measurement; last real number is
   rX") from the trend observatory (horovod_tpu/obs/trend.py), which
   owns record classification for this gate, bench.py's in-record
   sentinel and scripts/perf_report.py alike.
3. **Judge a candidate** (``--candidate fresh.json``) against its
   scenario's EWMA-over-the-last-K-real-records baseline
   (obs/trend.py's fold — one lucky round must not own the bar) with a
   configurable noise band
   (``--noise-pct``, default 5): a drop past the band exits nonzero so
   CI can gate on it.  Backend provenance (the ``provenance`` stamp
   bench.py embeds: platform / device kind / JAX_PLATFORMS) is printed
   beside the verdict so "tunnel flaked" and "ran on CPU" stop looking
   alike.

Without a candidate the gate is an auditor: it prints the partition and
per-scenario baselines and exits 0 (the committed trajectory is what it
is; only a fresh run can regress).

Exit codes: 0 clean, 1 regression past the noise band, 2 bad input.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

# Record classification is single-sourced in the trend observatory
# (horovod_tpu/obs/trend.py): the gate, bench.py's in-record sentinel
# and scripts/perf_report.py must never disagree about what counts as
# a real measurement.
from horovod_tpu.obs import trend as _trend  # noqa: E402

parsed_payload = _trend.parsed_payload
classify = _trend.classify
scenario_key = _trend.scenario_key


def load_records(record_dir):
    """[(round n, filename, doc)] sorted by round; unreadable files are
    reported on stderr and skipped (one corrupt record must not blind
    the gate to the rest of the trajectory)."""
    records = []
    for path in sorted(glob.glob(os.path.join(record_dir, "BENCH_*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"# unreadable record {os.path.basename(path)}: {exc}",
                  file=sys.stderr)
            continue
        if not isinstance(doc, dict):
            continue
        n = doc.get("n")
        records.append((n if isinstance(n, int) else 0,
                        os.path.basename(path), doc))
    records.sort()
    return records


def provenance_of(doc):
    """The backend-provenance stamp (platform, device kind,
    JAX_PLATFORMS), wherever bench.py landed it."""
    for holder in (doc, parsed_payload(doc) or {}):
        prov = holder.get("provenance")
        if isinstance(prov, dict):
            return prov
    parsed = parsed_payload(doc)
    if isinstance(parsed, dict) and parsed.get("device"):
        return {"device_kind": parsed["device"]}
    return {}


def _prov_str(prov):
    if not prov:
        return "provenance unknown"
    bits = []
    if prov.get("platform"):
        bits.append(f"platform={prov['platform']}")
    if prov.get("device_kind"):
        bits.append(f"device={prov['device_kind']}")
    if prov.get("jax_platforms"):
        bits.append(f"JAX_PLATFORMS={prov['jax_platforms']}")
    return " ".join(bits) or "provenance unknown"


def partition(records):
    """{bucket: [(n, fname, doc)]} over the classified trajectory."""
    out = {"real": [], "degraded": [], "failed": []}
    for n, fname, doc in records:
        out[classify(doc)].append((n, fname, doc))
    return out


def baselines(records):
    """{(metric, device): (fname, parsed)} — best real value per
    scenario."""
    best = {}
    for _, fname, doc in records:
        if classify(doc) != "real":
            continue
        parsed = parsed_payload(doc)
        key = scenario_key(parsed)
        if key not in best or parsed["value"] > best[key][1]["value"]:
            best[key] = (fname, parsed)
    return best


def judge(candidate, base, noise_pct):
    """(verdict, pct_delta): 'regression' | 'ok' | 'improved'."""
    old, new = base["value"], candidate["value"]
    if not old:
        return "ok", 0.0
    pct = (new - old) / old * 100.0
    if pct < -abs(noise_pct):
        return "regression", pct
    return ("improved" if pct > abs(noise_pct) else "ok"), pct


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Partition the BENCH trajectory and gate a fresh "
                    "measurement against the best non-degraded baseline.")
    p.add_argument("--records-dir", default=REPO_ROOT,
                   help="directory holding BENCH_r*.json "
                        "(default: repo root)")
    p.add_argument("--candidate", default=None,
                   help="fresh bench output JSON to judge (bench.py "
                        "stdout or a driver record); omitting it audits "
                        "the trajectory only")
    p.add_argument("--noise-pct", type=float, default=5.0,
                   help="regression band in percent (default 5): a "
                        "value drop past this fails the gate")
    p.add_argument("--json", action="store_true",
                   help="emit the machine verdict document on stdout "
                        "too")
    args = p.parse_args(argv)

    records = load_records(args.records_dir)
    if not records:
        print(f"no BENCH_*.json records under {args.records_dir}",
              file=sys.stderr)
        return 2
    buckets = partition(records)

    print(f"# BENCH trajectory: {len(records)} records "
          f"({len(buckets['real'])} real, "
          f"{len(buckets['degraded'])} degraded, "
          f"{len(buckets['failed'])} failed)")
    for bucket in ("real", "degraded", "failed"):
        for n, fname, doc in buckets[bucket]:
            parsed = parsed_payload(doc) or {}
            desc = parsed.get("metric") or doc.get(
                "failure_phase") or f"rc={doc.get('rc')}"
            val = parsed.get("value")
            val_s = f" value={val}" if isinstance(val, (int, float)) else ""
            print(f"  {bucket:9s} {fname}: {desc}{val_s} "
                  f"[{_prov_str(provenance_of(doc))}]")

    base = baselines(records)
    print(f"# baselines ({len(base)} scenario"
          f"{'s' if len(base) != 1 else ''}, real records only):")
    for (metric, device), (fname, parsed) in sorted(
            base.items(), key=lambda kv: str(kv[0])):
        print(f"  {metric} on {device or 'unknown device'}: "
              f"{parsed['value']} ({fname})")

    # The dark trajectory self-announces: how many rounds since the
    # last real number, and what that number was.  (Printed after the
    # per-record lines so the partition stays the first mention of
    # every record name — CI greps by first match.)
    streak = _trend.degraded_streak(records)
    print(f"# degraded-streak verdict: {streak['verdict']}")

    verdict = {
        "records": len(records),
        "real": [f for _, f, _ in buckets["real"]],
        "degraded": [f for _, f, _ in buckets["degraded"]],
        "failed": [f for _, f, _ in buckets["failed"]],
        "noise_pct": args.noise_pct,
        "degraded_streak": streak["streak"],
        "last_real_record": streak["last_real_record"],
        "regression": False,
    }

    rc = 0
    if args.candidate:
        try:
            with open(args.candidate) as f:
                cand_doc = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"unreadable candidate {args.candidate}: {exc}",
                  file=sys.stderr)
            return 2
        cand = parsed_payload(cand_doc)
        if not isinstance(cand, dict) or not cand.get("metric") \
                or not isinstance(cand.get("value"), (int, float)):
            print(f"candidate {args.candidate} carries no measurement "
                  f"(metric/value)", file=sys.stderr)
            return 2
        prov = _prov_str(provenance_of(cand_doc))
        key = scenario_key(cand)
        if cand.get("degraded"):
            # A degraded candidate is a trajectory placeholder: it can
            # never regress a real baseline (it is not comparable), and
            # it must say so loudly rather than pass as healthy.
            print(f"# candidate is DEGRADED ({prov}): recorded for the "
                  f"trajectory, not judged against "
                  f"{key[0]} on {key[1] or 'unknown device'}")
            verdict["candidate"] = {"scenario": list(key),
                                    "degraded": True}
        elif key not in base:
            print(f"# candidate scenario {key[0]} on "
                  f"{key[1] or 'unknown device'} has no real baseline "
                  f"({prov}) — first real measurement, nothing to "
                  f"regress from")
            verdict["candidate"] = {"scenario": list(key),
                                    "baseline": None}
        else:
            # EWMA over the last K real records of the scenario, not
            # the single best one: one lucky round must not own the bar
            # (obs/trend.py owns the fold; same baseline bench.py's
            # in-record sentinel uses).
            ewma = _trend.ewma_baseline(records, *key)
            word, pct = judge(cand, ewma, args.noise_pct)
            print(f"# candidate {cand['value']} vs EWMA baseline "
                  f"{ewma['value']} over {len(ewma['records'])} real "
                  f"record{'s' if len(ewma['records']) != 1 else ''} "
                  f"({', '.join(ewma['records'])}): {pct:+.2f}% "
                  f"[band ±{args.noise_pct}%] -> {word.upper()} ({prov})")
            verdict["candidate"] = {
                "scenario": list(key),
                "value": cand["value"],
                "baseline": ewma["value"],
                "baseline_record": ewma["newest"],
                "baseline_records": ewma["records"],
                "pct": round(pct, 2),
                "verdict": word,
            }
            if word == "regression":
                verdict["regression"] = True
                rc = 1
    if args.json:
        print(json.dumps(verdict, indent=1))
    return rc


if __name__ == "__main__":
    sys.exit(main())
