#!/usr/bin/env python
"""Capture a jax.profiler trace of the bench step and summarize hot ops.

Dev tool for the perf push (VERDICT r2 item 1). Writes the raw xplane to
--out (default /tmp/hvdtpu_trace) and prints a per-op-category time
breakdown parsed from the xplane proto.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def summarize_xplane(logdir: str) -> None:
    paths = glob.glob(
        os.path.join(logdir, "**", "*.trace.json.gz"), recursive=True
    )
    if not paths:
        print("no trace.json.gz found under", logdir)
        return
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    pid_names = {}
    tid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e["pid"]] = e["args"]["name"]
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_names[(e["pid"], e["tid"])] = e["args"]["name"]
    # Find TPU device pids (XLA op lines)
    by_name = defaultdict(float)
    total = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        pname = pid_names.get(e.get("pid"), "")
        tname = tid_names.get((e.get("pid"), e.get("tid")), "")
        # keep only the device XLA-op line
        if "tpu" not in pname.lower() or "XLA Ops" not in tname:
            continue
        dur = e.get("dur", 0) / 1e3  # us -> ms
        by_name[e["name"]] += dur
        total += dur
    print(f"== XLA op time by name (total {total:.2f} ms across trace) ==")
    items = sorted(by_name.items(), key=lambda kv: -kv[1])
    # group by fusion-category prefix
    by_cat = defaultdict(float)
    for name, dur in items:
        cat = name.split(".")[0].rstrip("0123456789")
        by_cat[cat] += dur
    print("-- by category --")
    for cat, dur in sorted(by_cat.items(), key=lambda kv: -kv[1])[:20]:
        print(f"{dur:10.2f} ms  {100*dur/total:5.1f}%  {cat}")
    print("-- top 30 ops --")
    for name, dur in items[:30]:
        print(f"{dur:10.2f} ms  {100*dur/total:5.1f}%  {name}")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="/tmp/hvdtpu_trace")
    parser.add_argument("--model", default="resnet50",
                        choices=["resnet50", "resnet101", "resnet18",
                                 "vgg16", "vgg19", "inception3",
                                 "gpt-small", "gpt-medium", "gpt-large"])
    parser.add_argument("--dtype", default="bf16")
    parser.add_argument("--batch-size", type=int, default=None,
                        help="default: 128 resnet, 8 gpt")
    parser.add_argument("--seq-len", type=int, default=1024)
    parser.add_argument("--remat", action="store_true")
    # keep in lockstep with bench.py: the profile must be of the tiles
    # the benchmark actually runs (512x256, the measured v5e winner)
    parser.add_argument("--flash-block-q", type=int, default=512)
    parser.add_argument("--flash-block-k", type=int, default=256)
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--summarize-only", action="store_true")
    args = parser.parse_args()

    if args.summarize_only:
        summarize_xplane(args.out)
        return 0

    import jax

    # the EXACT steps bench.py times
    from bench import build_gpt_step, build_step

    is_gpt = args.model.startswith("gpt-")
    if args.batch_size is None:
        args.batch_size = 8 if is_gpt else 128
    if is_gpt:
        step, state, _ = build_gpt_step(
            args.model[len("gpt-"):], args.dtype, args.batch_size,
            args.seq_len, remat=args.remat,
            flash_block_q=args.flash_block_q,
            flash_block_k=args.flash_block_k,
        )
        carry, const = list(state[:-1]), list(state[-1:])
    else:
        step, state, _ = build_step(args.model, args.dtype, args.batch_size)
        carry, const = list(state[:3]), list(state[3:])
    # warmup/compile
    for _ in range(3):
        *carry, loss = step(*carry, *const)
    float(loss)
    jax.profiler.start_trace(args.out)
    for _ in range(args.iters):
        *carry, loss = step(*carry, *const)
    float(loss)
    jax.profiler.stop_trace()
    print("trace written to", args.out)
    summarize_xplane(args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
