#!/usr/bin/env bash
# DEPRECATED (ISSUE 19): the ad-hoc sweep scripts are retired in favor
# of ONE resumable entry point.  This plan lives on (merged with
# hw_sweep.sh) as a campaign spec: committed points are journaled in
# campaign.json, a tunnel flake loses at most the in-flight point, and
# rerunning the same command resumes instead of starting over.
echo "scripts/hw_sweep2.sh is deprecated; run the resumable campaign instead:" >&2
echo "" >&2
echo "    python bench.py --campaign scripts/campaigns/hw_round.json" >&2
echo "" >&2
echo "then render results with:  python scripts/perf_report.py" >&2
exit 2
