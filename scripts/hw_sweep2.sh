#!/usr/bin/env bash
# Hardware sweep, part 2 — the configs the first tunnel window didn't
# reach (the outage killed hw_sweep.sh at gpt_small_rope) plus the
# follow-ups the part-1 results motivated: flash-block sizes were the
# dominant lever (128->512q: +69% tokens/sec), so push that axis further
# and retry the two GQA configs with a wider compile window (the kv-heads
# compile burned its whole 1440s budget in part 1).
#
#   scripts/hw_sweep2.sh [results_file]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/hw_sweep2_results.jsonl}"

. "$(dirname "$0")/_bench_run.sh"

# 1. the must-land records first: bf16 3-run median completion + the fp8
#    replication (VERDICT r5 task 8).  resnet executables are already in
#    .jax_cache, so the bf16 reps cost ~2 min each.
run resnet50_bf16_rep2 1800 1440
run resnet50_bf16_rep3 1800 1440
run resnet50_fp8_rep1 1800 1440 --dtype fp8
run resnet50_fp8_rep2 1800 1440 --dtype fp8
run resnet50_fp8_rep3 1800 1440 --dtype fp8
# 2. the other headline conv families (docs/benchmarks.md)
run inception3_bf16 1800 1440 --model inception3 --batch-size 128
run vgg16_bf16 1800 1440 --model vgg16 --batch-size 64
# 3. part-1 stragglers
run gpt_small_rope 1800 1440 --model gpt-small --pos-embedding rope
# 4. flash-block follow-ups (the big lever: 0.193 -> 0.325 MFU in part 1)
run gpt_small_blocks512x512 1800 1440 --model gpt-small --flash-block-q 512 --flash-block-k 512
run gpt_small_blocks1024q 1800 1440 --model gpt-small --flash-block-q 1024 --flash-block-k 256
run gpt_small_blocks512q_b16 1800 1440 --model gpt-small --flash-block-q 512 --flash-block-k 256 --batch-size 16
run gpt_small_ref_attn 1800 1440 --model gpt-small --attention reference
# 4b. transformer fp8 act storage (round-5 feature: e4m3 attention
#     context + branch deltas + gelu intermediates)
run gpt_small_fp8 1800 1440 --model gpt-small --dtype fp8
# 4c. sliding-window attention (round-5 feature: banded tiles skipped
#     fwd+bwd).  128x128 tiles on purpose: W=256 at seq 1024 then skips
#     21/36 causal tiles (58%) — at the default 512x256 tiles the band
#     only removes 1/6 and measures nothing.  Compare vs gpt_small_base
#     (also 128x128, part-1: 57.5k tok/s).
run gpt_small_window256 1800 1440 --model gpt-small --attention-window 256 --flash-block-q 128 --flash-block-k 128
# 5. GQA retries with a wide compile window (part-1 failure mode: compile
#    alone outlived the 780s watchdog AND the 1440s budget)
run gpt_small_gqa4 3000 2700 --model gpt-small --kv-heads 4 --watchdog-secs 2400
run gpt_small_rope_gqa_remat 3000 2700 --model gpt-small --pos-embedding rope --kv-heads 4 --remat --batch-size 16 --watchdog-secs 2400
# 6. scale-up: medium at the best small-model blocks
run gpt_medium_blocks512q 3000 2700 --model gpt-medium --flash-block-q 512 --flash-block-k 256 --watchdog-secs 2400
run gpt_small_moe8 3000 2700 --model gpt-small --moe-experts 8 --watchdog-secs 2400
# 7. trace-grade residual-bound analysis of the winning gpt config
#    (cache-warmed by section 4, so this costs ~2 min of chip time);
#    the per-category breakdown prints to the sweep log
timeout 900 python scripts/profile_bench.py --model gpt-small \
    --out /root/repo/gpt_trace_r05 2>&1 | tail -30 >&2 || true
echo "sweep2 complete -> $OUT" >&2
