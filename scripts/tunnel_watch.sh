#!/usr/bin/env bash
# Wait for the axon TPU tunnel to come back, then run the hardware
# measurement campaign (scripts/campaigns/hw_round.json) unattended.
# The probe is cheap (one jax.devices() with a hard timeout) so a
# multi-hour outage costs nothing but probes; the first successful
# probe triggers the campaign.  Because the campaign is resumable, a
# mid-sweep tunnel drop is cheap too: the loop keeps probing and the
# next window picks up from the campaign.json journal.
#
#   scripts/tunnel_watch.sh [campaign_spec]
set -u
cd "$(dirname "$0")/.."
SPEC="${1:-scripts/campaigns/hw_round.json}"
# A broken environment (no jax, wrong python) would fail every probe with
# the same silence as a tunnel outage and loop forever; tell them apart
# up front.
python -c "import jax" || {
    echo "# python environment cannot import jax; aborting" >&2
    exit 1
}
while true; do
    # The platform check matters: a failed TPU init can fall back to the
    # CPU backend, which would "succeed" instantly mid-outage and launch
    # the sweep against no hardware.
    if timeout 240 python -c \
            "import jax; assert jax.devices()[0].platform != 'cpu'" \
            >/dev/null 2>&1; then
        echo "# tunnel up at $(date -u +%FT%TZ); starting campaign" >&2
        # Resumable: a tunnel drop mid-campaign exits nonzero here and
        # the watch loop resumes probing; the next window continues
        # from the journal instead of starting over.  Bounded launches:
        # once every point's retry budget is spent the campaign keeps
        # exiting 1 with nothing left to run — don't loop on that.
        LAUNCHES=$((${LAUNCHES:-0} + 1))
        if python bench.py --campaign "$SPEC" || [ "$LAUNCHES" -ge 5 ]; then
            python scripts/perf_report.py || true
            exit 0
        fi
        echo "# campaign interrupted (launch $LAUNCHES); resuming probe loop" >&2
    fi
    echo "# tunnel down at $(date -u +%FT%TZ); next probe in 300s" >&2
    sleep 300
done
