#!/usr/bin/env bash
# Wait for the axon TPU tunnel to come back, then run the full hardware
# measurement sweep (scripts/hw_sweep.sh) unattended.  The probe is cheap
# (one jax.devices() with a hard timeout) so a multi-hour outage costs
# nothing but probes; the first successful probe triggers the sweep.
#
#   scripts/tunnel_watch.sh [results_file]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/hw_sweep_results.jsonl}"
# A broken environment (no jax, wrong python) would fail every probe with
# the same silence as a tunnel outage and loop forever; tell them apart
# up front.
python -c "import jax" || {
    echo "# python environment cannot import jax; aborting" >&2
    exit 1
}
while true; do
    # The platform check matters: a failed TPU init can fall back to the
    # CPU backend, which would "succeed" instantly mid-outage and launch
    # the sweep against no hardware.
    if timeout 240 python -c \
            "import jax; assert jax.devices()[0].platform != 'cpu'" \
            >/dev/null 2>&1; then
        echo "# tunnel up at $(date -u +%FT%TZ); starting sweep" >&2
        bash scripts/hw_sweep.sh "$OUT"
        exit 0
    fi
    echo "# tunnel down at $(date -u +%FT%TZ); next probe in 300s" >&2
    sleep 300
done
