#!/usr/bin/env python
"""Sweep per-compile XLA:TPU compiler options for the bench step.

Dev tool for the perf push: env XLA_FLAGS do not reach the TPU compiler
behind the axon tunnel, but jit ``compiler_options`` do.  Each variant
pays a fresh ~3 min compile; run on an otherwise idle machine.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_variant(opts, iters=20, warmup=5, batch=128):
    from bench import build_step

    step, state, _ = build_step("resnet50", "bf16", batch)
    compiled = step.lower(*state).compile(compiler_options=opts or None)
    params, batch_stats, opt_state, images, labels = state
    for _ in range(warmup):
        params, batch_stats, opt_state, loss = compiled(
            params, batch_stats, opt_state, images, labels
        )
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, batch_stats, opt_state, loss = compiled(
            params, batch_stats, opt_state, images, labels
        )
    float(loss)
    return batch * iters / (time.perf_counter() - t0)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("variant", type=int, help="index into VARIANTS")
    args = parser.parse_args()
    VARIANTS = [
        ("baseline", {}),
        ("vmem64m", {"xla_tpu_scoped_vmem_limit_kib": "65536"}),
        ("vmem96m", {"xla_tpu_scoped_vmem_limit_kib": "98304"}),
        ("vmem32m", {"xla_tpu_scoped_vmem_limit_kib": "32768"}),
    ]
    name, opts = VARIANTS[args.variant]
    print(f"{name}: {run_variant(opts):.1f} img/s", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
