#!/usr/bin/env python
"""Refresh tests/scaling_baseline.json — the committed trend baseline for
the cycle-scaling gate (tests/test_scaling.py).

VERDICT r4 weak #3: a hard floor of 0.25 only catches a catastrophic 4x
cliff; gating against a *recorded* measured ratio catches the actual
property (a reintroduced serial recv that halves np=8 goodput).  This
script IS the recording half: run it on an otherwise-idle machine, review
the printed JSON, commit it.

Usage: python scripts/record_scaling_baseline.py [--trials 5]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def measure_ratio(trials: int) -> dict:
    import horovod_tpu.run as hvdrun
    from tests.test_scaling import _rate_worker

    env = {"HVDTPU_EAGER_ENGINE": "native", "HVDTPU_CYCLE_TIME": "1"}
    ratios = []
    for t in range(trials):
        r2 = hvdrun.run(_rate_worker, (256, 40), np=2, use_cpu=True,
                        timeout=300, env=env)[0]
        r8 = hvdrun.run(_rate_worker, (256, 40), np=8, use_cpu=True,
                        timeout=300, env=env)[0]
        ratios.append(r8 / r2)
        print(f"# trial {t}: rate2={r2:.1f} rate8={r8:.1f} "
              f"ratio={r8 / r2:.3f}", file=sys.stderr)
    return {
        # median across trials: one loaded-machine outlier must not set
        # the bar every future CI run is graded against
        "np8_over_np2": round(statistics.median(ratios), 3),
        "trials": [round(r, 3) for r in ratios],
        # the gate takes best-of-N live trials and fails below
        # band * np8_over_np2 (noise only DEPRESSES the ratio, so
        # best-of-N vs a banded median is one-sided-safe).  For this
        # host's measured ratio (~0.47 idle, 1-core) the band must
        # exceed ~0.53 or the threshold falls under the 0.25 cliff
        # floor and the trend gate is inert; pushing it much past 0.7
        # crowds the observed worst trial (0.417) and flakes.  0.7
        # leaves the threshold (0.333) 25% under that worst trial.
        "band": 0.7,
        "note": "refresh with scripts/record_scaling_baseline.py on an "
                "idle machine; gate = max(0.25, band * np8_over_np2)",
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--trials", type=int, default=5)
    parser.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(__file__), "..", "tests",
                             "scaling_baseline.json"),
    )
    args = parser.parse_args()
    record = measure_ratio(args.trials)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
