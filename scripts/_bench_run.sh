# Shared helper for the hardware sweep drivers (hw_sweep.sh,
# hw_sweep2.sh): source this file, set OUT, then
#
#   run <label> <outer-timeout-secs> <bench-budget-secs> [bench args...]
#
# bench.py bounds its own wall-clock (--total-budget-secs across all
# retries); the outer timeout must be strictly larger so the sweep never
# kills bench mid-retry and records null for a config that would have
# recovered.  Every result is validated before it is embedded: the last
# stdout line must be a strict-JSON OBJECT (no bare scalars, no
# NaN/Infinity) or the config records null — a traceback tail must not
# corrupt the results file.
run() {
    local label="$1" tmo="$2" budget="$3"; shift 3
    echo "== $label: bench.py $* ==" >&2
    local line
    line=$(timeout "$tmo" python bench.py --total-budget-secs "$budget" \
           "$@" 2>/dev/null | tail -1)
    if [ -n "$line" ] && python - "$line" <<'EOF' 2>/dev/null
import json, sys
def _no_const(c):
    raise ValueError(c)
v = json.loads(sys.argv[1], parse_constant=_no_const)
assert isinstance(v, dict)
EOF
    then
        echo "{\"config\": \"$label\", \"result\": $line}" >> "$OUT"
        echo "$line" >&2
    else
        echo "{\"config\": \"$label\", \"result\": null}" >> "$OUT"
        echo "(no result)" >&2
    fi
}
