#!/usr/bin/env python
"""Perf-trend report: the whole BENCH/MULTICHIP trajectory, readable.

The observatory counterpart to scripts/perf_gate.py (which *gates*):
this script *reports*.  It loads every historical ``BENCH_r*.json`` /
``MULTICHIP_r*.json`` across all schema eras through the one shared
reader (horovod_tpu/obs/trend.py), separates real measurements from
degraded placeholders and failed rounds, prints the per-scenario EWMA
baselines and the degraded-streak verdict, and renders the campaign
verdict table for a ``campaign.json`` journal
(horovod_tpu/bench/campaign.py) when one exists.

``--write-docs`` re-renders the auto-generated trajectory section of
``docs/performance.md`` in place (between the ``perf-report`` markers),
so the committed docs can never drift from the committed records.

This replaces ``scripts/summarize_sweep.py`` (now a deprecation shim):
campaign journals carry per-point status/provenance an ad-hoc sweep's
results file never had.

Exit codes: 0 report rendered, 2 bad input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from horovod_tpu.obs import trend  # noqa: E402

DOCS_BEGIN = "<!-- perf-report:begin -->"
DOCS_END = "<!-- perf-report:end -->"


def campaign_table(journal: dict) -> list:
    """Text lines for the per-point campaign verdict table."""
    lines = [f"campaign {journal.get('name')} "
             f"(spec {journal.get('spec_sha')}, "
             f"updated {journal.get('updated')}):"]
    for pid in journal.get("order", []):
        entry = journal.get("points", {}).get(pid, {})
        record = entry.get("record") or {}
        value = record.get("value")
        val_s = f" value={value}" if isinstance(value, (int, float)) else ""
        lines.append(
            f"  {entry.get('status', 'pending'):9s} {pid}: "
            f"attempts={entry.get('attempts', 0)} "
            f"compile={entry.get('compile', '—')}{val_s}"
        )
    return lines


def load_campaign(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "points" not in doc:
        raise ValueError(f"{path} is not a campaign journal")
    return doc


def write_docs(docs_path: str, records_dir: str) -> bool:
    """Replace the marker-fenced auto-generated section; returns True
    when the file changed.  Missing markers are an error — silently
    appending would duplicate the section on every run."""
    with open(docs_path) as f:
        text = f.read()
    if DOCS_BEGIN not in text or DOCS_END not in text:
        raise ValueError(
            f"{docs_path} has no {DOCS_BEGIN} / {DOCS_END} markers")
    head, rest = text.split(DOCS_BEGIN, 1)
    _, tail = rest.split(DOCS_END, 1)
    body = trend.render_markdown(records_dir)
    new = head + DOCS_BEGIN + "\n" + body + DOCS_END + tail
    if new == text:
        return False
    with open(docs_path, "w") as f:
        f.write(new)
    return True


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Render the BENCH/MULTICHIP perf trajectory, EWMA "
                    "baselines, degraded-streak verdict and campaign "
                    "table.")
    p.add_argument("--records-dir", default=REPO_ROOT,
                   help="directory holding BENCH_*/MULTICHIP_* records "
                        "(default: repo root)")
    p.add_argument("--campaign", default=None,
                   help="campaign.json journal to render (default: "
                        "<records-dir>/campaign.json when present)")
    p.add_argument("--write-docs", nargs="?", const=os.path.join(
                       REPO_ROOT, "docs", "performance.md"),
                   default=None, metavar="PATH",
                   help="re-render the auto-generated trajectory "
                        "section of docs/performance.md (or PATH) in "
                        "place")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable trend document too")
    args = p.parse_args(argv)

    records = trend.load_bench_records(args.records_dir)
    multichip = trend.load_multichip_records(args.records_dir)
    if not records and not multichip:
        print(f"no BENCH_*/MULTICHIP_*.json records under "
              f"{args.records_dir}", file=sys.stderr)
        return 2

    counts = {"real": 0, "degraded": 0, "failed": 0}
    print(f"# BENCH trajectory: {len(records)} records")
    for row in trend.trajectory(records):
        counts[row["class"]] += 1
        desc = row["metric"] or f"rc={row['rc']}"
        val_s = (f" value={row['value']}"
                 if isinstance(row["value"], (int, float)) else "")
        mfu_s = (f" mfu={row['mfu']}"
                 if isinstance(row["mfu"], (int, float)) else "")
        print(f"  {row['class']:9s} {row['file']}: {desc}{val_s}{mfu_s}"
              f" [{row['device'] or 'unknown device'}]")
    print(f"# partition: {counts['real']} real, {counts['degraded']} "
          f"degraded, {counts['failed']} failed")

    scenarios = sorted(
        {trend.scenario_key(trend.parsed_payload(doc))
         for _, _, doc in records if trend.classify(doc) == "real"},
        key=str)
    for metric, device in scenarios:
        base = trend.ewma_baseline(records, metric, device)
        if base:
            print(f"# EWMA baseline {metric} on "
                  f"{device or 'unknown device'}: {base['value']} "
                  f"over {', '.join(base['records'])}")

    streak = trend.degraded_streak(records)
    print(f"# degraded-streak verdict: {streak['verdict']}")

    if multichip:
        print(f"# MULTICHIP rounds: {len(multichip)}")
        for n, fname, doc in multichip:
            print(f"  {fname}: n_devices={doc.get('n_devices')} "
                  f"ok={doc.get('ok')} skipped={doc.get('skipped')}")

    journal_path = args.campaign or os.path.join(
        args.records_dir, "campaign.json")
    journal = None
    if os.path.exists(journal_path):
        try:
            journal = load_campaign(journal_path)
        except (OSError, ValueError) as exc:
            print(f"unreadable campaign journal {journal_path}: {exc}",
                  file=sys.stderr)
            return 2
        for line in campaign_table(journal):
            print(line)
    elif args.campaign:
        print(f"campaign journal {args.campaign} not found",
              file=sys.stderr)
        return 2

    if args.write_docs:
        try:
            changed = write_docs(args.write_docs, args.records_dir)
        except (OSError, ValueError) as exc:
            print(f"--write-docs failed: {exc}", file=sys.stderr)
            return 2
        print(f"# docs: {args.write_docs} "
              f"{'updated' if changed else 'already current'}")

    if args.json:
        doc = {
            "records": len(records),
            "partition": counts,
            "degraded_streak": streak,
            "trend": trend.trend_stamp(args.records_dir),
        }
        if journal is not None:
            from horovod_tpu.bench.campaign import (  # noqa: PLC0415
                summarize_journal,
            )

            doc["campaign"] = summarize_journal(journal)
        print(json.dumps(doc, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
