#!/usr/bin/env python
"""HLO collective-schedule gate: every rank must compile the same program.

The source-level lint (HVD001/HVD010) rejects rank-divergent schedules
it can see in the AST; this gate checks the property on the artifact:
each simulated rank compiles the repo's three collective-bearing step
programs — the engine-style fused allreduce, the overlap bucket train
step, and the serve sequence-sharded decode attention step — in its own
process (rank-specific env, exactly how a real launcher differs per
rank), dumps the scheduled HLO, and
``python -m horovod_tpu.analysis.hlo`` asserts the extracted collective
sequences are identical.  Any code path that lets the rank leak into
the compiled schedule (a rank-guarded collective, a rank-dependent
bucket layout, a rank-chosen axis) diverges the dumps and fails CI.

    python scripts/hlo_gate.py                 # the gate (exit != 0 on divergence)
    python scripts/hlo_gate.py --seed-divergence   # self-test: a seeded
        # rank-guarded collective MUST be rejected (exit 0 iff it was)

Internal: ``--emit RANK`` runs the per-rank compile half (spawned by
the driver with JAX_PLATFORMS=cpu and a 4-device host platform).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROGRAMS = ("engine_allreduce", "overlap_bucket", "serve_decode",
            "serve_paged_width")
WORLD = 2  # simulated ranks; each compiles in its own process


# ---------------------------------------------------------------------------
# per-rank emitter (subprocess half)
# ---------------------------------------------------------------------------


def _emit(rank: int, out_dir: str) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.optim import overlap
    from horovod_tpu.ops.collectives import shard_map_compat
    from horovod_tpu.serve.longctx import sharded_decode_attention

    seed_divergent = os.environ.get("HVDTPU_HLO_GATE_DIVERGE") == "1"
    mesh = Mesh(np.asarray(jax.devices(), dtype=object).reshape(4),
                (hvd.DP_AXIS,))

    def dump(name: str, lowered) -> None:
        text = lowered.compile().as_text()
        path = os.path.join(out_dir, f"{name}.rank{rank}.txt")
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)

    # (1) engine-style fused allreduce: the device plane's jitted
    # shard_map psum over the staged (world, n) buffer, pre/post scaled
    # (the Average path).  The seeded divergence is the HVD010 bug as
    # an artifact: a collective only SOME ranks compile.
    def fused_allreduce(x):
        v = x * (1.0 / 4.0)
        total = lax.psum(v, hvd.DP_AXIS)
        if seed_divergent and rank != 0:
            total = total + lax.psum(jnp.sum(v), hvd.DP_AXIS)
        return total

    fn = jax.jit(shard_map_compat(
        fused_allreduce, mesh=mesh,
        in_specs=P(hvd.DP_AXIS), out_specs=P(),
    ))
    dump("engine_allreduce",
         fn.lower(jnp.ones((4, 64), jnp.float32)))

    # (2) overlap bucket train step: the PR-9 plane end to end (bucket
    # collectives planted in the backward), compiled exactly as the CI
    # overlap gate compiles it.
    def init_params(key):
        sizes = [16, 32, 32, 8]
        params = []
        for i in range(3):
            k, key = jax.random.split(key)
            params.append({
                "w": jax.random.normal(k, (sizes[i], sizes[i + 1])) * .1,
                "b": jnp.zeros(sizes[i + 1]),
            })
        return params

    def loss_fn(params, x, y):
        h = x
        for i, layer in enumerate(params):
            h = h @ layer["w"] + layer["b"]
            if i < 2:
                h = jax.nn.relu(h)
        return jnp.mean((h - y) ** 2)

    params = init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    y = jax.random.normal(jax.random.PRNGKey(2), (8, 8))
    plan = overlap.OverlapPlan(params, optax.sgd(0.05), mode="bucket",
                               mesh=mesh, bucket_mb=2 / 1024.0)
    spec = plan.state_spec()
    step = jax.jit(shard_map_compat(
        plan.local_step(loss_fn), mesh=mesh,
        in_specs=(spec, P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
        out_specs=(spec, P()),
    ), donate_argnums=(0,))
    dump("overlap_bucket", step.lower(plan.init(params), x, y))

    # (3) serve decode: the sequence-sharded decode attention step
    # (pmax + psum merge per decode step) over a 4-way sharded cache.
    import types
    cfg = types.SimpleNamespace(kv_heads=2, attention_window=None)

    def decode(q, k, v, pos):
        return sharded_decode_attention(cfg, q, k, v, pos, hvd.DP_AXIS)

    b, h, hd, s = 2, 4, 8, 32
    dec = jax.jit(shard_map_compat(
        decode, mesh=mesh,
        in_specs=(P(), P(None, hvd.DP_AXIS), P(None, hvd.DP_AXIS), P()),
        out_specs=P(),
    ))
    dump("serve_decode", dec.lower(
        jnp.ones((b, h, hd), jnp.float32),
        jnp.ones((b, s, 2, hd), jnp.float32),
        jnp.ones((b, s, 2, hd), jnp.float32),
        jnp.full((b,), 7, jnp.int32),
    ))

    # (4) serve paged width-sharded decode (ISSUE 15): the block-table
    # gather + Megatron width shard over a (replica, width) mesh view —
    # the program every rank of a width-sharded fleet serves from.  Two
    # row-parallel psums per block over the width axis; a rank-leaked
    # schedule here would desync the whole fleet's decode.
    from horovod_tpu.models.decode import (
        decode_step_paged, init_paged_pool,
    )
    from horovod_tpu.models.transformer import gpt
    from horovod_tpu.parallel.tensor_parallel import stack_tp_params
    from horovod_tpu.serve.engine import REPLICA_AXIS, WIDTH_AXIS

    model = gpt("nano", num_layers=1, num_heads=2, emb_dim=32,
                max_len=16, vocab_size=64, dtype=jnp.float32,
                attention_impl="reference")
    gcfg = model.cfg
    gparams = model.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))
    sh, rep = stack_tp_params(gparams, gcfg, 2)
    wmesh = Mesh(np.asarray(jax.devices(), dtype=object).reshape(2, 2),
                 (REPLICA_AXIS, WIDTH_AXIS))
    pool = init_paged_pool(gcfg, num_pages=6, page_size=4, num_slots=2)
    tables = jnp.zeros((2, 4), jnp.int32)
    pool_spec = {"k": P(None, None, None, WIDTH_AXIS, None),
                 "v": P(None, None, None, WIDTH_AXIS, None),
                 "pos": P()}

    def paged_step(sh_p, rep_p, pool_, tables_, toks, mask):
        p = jax.tree_util.tree_map(lambda a: a[0], sh_p)
        return decode_step_paged(gcfg, p, pool_, tables_, toks,
                                 write_mask=mask, tp_axis=WIDTH_AXIS,
                                 rep=rep_p)

    pstep = jax.jit(shard_map_compat(
        paged_step, mesh=wmesh,
        in_specs=(P(WIDTH_AXIS), P(), pool_spec, P(), P(), P()),
        out_specs=(P(), pool_spec),
    ))
    dump("serve_paged_width", pstep.lower(
        sh, rep, pool, tables,
        jnp.ones((2,), jnp.int32), jnp.ones((2,), bool),
    ))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _spawn_rank(rank: int, out_dir: str, diverge: bool) -> None:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        # Rank-specific env, like a real launcher: the gate's whole
        # claim is that none of this may reach the artifact.
        "HOROVOD_RANK": str(rank),
        "HVDTPU_HLO_GATE_DIVERGE": "1" if diverge else "0",
    })
    subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--emit", str(rank), "--out", out_dir],
        env=env, cwd=REPO, check=True, timeout=600,
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--emit", type=int, default=None,
                        help=argparse.SUPPRESS)
    parser.add_argument("--out", default=None)
    parser.add_argument("--seed-divergence", action="store_true",
                        help="self-test: assert a rank-guarded "
                             "collective is rejected")
    args = parser.parse_args(argv)

    if args.emit is not None:
        _emit(args.emit, args.out)
        return 0

    out_dir = args.out or tempfile.mkdtemp(prefix="hvdtpu-hlo-gate.")
    for rank in range(WORLD):
        _spawn_rank(rank, out_dir, args.seed_divergence)

    failures = 0
    for prog in PROGRAMS:
        dumps = [
            f"rank{r}={os.path.join(out_dir, f'{prog}.rank{r}.txt')}"
            for r in range(WORLD)
        ]
        rc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.analysis.hlo",
             *dumps, "--expect-collectives", "1"],
            cwd=REPO, timeout=120,
            env={**os.environ,
                 "PYTHONPATH": REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")},
        ).returncode
        expect_divergence = args.seed_divergence \
            and prog == "engine_allreduce"
        if expect_divergence:
            # rc == 1 exactly: the documented divergence verdict.  A
            # rc of 2 means the checker never compared anything
            # (unreadable dump) — accepting it would let a blind
            # checker pass its own blindness test.
            if rc != 1:
                print(f"hlo gate SELF-TEST FAILED: seeded divergent "
                      f"{prog} schedule was not rejected as a "
                      f"divergence (exit {rc})", file=sys.stderr)
                failures += 1
            else:
                print(f"hlo gate self-test OK: seeded divergent {prog} "
                      f"rejected (exit {rc})")
        elif rc != 0:
            print(f"hlo gate FAILED: {prog} schedules diverge across "
                  f"ranks (exit {rc})", file=sys.stderr)
            failures += 1
    if failures == 0:
        mode = "self-test" if args.seed_divergence else "gate"
        print(f"hlo {mode} OK: {len(PROGRAMS)} program(s) x {WORLD} "
              f"rank(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
