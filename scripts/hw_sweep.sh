#!/usr/bin/env bash
# Hardware measurement plan for the first available tunnel window
# (docs/performance.md "Round-4 transformer levers").  Sequential, each
# config tolerant of failure, everything appended as labeled JSON lines —
# a later hang can't erase earlier results.
#
#   scripts/hw_sweep.sh [results_file]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/hw_sweep_results.jsonl}"

# run <label> <outer-timeout> <bench-budget> [bench args...] — shared
# with hw_sweep2.sh (timeout/validation semantics documented there)
. "$(dirname "$0")/_bench_run.sh"

# 1. the headline record (VERDICT r3 item 1): expect ~2660 img/s bf16
#    (batch 128 is the measured sweet spot — performance.md "Knobs tried")
run resnet50_bf16_b128 1800 1440
# 2. first real-chip GPT number (VERDICT r3 item 2)
run gpt_small_base 1800 1440 --model gpt-small --flash-block-q 128 --flash-block-k 128
# 3. the round-4 levers, one at a time
run gpt_small_remat 1800 1440 --model gpt-small --remat --flash-block-q 128 --flash-block-k 128
run gpt_small_remat_b16 1800 1440 --model gpt-small --remat --batch-size 16 --flash-block-q 128 --flash-block-k 128
run gpt_small_blocks256 1800 1440 --model gpt-small --flash-block-q 256 --flash-block-k 256
run gpt_small_blocks512q 1800 1440 --model gpt-small --flash-block-q 512 --flash-block-k 256
run gpt_small_gqa4 1800 1440 --model gpt-small --kv-heads 4 --flash-block-q 128 --flash-block-k 128
run gpt_small_rope 1800 1440 --model gpt-small --pos-embedding rope --flash-block-q 128 --flash-block-k 128
run gpt_small_rope_gqa_remat 1800 1440 --model gpt-small --pos-embedding rope --kv-heads 4 --remat --batch-size 16
# 4. the other headline families (docs/benchmarks.md)
run inception3_bf16 1800 1440 --model inception3 --batch-size 128
run vgg16_bf16 1800 1440 --model vgg16 --batch-size 64
# 5. fp8-vs-bf16 replication (VERDICT r4 weak #2): 3-run medians in one
#    session; repeats are cache-warmed so each costs ~1 min of chip time
run resnet50_bf16_rep2 1800 1440
run resnet50_bf16_rep3 1800 1440
run resnet50_fp8_rep1 1800 1440 --dtype fp8
run resnet50_fp8_rep2 1800 1440 --dtype fp8
run resnet50_fp8_rep3 1800 1440 --dtype fp8
echo "sweep complete -> $OUT" >&2
