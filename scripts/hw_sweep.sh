#!/usr/bin/env bash
# Hardware measurement plan for the first available tunnel window
# (docs/performance.md "Round-4 transformer levers").  Sequential, each
# config tolerant of failure, everything appended as labeled JSON lines —
# a later hang can't erase earlier results.
#
#   scripts/hw_sweep.sh [results_file]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/hw_sweep_results.jsonl}"

run() {
    local label="$1"; shift
    echo "== $label: bench.py $* ==" >&2
    local line
    # bench.py bounds its own wall-clock (--total-budget-secs, default
    # 1440s across all retries); the outer timeout is a strictly larger
    # backstop so the sweep never kills bench mid-retry and records null
    # for a config that would have recovered.
    line=$(timeout 1800 python bench.py --total-budget-secs 1440 "$@" \
           2>/dev/null | tail -1)
    # Validate before embedding: a non-JSON last stdout line (a traceback
    # tail, a stray print) must not corrupt the results file.
    if [ -n "$line" ] && python - "$line" <<'EOF' 2>/dev/null
import json, sys
# A real bench result is a JSON OBJECT; reject bare scalars (a stray
# numeric line) and NaN/Infinity (json.loads accepts them but they
# corrupt the strict-JSON results file).
def _no_const(c):
    raise ValueError(c)
v = json.loads(sys.argv[1], parse_constant=_no_const)
assert isinstance(v, dict)
EOF
    then
        echo "{\"config\": \"$label\", \"result\": $line}" >> "$OUT"
        echo "$line" >&2
    else
        echo "{\"config\": \"$label\", \"result\": null}" >> "$OUT"
        echo "(no result)" >&2
    fi
}

# 1. the headline record (VERDICT r3 item 1): expect ~2660 img/s bf16
#    (batch 128 is the measured sweet spot — performance.md "Knobs tried")
run resnet50_bf16_b128
# 2. first real-chip GPT number (VERDICT r3 item 2)
run gpt_small_base --model gpt-small
# 3. the round-4 levers, one at a time
run gpt_small_remat --model gpt-small --remat
run gpt_small_remat_b16 --model gpt-small --remat --batch-size 16
run gpt_small_blocks256 --model gpt-small --flash-block-q 256 --flash-block-k 256
run gpt_small_blocks512q --model gpt-small --flash-block-q 512 --flash-block-k 256
run gpt_small_gqa4 --model gpt-small --kv-heads 4
run gpt_small_rope --model gpt-small --pos-embedding rope
run gpt_small_rope_gqa_remat --model gpt-small --pos-embedding rope --kv-heads 4 --remat --batch-size 16
# 4. the other headline families (docs/benchmarks.md)
run inception3_bf16 --model inception3 --batch-size 128
run vgg16_bf16 --model vgg16 --batch-size 64
# 5. fp8-vs-bf16 replication (VERDICT r4 weak #2): 3-run medians in one
#    session; repeats are cache-warmed so each costs ~1 min of chip time
run resnet50_bf16_rep2
run resnet50_bf16_rep3
run resnet50_fp8_rep1 --dtype fp8
run resnet50_fp8_rep2 --dtype fp8
run resnet50_fp8_rep3 --dtype fp8
echo "sweep complete -> $OUT" >&2
