#include "tcp.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>

namespace hvdtpu {

namespace {

constexpr int kConnectTimeoutSec = 120;

Status Errno(const char* what) {
  return Status::Error(StatusCode::UNKNOWN_ERROR,
                       std::string(what) + ": " + std::strerror(errno));
}

void SetSockOpts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

bool SplitHostPort(const std::string& addr, std::string* host, int* port) {
  auto pos = addr.rfind(':');
  if (pos == std::string::npos) return false;
  *host = addr.substr(0, pos);
  *port = std::atoi(addr.c_str() + pos + 1);
  return *port > 0;
}

}  // namespace

TcpMesh::~TcpMesh() { Close(); }

Status TcpMesh::Listen(int* port_out) {
  listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return Errno("socket");
  int one = 1;
  setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_ANY);
  sa.sin_port = 0;
  if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) < 0)
    return Errno("bind");
  if (listen(listen_fd_, 128) < 0) return Errno("listen");
  socklen_t slen = sizeof(sa);
  if (getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&sa), &slen) < 0)
    return Errno("getsockname");
  *port_out = ntohs(sa.sin_port);
  return Status::OK();
}

Status TcpMesh::Connect(int rank, int size,
                        const std::vector<std::string>& addrs) {
  rank_ = rank;
  size_ = size;
  fds_.assign(size, -1);
  if (size == 1) return Status::OK();

  // Outbound: connect to every lower rank (retry while the peer's accept
  // loop comes up — ranks start at slightly different times).
  for (int peer = 0; peer < rank; peer++) {
    std::string host;
    int port;
    if (!SplitHostPort(addrs[peer], &host, &port))
      return Status::Error(StatusCode::INVALID_ARGUMENT,
                           "bad address " + addrs[peer]);
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res)
      return Status::Error(StatusCode::UNKNOWN_ERROR, "resolve " + host);
    sockaddr_in sa = *reinterpret_cast<sockaddr_in*>(res->ai_addr);
    sa.sin_port = htons(static_cast<uint16_t>(port));
    freeaddrinfo(res);

    int fd = -1;
    for (int attempt = 0; attempt < kConnectTimeoutSec * 10; attempt++) {
      fd = socket(AF_INET, SOCK_STREAM, 0);
      if (fd < 0) return Errno("socket");
      if (connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) == 0)
        break;
      close(fd);
      fd = -1;
      usleep(100 * 1000);
    }
    if (fd < 0)
      return Status::Error(StatusCode::UNKNOWN_ERROR,
                           "connect to rank " + std::to_string(peer) + " at " +
                               addrs[peer] + " timed out");
    SetSockOpts(fd);
    int32_t hello = rank_;
    Status s = SendAll(fd, &hello, sizeof(hello));
    if (!s.ok()) return s;
    fds_[peer] = fd;
  }

  // Inbound: accept from every higher rank; hello identifies the peer.
  for (int n = rank + 1; n < size; n++) {
    pollfd p{listen_fd_, POLLIN, 0};
    int r = poll(&p, 1, kConnectTimeoutSec * 1000);
    if (r <= 0)
      return Status::Error(StatusCode::UNKNOWN_ERROR,
                           "timed out accepting mesh connections");
    int fd = accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return Errno("accept");
    SetSockOpts(fd);
    int32_t hello = -1;
    Status s = RecvAll(fd, &hello, sizeof(hello));
    if (!s.ok()) return s;
    if (hello <= rank_ || hello >= size_ || fds_[hello] != -1) {
      close(fd);
      return Status::Error(StatusCode::UNKNOWN_ERROR,
                           "unexpected mesh hello rank " + std::to_string(hello));
    }
    fds_[hello] = fd;
  }
  return Status::OK();
}

Status TcpMesh::SendAll(int fd, const void* data, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  while (len > 0) {
    ssize_t n = send(fd, p, len, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return Errno("send");
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return Status::OK();
}

Status TcpMesh::RecvAll(int fd, void* data, size_t len) {
  uint8_t* p = static_cast<uint8_t*>(data);
  while (len > 0) {
    ssize_t n = recv(fd, p, len, 0);
    if (n == 0)
      return Status::Error(StatusCode::ABORTED, "peer closed connection");
    if (n < 0) {
      if (errno == EINTR) continue;
      return Errno("recv");
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return Status::OK();
}

Status TcpMesh::SendMsg(int to, const uint8_t* data, size_t len) {
  uint64_t hdr = len;
  Status s = SendAll(fds_[to], &hdr, sizeof(hdr));
  if (!s.ok()) return s;
  return SendAll(fds_[to], data, len);
}

Status TcpMesh::RecvMsg(int from, std::vector<uint8_t>* out) {
  uint64_t hdr = 0;
  Status s = RecvAll(fds_[from], &hdr, sizeof(hdr));
  if (!s.ok()) return s;
  if (hdr > (1ull << 34))
    return Status::Error(StatusCode::UNKNOWN_ERROR, "oversized message");
  out->resize(hdr);
  return RecvAll(fds_[from], out->data(), hdr);
}

Status TcpMesh::RecvMsgMulti(const std::vector<int>& peers,
                             std::vector<std::vector<uint8_t>>* out) {
  // Per-peer incremental framing state; bytes are consumed from whichever
  // socket poll() reports readable, so one slow worker never serializes
  // the others behind it.
  struct PeerState {
    int peer = -1;
    uint64_t hdr = 0;
    size_t hdr_got = 0;   // bytes of the 8-byte length header received
    size_t body_got = 0;  // bytes of the payload received
    bool done = false;
  };
  std::vector<PeerState> states(peers.size());
  for (size_t i = 0; i < peers.size(); i++) states[i].peer = peers[i];
  size_t remaining = peers.size();

  std::vector<pollfd> pfds(peers.size());
  while (remaining > 0) {
    size_t n = 0;
    for (auto& st : states) {
      if (st.done) continue;
      pfds[n].fd = fds_[st.peer];
      pfds[n].events = POLLIN;
      pfds[n].revents = 0;
      n++;
    }
    int r = poll(pfds.data(), static_cast<nfds_t>(n),
                 kConnectTimeoutSec * 1000);
    if (r == 0)
      return Status::Error(StatusCode::UNKNOWN_ERROR,
                           "negotiation recv timed out");
    if (r < 0) {
      if (errno == EINTR) continue;
      return Errno("poll");
    }
    size_t pi = 0;
    for (auto& st : states) {
      if (st.done) continue;
      const pollfd& p = pfds[pi++];
      if (!(p.revents & (POLLIN | POLLERR | POLLHUP))) continue;
      // One read per readiness event; partial reads park the state until
      // the socket is ready again.
      if (st.hdr_got < sizeof(st.hdr)) {
        ssize_t k = read(p.fd,
                         reinterpret_cast<uint8_t*>(&st.hdr) + st.hdr_got,
                         sizeof(st.hdr) - st.hdr_got);
        if (k == 0)
          return Status::Error(StatusCode::ABORTED, "peer closed connection");
        if (k < 0) return Errno("read (negotiation header)");
        st.hdr_got += static_cast<size_t>(k);
        if (st.hdr_got == sizeof(st.hdr)) {
          if (st.hdr > (1ull << 34))
            return Status::Error(StatusCode::UNKNOWN_ERROR,
                                 "oversized message");
          (*out)[static_cast<size_t>(st.peer)].resize(st.hdr);
          if (st.hdr == 0) {
            st.done = true;
            remaining--;
          }
        }
        continue;
      }
      auto& buf = (*out)[static_cast<size_t>(st.peer)];
      ssize_t k = read(p.fd, buf.data() + st.body_got,
                       buf.size() - st.body_got);
      if (k == 0)
        return Status::Error(StatusCode::ABORTED, "peer closed connection");
      if (k < 0) return Errno("read (negotiation payload)");
      st.body_got += static_cast<size_t>(k);
      if (st.body_got == buf.size()) {
        st.done = true;
        remaining--;
      }
    }
  }
  return Status::OK();
}

Status TcpMesh::SendBytes(int to, const void* data, size_t len) {
  return SendAll(fds_[to], data, len);
}

Status TcpMesh::RecvBytes(int from, void* data, size_t len) {
  return RecvAll(fds_[from], data, len);
}

Status TcpMesh::SendRecv(int to, const void* sendbuf, size_t sendlen,
                         int from, void* recvbuf, size_t recvlen) {
  // Interleave so both directions drain regardless of kernel buffer size;
  // blocking send-then-recv on both sides of a pair can deadlock once
  // sendlen exceeds the socket buffer.
  const uint8_t* sp = static_cast<const uint8_t*>(sendbuf);
  uint8_t* rp = static_cast<uint8_t*>(recvbuf);
  size_t sleft = sendlen, rleft = recvlen;
  int sfd = fds_[to], rfd = fds_[from];
  while (sleft > 0 || rleft > 0) {
    pollfd p[2];
    int n = 0;
    int si = -1, ri = -1;
    if (sleft > 0) {
      si = n;
      p[n++] = {sfd, POLLOUT, 0};
    }
    if (rleft > 0) {
      ri = n;
      p[n++] = {rfd, POLLIN, 0};
    }
    int r = poll(p, static_cast<nfds_t>(n), 300 * 1000);
    if (r < 0) {
      if (errno == EINTR) continue;
      return Errno("poll");
    }
    if (r == 0)
      return Status::Error(StatusCode::UNKNOWN_ERROR, "sendrecv timed out");
    if (si >= 0 && (p[si].revents & (POLLOUT | POLLERR | POLLHUP))) {
      ssize_t k = send(sfd, sp, sleft, MSG_NOSIGNAL);
      if (k < 0 && errno != EINTR && errno != EAGAIN) return Errno("send");
      if (k > 0) {
        sp += k;
        sleft -= static_cast<size_t>(k);
      }
    }
    if (ri >= 0 && (p[ri].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t k = recv(rfd, rp, rleft, 0);
      if (k == 0)
        return Status::Error(StatusCode::ABORTED, "peer closed connection");
      if (k < 0 && errno != EINTR && errno != EAGAIN) return Errno("recv");
      if (k > 0) {
        rp += k;
        rleft -= static_cast<size_t>(k);
      }
    }
  }
  return Status::OK();
}

void TcpMesh::Close() {
  for (auto& fd : fds_) {
    if (fd >= 0) close(fd);
    fd = -1;
  }
  if (listen_fd_ >= 0) close(listen_fd_);
  listen_fd_ = -1;
}

}  // namespace hvdtpu
