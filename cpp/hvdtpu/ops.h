// Host-tensor collective algorithms over the TCP mesh.
//
// Reference: horovod/common/ops/{mpi,gloo,nccl}_operations.cc delegate to
// library collectives (MPI_Allreduce, gloo ring, ncclAllReduce); this
// build's CPU data plane implements the algorithms directly:
//   * allreduce  — ring reduce-scatter + ring allgather (bandwidth-optimal,
//                  the same schedule NCCL/gloo use)
//   * allgatherv — ragged ring (per-rank dim0 sizes from negotiation)
//   * broadcast  — binomial tree from the root
//   * alltoall   — pairwise shifted exchange
//   * adasum     — Vector-Halving Distance-Doubling with the projection
//                  rule (reference ops/adasum/adasum.h:167-299)
// The device data plane is XLA's (ops/collectives.py); these serve the
// eager host path.
#pragma once

#include <cstdint>
#include <vector>

#include "common.h"
#include "tcp.h"

namespace hvdtpu {

// In-place allreduce of `count` elements of `dtype` in buf, op in
// {SUM, MIN, MAX} (AVERAGE = SUM + caller-side scale).
Status RingAllreduce(TcpMesh* mesh, void* buf, int64_t count, DataType dtype,
                     ReduceOp op);

// Ragged allgather: local `send` holds counts[rank] elements; on return
// `recv` holds sum(counts) elements ordered by rank.  counts are element
// counts (dim0 * row_elems already folded in).
Status RingAllgatherv(TcpMesh* mesh, const void* send, void* recv,
                      const std::vector<int64_t>& counts, DataType dtype);

// In-place binomial-tree broadcast from root.
Status TreeBroadcast(TcpMesh* mesh, void* buf, int64_t count, DataType dtype,
                     int root);

// Alltoall: send[i*chunk .. (i+1)*chunk) goes to rank i; recv likewise.
Status PairwiseAlltoall(TcpMesh* mesh, const void* send, void* recv,
                        int64_t chunk_elems, DataType dtype);

// In-place Adasum allreduce (VHDD when size is a power of two; otherwise
// gather-to-root + sequential binary-tree combine + broadcast, matching the
// Python engine's _numpy_adasum_rows ordering).  Math in double.
Status AdasumAllreduce(TcpMesh* mesh, void* buf, int64_t count,
                       DataType dtype);

}  // namespace hvdtpu
