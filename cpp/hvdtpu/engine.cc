// The native eager engine: global state, background thread, C API.
//
// Reference: horovod/common/operations.cc — singleton HorovodGlobalState
// (operations.cc:114), one background thread owning all communication
// (InitializeHorovodOnce :604-650, BackgroundThreadLoop :333-600, rationale
// for single ownership :311-330), RunLoopOnce cycle (:550), PerformOperation
// executing fused responses (:232-309), Enqueue* APIs (:803-954) and the
// extern "C" surface (:661-799) loaded via ctypes (basics.py).
//
// The Python binding (horovod_tpu/runtime/native.py) exchanges TCP
// addresses through the already-running coordination service and then hands
// this engine full ownership of the eager data path: negotiation with the
// rank-0 coordinator, response-cache fast path, tensor fusion, ring
// collectives, Adasum VHDD, timeline, stall inspection.
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "controller.h"
#include "dtype_math.h"
#include "ops.h"
#include "response_cache.h"
#include "tcp.h"
#include "timeline.h"
#include "wire.h"

namespace hvdtpu {

LogLevel GlobalLogLevel() {
  static LogLevel level = [] {
    const char* v = std::getenv("HVDTPU_LOG_LEVEL");
    if (!v) return LogLevel::WARNING;
    std::string s(v);
    if (s == "trace") return LogLevel::TRACE;
    if (s == "debug") return LogLevel::DEBUG;
    if (s == "info") return LogLevel::INFO;
    if (s == "warning") return LogLevel::WARNING;
    if (s == "error") return LogLevel::ERROR;
    if (s == "fatal") return LogLevel::FATAL;
    return LogLevel::WARNING;
  }();
  return level;
}

namespace {

constexpr const char* kShutdownError =
    "horovod_tpu has been shut down. This was caused by an exception on one "
    "of the ranks or an asymmetric shutdown; check the logs of other ranks."
    "  (reference: common.h:154-159)";

enum HandleStatus : int { kPending = 0, kOk = 1, kError = 2 };

// Completion record behind an integer handle (reference
// horovod/torch/handle_manager.cc).
struct HandleState {
  int status = kPending;
  std::string error;
  std::vector<uint8_t> output;      // result payload
  std::vector<int64_t> out_shape;   // result geometry
};

// One enqueued named tensor (reference TensorTableEntry, common.h:233-250).
struct Entry {
  int64_t handle = -1;
  Request req;
  std::vector<uint8_t> data;
};

class Engine {
 public:
  static Engine& Get() {
    static Engine* e = new Engine();  // leaked on purpose (atexit ordering)
    return *e;
  }

  int Listen() {
    int port = -1;
    Status s = mesh_.Listen(&port);
    if (!s.ok()) {
      HVD_LOG(LogLevel::ERROR, rank_, "listen failed: %s", s.reason.c_str());
      return -1;
    }
    return port;
  }

  int Connect(int rank, int size, const std::vector<std::string>& addrs,
              int64_t fusion_bytes, double cycle_ms, int cache_capacity,
              double stall_warn, double stall_shutdown,
              const std::string& timeline_path, bool timeline_cycles) {
    rank_ = rank;
    size_ = size;
    fusion_bytes_ = fusion_bytes;
    cycle_ms_ = cycle_ms;
    cache_ = std::make_unique<ResponseCache>(
        static_cast<size_t>(cache_capacity));
    Status s = mesh_.Connect(rank, size, addrs);
    if (!s.ok()) {
      HVD_LOG(LogLevel::ERROR, rank_, "mesh connect failed: %s",
              s.reason.c_str());
      return -1;
    }
    // Every rank records its own timeline (the python side hands each
    // rank a distinct per-rank path; the launcher merges at job end).
    // Negotiation events stay rank-0-only — the controller lives there.
    timeline_.Initialize(timeline_path, rank_, timeline_cycles);
    if (rank_ == 0) {
      ControllerConfig cfg;
      cfg.world_size = size;
      cfg.fusion_threshold_bytes = fusion_bytes;
      cfg.stall_warn_secs = stall_warn;
      cfg.stall_shutdown_secs = stall_shutdown;
      controller_ = std::make_unique<Controller>(cfg);
      controller_->SetCache(cache_.get());
      controller_->SetTimeline(timeline_.enabled() ? &timeline_ : nullptr);
    }
    running_ = true;
    bg_ = std::thread(&Engine::BackgroundLoop, this);
    return 0;
  }

  int64_t Enqueue(RequestType op, const std::string& name, const void* data,
                  const std::vector<int64_t>& shape, DataType dtype,
                  ReduceOp reduce_op, int root_rank, double prescale,
                  double postscale) {
    auto e = std::make_shared<Entry>();
    e->req.request_rank = rank_;
    e->req.request_type = op;
    e->req.tensor_name = name;
    e->req.dtype = dtype;
    e->req.shape = shape;
    e->req.reduce_op = reduce_op;
    e->req.root_rank = root_rank;
    e->req.prescale = prescale;
    e->req.postscale = postscale;
    size_t nbytes =
        static_cast<size_t>(e->req.NumElements()) * DataTypeSize(dtype);
    e->data.resize(nbytes);
    if (data && nbytes) std::memcpy(e->data.data(), data, nbytes);

    std::lock_guard<std::mutex> l(mu_);
    int64_t h = next_handle_++;
    e->handle = h;
    auto hs = std::make_shared<HandleState>();
    handles_[h] = hs;
    if (done_) {
      hs->status = kError;
      hs->error = kShutdownError;
      return h;
    }
    if (table_.count(name)) {
      hs->status = kError;
      hs->error = "Requested to " + std::string(OpLower(op)) +
                  " a tensor with the same name as another tensor that is "
                  "currently being processed.  (reference: common.h:161-164)";
      return h;
    }
    table_[name] = e;
    pending_.push_back(e);
    return h;
  }

  int64_t Join() {
    std::lock_guard<std::mutex> l(mu_);
    int64_t h = next_handle_++;
    auto hs = std::make_shared<HandleState>();
    handles_[h] = hs;
    if (done_) {
      hs->status = kError;
      hs->error = kShutdownError;
      return h;
    }
    joined_ = true;
    join_handles_.push_back(h);
    return h;
  }

  int Poll(int64_t h) {
    std::lock_guard<std::mutex> l(mu_);
    auto it = handles_.find(h);
    return it == handles_.end() ? -1 : it->second->status;
  }

  int Wait(int64_t h) {
    std::unique_lock<std::mutex> l(mu_);
    auto it = handles_.find(h);
    if (it == handles_.end()) return -1;
    auto hs = it->second;
    cv_.wait(l, [&] { return hs->status != kPending; });
    return hs->status;
  }

  std::shared_ptr<HandleState> GetHandle(int64_t h) {
    std::lock_guard<std::mutex> l(mu_);
    auto it = handles_.find(h);
    return it == handles_.end() ? nullptr : it->second;
  }

  void Release(int64_t h) {
    std::lock_guard<std::mutex> l(mu_);
    handles_.erase(h);
  }

  void Shutdown() {
    {
      std::lock_guard<std::mutex> l(mu_);
      if (done_ || !running_) {
        done_ = true;
        return;
      }
      shutdown_requested_ = true;
    }
    if (bg_.joinable() && bg_.get_id() != std::this_thread::get_id())
      bg_.join();
    timeline_.Shutdown();
  }

  bool IsDone() {
    std::lock_guard<std::mutex> l(mu_);
    return done_;
  }

 private:
  Engine() = default;

  static const char* OpLower(RequestType t) {
    switch (t) {
      case RequestType::ALLREDUCE: return "allreduce";
      case RequestType::ALLGATHER: return "allgather";
      case RequestType::BROADCAST: return "broadcast";
      case RequestType::JOIN: return "join";
      case RequestType::ADASUM: return "adasum";
      case RequestType::ALLTOALL: return "alltoall";
      case RequestType::BARRIER: return "barrier";
      case RequestType::REDUCESCATTER: return "reducescatter";
    }
    return "?";
  }

  // ------------------------------------------------------- background loop

  void BackgroundLoop() {
    while (true) {
      auto cycle_start = std::chrono::steady_clock::now();
      bool keep_going = RunLoopOnce();
      if (!keep_going) break;
      auto elapsed = std::chrono::duration<double, std::milli>(
                         std::chrono::steady_clock::now() - cycle_start)
                         .count();
      double cycle_ms = cycle_ms_.load();
      if (elapsed < cycle_ms) {
        std::this_thread::sleep_for(std::chrono::duration<double, std::milli>(
            cycle_ms - elapsed));
      }
    }
    FailAll(kShutdownError);
    mesh_.Close();
  }

  // One negotiation + execution cycle (reference RunLoopOnce,
  // operations.cc:550).
  bool RunLoopOnce() {
    timeline_.MarkCycle();
    RequestList my_list;
    std::vector<std::shared_ptr<Entry>> cached_entries;
    {
      std::lock_guard<std::mutex> l(mu_);
      my_list.shutdown = shutdown_requested_;
      my_list.joined = joined_;
      for (auto& e : pending_) {
        int32_t slot = cache_enabled_ ? cache_->Lookup(e->req) : -1;
        if (slot >= 0) {
          my_list.cache_hits.push_back(static_cast<uint32_t>(slot));
        } else {
          my_list.requests.push_back(e->req);
        }
      }
      pending_.clear();
    }

    // --- negotiation transport (reference mpi_controller.cc:107-199:
    // gather to rank 0, broadcast ResponseList back) ---
    ResponseList rlist;
    if (rank_ == 0) {
      std::vector<RequestList> lists(static_cast<size_t>(size_));
      lists[0] = std::move(my_list);
      // Poll-multiplexed gather: one framed RequestList from every worker,
      // consumed in arrival order — the coordinator's cycle cost does not
      // serialize behind a slow worker (reference gathers with a single
      // MPI_Gatherv, mpi_controller.cc:107-150).
      std::vector<std::vector<uint8_t>> bufs(static_cast<size_t>(size_));
      std::vector<int> workers;
      workers.reserve(static_cast<size_t>(size_) - 1);
      for (int r = 1; r < size_; r++) workers.push_back(r);
      if (!mesh_.RecvMsgMulti(workers, &bufs).ok()) {
        FailAll("negotiation transport failed (worker unreachable)");
        return false;
      }
      for (int r = 1; r < size_; r++) {
        if (!ParseRequestList(bufs[static_cast<size_t>(r)].data(),
                              bufs[static_cast<size_t>(r)].size(),
                              &lists[r])) {
          FailAll("negotiation transport failed (worker unreachable)");
          return false;
        }
      }
      bool should_shutdown = false;
      rlist = controller_->ComputeResponseList(lists, &should_shutdown);
      {
        // Attach the autotuner's latest move so every rank (this one
        // included) applies it at the same cycle boundary (reference
        // SynchronizeParameters, controller.cc:33-47).
        std::lock_guard<std::mutex> l(mu_);
        if (params_pending_) {
          rlist.has_params = true;
          rlist.tuned_fusion_bytes = params_fusion_bytes_;
          rlist.tuned_cycle_ms = params_cycle_ms_;
          rlist.tuned_cache_enabled = params_cache_enabled_;
          params_pending_ = false;
        }
      }
      std::vector<uint8_t> out;
      SerializeResponseList(rlist, &out);
      for (int r = 1; r < size_; r++) {
        if (!mesh_.SendMsg(r, out.data(), out.size()).ok()) {
          FailAll("negotiation transport failed (worker unreachable)");
          return false;
        }
      }
    } else {
      std::vector<uint8_t> out;
      SerializeRequestList(my_list, &out);
      if (!mesh_.SendMsg(0, out.data(), out.size()).ok()) {
        FailAll("negotiation transport failed (coordinator unreachable)");
        return false;
      }
      std::vector<uint8_t> buf;
      if (!mesh_.RecvMsg(0, &buf).ok() ||
          !ParseResponseList(buf.data(), buf.size(), &rlist)) {
        FailAll("negotiation transport failed (coordinator unreachable)");
        return false;
      }
    }

    // --- apply synced params BEFORE cache updates and fusion: all ranks
    // must fuse this cycle's responses with the same threshold ---
    if (rlist.has_params) {
      fusion_bytes_.store(rlist.tuned_fusion_bytes);
      cycle_ms_.store(rlist.tuned_cycle_ms);
      cache_enabled_.store(rlist.tuned_cache_enabled);
      HVD_LOG(LogLevel::DEBUG, rank_,
              "autotune applied: fusion=%lld cycle=%.2fms cache=%d",
              static_cast<long long>(rlist.tuned_fusion_bytes),
              rlist.tuned_cycle_ms, rlist.tuned_cache_enabled ? 1 : 0);
    }

    // --- reconstruct cached responses, update cache, fuse, execute ---
    std::vector<Response> exec;
    exec.reserve(rlist.cached_slots.size() + rlist.responses.size());
    for (uint32_t slot : rlist.cached_slots) {
      exec.push_back(cache_->Get(slot));
      cache_->Touch(slot);
    }
    for (auto& resp : rlist.responses) {
      if (cache_enabled_ && !rlist.cache_frozen &&
          resp.response_type != ResponseType::ERROR &&
          resp.response_type != ResponseType::JOIN &&
          resp.response_type != ResponseType::BARRIER) {
        std::lock_guard<std::mutex> l(mu_);
        auto it = table_.find(resp.tensor_names[0]);
        if (it != table_.end()) cache_->Put(it->second->req, resp);
      }
      exec.push_back(std::move(resp));
    }
    FuseResponseList(&exec, fusion_bytes_);

    for (const auto& resp : exec) PerformOperation(resp);

    return !rlist.shutdown;
  }

  // ------------------------------------------------------------- execution

  void PerformOperation(const Response& resp) {
    // reference PerformOperation (operations.cc:232-309).
    if (resp.response_type == ResponseType::JOIN) {
      std::vector<int64_t> hs;
      {
        std::lock_guard<std::mutex> l(mu_);
        hs.swap(join_handles_);
        joined_ = false;
      }
      for (int64_t h : hs) Complete(h, nullptr, 0, {});
      return;
    }

    std::vector<std::shared_ptr<Entry>> entries(resp.tensor_names.size());
    {
      std::lock_guard<std::mutex> l(mu_);
      for (size_t i = 0; i < resp.tensor_names.size(); i++) {
        auto it = table_.find(resp.tensor_names[i]);
        if (it != table_.end()) {
          entries[i] = it->second;
          table_.erase(it);
        }
      }
    }

    if (resp.response_type == ResponseType::ERROR) {
      for (auto& e : entries)
        if (e) Fail(e->handle, resp.error_message);
      return;
    }

    std::string names = resp.tensor_names[0];
    if (resp.tensor_names.size() > 1)
      names += "+" + std::to_string(resp.tensor_names.size() - 1);
    const char* opname =
        resp.response_type == ResponseType::ALLREDUCE       ? "ALLREDUCE"
        : resp.response_type == ResponseType::ALLGATHER     ? "ALLGATHER"
        : resp.response_type == ResponseType::BROADCAST     ? "BROADCAST"
        : resp.response_type == ResponseType::ADASUM        ? "ADASUM"
        : resp.response_type == ResponseType::ALLTOALL      ? "ALLTOALL"
        : resp.response_type == ResponseType::REDUCESCATTER ? "REDUCESCATTER"
                                                            : "BARRIER";
    timeline_.Start(names, opname);
    Status s;
    switch (resp.response_type) {
      case ResponseType::ALLREDUCE:
      case ResponseType::ADASUM:
        s = ExecAllreduce(resp, entries);
        break;
      case ResponseType::ALLGATHER:
        s = ExecAllgather(resp, entries);
        break;
      case ResponseType::BROADCAST:
        s = ExecBroadcast(resp, entries);
        break;
      case ResponseType::ALLTOALL:
        s = ExecAlltoall(resp, entries);
        break;
      case ResponseType::REDUCESCATTER:
        s = ExecReducescatter(resp, entries);
        break;
      case ResponseType::BARRIER:
        if (entries[0]) Complete(entries[0]->handle, nullptr, 0, {});
        break;
      default:
        break;
    }
    timeline_.End(names, opname);
    if (!s.ok()) {
      for (auto& e : entries)
        if (e) Fail(e->handle, s.reason);
    }
  }

  Status ExecAllreduce(const Response& resp,
                       const std::vector<std::shared_ptr<Entry>>& entries) {
    size_t elem = DataTypeSize(resp.dtype);
    // Fusion buffer assembly (reference MemcpyInFusionBuffer,
    // collective_operations.cc:159-210).  A joined/absent rank contributes
    // zeros of the negotiated shape (reference tensor_queue.h:39-41).
    int64_t total = 0;
    std::vector<int64_t> counts(entries.size());
    for (size_t i = 0; i < entries.size(); i++) {
      int64_t n = 1;
      for (auto d : resp.shapes[i]) n *= d;
      counts[i] = n;
      total += n;
    }
    std::string names = resp.tensor_names[0];
    timeline_.ActivityStart(names, "MEMCPY_IN_FUSION_BUFFER");
    std::vector<uint8_t> fused(static_cast<size_t>(total) * elem, 0);
    int64_t off = 0;
    for (size_t i = 0; i < entries.size(); i++) {
      if (entries[i])
        std::memcpy(fused.data() + off * elem, entries[i]->data.data(),
                    static_cast<size_t>(counts[i]) * elem);
      off += counts[i];
    }
    timeline_.ActivityEnd(names, "MEMCPY_IN_FUSION_BUFFER");

    if (resp.prescale != 1.0)
      ScaleInPlace(resp.dtype, fused.data(), static_cast<size_t>(total),
                   resp.prescale);

    Status s;
    if (resp.response_type == ResponseType::ADASUM ||
        resp.reduce_op == ReduceOp::ADASUM) {
      timeline_.ActivityStart(names, "ADASUM_VHDD");
      s = AdasumAllreduce(&mesh_, fused.data(), total, resp.dtype);
      timeline_.ActivityEnd(names, "ADASUM_VHDD");
    } else {
      ReduceOp ring_op = resp.reduce_op == ReduceOp::MIN   ? ReduceOp::MIN
                         : resp.reduce_op == ReduceOp::MAX ? ReduceOp::MAX
                                                           : ReduceOp::SUM;
      timeline_.ActivityStart(names, "RING_ALLREDUCE");
      s = RingAllreduce(&mesh_, fused.data(), total, resp.dtype, ring_op);
      timeline_.ActivityEnd(names, "RING_ALLREDUCE");
      if (s.ok() && resp.reduce_op == ReduceOp::AVERAGE)
        ScaleInPlace(resp.dtype, fused.data(), static_cast<size_t>(total),
                     1.0 / size_);
    }
    if (!s.ok()) return s;
    if (resp.postscale != 1.0)
      ScaleInPlace(resp.dtype, fused.data(), static_cast<size_t>(total),
                   resp.postscale);

    perf_bytes_ += static_cast<long long>(total) * elem;
    timeline_.ActivityStart(names, "MEMCPY_OUT_FUSION_BUFFER");
    off = 0;
    for (size_t i = 0; i < entries.size(); i++) {
      if (entries[i]) {
        Complete(entries[i]->handle, fused.data() + off * elem,
                 static_cast<size_t>(counts[i]) * elem, resp.shapes[i]);
      }
      off += counts[i];
    }
    timeline_.ActivityEnd(names, "MEMCPY_OUT_FUSION_BUFFER");
    return Status::OK();
  }

  Status ExecAllgather(const Response& resp,
                       const std::vector<std::shared_ptr<Entry>>& entries) {
    size_t elem = DataTypeSize(resp.dtype);
    const auto& shape = resp.shapes[0];
    int64_t row = 1;
    for (size_t i = 1; i < shape.size(); i++) row *= shape[i];
    std::vector<int64_t> counts(resp.tensor_sizes.size());
    int64_t total_rows = 0;
    for (size_t i = 0; i < counts.size(); i++) {
      counts[i] = resp.tensor_sizes[i] * row;
      total_rows += resp.tensor_sizes[i];
    }
    std::vector<uint8_t> out(static_cast<size_t>(total_rows * row) * elem);
    const void* send =
        entries[0] ? static_cast<const void*>(entries[0]->data.data())
                   : static_cast<const void*>(out.data());  // 0 elems
    Status s = RingAllgatherv(&mesh_, send, out.data(), counts, resp.dtype);
    if (!s.ok()) return s;
    perf_bytes_ += static_cast<long long>(out.size());
    if (entries[0]) {
      std::vector<int64_t> out_shape = shape;
      out_shape[0] = total_rows;
      Complete(entries[0]->handle, out.data(), out.size(), out_shape);
    }
    return Status::OK();
  }

  Status ExecBroadcast(const Response& resp,
                       const std::vector<std::shared_ptr<Entry>>& entries) {
    size_t elem = DataTypeSize(resp.dtype);
    int64_t n = 1;
    for (auto d : resp.shapes[0]) n *= d;
    std::vector<uint8_t> buf(static_cast<size_t>(n) * elem, 0);
    if (entries[0])
      std::memcpy(buf.data(), entries[0]->data.data(), buf.size());
    Status s = TreeBroadcast(&mesh_, buf.data(), n, resp.dtype,
                             resp.root_rank);
    if (!s.ok()) return s;
    perf_bytes_ += static_cast<long long>(buf.size());
    if (entries[0])
      Complete(entries[0]->handle, buf.data(), buf.size(), resp.shapes[0]);
    return Status::OK();
  }

  Status ExecAlltoall(const Response& resp,
                      const std::vector<std::shared_ptr<Entry>>& entries) {
    size_t elem = DataTypeSize(resp.dtype);
    const auto& shape = resp.shapes[0];
    int64_t n = 1;
    for (auto d : shape) n *= d;
    if (!shape.empty() && shape[0] % size_ != 0) {
      return Status::Error(
          StatusCode::INVALID_ARGUMENT,
          "alltoall dim0 (" + std::to_string(shape[0]) +
              ") must divide world size (" + std::to_string(size_) + ")");
    }
    std::vector<uint8_t> in(static_cast<size_t>(n) * elem, 0);
    std::vector<uint8_t> out(static_cast<size_t>(n) * elem, 0);
    if (entries[0]) std::memcpy(in.data(), entries[0]->data.data(), in.size());
    Status s = PairwiseAlltoall(&mesh_, in.data(), out.data(), n / size_,
                                resp.dtype);
    if (!s.ok()) return s;
    if (entries[0])
      Complete(entries[0]->handle, out.data(), out.size(), shape);
    return Status::OK();
  }

  Status ExecReducescatter(const Response& resp,
                           const std::vector<std::shared_ptr<Entry>>& entries) {
    // Sum across ranks, keep this rank's dim-0 rows; uneven splits give
    // the first (dim0 % size) ranks one extra row (the convention later
    // Horovod versions adopted for reducescatter).
    size_t elem = DataTypeSize(resp.dtype);
    const auto& shape = resp.shapes[0];
    int64_t n = 1;
    for (auto d : shape) n *= d;
    int64_t row = shape.empty() ? 1 : n / std::max<int64_t>(shape[0], 1);
    std::vector<uint8_t> buf(static_cast<size_t>(n) * elem, 0);
    if (entries[0])
      std::memcpy(buf.data(), entries[0]->data.data(), buf.size());
    if (resp.prescale != 1.0)
      ScaleInPlace(resp.dtype, buf.data(), static_cast<size_t>(n),
                   resp.prescale);
    Status s = RingAllreduce(&mesh_, buf.data(), n, resp.dtype, ReduceOp::SUM);
    if (!s.ok()) return s;
    if (resp.reduce_op == ReduceOp::AVERAGE)
      ScaleInPlace(resp.dtype, buf.data(), static_cast<size_t>(n),
                   1.0 / size_);
    if (resp.postscale != 1.0)
      ScaleInPlace(resp.dtype, buf.data(), static_cast<size_t>(n),
                   resp.postscale);
    perf_bytes_ += static_cast<long long>(buf.size());
    if (entries[0]) {
      int64_t dim0 = shape.empty() ? 1 : shape[0];
      int64_t base = dim0 / size_, rem = dim0 % size_;
      int64_t start = rank_ * base + std::min<int64_t>(rank_, rem);
      int64_t rows = base + (rank_ < rem ? 1 : 0);
      std::vector<int64_t> out_shape = shape;
      out_shape[0] = rows;
      Complete(entries[0]->handle, buf.data() + start * row * elem,
               static_cast<size_t>(rows * row) * elem, out_shape);
    }
    return Status::OK();
  }

  // ------------------------------------------------------------ completion

  void Complete(int64_t h, const void* data, size_t nbytes,
                const std::vector<int64_t>& shape) {
    {
      std::lock_guard<std::mutex> l(mu_);
      auto it = handles_.find(h);
      if (it != handles_.end()) {
        auto& hs = *it->second;
        hs.output.assign(static_cast<const uint8_t*>(data),
                         static_cast<const uint8_t*>(data) + nbytes);
        hs.out_shape = shape;
        hs.status = kOk;
      }
    }
    cv_.notify_all();
  }

  void Fail(int64_t h, const std::string& err) {
    {
      std::lock_guard<std::mutex> l(mu_);
      auto it = handles_.find(h);
      if (it != handles_.end()) {
        it->second->error = err;
        it->second->status = kError;
      }
    }
    cv_.notify_all();
  }

  void FailAll(const std::string& err) {
    std::vector<int64_t> hs;
    {
      std::lock_guard<std::mutex> l(mu_);
      done_ = true;
      for (auto& [name, e] : table_) hs.push_back(e->handle);
      table_.clear();
      pending_.clear();
      for (int64_t h : join_handles_) hs.push_back(h);
      join_handles_.clear();
    }
    for (int64_t h : hs) Fail(h, err);
    cv_.notify_all();
  }

  int rank_ = 0;
  int size_ = 1;
  // Atomic: written by the RunLoop thread when synced params apply,
  // read lock-free by the Python autotune thread via hvdtpu_get_*.
  std::atomic<int64_t> fusion_bytes_{64 * 1024 * 1024};
  std::atomic<double> cycle_ms_{5.0};
  std::atomic<bool> cache_enabled_{true};

  // Autotune plumbing: the Python ParameterManager (rank 0) reads the
  // bytes counter to score bytes/sec and pushes proposals via
  // hvdtpu_set_params; they ride the next ResponseList to every rank
  // (reference parameter_manager.cc:528 + controller.cc:33-47).
  std::atomic<long long> perf_bytes_{0};
  bool params_pending_ = false;
  int64_t params_fusion_bytes_ = 0;
  double params_cycle_ms_ = 0.0;
  bool params_cache_enabled_ = true;

 public:
  void SetParams(int64_t fusion_bytes, double cycle_ms, bool cache_enabled) {
    std::lock_guard<std::mutex> l(mu_);
    params_pending_ = true;
    params_fusion_bytes_ = fusion_bytes;
    params_cycle_ms_ = cycle_ms;
    params_cache_enabled_ = cache_enabled;
  }
  long long PerfBytes() const { return perf_bytes_.load(); }
  long long FusionBytes() const { return fusion_bytes_.load(); }
  double CycleMs() const { return cycle_ms_.load(); }

  // Fault injection (tests only): flip THIS rank's cache gate without the
  // params sync, recreating the transient divergence a tuner cache toggle
  // can cause when an enqueue straggles across the flip cycle.  Production
  // toggles must go through SetParams, which synchronizes all ranks.
  void InjectLocalCacheEnabled(bool on) { cache_enabled_.store(on); }

 private:

  TcpMesh mesh_;
  std::unique_ptr<Controller> controller_;
  std::unique_ptr<ResponseCache> cache_;
  Timeline timeline_;
  std::thread bg_;

  std::mutex mu_;
  std::condition_variable cv_;
  std::vector<std::shared_ptr<Entry>> pending_;
  std::unordered_map<std::string, std::shared_ptr<Entry>> table_;
  std::unordered_map<int64_t, std::shared_ptr<HandleState>> handles_;
  std::vector<int64_t> join_handles_;
  int64_t next_handle_ = 1;
  bool joined_ = false;
  bool shutdown_requested_ = false;
  bool done_ = false;
  bool running_ = false;
};

}  // namespace
}  // namespace hvdtpu

// ---------------------------------------------------------------- C API
// (reference operations.cc:661-799 — the surface HorovodBasics wraps with
// ctypes; handles follow torch/handle_manager.cc.)

extern "C" {

int hvdtpu_listen() { return hvdtpu::Engine::Get().Listen(); }

int hvdtpu_connect(int rank, int size, const char* addrs_csv,
                   long long fusion_bytes, double cycle_ms, int cache_capacity,
                   double stall_warn, double stall_shutdown,
                   const char* timeline_path, int timeline_mark_cycles) {
  std::vector<std::string> addrs;
  std::string cur;
  for (const char* p = addrs_csv; *p; p++) {
    if (*p == ',') {
      addrs.push_back(cur);
      cur.clear();
    } else {
      cur.push_back(*p);
    }
  }
  if (!cur.empty()) addrs.push_back(cur);
  if (static_cast<int>(addrs.size()) != size) return -2;
  return hvdtpu::Engine::Get().Connect(
      rank, size, addrs, fusion_bytes, cycle_ms, cache_capacity, stall_warn,
      stall_shutdown, timeline_path ? timeline_path : "",
      timeline_mark_cycles != 0);
}

long long hvdtpu_enqueue(int op, const char* name, const void* data,
                         const long long* shape, int ndim, int dtype,
                         int reduce_op, int root_rank, double prescale,
                         double postscale) {
  std::vector<int64_t> sh(shape, shape + ndim);
  return hvdtpu::Engine::Get().Enqueue(
      static_cast<hvdtpu::RequestType>(op), name, data, sh,
      static_cast<hvdtpu::DataType>(dtype),
      static_cast<hvdtpu::ReduceOp>(reduce_op), root_rank, prescale,
      postscale);
}

long long hvdtpu_join() { return hvdtpu::Engine::Get().Join(); }

int hvdtpu_poll(long long handle) {
  return hvdtpu::Engine::Get().Poll(handle);
}

int hvdtpu_wait(long long handle) {
  return hvdtpu::Engine::Get().Wait(handle);
}

const char* hvdtpu_error(long long handle) {
  auto hs = hvdtpu::Engine::Get().GetHandle(handle);
  // Pointer stays valid until hvdtpu_release (shared_ptr in handle table).
  return hs ? hs->error.c_str() : "unknown handle";
}

long long hvdtpu_result_nbytes(long long handle) {
  auto hs = hvdtpu::Engine::Get().GetHandle(handle);
  return hs ? static_cast<long long>(hs->output.size()) : -1;
}

int hvdtpu_result_ndim(long long handle) {
  auto hs = hvdtpu::Engine::Get().GetHandle(handle);
  return hs ? static_cast<int>(hs->out_shape.size()) : -1;
}

void hvdtpu_result_shape(long long handle, long long* out) {
  auto hs = hvdtpu::Engine::Get().GetHandle(handle);
  if (!hs) return;
  for (size_t i = 0; i < hs->out_shape.size(); i++) out[i] = hs->out_shape[i];
}

int hvdtpu_result_copy(long long handle, void* out) {
  auto hs = hvdtpu::Engine::Get().GetHandle(handle);
  if (!hs || hs->status != 1) return -1;
  std::memcpy(out, hs->output.data(), hs->output.size());
  return 0;
}

void hvdtpu_release(long long handle) {
  hvdtpu::Engine::Get().Release(handle);
}

void hvdtpu_shutdown() { hvdtpu::Engine::Get().Shutdown(); }

int hvdtpu_is_shutdown() {
  return hvdtpu::Engine::Get().IsDone() ? 1 : 0;
}

// Autotune surface (reference parameter_manager.cc scoring + param sync):
// rank 0's Python ParameterManager polls the bytes counter and pushes
// proposals; the engine ships them to all ranks on the next cycle.
void hvdtpu_set_params(long long fusion_bytes, double cycle_ms,
                       int cache_enabled) {
  hvdtpu::Engine::Get().SetParams(fusion_bytes, cycle_ms, cache_enabled != 0);
}

long long hvdtpu_perf_bytes() { return hvdtpu::Engine::Get().PerfBytes(); }

void hvdtpu_inject_local_cache_enabled(int on) {
  hvdtpu::Engine::Get().InjectLocalCacheEnabled(on != 0);
}

long long hvdtpu_get_fusion_bytes() {
  return hvdtpu::Engine::Get().FusionBytes();
}

double hvdtpu_get_cycle_ms() { return hvdtpu::Engine::Get().CycleMs(); }

}  // extern "C"
