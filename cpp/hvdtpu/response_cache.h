// LRU cache of negotiated responses — the steady-state fast path.
//
// Reference: horovod/common/response_cache.{h,cc} — after a tensor has been
// negotiated once, later cycles communicate it as a cache *slot* instead of
// a re-serialized Request; when every queued tensor is a cache hit on every
// rank, the whole negotiation payload is a handful of slot ids (the
// reference packs them as bit vectors synced with MPI_Allreduce BAND,
// response_cache.h:107-167; here they ride the normal coordinator messages
// as position lists, which equally skips request serialization).
//
// Coherence invariant (same as the reference's): every rank performs
// identical put/evict sequences because puts happen in ResponseList order,
// which the coordinator broadcast makes identical everywhere — so slot ids
// agree across ranks without any extra synchronization.
#pragma once

#include <cstdint>
#include <list>
#include <string>
#include <unordered_map>
#include <vector>

#include "wire.h"

namespace hvdtpu {

class ResponseCache {
 public:
  explicit ResponseCache(size_t capacity) : capacity_(capacity) {}

  bool enabled() const { return capacity_ > 0; }

  // Slot of `req` if this rank's cached entry matches it exactly
  // (name + type + dtype + shape + op params); -1 on miss.
  int32_t Lookup(const Request& req) const;

  // Insert (or refresh) the response negotiated for `req`; evicts LRU when
  // full.  Must be called in ResponseList order on every rank.
  void Put(const Request& req, const Response& resp);

  // The cached response in `slot` (valid until the next Put).
  const Response& Get(uint32_t slot) const { return slots_[slot].response; }

  // The request stored in `slot`, or nullptr when the slot is not live.
  // Used by the coordinator's divergence repair (see Controller): rank 0's
  // copy of the (globally coherent) cache identifies which tensor a
  // worker's slot vote refers to.
  const Request* RequestFor(uint32_t slot) const {
    return (slot < slots_.size() && slots_[slot].live)
               ? &slots_[slot].request
               : nullptr;
  }

  // Mark slot most-recently-used (call when a cached response executes).
  void Touch(uint32_t slot);

  size_t size() const { return by_name_.size(); }

 private:
  struct Slot {
    Request request;   // this rank's request params at insertion
    Response response;
    bool live = false;
    std::list<uint32_t>::iterator lru_it;
  };

  size_t capacity_;
  std::vector<Slot> slots_;
  std::vector<uint32_t> free_slots_;
  std::list<uint32_t> lru_;  // front = most recent
  std::unordered_map<std::string, uint32_t> by_name_;
};

}  // namespace hvdtpu
