// Full-mesh TCP transport for the native eager engine.
//
// Reference: horovod/common/gloo/gloo_context.cc builds a full TCP mesh via
// HTTP-KV rendezvous (gloo_context.cc:113-157).  Here the mesh is built the
// same way, but address exchange happens in Python (basics_native.py uses
// the already-running coordination service), so this class only needs to
// listen, connect, and move framed byte messages.
//
// Concurrency model follows the reference's single-owner rule
// (operations.cc:311-330): after Connect(), every socket is owned by the
// background thread exclusively — no locking on the data path.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

class TcpMesh {
 public:
  TcpMesh() = default;
  ~TcpMesh();
  TcpMesh(const TcpMesh&) = delete;
  TcpMesh& operator=(const TcpMesh&) = delete;

  // Bind + listen on an ephemeral port; returns it.  Call before address
  // exchange so the advertised port is real.
  Status Listen(int* port_out);

  // Build the full mesh: rank i initiates connections to every j < i and
  // accepts from every j > i; each inbound connection self-identifies with
  // a 4-byte rank hello.  addrs[j] = "host:port".
  Status Connect(int rank, int size, const std::vector<std::string>& addrs);

  // Framed message passing: [u64 length][payload].
  Status SendMsg(int to, const uint8_t* data, size_t len);
  Status RecvMsg(int from, std::vector<uint8_t>* out);

  // Poll-multiplexed receive of ONE framed message from EACH listed peer,
  // consuming bytes from whichever socket is ready (reference contrast:
  // MPIController gathers all workers' requests in one MPI_Gatherv,
  // mpi_controller.cc:107-150 — a serial per-worker blocking recv loop
  // would make the coordinator's cycle time linear in world size when any
  // worker is slow).  out->at(peer) receives that peer's payload; entries
  // for ranks not in `peers` are left untouched.
  Status RecvMsgMulti(const std::vector<int>& peers,
                      std::vector<std::vector<uint8_t>>* out);

  // Raw byte transfer (data plane; no frame header).
  Status SendBytes(int to, const void* data, size_t len);
  Status RecvBytes(int from, void* data, size_t len);

  // Bidirectional exchange with (possibly distinct) peers, interleaved via
  // poll() so large transfers can't deadlock on full kernel buffers.
  Status SendRecv(int to, const void* sendbuf, size_t sendlen, int from,
                  void* recvbuf, size_t recvlen);

  void Close();

  int rank() const { return rank_; }
  int size() const { return size_; }

 private:
  Status SendAll(int fd, const void* data, size_t len);
  Status RecvAll(int fd, void* data, size_t len);

  int rank_ = 0;
  int size_ = 1;
  int listen_fd_ = -1;
  std::vector<int> fds_;  // fds_[peer] = connected socket, -1 for self
};

}  // namespace hvdtpu
