#include "dtype_math.h"

#include <algorithm>
#include <cmath>
#include <cstring>

namespace hvdtpu {

float Bf16ToF32(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

uint16_t F32ToBf16(float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, 4);
  // Round to nearest even (the TPU's own bf16 rounding).
  uint32_t lsb = (bits >> 16) & 1;
  bits += 0x7FFF + lsb;
  return static_cast<uint16_t>(bits >> 16);
}

float F16ToF32(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t mant = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {  // subnormal: normalize
      int shift = 0;
      while (!(mant & 0x400)) {
        mant <<= 1;
        shift++;
      }
      mant &= 0x3FF;
      bits = sign | ((127 - 15 - shift) << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

uint16_t F32ToF16(float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xFF) - 127 + 15;
  uint32_t mant = bits & 0x7FFFFF;
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7C00u);  // inf/overflow
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t mid = 1u << (shift - 1);
    if (rem > mid || (rem == mid && (half & 1))) half++;
    return static_cast<uint16_t>(sign | half);
  }
  uint32_t half = (static_cast<uint32_t>(exp) << 10) | (mant >> 13);
  uint32_t rem = mant & 0x1FFF;
  if (rem > 0x1000 || (rem == 0x1000 && (half & 1))) half++;
  return static_cast<uint16_t>(sign | half);
}

namespace {

template <typename T>
void ReduceTyped(ReduceOp op, T* acc, const T* in, size_t n) {
  switch (op) {
    case ReduceOp::MIN:
      for (size_t i = 0; i < n; i++) acc[i] = std::min(acc[i], in[i]);
      break;
    case ReduceOp::MAX:
      for (size_t i = 0; i < n; i++) acc[i] = std::max(acc[i], in[i]);
      break;
    default:  // SUM / AVERAGE (divide applied later) / ADASUM handled upstream
      for (size_t i = 0; i < n; i++) acc[i] += in[i];
      break;
  }
}

template <uint16_t (*ToBits)(float), float (*FromBits)(uint16_t)>
void ReduceHalf(ReduceOp op, uint16_t* acc, const uint16_t* in, size_t n) {
  for (size_t i = 0; i < n; i++) {
    float a = FromBits(acc[i]), b = FromBits(in[i]);
    float r;
    switch (op) {
      case ReduceOp::MIN: r = std::min(a, b); break;
      case ReduceOp::MAX: r = std::max(a, b); break;
      default: r = a + b; break;
    }
    acc[i] = ToBits(r);
  }
}

void ReduceBool(ReduceOp op, uint8_t* acc, const uint8_t* in, size_t n) {
  // Sum on bool = logical OR, min = AND, max = OR (MPI's C_BOOL behavior).
  switch (op) {
    case ReduceOp::MIN:
      for (size_t i = 0; i < n; i++) acc[i] = acc[i] && in[i];
      break;
    default:
      for (size_t i = 0; i < n; i++) acc[i] = acc[i] || in[i];
      break;
  }
}

}  // namespace

void ReduceInto(DataType t, ReduceOp op, void* acc, const void* in,
                size_t count) {
  switch (t) {
    case DataType::UINT8:
      ReduceTyped(op, static_cast<uint8_t*>(acc),
                  static_cast<const uint8_t*>(in), count);
      break;
    case DataType::INT8:
      ReduceTyped(op, static_cast<int8_t*>(acc),
                  static_cast<const int8_t*>(in), count);
      break;
    case DataType::INT32:
      ReduceTyped(op, static_cast<int32_t*>(acc),
                  static_cast<const int32_t*>(in), count);
      break;
    case DataType::INT64:
      ReduceTyped(op, static_cast<int64_t*>(acc),
                  static_cast<const int64_t*>(in), count);
      break;
    case DataType::FLOAT16:
      ReduceHalf<F32ToF16, F16ToF32>(op, static_cast<uint16_t*>(acc),
                                     static_cast<const uint16_t*>(in), count);
      break;
    case DataType::BFLOAT16:
      ReduceHalf<F32ToBf16, Bf16ToF32>(op, static_cast<uint16_t*>(acc),
                                       static_cast<const uint16_t*>(in), count);
      break;
    case DataType::FLOAT32:
      ReduceTyped(op, static_cast<float*>(acc),
                  static_cast<const float*>(in), count);
      break;
    case DataType::FLOAT64:
      ReduceTyped(op, static_cast<double*>(acc),
                  static_cast<const double*>(in), count);
      break;
    case DataType::BOOL:
      ReduceBool(op, static_cast<uint8_t*>(acc),
                 static_cast<const uint8_t*>(in), count);
      break;
  }
}

void ScaleInPlace(DataType t, void* buf, size_t count, double factor) {
  switch (t) {
    case DataType::UINT8: {
      auto* p = static_cast<uint8_t*>(buf);
      for (size_t i = 0; i < count; i++)
        p[i] = static_cast<uint8_t>(p[i] * factor);
      break;
    }
    case DataType::INT8: {
      auto* p = static_cast<int8_t*>(buf);
      for (size_t i = 0; i < count; i++)
        p[i] = static_cast<int8_t>(p[i] * factor);
      break;
    }
    case DataType::INT32: {
      auto* p = static_cast<int32_t*>(buf);
      for (size_t i = 0; i < count; i++)
        p[i] = static_cast<int32_t>(p[i] * factor);
      break;
    }
    case DataType::INT64: {
      auto* p = static_cast<int64_t*>(buf);
      for (size_t i = 0; i < count; i++)
        p[i] = static_cast<int64_t>(p[i] * factor);
      break;
    }
    case DataType::FLOAT16: {
      auto* p = static_cast<uint16_t*>(buf);
      for (size_t i = 0; i < count; i++)
        p[i] = F32ToF16(static_cast<float>(F16ToF32(p[i]) * factor));
      break;
    }
    case DataType::BFLOAT16: {
      auto* p = static_cast<uint16_t*>(buf);
      for (size_t i = 0; i < count; i++)
        p[i] = F32ToBf16(static_cast<float>(Bf16ToF32(p[i]) * factor));
      break;
    }
    case DataType::FLOAT32: {
      auto* p = static_cast<float*>(buf);
      for (size_t i = 0; i < count; i++)
        p[i] = static_cast<float>(p[i] * factor);
      break;
    }
    case DataType::FLOAT64: {
      auto* p = static_cast<double*>(buf);
      for (size_t i = 0; i < count; i++) p[i] *= factor;
      break;
    }
    case DataType::BOOL:
      break;  // scaling bools is meaningless; Average on bool stays OR
  }
}

void ToDouble(DataType t, const void* in, double* out, size_t count) {
  switch (t) {
    case DataType::UINT8: {
      auto* p = static_cast<const uint8_t*>(in);
      for (size_t i = 0; i < count; i++) out[i] = p[i];
      break;
    }
    case DataType::INT8: {
      auto* p = static_cast<const int8_t*>(in);
      for (size_t i = 0; i < count; i++) out[i] = p[i];
      break;
    }
    case DataType::INT32: {
      auto* p = static_cast<const int32_t*>(in);
      for (size_t i = 0; i < count; i++) out[i] = p[i];
      break;
    }
    case DataType::INT64: {
      auto* p = static_cast<const int64_t*>(in);
      for (size_t i = 0; i < count; i++) out[i] = static_cast<double>(p[i]);
      break;
    }
    case DataType::FLOAT16: {
      auto* p = static_cast<const uint16_t*>(in);
      for (size_t i = 0; i < count; i++) out[i] = F16ToF32(p[i]);
      break;
    }
    case DataType::BFLOAT16: {
      auto* p = static_cast<const uint16_t*>(in);
      for (size_t i = 0; i < count; i++) out[i] = Bf16ToF32(p[i]);
      break;
    }
    case DataType::FLOAT32: {
      auto* p = static_cast<const float*>(in);
      for (size_t i = 0; i < count; i++) out[i] = p[i];
      break;
    }
    case DataType::FLOAT64:
      std::memcpy(out, in, count * sizeof(double));
      break;
    case DataType::BOOL: {
      auto* p = static_cast<const uint8_t*>(in);
      for (size_t i = 0; i < count; i++) out[i] = p[i] ? 1.0 : 0.0;
      break;
    }
  }
}

void FromDouble(DataType t, const double* in, void* out, size_t count) {
  switch (t) {
    case DataType::UINT8: {
      auto* p = static_cast<uint8_t*>(out);
      for (size_t i = 0; i < count; i++) p[i] = static_cast<uint8_t>(in[i]);
      break;
    }
    case DataType::INT8: {
      auto* p = static_cast<int8_t*>(out);
      for (size_t i = 0; i < count; i++) p[i] = static_cast<int8_t>(in[i]);
      break;
    }
    case DataType::INT32: {
      auto* p = static_cast<int32_t*>(out);
      for (size_t i = 0; i < count; i++) p[i] = static_cast<int32_t>(in[i]);
      break;
    }
    case DataType::INT64: {
      auto* p = static_cast<int64_t*>(out);
      for (size_t i = 0; i < count; i++) p[i] = static_cast<int64_t>(in[i]);
      break;
    }
    case DataType::FLOAT16: {
      auto* p = static_cast<uint16_t*>(out);
      for (size_t i = 0; i < count; i++)
        p[i] = F32ToF16(static_cast<float>(in[i]));
      break;
    }
    case DataType::BFLOAT16: {
      auto* p = static_cast<uint16_t*>(out);
      for (size_t i = 0; i < count; i++)
        p[i] = F32ToBf16(static_cast<float>(in[i]));
      break;
    }
    case DataType::FLOAT32: {
      auto* p = static_cast<float*>(out);
      for (size_t i = 0; i < count; i++) p[i] = static_cast<float>(in[i]);
      break;
    }
    case DataType::FLOAT64:
      std::memcpy(out, in, count * sizeof(double));
      break;
    case DataType::BOOL: {
      auto* p = static_cast<uint8_t*>(out);
      for (size_t i = 0; i < count; i++) p[i] = in[i] != 0.0 ? 1 : 0;
      break;
    }
  }
}

}  // namespace hvdtpu
