#include "response_cache.h"

namespace hvdtpu {

namespace {

bool SameParams(const Request& a, const Request& b) {
  return a.request_type == b.request_type && a.dtype == b.dtype &&
         a.shape == b.shape && a.reduce_op == b.reduce_op &&
         a.root_rank == b.root_rank && a.prescale == b.prescale &&
         a.postscale == b.postscale;
}

}  // namespace

int32_t ResponseCache::Lookup(const Request& req) const {
  auto it = by_name_.find(req.tensor_name);
  if (it == by_name_.end()) return -1;
  const Slot& s = slots_[it->second];
  if (!SameParams(s.request, req)) return -1;
  return static_cast<int32_t>(it->second);
}

void ResponseCache::Put(const Request& req, const Response& resp) {
  if (!enabled()) return;
  auto it = by_name_.find(req.tensor_name);
  if (it != by_name_.end()) {  // refresh in place (params may have changed)
    Slot& s = slots_[it->second];
    s.request = req;
    s.response = resp;
    Touch(it->second);
    return;
  }
  uint32_t slot;
  if (!free_slots_.empty()) {
    slot = free_slots_.back();
    free_slots_.pop_back();
  } else if (slots_.size() < capacity_) {
    slot = static_cast<uint32_t>(slots_.size());
    slots_.emplace_back();
  } else {  // evict LRU — deterministic across ranks (identical sequences)
    slot = lru_.back();
    lru_.pop_back();
    by_name_.erase(slots_[slot].request.tensor_name);
    slots_[slot].live = false;
  }
  Slot& s = slots_[slot];
  s.request = req;
  s.response = resp;
  s.live = true;
  lru_.push_front(slot);
  s.lru_it = lru_.begin();
  by_name_[req.tensor_name] = slot;
}

void ResponseCache::Touch(uint32_t slot) {
  Slot& s = slots_[slot];
  if (!s.live) return;
  lru_.erase(s.lru_it);
  lru_.push_front(slot);
  s.lru_it = lru_.begin();
}


}  // namespace hvdtpu
