// Chrome-tracing timeline with a dedicated writer thread.
//
// Reference: horovod/common/timeline.{h,cc} — rank 0 writes
// chrome://tracing JSON; events are produced on the background thread and
// drained by a writer thread through a queue (the reference uses a boost
// lockfree SPSC queue, timeline.h:68-70; a mutex+cv deque is equivalent
// here — the producer is a single thread either way).  Event vocabulary
// follows common.h:31-59: NEGOTIATE_<OP>, <OP>, CYCLE_START, and per-op
// activities.  Enabled via HVDTPU_TIMELINE=<path> on rank 0.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <thread>

namespace hvdtpu {

class Timeline {
 public:
  // path empty => disabled (all emit calls are no-ops).
  void Initialize(const std::string& path, int rank, bool mark_cycles);
  void Shutdown();
  ~Timeline() { Shutdown(); }

  bool enabled() const { return enabled_; }

  // Negotiation lifecycle (reference timeline.h:77 state machine).
  void NegotiateStart(const std::string& name, const std::string& op);
  void NegotiateRankReady(const std::string& name, int rank);
  void NegotiateEnd(const std::string& name, const std::string& op);
  // Top-level op execution span.
  void Start(const std::string& name, const std::string& op);
  void End(const std::string& name, const std::string& op);
  // Activity within an op (e.g. MEMCPY_IN_FUSION_BUFFER, RING_ALLREDUCE).
  void ActivityStart(const std::string& name, const std::string& activity);
  void ActivityEnd(const std::string& name, const std::string& activity);
  void MarkCycle();

 private:
  void Emit(char ph, const std::string& name, const std::string& cat,
            const std::string& args_json);
  void WriterLoop();

  bool enabled_ = false;
  bool mark_cycles_ = false;
  int rank_ = 0;
  int64_t start_us_ = 0;
  std::FILE* file_ = nullptr;
  bool first_event_ = true;

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  bool stop_ = false;
  std::thread writer_;
};

}  // namespace hvdtpu
