#include "timeline.h"

#include <chrono>
#include <cstdio>

namespace hvdtpu {

namespace {

int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Minimal JSON string escape (names come from user tensor names).
std::string Escape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    if (c == '"' || c == '\\') {
      out.push_back('\\');
      out.push_back(c);
    } else if (static_cast<unsigned char>(c) < 0x20) {
      char buf[8];
      std::snprintf(buf, sizeof(buf), "\\u%04x", c);
      out += buf;
    } else {
      out.push_back(c);
    }
  }
  return out;
}

}  // namespace

void Timeline::Initialize(const std::string& path, int rank,
                          bool mark_cycles) {
  if (path.empty()) return;
  file_ = std::fopen(path.c_str(), "w");
  if (!file_) return;
  std::fputs("[\n", file_);
  rank_ = rank;
  mark_cycles_ = mark_cycles;
  start_us_ = NowUs();
  enabled_ = true;
  writer_ = std::thread(&Timeline::WriterLoop, this);
}

void Timeline::Shutdown() {
  if (!enabled_) return;
  {
    std::lock_guard<std::mutex> l(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  if (writer_.joinable()) writer_.join();
  std::fputs("\n]\n", file_);
  std::fclose(file_);
  file_ = nullptr;
  enabled_ = false;
}

void Timeline::Emit(char ph, const std::string& name, const std::string& cat,
                    const std::string& args_json) {
  if (!enabled_) return;
  char buf[512];
  int n = std::snprintf(
      buf, sizeof(buf),
      "{\"ph\":\"%c\",\"name\":\"%s\",\"cat\":\"%s\",\"ts\":%lld,"
      "\"pid\":%d,\"tid\":0%s%s}",
      ph, Escape(name).c_str(), Escape(cat).c_str(),
      static_cast<long long>(NowUs() - start_us_), rank_,
      args_json.empty() ? "" : ",", args_json.c_str());
  if (n <= 0) return;
  {
    std::lock_guard<std::mutex> l(mu_);
    queue_.emplace_back(buf, static_cast<size_t>(n));
  }
  cv_.notify_one();
}

void Timeline::WriterLoop() {
  std::unique_lock<std::mutex> l(mu_);
  while (!stop_ || !queue_.empty()) {
    cv_.wait(l, [&] { return stop_ || !queue_.empty(); });
    while (!queue_.empty()) {
      std::string ev = std::move(queue_.front());
      queue_.pop_front();
      l.unlock();
      if (!first_event_) std::fputs(",\n", file_);
      first_event_ = false;
      std::fputs(ev.c_str(), file_);
      l.lock();
    }
    std::fflush(file_);
  }
}

void Timeline::NegotiateStart(const std::string& name, const std::string& op) {
  Emit('B', name, "NEGOTIATE_" + op, "");
}

void Timeline::NegotiateRankReady(const std::string& name, int rank) {
  Emit('i', name, "RANK_READY",
       "\"args\":{\"rank\":" + std::to_string(rank) + "}");
}

void Timeline::NegotiateEnd(const std::string& name, const std::string& op) {
  Emit('E', name, "NEGOTIATE_" + op, "");
}

void Timeline::Start(const std::string& name, const std::string& op) {
  Emit('B', name, op, "");
}

void Timeline::End(const std::string& name, const std::string& op) {
  Emit('E', name, op, "");
}

void Timeline::ActivityStart(const std::string& name,
                             const std::string& activity) {
  Emit('B', name, activity, "");
}

void Timeline::ActivityEnd(const std::string& name,
                           const std::string& activity) {
  Emit('E', name, activity, "");
}

void Timeline::MarkCycle() {
  if (mark_cycles_) Emit('i', "CYCLE_START", "CYCLE", "");
}

}  // namespace hvdtpu
