#include "ops.h"

#include <algorithm>
#include <cmath>
#include <cstring>

#include "dtype_math.h"

namespace hvdtpu {

namespace {

// Balanced chunk boundary: chunk i of `count` elements across `n` chunks.
inline int64_t ChunkOff(int64_t count, int n, int i) {
  return count * i / n;
}

}  // namespace

Status RingAllreduce(TcpMesh* mesh, void* buf, int64_t count, DataType dtype,
                     ReduceOp op) {
  int n = mesh->size(), rank = mesh->rank();
  if (n == 1 || count == 0) return Status::OK();
  size_t elem = DataTypeSize(dtype);
  uint8_t* b = static_cast<uint8_t*>(buf);
  int next = (rank + 1) % n, prev = (rank - 1 + n) % n;

  int64_t max_chunk = 0;
  for (int i = 0; i < n; i++)
    max_chunk = std::max(max_chunk, ChunkOff(count, n, i + 1) - ChunkOff(count, n, i));
  std::vector<uint8_t> scratch(static_cast<size_t>(max_chunk) * elem);

  // Reduce-scatter: after n-1 steps, chunk (rank+1)%n holds the full sum.
  for (int step = 0; step < n - 1; step++) {
    int send_c = (rank - step + n) % n;
    int recv_c = (rank - step - 1 + n) % n;
    int64_t so = ChunkOff(count, n, send_c), sl = ChunkOff(count, n, send_c + 1) - so;
    int64_t ro = ChunkOff(count, n, recv_c), rl = ChunkOff(count, n, recv_c + 1) - ro;
    Status s = mesh->SendRecv(next, b + so * elem, static_cast<size_t>(sl) * elem,
                              prev, scratch.data(), static_cast<size_t>(rl) * elem);
    if (!s.ok()) return s;
    ReduceInto(dtype, op, b + ro * elem, scratch.data(), static_cast<size_t>(rl));
  }
  // Ring allgather of the reduced chunks.
  for (int step = 0; step < n - 1; step++) {
    int send_c = (rank + 1 - step + n) % n;
    int recv_c = (rank - step + n) % n;
    int64_t so = ChunkOff(count, n, send_c), sl = ChunkOff(count, n, send_c + 1) - so;
    int64_t ro = ChunkOff(count, n, recv_c), rl = ChunkOff(count, n, recv_c + 1) - ro;
    Status s = mesh->SendRecv(next, b + so * elem, static_cast<size_t>(sl) * elem,
                              prev, b + ro * elem, static_cast<size_t>(rl) * elem);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status RingAllgatherv(TcpMesh* mesh, const void* send, void* recv,
                      const std::vector<int64_t>& counts, DataType dtype) {
  int n = mesh->size(), rank = mesh->rank();
  size_t elem = DataTypeSize(dtype);
  std::vector<int64_t> offs(n + 1, 0);
  for (int i = 0; i < n; i++) offs[i + 1] = offs[i] + counts[i];
  uint8_t* r = static_cast<uint8_t*>(recv);
  std::memcpy(r + offs[rank] * elem, send,
              static_cast<size_t>(counts[rank]) * elem);
  if (n == 1) return Status::OK();
  int next = (rank + 1) % n, prev = (rank - 1 + n) % n;
  for (int step = 0; step < n - 1; step++) {
    int send_b = (rank - step + n) % n;
    int recv_b = (rank - step - 1 + n) % n;
    Status s = mesh->SendRecv(
        next, r + offs[send_b] * elem, static_cast<size_t>(counts[send_b]) * elem,
        prev, r + offs[recv_b] * elem, static_cast<size_t>(counts[recv_b]) * elem);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status TreeBroadcast(TcpMesh* mesh, void* buf, int64_t count, DataType dtype,
                     int root) {
  int n = mesh->size(), rank = mesh->rank();
  if (n == 1 || count == 0) return Status::OK();
  size_t len = static_cast<size_t>(count) * DataTypeSize(dtype);
  int vr = (rank - root + n) % n;
  for (int mask = 1; mask < n; mask <<= 1) {
    if (vr < mask) {
      int peer_vr = vr + mask;
      if (peer_vr < n) {
        Status s = mesh->SendBytes((peer_vr + root) % n, buf, len);
        if (!s.ok()) return s;
      }
    } else if (vr < 2 * mask) {
      Status s = mesh->RecvBytes((vr - mask + root) % n, buf, len);
      if (!s.ok()) return s;
    }
  }
  return Status::OK();
}

Status PairwiseAlltoall(TcpMesh* mesh, const void* send, void* recv,
                        int64_t chunk_elems, DataType dtype) {
  int n = mesh->size(), rank = mesh->rank();
  size_t chunk = static_cast<size_t>(chunk_elems) * DataTypeSize(dtype);
  const uint8_t* s = static_cast<const uint8_t*>(send);
  uint8_t* r = static_cast<uint8_t*>(recv);
  std::memcpy(r + rank * chunk, s + rank * chunk, chunk);
  for (int i = 1; i < n; i++) {
    int to = (rank + i) % n, from = (rank - i + n) % n;
    Status st = mesh->SendRecv(to, s + to * chunk, chunk, from,
                               r + from * chunk, chunk);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

namespace {

// Wire codecs: the Adasum buffer travels point-to-point in its OWN dtype
// (bf16/f16 at 2 B/elt — half the f32 bytes, a quarter of the old f64
// wire), while dots/norms/coefficients accumulate in double, matching the
// reference's fp16 kernels that widen only in registers
// (adasum.h:101-120 DispatchComputeDotAndNormSqrds, ComputeDotAndNormSqrdsfp16).
struct F32Codec {
  using wire_t = float;
  static double Load(wire_t v) { return v; }
  static wire_t Store(double v) { return static_cast<float>(v); }
};
struct F64Codec {
  using wire_t = double;
  static double Load(wire_t v) { return v; }
  static wire_t Store(double v) { return v; }
};
struct Bf16Codec {
  using wire_t = uint16_t;
  static double Load(wire_t v) { return Bf16ToF32(v); }
  static wire_t Store(double v) { return F32ToBf16(static_cast<float>(v)); }
};
struct F16Codec {
  using wire_t = uint16_t;
  static double Load(wire_t v) { return F16ToF32(v); }
  static wire_t Store(double v) { return F32ToF16(static_cast<float>(v)); }
};

// Pairwise full-vector combine, w as "A" and other as "B":
// w = coefA * w + coefB * other, inner products in double (reference
// adasum.h:239-263).
template <typename C>
void PairCombine(typename C::wire_t* w, const typename C::wire_t* other,
                 int64_t count) {
  double dot = 0, na2 = 0, nb2 = 0;
  for (int64_t i = 0; i < count; i++) {
    double a = C::Load(w[i]);
    double b = C::Load(other[i]);
    dot += a * b;
    na2 += a * a;
    nb2 += b * b;
  }
  double ca = 1.0 - dot / (2.0 * std::max(na2, 1e-30));
  double cb = 1.0 - dot / (2.0 * std::max(nb2, 1e-30));
  for (int64_t i = 0; i < count; i++) {
    w[i] = C::Store(ca * C::Load(w[i]) + cb * C::Load(other[i]));
  }
}

// VHDD over the power-of-2 group {0..p-1} with a fold-in pre/post phase
// for the extra ranks {p..n-1} (the standard VHDD extension; replaces the
// old gather-to-rank-0 tree, which funneled all rows through one host).
//
// Grouping (mirrored by the Python engine's _numpy_adasum_rows so both
// engines agree on non-power-of-2 worlds):
//   pre:  extra rank p+j sends its vector to rank j, which pair-combines.
//   core: VHDD (reference adasum.h:167-299) over ranks 0..p-1.
//   post: rank j sends the finished vector back to extra p+j.
template <typename C>
Status AdasumImpl(TcpMesh* mesh, typename C::wire_t* w, int64_t count) {
  using W = typename C::wire_t;
  int n = mesh->size(), rank = mesh->rank();
  int p = 1;
  while (p * 2 <= n) p *= 2;
  int extras = n - p;
  size_t nbytes = static_cast<size_t>(count) * sizeof(W);

  if (rank >= p) {  // extra: fold in, then receive the final result
    int partner = rank - p;
    Status s = mesh->SendBytes(partner, w, nbytes);
    if (!s.ok()) return s;
    return mesh->RecvBytes(partner, w, nbytes);
  }
  std::vector<W> other;
  if (rank < extras) {  // fold-in target: absorb the extra's contribution
    other.resize(static_cast<size_t>(count));
    Status s = mesh->RecvBytes(p + rank, other.data(), nbytes);
    if (!s.ok()) return s;
    PairCombine<C>(w, other.data(), count);
  }

  // --- VHDD halving phase over the p-group ---
  int64_t start = 0, len = count;
  std::vector<std::pair<int64_t, int64_t>> seg_stack;
  for (int distance = 1; distance < p; distance <<= 1) {
    int partner = rank ^ distance;
    seg_stack.emplace_back(start, len);
    int64_t h = len / 2;
    int64_t my_start, my_len, send_off, send_len;
    if (rank < partner) {  // keep first half, hand off second
      my_start = start;
      my_len = h;
      send_off = start + h;
      send_len = len - h;
    } else {
      my_start = start + h;
      my_len = len - h;
      send_off = start;
      send_len = h;
    }
    other.resize(static_cast<size_t>(my_len));
    Status s = mesh->SendRecv(partner, w + send_off,
                              static_cast<size_t>(send_len) * sizeof(W),
                              partner, other.data(),
                              static_cast<size_t>(my_len) * sizeof(W));
    if (!s.ok()) return s;

    // Partial inner products on my piece, oriented so the lower block's
    // subtree vector is "A" group-wide (reference adasum.h reorients
    // before SumAllreduceWithComm).
    double dot = 0, mine2 = 0, theirs2 = 0;
    for (int64_t i = 0; i < my_len; i++) {
      double a = C::Load(w[my_start + i]);
      double b = C::Load(other[static_cast<size_t>(i)]);
      dot += a * b;
      mine2 += a * a;
      theirs2 += b * b;
    }
    bool lower = (rank & distance) == 0;
    double triple[3] = {lower ? mine2 : theirs2, lower ? theirs2 : mine2,
                        dot};
    // Recursive-doubling sum across the 2*distance block.
    for (int bit = 1; bit < 2 * distance; bit <<= 1) {
      int q = rank ^ bit;
      double in[3];
      Status st =
          mesh->SendRecv(q, triple, sizeof(triple), q, in, sizeof(in));
      if (!st.ok()) return st;
      triple[0] += in[0];
      triple[1] += in[1];
      triple[2] += in[2];
    }
    double normA = std::max(triple[0], 1e-30);
    double normB = std::max(triple[1], 1e-30);
    double full_dot = triple[2];
    double coefA = 1.0 - full_dot / (2.0 * normA);
    double coefB = 1.0 - full_dot / (2.0 * normB);
    double my_coef = lower ? coefA : coefB;
    double their_coef = lower ? coefB : coefA;
    for (int64_t i = 0; i < my_len; i++) {
      w[my_start + i] =
          C::Store(my_coef * C::Load(w[my_start + i]) +
                   their_coef * C::Load(other[static_cast<size_t>(i)]));
    }
    start = my_start;
    len = my_len;
  }

  // --- distance-doubling reassembly (mirror of the halving) ---
  for (int distance = p >> 1; distance >= 1; distance >>= 1) {
    int partner = rank ^ distance;
    auto [pstart, plen] = seg_stack.back();
    seg_stack.pop_back();
    int64_t h = plen / 2;
    int64_t their_off, their_len;
    if (rank < partner) {
      their_off = pstart + h;
      their_len = plen - h;
    } else {
      their_off = pstart;
      their_len = h;
    }
    Status s = mesh->SendRecv(partner, w + start,
                              static_cast<size_t>(len) * sizeof(W), partner,
                              w + their_off,
                              static_cast<size_t>(their_len) * sizeof(W));
    if (!s.ok()) return s;
    start = pstart;
    len = plen;
  }

  if (rank < extras) {  // hand the result back to the folded-in extra
    return mesh->SendBytes(p + rank, w, nbytes);
  }
  return Status::OK();
}

}  // namespace

Status AdasumAllreduce(TcpMesh* mesh, void* buf, int64_t count,
                       DataType dtype) {
  if (mesh->size() == 1 || count == 0) return Status::OK();
  switch (dtype) {
    case DataType::FLOAT32:
      return AdasumImpl<F32Codec>(mesh, static_cast<float*>(buf), count);
    case DataType::FLOAT64:
      return AdasumImpl<F64Codec>(mesh, static_cast<double*>(buf), count);
    case DataType::BFLOAT16:
      return AdasumImpl<Bf16Codec>(mesh, static_cast<uint16_t*>(buf), count);
    case DataType::FLOAT16:
      return AdasumImpl<F16Codec>(mesh, static_cast<uint16_t*>(buf), count);
    default: {
      // Exotic dtypes (ints): widen to a double scratch vector, run the
      // same distributed scheme, narrow back.  Correctness path only.
      std::vector<double> d(static_cast<size_t>(count));
      ToDouble(dtype, buf, d.data(), static_cast<size_t>(count));
      Status s = AdasumImpl<F64Codec>(mesh, d.data(), count);
      if (!s.ok()) return s;
      FromDouble(dtype, d.data(), buf, static_cast<size_t>(count));
      return s;
    }
  }
}

}  // namespace hvdtpu
