#include "ops.h"

#include <algorithm>
#include <cmath>
#include <cstring>

#include "dtype_math.h"

namespace hvdtpu {

namespace {

// Balanced chunk boundary: chunk i of `count` elements across `n` chunks.
inline int64_t ChunkOff(int64_t count, int n, int i) {
  return count * i / n;
}

}  // namespace

Status RingAllreduce(TcpMesh* mesh, void* buf, int64_t count, DataType dtype,
                     ReduceOp op) {
  int n = mesh->size(), rank = mesh->rank();
  if (n == 1 || count == 0) return Status::OK();
  size_t elem = DataTypeSize(dtype);
  uint8_t* b = static_cast<uint8_t*>(buf);
  int next = (rank + 1) % n, prev = (rank - 1 + n) % n;

  int64_t max_chunk = 0;
  for (int i = 0; i < n; i++)
    max_chunk = std::max(max_chunk, ChunkOff(count, n, i + 1) - ChunkOff(count, n, i));
  std::vector<uint8_t> scratch(static_cast<size_t>(max_chunk) * elem);

  // Reduce-scatter: after n-1 steps, chunk (rank+1)%n holds the full sum.
  for (int step = 0; step < n - 1; step++) {
    int send_c = (rank - step + n) % n;
    int recv_c = (rank - step - 1 + n) % n;
    int64_t so = ChunkOff(count, n, send_c), sl = ChunkOff(count, n, send_c + 1) - so;
    int64_t ro = ChunkOff(count, n, recv_c), rl = ChunkOff(count, n, recv_c + 1) - ro;
    Status s = mesh->SendRecv(next, b + so * elem, static_cast<size_t>(sl) * elem,
                              prev, scratch.data(), static_cast<size_t>(rl) * elem);
    if (!s.ok()) return s;
    ReduceInto(dtype, op, b + ro * elem, scratch.data(), static_cast<size_t>(rl));
  }
  // Ring allgather of the reduced chunks.
  for (int step = 0; step < n - 1; step++) {
    int send_c = (rank + 1 - step + n) % n;
    int recv_c = (rank - step + n) % n;
    int64_t so = ChunkOff(count, n, send_c), sl = ChunkOff(count, n, send_c + 1) - so;
    int64_t ro = ChunkOff(count, n, recv_c), rl = ChunkOff(count, n, recv_c + 1) - ro;
    Status s = mesh->SendRecv(next, b + so * elem, static_cast<size_t>(sl) * elem,
                              prev, b + ro * elem, static_cast<size_t>(rl) * elem);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status RingAllgatherv(TcpMesh* mesh, const void* send, void* recv,
                      const std::vector<int64_t>& counts, DataType dtype) {
  int n = mesh->size(), rank = mesh->rank();
  size_t elem = DataTypeSize(dtype);
  std::vector<int64_t> offs(n + 1, 0);
  for (int i = 0; i < n; i++) offs[i + 1] = offs[i] + counts[i];
  uint8_t* r = static_cast<uint8_t*>(recv);
  std::memcpy(r + offs[rank] * elem, send,
              static_cast<size_t>(counts[rank]) * elem);
  if (n == 1) return Status::OK();
  int next = (rank + 1) % n, prev = (rank - 1 + n) % n;
  for (int step = 0; step < n - 1; step++) {
    int send_b = (rank - step + n) % n;
    int recv_b = (rank - step - 1 + n) % n;
    Status s = mesh->SendRecv(
        next, r + offs[send_b] * elem, static_cast<size_t>(counts[send_b]) * elem,
        prev, r + offs[recv_b] * elem, static_cast<size_t>(counts[recv_b]) * elem);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status TreeBroadcast(TcpMesh* mesh, void* buf, int64_t count, DataType dtype,
                     int root) {
  int n = mesh->size(), rank = mesh->rank();
  if (n == 1 || count == 0) return Status::OK();
  size_t len = static_cast<size_t>(count) * DataTypeSize(dtype);
  int vr = (rank - root + n) % n;
  for (int mask = 1; mask < n; mask <<= 1) {
    if (vr < mask) {
      int peer_vr = vr + mask;
      if (peer_vr < n) {
        Status s = mesh->SendBytes((peer_vr + root) % n, buf, len);
        if (!s.ok()) return s;
      }
    } else if (vr < 2 * mask) {
      Status s = mesh->RecvBytes((vr - mask + root) % n, buf, len);
      if (!s.ok()) return s;
    }
  }
  return Status::OK();
}

Status PairwiseAlltoall(TcpMesh* mesh, const void* send, void* recv,
                        int64_t chunk_elems, DataType dtype) {
  int n = mesh->size(), rank = mesh->rank();
  size_t chunk = static_cast<size_t>(chunk_elems) * DataTypeSize(dtype);
  const uint8_t* s = static_cast<const uint8_t*>(send);
  uint8_t* r = static_cast<uint8_t*>(recv);
  std::memcpy(r + rank * chunk, s + rank * chunk, chunk);
  for (int i = 1; i < n; i++) {
    int to = (rank + i) % n, from = (rank - i + n) % n;
    Status st = mesh->SendRecv(to, s + to * chunk, chunk, from,
                               r + from * chunk, chunk);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

namespace {

// Sequential binary-tree adasum over gathered rows — mirrors the Python
// engine's _numpy_adasum_rows (ops/adasum.py) so both engines agree
// bit-for-bit on the non-power-of-2 path.
void TreeAdasum(std::vector<std::vector<double>>& rows, int lo, int hi,
                std::vector<double>* out) {
  if (hi - lo == 1) {
    *out = rows[lo];
    return;
  }
  int half = (hi - lo) / 2;
  std::vector<double> a, b;
  TreeAdasum(rows, lo, lo + half, &a);
  TreeAdasum(rows, lo + half, hi, &b);
  double dot = 0, na2 = 0, nb2 = 0;
  for (size_t i = 0; i < a.size(); i++) {
    dot += a[i] * b[i];
    na2 += a[i] * a[i];
    nb2 += b[i] * b[i];
  }
  double ac = 1.0 - dot / (2.0 * std::max(na2, 1e-30));
  double bc = 1.0 - dot / (2.0 * std::max(nb2, 1e-30));
  out->resize(a.size());
  for (size_t i = 0; i < a.size(); i++) (*out)[i] = ac * a[i] + bc * b[i];
}

}  // namespace

Status AdasumAllreduce(TcpMesh* mesh, void* buf, int64_t count,
                       DataType dtype) {
  int n = mesh->size(), rank = mesh->rank();
  if (n == 1) return Status::OK();
  std::vector<double> d(static_cast<size_t>(count));
  ToDouble(dtype, buf, d.data(), static_cast<size_t>(count));

  bool pow2 = (n & (n - 1)) == 0;
  if (!pow2) {
    // Gather rows to rank 0, binary-tree combine, broadcast back.
    if (rank == 0) {
      std::vector<std::vector<double>> rows(static_cast<size_t>(n));
      rows[0] = d;
      for (int r = 1; r < n; r++) {
        rows[r].resize(static_cast<size_t>(count));
        Status s = mesh->RecvBytes(r, rows[r].data(), rows[r].size() * 8);
        if (!s.ok()) return s;
      }
      std::vector<double> out;
      TreeAdasum(rows, 0, n, &out);
      d = out;
    } else {
      Status s = mesh->SendBytes(0, d.data(), d.size() * 8);
      if (!s.ok()) return s;
    }
    Status s = TreeBroadcast(mesh, d.data(), count, DataType::FLOAT64, 0);
    if (!s.ok()) return s;
    FromDouble(dtype, d.data(), buf, static_cast<size_t>(count));
    return Status::OK();
  }

  // VHDD (reference ops/adasum/adasum.h:167-299): log2(n) halving levels
  // with partner rank^distance, per-level full-vector dots via a recursive-
  // doubling sum over the 2*distance-rank block, then the mirror doubling
  // phase to reassemble the full vector.
  int64_t start = 0, len = count;
  std::vector<std::pair<int64_t, int64_t>> seg_stack;
  std::vector<double> other;
  for (int distance = 1; distance < n; distance <<= 1) {
    int partner = rank ^ distance;
    seg_stack.emplace_back(start, len);
    int64_t h = len / 2;
    int64_t my_start, my_len, send_off, send_len;
    if (rank < partner) {  // keep first half, hand off second
      my_start = start;
      my_len = h;
      send_off = start + h;
      send_len = len - h;
    } else {
      my_start = start + h;
      my_len = len - h;
      send_off = start;
      send_len = h;
    }
    other.resize(static_cast<size_t>(my_len));
    Status s = mesh->SendRecv(partner, d.data() + send_off,
                              static_cast<size_t>(send_len) * 8, partner,
                              other.data(), static_cast<size_t>(my_len) * 8);
    if (!s.ok()) return s;

    // Partial inner products on my piece.  Orient (normA, normB) by block:
    // the lower block's subtree vector is "A" group-wide, so upper-block
    // ranks swap their locals before the group sum (reference adasum.h
    // does the same reorientation before SumAllreduceWithComm).
    double dot = 0, mine2 = 0, theirs2 = 0;
    for (int64_t i = 0; i < my_len; i++) {
      double a = d[static_cast<size_t>(my_start + i)];
      double b = other[static_cast<size_t>(i)];
      dot += a * b;
      mine2 += a * a;
      theirs2 += b * b;
    }
    bool lower = (rank & distance) == 0;
    double triple[3] = {lower ? mine2 : theirs2, lower ? theirs2 : mine2, dot};
    // Recursive-doubling sum across the 2*distance block (partners rank^bit
    // all lie inside the block).
    for (int bit = 1; bit < 2 * distance; bit <<= 1) {
      int p = rank ^ bit;
      double in[3];
      Status st = mesh->SendRecv(p, triple, sizeof(triple), p, in, sizeof(in));
      if (!st.ok()) return st;
      triple[0] += in[0];
      triple[1] += in[1];
      triple[2] += in[2];
    }
    double normA = std::max(triple[0], 1e-30);
    double normB = std::max(triple[1], 1e-30);
    double full_dot = triple[2];
    double coefA = 1.0 - full_dot / (2.0 * normA);
    double coefB = 1.0 - full_dot / (2.0 * normB);
    double my_coef = lower ? coefA : coefB;
    double their_coef = lower ? coefB : coefA;
    for (int64_t i = 0; i < my_len; i++) {
      d[static_cast<size_t>(my_start + i)] =
          my_coef * d[static_cast<size_t>(my_start + i)] +
          their_coef * other[static_cast<size_t>(i)];
    }
    start = my_start;
    len = my_len;
  }

  // Distance-doubling reassembly (mirror of the halving, reference
  // adasum.h second phase): exchange my combined piece with the level's
  // partner to rebuild the parent segment.
  for (int distance = n >> 1; distance >= 1; distance >>= 1) {
    int partner = rank ^ distance;
    auto [pstart, plen] = seg_stack.back();
    seg_stack.pop_back();
    int64_t h = plen / 2;
    int64_t their_off, their_len;
    if (rank < partner) {
      their_off = pstart + h;
      their_len = plen - h;
    } else {
      their_off = pstart;
      their_len = h;
    }
    Status s = mesh->SendRecv(partner, d.data() + start,
                              static_cast<size_t>(len) * 8, partner,
                              d.data() + their_off,
                              static_cast<size_t>(their_len) * 8);
    if (!s.ok()) return s;
    start = pstart;
    len = plen;
  }

  FromDouble(dtype, d.data(), buf, static_cast<size_t>(count));
  return Status::OK();
}

}  // namespace hvdtpu
