// horovod_tpu native core — shared types.
//
// Reference: horovod/common/common.h (Status, DataType, TensorTableEntry)
// and horovod/common/message.h (Request/Response types).  This library is
// the TPU build's native equivalent of the reference's L1-L3 (controller
// transport, negotiation, fusion, host-tensor collectives); the device
// data path stays in XLA (jit collectives), this engine serves the eager
// per-op API on host tensors.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace hvdtpu {

// Mirrors horovod_tpu/runtime/messages.py RequestType/ResponseType (which
// mirror reference message.h:52-58,137-144).  Values must stay in sync with
// the Python enums.
enum class RequestType : uint8_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  JOIN = 3,
  ADASUM = 4,
  ALLTOALL = 5,
  BARRIER = 6,
  // 7 is reserved: ResponseType::ERROR holds it, and the controller maps
  // request -> response by numeric value (ConstructResponse).
  REDUCESCATTER = 8,
};

enum class ResponseType : uint8_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  JOIN = 3,
  ADASUM = 4,
  ALLTOALL = 5,
  BARRIER = 6,
  ERROR = 7,
  REDUCESCATTER = 8,
};

// Mirrors horovod_tpu/ops/collectives.py ReduceOp (which follows reference
// horovod_reduce_op_{average,sum,adasum}, operations.cc:726-799).
enum class ReduceOp : uint8_t {
  AVERAGE = 1,
  SUM = 2,
  ADASUM = 3,
  MIN = 4,
  MAX = 5,
};

// Host tensor dtypes (reference message.h:27-38 DataType).  Values are the
// wire/C-API contract with basics_native.py.
enum class DataType : uint8_t {
  UINT8 = 0,
  INT8 = 1,
  INT32 = 2,
  INT64 = 3,
  FLOAT16 = 4,
  BFLOAT16 = 5,
  FLOAT32 = 6,
  FLOAT64 = 7,
  BOOL = 8,
};

inline size_t DataTypeSize(DataType t) {
  switch (t) {
    case DataType::UINT8:
    case DataType::INT8:
    case DataType::BOOL:
      return 1;
    case DataType::FLOAT16:
    case DataType::BFLOAT16:
      return 2;
    case DataType::INT32:
    case DataType::FLOAT32:
      return 4;
    case DataType::INT64:
    case DataType::FLOAT64:
      return 8;
  }
  return 1;
}

inline const char* DataTypeName(DataType t) {
  switch (t) {
    case DataType::UINT8: return "uint8";
    case DataType::INT8: return "int8";
    case DataType::INT32: return "int32";
    case DataType::INT64: return "int64";
    case DataType::FLOAT16: return "float16";
    case DataType::BFLOAT16: return "bfloat16";
    case DataType::FLOAT32: return "float32";
    case DataType::FLOAT64: return "float64";
    case DataType::BOOL: return "bool";
  }
  return "?";
}

// Reference common.h:107-148 Status — collapsed to what the C API needs.
enum class StatusCode : int32_t {
  OK = 0,
  IN_PROGRESS = 1,
  UNKNOWN_ERROR = 2,
  PRECONDITION_ERROR = 3,
  ABORTED = 4,
  INVALID_ARGUMENT = 5,
};

struct Status {
  StatusCode code = StatusCode::OK;
  std::string reason;
  static Status OK() { return Status{}; }
  static Status Error(StatusCode c, std::string r) { return Status{c, std::move(r)}; }
  bool ok() const { return code == StatusCode::OK; }
};

// Log levels follow reference logging.h; level from HVDTPU_LOG_LEVEL.
enum class LogLevel : int { TRACE = 0, DEBUG = 1, INFO = 2, WARNING = 3, ERROR = 4, FATAL = 5 };

LogLevel GlobalLogLevel();

#define HVD_LOG(level, rank, fmt, ...)                                        \
  do {                                                                        \
    if (static_cast<int>(level) >= static_cast<int>(::hvdtpu::GlobalLogLevel())) { \
      std::fprintf(stderr, "[hvdtpu %d] " fmt "\n", (rank), ##__VA_ARGS__);   \
    }                                                                         \
  } while (0)

}  // namespace hvdtpu
