#include "controller.h"

#include <algorithm>

#include "common.h"

namespace hvdtpu {

namespace {

const char* OpName(RequestType t) {
  switch (t) {
    case RequestType::ALLREDUCE: return "ALLREDUCE";
    case RequestType::ALLGATHER: return "ALLGATHER";
    case RequestType::BROADCAST: return "BROADCAST";
    case RequestType::JOIN: return "JOIN";
    case RequestType::ADASUM: return "ADASUM";
    case RequestType::ALLTOALL: return "ALLTOALL";
    case RequestType::BARRIER: return "BARRIER";
    case RequestType::REDUCESCATTER: return "REDUCESCATTER";
  }
  return "?";
}

std::string ShapeStr(const std::vector<int64_t>& s) {
  std::string out = "(";
  for (size_t i = 0; i < s.size(); i++) {
    if (i) out += ", ";
    out += std::to_string(s[i]);
  }
  out += ")";
  return out;
}

}  // namespace

// Consistency checks of ConstructResponse (reference controller.cc:378-611).
// Error strings match the Python controller (runtime/controller.py) so both
// engines surface identical messages to tests and users.
std::string Controller::Validate(const TableEntry& e) const {
  const Request& first = e.requests.begin()->second;
  if (first.request_type == RequestType::ALLGATHER && first.shape.empty()) {
    return "Allgather of " + first.tensor_name +
           " requires at least a 1-dimensional tensor (got a scalar).";
  }
  if (first.request_type == RequestType::REDUCESCATTER &&
      first.shape.empty()) {
    return "Reducescatter of " + first.tensor_name +
           " requires at least a 1-dimensional tensor (got a scalar).";
  }
  for (const auto& [rank, r] : e.requests) {
    if (r.dtype != first.dtype) {
      return "Mismatched data types for " + first.tensor_name + ": rank " +
             std::to_string(first.request_rank) + " sent " +
             DataTypeName(first.dtype) + ", rank " + std::to_string(rank) +
             " sent " + DataTypeName(r.dtype) + ".";
    }
    if (r.request_type != first.request_type) {
      return "Mismatched collective operations for " + first.tensor_name + ".";
    }
    if (r.reduce_op != first.reduce_op || r.prescale != first.prescale ||
        r.postscale != first.postscale) {
      return "Mismatched reduce options for " + first.tensor_name + ".";
    }
    switch (first.request_type) {
      case RequestType::ALLREDUCE:
      case RequestType::ADASUM:
      case RequestType::BROADCAST:
      case RequestType::ALLTOALL:
      case RequestType::REDUCESCATTER:
        if (r.shape != first.shape) {
          return "Mismatched shapes for " + first.tensor_name + ": " +
                 ShapeStr(first.shape) + " vs " + ShapeStr(r.shape) + ".";
        }
        break;
      case RequestType::ALLGATHER: {
        if (r.shape.empty()) {
          return "Allgather of " + first.tensor_name +
                 " requires at least a 1-dimensional tensor (got a scalar).";
        }
        if (!std::equal(r.shape.begin() + 1, r.shape.end(),
                        first.shape.begin() + 1, first.shape.end())) {
          return "Mismatched allgather shapes beyond dim 0 for " +
                 first.tensor_name + ".";
        }
        break;
      }
      default:
        break;
    }
    if (first.request_type == RequestType::BROADCAST &&
        r.root_rank != first.root_rank) {
      return "Mismatched root ranks for broadcast " + first.tensor_name +
             ": " + std::to_string(first.root_rank) + " vs " +
             std::to_string(r.root_rank) + ".";
    }
  }
  return "";
}

Response Controller::ConstructResponse(const TableEntry& e) const {
  const Request& first = e.requests.begin()->second;
  Response resp;
  resp.response_type = static_cast<ResponseType>(first.request_type);
  resp.tensor_names = {first.tensor_name};
  resp.dtype = first.dtype;
  resp.reduce_op = first.reduce_op;
  resp.root_rank = first.root_rank;
  resp.prescale = first.prescale;
  resp.postscale = first.postscale;
  resp.shapes = {first.shape};
  if (first.request_type == RequestType::ALLGATHER) {
    // Ragged per-rank dim0 sizes; joined/absent ranks contribute 0 rows
    // (reference controller.cc:453-518).
    resp.tensor_sizes.assign(cfg_.world_size, 0);
    for (const auto& [rank, r] : e.requests)
      resp.tensor_sizes[rank] = r.shape.empty() ? 0 : r.shape[0];
  }
  return resp;
}

void FuseResponseList(std::vector<Response>* responses,
                      int64_t fusion_threshold_bytes) {
  std::vector<Response> fused;
  for (auto& resp : *responses) {
    bool fusible =
        resp.response_type == ResponseType::ALLREDUCE && !fused.empty() &&
        fused.back().response_type == ResponseType::ALLREDUCE &&
        fused.back().dtype == resp.dtype &&
        fused.back().reduce_op == resp.reduce_op &&
        fused.back().prescale == resp.prescale &&
        fused.back().postscale == resp.postscale;
    if (fusible) {
      auto numel = [](const Response& r) {
        int64_t n = 0;
        for (const auto& s : r.shapes) {
          int64_t m = 1;
          for (auto d : s) m *= d;
          n += m;
        }
        return n;
      };
      int64_t bytes = (numel(fused.back()) + numel(resp)) *
                      static_cast<int64_t>(DataTypeSize(resp.dtype));
      if (bytes <= fusion_threshold_bytes) {
        fused.back().tensor_names.push_back(resp.tensor_names[0]);
        fused.back().shapes.push_back(resp.shapes[0]);
        continue;
      }
    }
    fused.push_back(std::move(resp));
  }
  *responses = std::move(fused);
}

ResponseList Controller::ComputeResponseList(
    const std::vector<RequestList>& lists, bool* should_shutdown) {
  ResponseList out;

  // Absorb join/shutdown flags (reference controller.cc:219-221,256-259).
  for (int r = 0; r < static_cast<int>(lists.size()); r++) {
    if (lists[r].shutdown) shutdown_seen_ = true;
    if (lists[r].joined) joined_ranks_.insert(r);
    for (uint32_t slot : lists[r].cache_hits) slot_ready_[slot].insert(r);
  }

  for (const auto& rl : lists) {
    for (const auto& req : rl.requests) {
      if (req.request_type == RequestType::JOIN) continue;
      auto [it, inserted] = table_.try_emplace(req.tensor_name);
      if (inserted) {
        it->second.first_seen = std::chrono::steady_clock::now();
        it->second.arrival_order = arrival_counter_++;
        if (timeline_)
          timeline_->NegotiateStart(req.tensor_name,
                                    OpName(req.request_type));
      }
      if (timeline_)
        timeline_->NegotiateRankReady(req.tensor_name, req.request_rank);
      it->second.requests[req.request_rank] = req;
    }
  }
  out.cache_frozen = !joined_ranks_.empty();

  // Divergence repair: a tuner cache toggle can land on opposite sides of
  // a straggler enqueue, so one rank classifies a tensor as a cache hit
  // (slot vote) while another negotiates it as a full request.  Neither
  // side completes alone — the slot waits on the requesting rank, the
  // request waits on the voting rank.  Rank 0's replicated cache knows the
  // slot's identity, so reconcile: fold each voting rank into the request
  // table using the cached request params, and drop the slot vote.
  if (cache_ != nullptr) {
    for (auto it = slot_ready_.begin(); it != slot_ready_.end();) {
      const Request* cached = cache_->RequestFor(it->first);
      auto tit = cached ? table_.find(cached->tensor_name) : table_.end();
      if (tit == table_.end()) {
        ++it;
        continue;
      }
      for (int32_t r : it->second) {
        Request req = *cached;
        req.request_rank = r;
        tit->second.requests.emplace(r, std::move(req));
        if (timeline_)
          timeline_->NegotiateRankReady(cached->tensor_name, r);
      }
      it = slot_ready_.erase(it);
    }
  }

  int needed = cfg_.world_size - static_cast<int>(joined_ranks_.size());

  // Cache fast path: slots every non-joined rank marked ready.
  for (auto it = slot_ready_.begin(); it != slot_ready_.end();) {
    int count = 0;
    for (int32_t r : it->second)
      if (!joined_ranks_.count(r)) count++;
    if (count >= needed) {
      out.cached_slots.push_back(it->first);
      it = slot_ready_.erase(it);
    } else {
      ++it;
    }
  }
  std::sort(out.cached_slots.begin(), out.cached_slots.end());

  // Ready uncached tensors, in first-arrival order (deterministic).
  std::vector<std::pair<uint64_t, std::string>> ready;
  for (const auto& [name, e] : table_) {
    if (static_cast<int>(e.requests.size()) >= needed)
      ready.emplace_back(e.arrival_order, name);
  }
  std::sort(ready.begin(), ready.end());

  for (const auto& [order, name] : ready) {
    auto it = table_.find(name);
    if (timeline_) {
      timeline_->NegotiateEnd(
          name, OpName(it->second.requests.begin()->second.request_type));
    }
    std::string err = Validate(it->second);
    if (!err.empty()) {
      Response resp;
      resp.response_type = ResponseType::ERROR;
      resp.tensor_names = {name};
      resp.error_message = err;
      out.responses.push_back(std::move(resp));
    } else {
      out.responses.push_back(ConstructResponse(it->second));
    }
    table_.erase(it);
  }

  // Join completion: everyone joined -> JOIN response resets state
  // (reference controller.cc:300-307).
  if (!joined_ranks_.empty() &&
      static_cast<int>(joined_ranks_.size()) == cfg_.world_size) {
    Response resp;
    resp.response_type = ResponseType::JOIN;
    resp.tensor_names = {"join"};
    out.responses.push_back(std::move(resp));
    joined_ranks_.clear();
  }

  CheckStalls(should_shutdown);

  if (shutdown_seen_) *should_shutdown = true;
  out.shutdown = *should_shutdown;
  return out;
}

void Controller::CheckStalls(bool* should_shutdown) {
  // Reference stall_inspector.cc: rank 0 warns when a tensor has been
  // waiting on some ranks past the threshold; optionally escalates to a
  // coordinated shutdown; stalled cached tensors are invalidated.
  auto now = std::chrono::steady_clock::now();
  double since_check =
      std::chrono::duration<double>(now - last_stall_check_).count();
  if (since_check < std::min(cfg_.stall_warn_secs, 10.0)) return;
  last_stall_check_ = now;
  for (const auto& [name, e] : table_) {
    double age = std::chrono::duration<double>(now - e.first_seen).count();
    if (age <= cfg_.stall_warn_secs) continue;
    std::string missing;
    for (int r = 0; r < cfg_.world_size; r++) {
      if (!e.requests.count(r) && !joined_ranks_.count(r)) {
        if (!missing.empty()) missing += ",";
        missing += std::to_string(r);
      }
    }
    HVD_LOG(LogLevel::WARNING, 0,
            "One or more tensors were submitted to be reduced/gathered but "
            "some ranks have not yet done so after %.0f s: tensor %s is "
            "waiting on ranks [%s]",
            age, name.c_str(), missing.c_str());
    // NOTE: the reference invalidates stalled *cached* tensors here
    // (stall_inspector InvalidateStalledCachedTensors), but it coordinates
    // the eviction across ranks through the cache-bit sync.  Our stall check
    // fires on rank-local wall clocks, so a local cache->Erase would free a
    // slot on this rank only and desynchronize slot numbering across the
    // job (slots are negotiated by id).  A stalled tensor is still pending
    // negotiation — it has no cache entry to evict — so we only warn.
    if (cfg_.stall_shutdown_secs > 0 && age > cfg_.stall_shutdown_secs) {
      HVD_LOG(LogLevel::ERROR, 0,
              "Stalled tensor %s exceeded shutdown threshold (%.0f s); "
              "aborting the job",
              name.c_str(), cfg_.stall_shutdown_secs);
      *should_shutdown = true;
    }
  }
}

}  // namespace hvdtpu
