// Elementwise reduction kernels over raw byte buffers, per DataType.
//
// Reference: the MPI backend leans on MPI_SUM/MIN/MAX with a custom AVX
// fp16 op (horovod/common/half.cc:42-78); here the kernels are our own,
// with bfloat16 first-class (the TPU's native half type) via round-to-
// nearest-even float conversion.
#pragma once

#include <cstddef>
#include <cstdint>

#include "common.h"

namespace hvdtpu {

// acc[i] op= in[i] for count elements of dtype t.
void ReduceInto(DataType t, ReduceOp op, void* acc, const void* in,
                size_t count);

// buf[i] *= factor (elementwise, in dtype).  Used for pre/postscale and
// Average's divide-by-size.
void ScaleInPlace(DataType t, void* buf, size_t count, double factor);

// dtype <-> double conversion for the Adasum path (dots accumulate in
// double, as the reference's DispatchComputeDotAndNormSqrds does).
void ToDouble(DataType t, const void* in, double* out, size_t count);
void FromDouble(DataType t, const double* in, void* out, size_t count);

// bfloat16/float16 scalar conversions (round-to-nearest-even on the way
// back down).
float Bf16ToF32(uint16_t v);
uint16_t F32ToBf16(float v);
float F16ToF32(uint16_t v);
uint16_t F32ToF16(float v);

}  // namespace hvdtpu
