#include "wire.h"

#include <cstring>

namespace hvdtpu {
namespace {

// Little-endian primitive writers/readers.  x86-64 and every TPU host VM
// are little-endian; memcpy keeps it alignment-safe.
template <typename T>
void Put(std::vector<uint8_t>* out, T v) {
  size_t n = out->size();
  out->resize(n + sizeof(T));
  std::memcpy(out->data() + n, &v, sizeof(T));
}

void PutStr(std::vector<uint8_t>* out, const std::string& s) {
  Put<uint32_t>(out, static_cast<uint32_t>(s.size()));
  out->insert(out->end(), s.begin(), s.end());
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  template <typename T>
  T Get() {
    T v{};
    if (p + sizeof(T) > end) {
      ok = false;
      return v;
    }
    std::memcpy(&v, p, sizeof(T));
    p += sizeof(T);
    return v;
  }

  std::string GetStr() {
    uint32_t n = Get<uint32_t>();
    if (!ok || p + n > end) {
      ok = false;
      return "";
    }
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

void PutRequest(std::vector<uint8_t>* out, const Request& r) {
  Put<int32_t>(out, r.request_rank);
  Put<uint8_t>(out, static_cast<uint8_t>(r.request_type));
  Put<uint8_t>(out, static_cast<uint8_t>(r.dtype));
  Put<uint8_t>(out, static_cast<uint8_t>(r.reduce_op));
  Put<int32_t>(out, r.root_rank);
  Put<double>(out, r.prescale);
  Put<double>(out, r.postscale);
  PutStr(out, r.tensor_name);
  Put<uint32_t>(out, static_cast<uint32_t>(r.shape.size()));
  for (auto d : r.shape) Put<int64_t>(out, d);
}

bool GetRequest(Reader* rd, Request* r) {
  r->request_rank = rd->Get<int32_t>();
  r->request_type = static_cast<RequestType>(rd->Get<uint8_t>());
  r->dtype = static_cast<DataType>(rd->Get<uint8_t>());
  r->reduce_op = static_cast<ReduceOp>(rd->Get<uint8_t>());
  r->root_rank = rd->Get<int32_t>();
  r->prescale = rd->Get<double>();
  r->postscale = rd->Get<double>();
  r->tensor_name = rd->GetStr();
  uint32_t nd = rd->Get<uint32_t>();
  if (!rd->ok || nd > 64) return false;
  r->shape.resize(nd);
  for (uint32_t i = 0; i < nd; i++) r->shape[i] = rd->Get<int64_t>();
  return rd->ok;
}

void PutResponse(std::vector<uint8_t>* out, const Response& r) {
  Put<uint8_t>(out, static_cast<uint8_t>(r.response_type));
  Put<uint8_t>(out, static_cast<uint8_t>(r.dtype));
  Put<uint8_t>(out, static_cast<uint8_t>(r.reduce_op));
  Put<int32_t>(out, r.root_rank);
  Put<double>(out, r.prescale);
  Put<double>(out, r.postscale);
  PutStr(out, r.error_message);
  Put<uint32_t>(out, static_cast<uint32_t>(r.tensor_names.size()));
  for (const auto& n : r.tensor_names) PutStr(out, n);
  Put<uint32_t>(out, static_cast<uint32_t>(r.shapes.size()));
  for (const auto& s : r.shapes) {
    Put<uint32_t>(out, static_cast<uint32_t>(s.size()));
    for (auto d : s) Put<int64_t>(out, d);
  }
  Put<uint32_t>(out, static_cast<uint32_t>(r.tensor_sizes.size()));
  for (auto s : r.tensor_sizes) Put<int64_t>(out, s);
}

bool GetResponse(Reader* rd, Response* r) {
  r->response_type = static_cast<ResponseType>(rd->Get<uint8_t>());
  r->dtype = static_cast<DataType>(rd->Get<uint8_t>());
  r->reduce_op = static_cast<ReduceOp>(rd->Get<uint8_t>());
  r->root_rank = rd->Get<int32_t>();
  r->prescale = rd->Get<double>();
  r->postscale = rd->Get<double>();
  r->error_message = rd->GetStr();
  uint32_t nn = rd->Get<uint32_t>();
  if (!rd->ok || nn > (1u << 20)) return false;
  r->tensor_names.resize(nn);
  for (auto& n : r->tensor_names) n = rd->GetStr();
  uint32_t ns = rd->Get<uint32_t>();
  if (!rd->ok || ns > (1u << 20)) return false;
  r->shapes.resize(ns);
  for (auto& s : r->shapes) {
    uint32_t nd = rd->Get<uint32_t>();
    if (!rd->ok || nd > 64) return false;
    s.resize(nd);
    for (auto& d : s) d = rd->Get<int64_t>();
  }
  uint32_t nz = rd->Get<uint32_t>();
  if (!rd->ok || nz > (1u << 20)) return false;
  r->tensor_sizes.resize(nz);
  for (auto& z : r->tensor_sizes) z = rd->Get<int64_t>();
  return rd->ok;
}

}  // namespace

void SerializeRequestList(const RequestList& rl, std::vector<uint8_t>* out) {
  Put<uint8_t>(out, rl.shutdown ? 1 : 0);
  Put<uint8_t>(out, rl.joined ? 1 : 0);
  Put<uint32_t>(out, static_cast<uint32_t>(rl.cache_hits.size()));
  for (auto h : rl.cache_hits) Put<uint32_t>(out, h);
  Put<uint32_t>(out, static_cast<uint32_t>(rl.requests.size()));
  for (const auto& r : rl.requests) PutRequest(out, r);
}

bool ParseRequestList(const uint8_t* data, size_t len, RequestList* out) {
  Reader rd{data, data + len};
  out->shutdown = rd.Get<uint8_t>() != 0;
  out->joined = rd.Get<uint8_t>() != 0;
  uint32_t nh = rd.Get<uint32_t>();
  if (!rd.ok || nh > (1u << 20)) return false;
  out->cache_hits.resize(nh);
  for (auto& h : out->cache_hits) h = rd.Get<uint32_t>();
  uint32_t nr = rd.Get<uint32_t>();
  if (!rd.ok || nr > (1u << 20)) return false;
  out->requests.resize(nr);
  for (auto& r : out->requests)
    if (!GetRequest(&rd, &r)) return false;
  return rd.ok;
}

void SerializeResponseList(const ResponseList& rl, std::vector<uint8_t>* out) {
  Put<uint8_t>(out, rl.shutdown ? 1 : 0);
  Put<uint8_t>(out, rl.cache_frozen ? 1 : 0);
  Put<uint8_t>(out, rl.has_params ? 1 : 0);
  if (rl.has_params) {
    Put<int64_t>(out, rl.tuned_fusion_bytes);
    Put<double>(out, rl.tuned_cycle_ms);
    Put<uint8_t>(out, rl.tuned_cache_enabled ? 1 : 0);
  }
  Put<uint32_t>(out, static_cast<uint32_t>(rl.cached_slots.size()));
  for (auto s : rl.cached_slots) Put<uint32_t>(out, s);
  Put<uint32_t>(out, static_cast<uint32_t>(rl.responses.size()));
  for (const auto& r : rl.responses) PutResponse(out, r);
}

bool ParseResponseList(const uint8_t* data, size_t len, ResponseList* out) {
  Reader rd{data, data + len};
  out->shutdown = rd.Get<uint8_t>() != 0;
  out->cache_frozen = rd.Get<uint8_t>() != 0;
  out->has_params = rd.Get<uint8_t>() != 0;
  if (out->has_params) {
    out->tuned_fusion_bytes = rd.Get<int64_t>();
    out->tuned_cycle_ms = rd.Get<double>();
    out->tuned_cache_enabled = rd.Get<uint8_t>() != 0;
  }
  uint32_t ns = rd.Get<uint32_t>();
  if (!rd.ok || ns > (1u << 20)) return false;
  out->cached_slots.resize(ns);
  for (auto& s : out->cached_slots) s = rd.Get<uint32_t>();
  uint32_t nr = rd.Get<uint32_t>();
  if (!rd.ok || nr > (1u << 20)) return false;
  out->responses.resize(nr);
  for (auto& r : out->responses)
    if (!GetResponse(&rd, &r)) return false;
  return rd.ok;
}

}  // namespace hvdtpu
