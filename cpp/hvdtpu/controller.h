// Rank-0 coordinator negotiation — the native controller.
//
// Reference: horovod/common/controller.cc.  Workers send their ready-tensor
// RequestLists to rank 0 each cycle; rank 0 counts per-name readiness
// (IncrementTensorCount, controller.cc:789-812), validates consistency and
// builds Responses (ConstructResponse, controller.cc:378-611), fuses
// adjacent allreduces under the fusion threshold (FuseResponses,
// controller.cc:640-761), and broadcasts the ResponseList.  Join and
// shutdown flags ride the same messages (controller.cc:219-221,256-259).
// The stall inspector (stall_inspector.cc) lives here too: rank 0 warns on
// tensors some ranks submitted and others haven't.
#pragma once

#include <chrono>
#include <cstdint>
#include <map>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

#include "response_cache.h"
#include "timeline.h"
#include "wire.h"

namespace hvdtpu {

// Fuse adjacent ALLREDUCE responses with identical dtype/op/scaling under
// the fusion threshold (reference FuseResponses, controller.cc:640-761,
// same-dtype look at :676-689).  Free function because EVERY rank fuses the
// [cached + new] response stream locally — inputs are identical everywhere
// (coordinator broadcast), so outputs are too.
void FuseResponseList(std::vector<Response>* responses,
                      int64_t fusion_threshold_bytes);

struct ControllerConfig {
  int world_size = 1;
  int64_t fusion_threshold_bytes = 64 * 1024 * 1024;
  double stall_warn_secs = 60.0;
  double stall_shutdown_secs = 0.0;  // 0 = never escalate
};

class Controller {
 public:
  explicit Controller(const ControllerConfig& cfg) : cfg_(cfg) {}

  // One coordinator cycle: merge all ranks' lists (index = rank), emit the
  // ResponseList every rank will execute.  `cache` is rank 0's copy of the
  // (globally coherent) response cache, used to count cache-slot readiness;
  // responses for ready slots come back as ResponseList::cached_slots.
  // Sets *should_shutdown when any rank raised the flag or a stall
  // escalated.
  ResponseList ComputeResponseList(const std::vector<RequestList>& lists,
                                   bool* should_shutdown);

  int joined_count() const { return static_cast<int>(joined_ranks_.size()); }

  // Rank 0's timeline receives the negotiation events (reference emits them
  // from IncrementTensorCount / response construction).
  void SetTimeline(Timeline* t) { timeline_ = t; }

  // Rank 0's replicated response cache, consulted to reconcile slot votes
  // against full requests for the same tensor (divergence repair).
  void SetCache(const ResponseCache* c) { cache_ = c; }

 private:
  struct TableEntry {
    std::map<int32_t, Request> requests;  // rank -> request
    std::chrono::steady_clock::time_point first_seen;
    uint64_t arrival_order = 0;
  };

  std::string Validate(const TableEntry& e) const;
  Response ConstructResponse(const TableEntry& e) const;
  void CheckStalls(bool* should_shutdown);

  Timeline* timeline_ = nullptr;
  const ResponseCache* cache_ = nullptr;
  ControllerConfig cfg_;
  std::unordered_map<std::string, TableEntry> table_;
  std::map<uint32_t, std::set<int32_t>> slot_ready_;  // cache slot -> ranks
  std::set<int32_t> joined_ranks_;
  bool shutdown_seen_ = false;
  uint64_t arrival_counter_ = 0;
  std::chrono::steady_clock::time_point last_stall_check_ =
      std::chrono::steady_clock::now();
};

}  // namespace hvdtpu
