// Binary wire format for the native negotiation protocol.
//
// Reference: horovod/common/message.{h,cc} + wire/message.fbs (FlatBuffers).
// This build's control messages travel native→native only (workers ↔ the
// rank-0 coordinator over the TCP mesh), so the format is a hand-rolled
// little-endian encoding — one schema, defined here, no codegen.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

// Reference message.h:47-100.
struct Request {
  int32_t request_rank = 0;
  RequestType request_type = RequestType::ALLREDUCE;
  std::string tensor_name;
  DataType dtype = DataType::FLOAT32;
  std::vector<int64_t> shape;
  ReduceOp reduce_op = ReduceOp::SUM;
  int32_t root_rank = -1;
  double prescale = 1.0;
  double postscale = 1.0;

  int64_t NumElements() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
};

// Reference message.h:103-129 — plus the response-cache fast path: cache
// hits travel as slot positions, not re-serialized requests (reference
// response_cache.h CacheCoordinator bit vectors).
struct RequestList {
  std::vector<Request> requests;
  std::vector<uint32_t> cache_hits;  // ready cache slots on this rank
  bool shutdown = false;
  bool joined = false;
};

// Reference message.h:132-194.  Carries everything execution needs so a
// rank that never saw the tensor (joined) can participate with zeros.
struct Response {
  ResponseType response_type = ResponseType::ALLREDUCE;
  std::vector<std::string> tensor_names;
  std::string error_message;
  // Per-fused-entry geometry (negotiated): shapes[i] is entry i's shape.
  std::vector<std::vector<int64_t>> shapes;
  // Ragged allgather: per-rank dim0 sizes (reference Response::tensor_sizes,
  // controller.cc:453-518).
  std::vector<int64_t> tensor_sizes;
  DataType dtype = DataType::FLOAT32;
  ReduceOp reduce_op = ReduceOp::SUM;
  int32_t root_rank = -1;
  double prescale = 1.0;
  double postscale = 1.0;
};

struct ResponseList {
  std::vector<Response> responses;
  // Responses reconstructed from each rank's local cache, by slot.
  std::vector<uint32_t> cached_slots;
  bool shutdown = false;
  // True while any rank is joined: all ranks uniformly skip cache Puts so
  // the cache stays coherent for ranks that are absent from negotiation
  // (the joined rank can't observe new entries; freezing keeps every
  // rank's put/evict sequence identical — the invariant slot ids rest on).
  bool cache_frozen = false;
  // Autotuned parameter sync (reference SynchronizeParameters,
  // controller.cc:33-47): rank 0 attaches the tuner's latest move; every
  // rank applies it at the same cycle boundary, which keeps the fusion
  // threshold (and therefore fused-response layout) identical everywhere.
  bool has_params = false;
  int64_t tuned_fusion_bytes = 0;
  double tuned_cycle_ms = 0.0;
  bool tuned_cache_enabled = true;
};

// Serialization: append to / parse from a byte vector.
void SerializeRequestList(const RequestList& rl, std::vector<uint8_t>* out);
bool ParseRequestList(const uint8_t* data, size_t len, RequestList* out);
void SerializeResponseList(const ResponseList& rl, std::vector<uint8_t>* out);
bool ParseResponseList(const uint8_t* data, size_t len, ResponseList* out);

}  // namespace hvdtpu
