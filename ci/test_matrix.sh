#!/usr/bin/env bash
# CI matrix driver (reference: .buildkite/gen-pipeline.sh:10-33 crossing
# {MPI,Gloo,...} x {py} x {framework} images; here the axes that exist in
# the TPU build: eager engine {python,native} x world size {1,2,4}).
#
# Usage: ci/test_matrix.sh            # full matrix
#        ci/test_matrix.sh quick      # unit suite + np=2 cross-engine only
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== build native engine =="
make -C cpp

# Static-analysis gate (ISSUE 5) — runs FIRST in every tier: it is the
# cheapest check and rejects whole bug classes (rank-divergent collective
# schedules, lock-order/signal-safety violations) no test below can see.
analysis_gate() {
    echo "== analysis gate: hvdtpu-lint over the full surface =="
    # ONE full-surface run serves both checks: the committed tree must
    # lint clean against the committed baseline (exit 0 + summary.new
    # asserted below) and the JSON report must be schema-valid.  No
    # explicit paths: the [tool.hvdtpu-lint] config supplies the same
    # surface, AND a config-default run is the one that reports stale
    # baseline entries (fixed findings whose entries should be removed).
    LINT_TMP=$(mktemp -d)
    # --strict-baseline: stale suppressions (entries whose finding no
    # longer fires) fail the gate — dead entries would silently swallow
    # a FUTURE finding at the same (rule, path, context).
    if ! python -m horovod_tpu.analysis \
        --baseline horovod_tpu/analysis/baseline.json \
        --strict-baseline \
        --format json > "$LINT_TMP/report.json"; then
        echo "analysis gate FAILED: new findings on the clean tree" >&2
        python - "$LINT_TMP/report.json" <<'EOF' >&2 || cat "$LINT_TMP/report.json" >&2
import json, sys
for f in json.load(open(sys.argv[1]))["findings"]:
    if f["status"] == "new":
        print(f"{f['path']}:{f['line']}: {f['rule']} {f['message']}")
EOF
        rm -rf "$LINT_TMP"
        exit 1
    fi
    python - "$LINT_TMP/report.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["schema"] == "hvdtpu-lint-v1", doc["schema"]
assert isinstance(doc["rules"], dict) and len(doc["rules"]) >= 20
for rid, r in doc["rules"].items():
    assert {"name", "severity", "summary"} <= set(r), (rid, r)
    assert r["severity"] in ("error", "warning"), (rid, r)
for f in doc["findings"]:
    assert {"rule", "severity", "path", "line", "col", "message",
            "context", "status"} <= set(f), f
    assert f["status"] in ("new", "baselined", "suppressed"), f
    assert isinstance(f["line"], int) and f["line"] >= 1, f
s = doc["summary"]
assert s["new"] == 0, f"clean-tree run reported new findings: {s}"
assert s["total"] == len(doc["findings"])
print(f"analysis gate: schema OK ({len(doc['rules'])} rules, "
      f"{s['baselined']} baselined, {s['suppressed']} suppressed)")
EOF
    # 3) the gate actually GATES: a seeded violation must fail the run
    cat > "$LINT_TMP/seeded_bad.py" <<'EOF'
import horovod_tpu as hvd

def step(x):
    if hvd.rank() == 0:          # rank-guarded collective: deadlock
        return hvd.allreduce(x)
    return x
EOF
    if python -m horovod_tpu.analysis "$LINT_TMP/seeded_bad.py" \
        --baseline horovod_tpu/analysis/baseline.json \
        > "$LINT_TMP/seeded.out" 2>&1; then
        echo "analysis gate FAILED: seeded violation passed the linter" >&2
        cat "$LINT_TMP/seeded.out" >&2
        rm -rf "$LINT_TMP"
        exit 1
    fi
    grep -q "HVD001" "$LINT_TMP/seeded.out" || {
        echo "analysis gate FAILED: seeded violation not attributed to HVD001" >&2
        cat "$LINT_TMP/seeded.out" >&2
        rm -rf "$LINT_TMP"
        exit 1
    }
    # 4) the mesh-aware family gates too (ISSUE 12): a rank-guarded
    # subgroup collective inside a shard_map body must fail as HVD010,
    # including the interprocedural shape where the rank read and the
    # collective live in different functions.
    cat > "$LINT_TMP/seeded_subgroup.py" <<'EOF'
import horovod_tpu as hvd
from jax import lax
from jax.experimental.shard_map import shard_map

def body(x):
    if hvd.rank() == 0:              # world taint, local group: deadlock
        return lax.psum(x, "hvd_local")
    return x

def reduce_part(flag, x):
    if flag == 0:                    # taint arrives through the argument
        return lax.psum(x, "hvd_cross")
    return x

def step(x):
    return reduce_part(hvd.cross_rank(), x)
EOF
    if python -m horovod_tpu.analysis "$LINT_TMP/seeded_subgroup.py" \
        --baseline horovod_tpu/analysis/baseline.json \
        > "$LINT_TMP/seeded_sub.out" 2>&1; then
        echo "analysis gate FAILED: seeded subgroup-divergent collective passed" >&2
        cat "$LINT_TMP/seeded_sub.out" >&2
        rm -rf "$LINT_TMP"
        exit 1
    fi
    # both the direct and the interprocedural hit, attributed to HVD010
    # with the producing call chain named
    [ "$(grep -c "HVD010" "$LINT_TMP/seeded_sub.out")" -ge 2 ] || {
        echo "analysis gate FAILED: seeded subgroup violations not attributed to HVD010" >&2
        cat "$LINT_TMP/seeded_sub.out" >&2
        rm -rf "$LINT_TMP"
        exit 1
    }
    grep -q "step \[.*\] -> reduce_part" "$LINT_TMP/seeded_sub.out" || {
        echo "analysis gate FAILED: HVD010 finding lost its call-chain attribution" >&2
        cat "$LINT_TMP/seeded_sub.out" >&2
        rm -rf "$LINT_TMP"
        exit 1
    }
    rm -rf "$LINT_TMP"
    echo "analysis gate OK"
}

# Race gate (ISSUE 20): the guarded-by data-race family (HVDC108/109/
# 110) specifically.  Two halves: the committed tree restricted to the
# race rules must be clean against the committed baseline (every racy
# access in the serving fleet is either fixed or carries a reasoned
# baseline entry), and a seeded unguarded-write fixture must FAIL the
# run with the class, the field AND the inferred guard named — a gate
# that cannot fail, or that fails without attribution, is decorative.
races_gate() {
    echo "== races gate: HVDC108-110 clean tree vs baseline =="
    RG_TMP=$(mktemp -d)
    # --rules is a partial view, so baseline-staleness policing stays
    # with analysis_gate's full-surface --strict-baseline run; this
    # run asserts the race family's own verdict in isolation.
    if ! python -m horovod_tpu.analysis \
        --rules HVDC108,HVDC109,HVDC110 \
        --baseline horovod_tpu/analysis/baseline.json \
        > "$RG_TMP/clean.out"; then
        echo "races gate FAILED: new race findings on the clean tree" >&2
        cat "$RG_TMP/clean.out" >&2
        rm -rf "$RG_TMP"
        exit 1
    fi
    echo "== races gate: seeded unguarded write must fail, attributed =="
    cat > "$RG_TMP/seeded_race.py" <<'EOF'
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._depth = 0

    def start(self):
        threading.Thread(target=self._run).start()

    def _run(self):
        with self._lock:
            self._depth += 1
        with self._lock:
            self._depth -= 1

    def depth(self):
        with self._lock:
            return self._depth

    def spill(self):
        self._depth = 0     # write outside the inferred guard: HVDC108
EOF
    if python -m horovod_tpu.analysis "$RG_TMP/seeded_race.py" \
        --baseline horovod_tpu/analysis/baseline.json \
        > "$RG_TMP/seeded.out" 2>&1; then
        echo "races gate FAILED: seeded unguarded write passed the linter" >&2
        cat "$RG_TMP/seeded.out" >&2
        rm -rf "$RG_TMP"
        exit 1
    fi
    # the finding must name the class+field and the inferred lock
    for want in "HVDC108" "Pump._depth" "Pump.self._lock"; do
        grep -q "$want" "$RG_TMP/seeded.out" || {
            echo "races gate FAILED: finding lost its attribution ($want)" >&2
            cat "$RG_TMP/seeded.out" >&2
            rm -rf "$RG_TMP"
            exit 1
        }
    done
    rm -rf "$RG_TMP"
    echo "races gate OK"
}

if [ "${1:-full}" = "quick" ]; then
    # Fast lint pre-gate: changed-files-only via the dev-loop wrapper
    # (ISSUE 20 satellite) — on a per-commit diff this is seconds; the
    # FULL-surface analysis_gate + races_gate stay in the full tier,
    # where their cost is amortized against the long pole.
    echo "== quick tier: lint pre-gate over changed files =="
    python scripts/lint.py --changed
    # per-commit tier: everything except the long pole (soak, differential
    # fuzz, fp8 numerics contract, scaling gates) — see pytest.ini markers.
    # The elastic/fault-injection suite runs first and by name: recovery
    # paths only stay honest while the chaos tests that drive them
    # (ISSUE 1 acceptance) are exercised on every commit.
    echo "== quick tier: elastic fault-tolerance + injection paths =="
    python -m pytest tests/test_elastic.py tests/test_ckpt.py \
        "tests/test_checkpoint.py::test_injected_ckpt_failure_raises_on_all_ranks" \
        -x -q
    echo "== quick tier: observability plane =="
    python -m pytest tests/test_obs.py tests/test_obs_live.py \
        tests/test_postmortem.py tests/test_trace.py \
        tests/test_health.py -x -q
    echo "== quick tier: unit + multiprocess suite minus -m full =="
    # test_elastic.py / test_obs*.py and the injection case already ran
    # above — don't pay for the multiprocess chaos cases twice per commit.
    python -m pytest tests/ -x -q -m "not full and not slow" \
        --ignore=tests/test_elastic.py \
        --ignore=tests/test_ckpt.py \
        --ignore=tests/test_obs.py \
        --ignore=tests/test_obs_live.py \
        --ignore=tests/test_postmortem.py \
        --ignore=tests/test_trace.py \
        --ignore=tests/test_health.py \
        --deselect "tests/test_checkpoint.py::test_injected_ckpt_failure_raises_on_all_ranks"
    exit 0
fi

analysis_gate
races_gate

echo "== unit + in-process multiprocess suite (builds cover both engines) =="
# Parallel full tier (VERDICT r4 weak #6: 30 min single-threaded and
# growing).  The suite is sleep/IO-dominated (negotiation cycle sleeps,
# rendezvous polling, worker-process spawns), so oversubscribing even a
# 1-core host with 4 pytest workers cuts wall-clock.  Tests that assert
# wall-clock/throughput bounds carry -m serial and run alone afterwards
# so parallel load can't flake them.  Environments without pytest-xdist
# (it's in the test extra + Dockerfile.test, but a bare `pip install
# pytest` isn't) fall back to the single-process run.
if python -c "import xdist" 2>/dev/null; then
    # slow-marked acceptances are excluded here and run by node id
    # from their own gates (slow_multiproc/serve/paged/autoscale/mem)
    # — without the filter every one of them would execute twice.
    python -m pytest tests/ -x -q -m "not serial and not slow" -n 4 --dist load
else
    echo "pytest-xdist not installed; falling back to serial full tier" >&2
    python -m pytest tests/ -x -q -m "not serial and not slow"
fi
echo "== serial (timing-sensitive) tier =="
python -m pytest tests/ -x -q -m serial

echo "== slow_multiproc gate: tier-1-budget-triaged acceptances by node id =="
# These spawn real worker fleets and together cost ~100s — slow-marked
# out of the driver's tier-1 budget (ISSUE 15 hygiene), run HERE
# explicitly so the coverage never silently lapses.
python -m pytest \
    "tests/test_multiprocess.py::test_stall_shutdown_aborts_instead_of_hanging" \
    "tests/test_multiprocess.py::test_tf_interop_across_processes" \
    "tests/test_multiprocess.py::test_tf_broadcast_hook_in_monitored_session" \
    "tests/test_multiprocess.py::test_tf_adasum_optimizer_matches_numpy_reference" \
    "tests/test_multiprocess.py::test_keras_fit_across_processes" \
    -x -q

# Engine x world-size smoke matrix through the REAL launcher CLI (the
# reference runs examples under both mpirun and horovodrun for every
# image, gen-pipeline.sh:134-232).
for engine in python native; do
    for np in 1 2 4; do
        echo "== smoke: engine=$engine np=$np =="
        HVDTPU_EAGER_ENGINE=$engine \
        JAX_PLATFORMS=cpu \
            python -m horovod_tpu.run -np "$np" -H "localhost:$np" \
            python examples/mnist.py --smoke
    done
done

# Frontend + subsystem examples at np=2 (one engine each is enough: the
# differential fuzz test pins engine equivalence at the op level).
for ex in torch_mnist tf2_mnist keras_mnist adasum_small_model \
          checkpoint_resume estimator_train long_context_zigzag; do
    echo "== example smoke: $ex =="
    JAX_PLATFORMS=cpu \
        python -m horovod_tpu.run -np 2 python "examples/$ex.py"
done

# single-process multi-device examples (in-process mesh, --cpu sets the
# platform inside the process like tests/conftest.py)
for argset in "--smoke --cpu" "--smoke --cpu --circles 2"; do
    echo "== example smoke: pipeline_train $argset =="
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/pipeline_train.py $argset
done

# Observability gate: the obs unit suite plus a 2-process launcher
# smoke — per-rank metrics dumps and the merged all-rank timeline must
# both exist and parse as JSON (ISSUE 2: nothing quantitative survived
# a job before this plane existed).
echo "== obs gate: unit suite =="
python -m pytest tests/test_obs.py -x -q
echo "== obs gate: 2-process metrics dump + merged timeline smoke =="
OBS_TMP=$(mktemp -d)
cat > "$OBS_TMP/worker.py" <<'EOF'
import numpy as np
import horovod_tpu as hvd

hvd.init()
for i in range(4):
    hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name=f"t{i}")
hvd.shutdown()
EOF
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
HVDTPU_METRICS_DUMP="$OBS_TMP" \
HVDTPU_TIMELINE="$OBS_TMP/trace.json" \
HVDTPU_TIMELINE_MARK_CYCLES=1 \
    python -m horovod_tpu.run -np 2 --stats-summary \
    python "$OBS_TMP/worker.py"
python - "$OBS_TMP" <<'EOF'
import glob, json, sys
d = sys.argv[1]
dumps = glob.glob(f"{d}/metrics.*rank*.json")
assert len(dumps) == 2, f"expected 2 per-rank metrics dumps, got {dumps}"
for p in dumps:
    doc = json.load(open(p))
    assert doc["metrics"], f"empty metrics dump {p}"
merged = json.load(open(f"{d}/trace.json"))
assert merged, "merged timeline is empty"
pids = {e.get("pid") for e in merged if e.get("ph") != "M"}
assert pids == {0, 1}, f"expected a lane per rank, got pids={pids}"
print(f"obs gate OK: {len(dumps)} dumps, {len(merged)} timeline events")
EOF
rm -rf "$OBS_TMP"

# Live telemetry gate (ISSUE 3): a 2-proc job streaming metrics to the
# launcher; an external scraper attaches to GET /metrics MID-RUN and
# must read non-empty, parseable Prometheus exposition with a sample
# per rank, and live_history.jsonl must gain parseable rows.
echo "== obs_live gate: mid-run /metrics scrape + live history =="
LIVE_TMP=$(mktemp -d)
cat > "$LIVE_TMP/worker.py" <<'EOF'
import time

import numpy as np

import horovod_tpu as hvd

hvd.init()
for i in range(16):
    hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name=f"t{i}")
    time.sleep(0.25)
hvd.shutdown()
EOF
cat > "$LIVE_TMP/scrape.py" <<'EOF'
import json, os, re, subprocess, sys, time, urllib.request

tmp = sys.argv[1]
hist = os.path.join(tmp, "live_history.jsonl")
proc = subprocess.Popen(
    [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
     "--live-stats-secs", "0.3", "--live-history-file", hist,
     sys.executable, os.path.join(tmp, "worker.py")],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    env={**os.environ, "JAX_PLATFORMS": "cpu"},
)
endpoint = None
deadline = time.time() + 90
while time.time() < deadline and endpoint is None:
    line = proc.stdout.readline()
    if not line:
        break
    sys.stdout.write(line)
    m = re.search(r"scrape endpoint (http://\S+/metrics)", line)
    if m:
        endpoint = m.group(1)
assert endpoint, "launcher never announced the scrape endpoint"

# scrape MID-RUN until per-rank samples appear
body = ""
while time.time() < deadline:
    body = urllib.request.urlopen(endpoint, timeout=5).read().decode()
    if 'rank="0"' in body and 'rank="1"' in body:
        break
    time.sleep(0.3)
assert proc.poll() is None, "job finished before the mid-run scrape"
sample = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? (NaN|[-+0-9.eE]+)$')
lines = [l for l in body.rstrip().splitlines() if not l.startswith("#")]
assert lines, "empty exposition"
for l in lines:
    assert sample.match(l), f"unparseable exposition line: {l!r}"
assert "hvdtpu_engine_collectives_completed" in body

proc.stdout.read()
assert proc.wait(timeout=120) == 0
rows = [json.loads(l) for l in open(hist)]
assert rows, "live_history.jsonl gained no rows"
assert rows[-1]["ranks_reporting"] >= 1
print(f"obs_live gate OK: {len(lines)} exposition lines, "
      f"{len(rows)} history rows")
EOF
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python "$LIVE_TMP/scrape.py" "$LIVE_TMP"
rm -rf "$LIVE_TMP"

# Goodput gate (ISSUE 17): the per-rank goodput ledger, the tenant SLO
# burn-rate plane, and the bench regression sentinel.  hvdtpu-lint
# stays clean over the new surface, the decision-table suites run
# (tiling invariant, two-window burn alerting, trajectory partition),
# the sentinel audits the committed BENCH trajectory (the CPU-fallback
# rounds r06-r12 must be labelled degraded and excluded from the
# baselines, r01-r02 stay real, exit 0), and a seeded regressing
# candidate must FAIL it — a sentinel that cannot fail is decorative.
echo "== goodput gate: lint + decision-table suites =="
python -m horovod_tpu.analysis horovod_tpu/obs/goodput.py \
    horovod_tpu/obs/slo.py scripts/perf_gate.py \
    --baseline horovod_tpu/analysis/baseline.json
JAX_PLATFORMS=cpu \
    timeout 300 python -m pytest tests/test_goodput.py \
    tests/test_slo.py tests/test_perf_gate.py -x -q
echo "== goodput gate: sentinel audits the committed BENCH trajectory =="
GP_TMP=$(mktemp -d)
python scripts/perf_gate.py --records-dir . | tee "$GP_TMP/audit.txt"
python - "$GP_TMP" <<'EOF'
import sys

lines = open(f"{sys.argv[1]}/audit.txt").read().splitlines()

def bucket(rec):
    for line in lines:
        if rec in line:
            return line.split()[0]
    return None

for n in (1, 2):
    assert bucket(f"BENCH_r{n:02d}.json") == "real", n
for n in range(6, 13):
    assert bucket(f"BENCH_r{n:02d}.json") == "degraded", n
assert any(l.startswith("# baselines") for l in lines), "no baselines"
print("goodput gate: trajectory partition OK")
EOF
echo "== goodput gate: seeded regression must fail the sentinel =="
cat > "$GP_TMP/cand.json" <<'EOF'
{"metric": "resnet50_bf16_images_per_sec_per_chip", "value": 1000.0,
 "device": "TPU v5 lite",
 "provenance": {"platform": "tpu", "device_kind": "TPU v5 lite",
                "jax_platforms": ""}}
EOF
if python scripts/perf_gate.py --records-dir . \
        --candidate "$GP_TMP/cand.json" > "$GP_TMP/verdict.txt"; then
    echo "goodput gate FAILED: seeded regression passed the sentinel" >&2
    exit 1
fi
grep -q "REGRESSION" "$GP_TMP/verdict.txt" || {
    echo "goodput gate FAILED: sentinel failed without a REGRESSION verdict" >&2
    exit 1
}
rm -rf "$GP_TMP"

# Campaign gate (ISSUE 19): the resumable-campaign plane end to end.
# Lint stays clean over the new surface, the unit suite runs, then the
# acceptance chaos shape through the REAL front door (bench.py
# --campaign on a 2-point CPU spec): a seeded SIGABRT between point 1's
# journal commit and point 2's launch kills the first session; the
# journal on disk must still be schema-valid with point 1 committed and
# point 2 pending; the rerun (no fault) must resume and run ONLY point
# 2.  Every landed record must carry the step-time anatomy (components
# tiling the step within 5%) and the trend stamp, and perf_report.py
# must name the committed trajectory's degraded streak with r02 as the
# last real number.
echo "== campaign gate: lint + unit suite =="
python -m horovod_tpu.analysis horovod_tpu/bench/campaign.py \
    horovod_tpu/obs/trend.py horovod_tpu/obs/anatomy.py \
    scripts/perf_report.py \
    --baseline horovod_tpu/analysis/baseline.json
JAX_PLATFORMS=cpu \
    timeout 300 python -m pytest tests/test_campaign.py -x -q
echo "== campaign gate: seeded abort between points, then resume =="
CP_TMP=$(mktemp -d)
cat > "$CP_TMP/spec.json" <<'EOF'
{"name": "ci_campaign",
 "base_args": ["--cpu", "--model", "resnet18", "--batch-size", "4",
               "--image-size", "64", "--iters", "2", "--warmup", "1"],
 "points": [{"name": "p1", "args": []},
            {"name": "p2", "args": ["--batch-size", "8"]}],
 "retry_degraded": 0,
 "point_budget_secs": 600}
EOF
if JAX_PLATFORMS=cpu HVDTPU_RECORD_DIR="$CP_TMP/records" \
   HVDTPU_FAULT_SPEC="campaign_point:step=2:action=abort" \
       timeout 900 python bench.py --campaign "$CP_TMP/spec.json"; then
    echo "campaign gate FAILED: aborted campaign reported success" >&2
    exit 1
fi
python - "$CP_TMP/records" <<'EOF'
import json, sys
j = json.load(open(f"{sys.argv[1]}/campaign.json"))
assert j["schema"] == "hvdtpu-campaign-v1", j["schema"]
assert j["points"]["p1"]["status"] == "degraded", j["points"]["p1"]
assert j["points"]["p2"]["status"] == "pending", j["points"]["p2"]
print("campaign gate: journal survived the abort intact")
EOF
JAX_PLATFORMS=cpu HVDTPU_RECORD_DIR="$CP_TMP/records" \
    timeout 900 python bench.py --campaign "$CP_TMP/spec.json"
python - "$CP_TMP/records" <<'EOF'
import glob, json, sys
d = sys.argv[1]
j = json.load(open(f"{d}/campaign.json"))
# Resume ran ONLY the in-flight point: p1's single pre-abort attempt
# stands (retry_degraded=0), p2 completed exactly once.
assert j["points"]["p1"]["attempts"] == 1, j["points"]["p1"]
assert j["points"]["p2"]["attempts"] == 1, j["points"]["p2"]
assert j["points"]["p2"]["status"] == "degraded", j["points"]["p2"]
records = sorted(glob.glob(f"{d}/BENCH_*.json"))
assert len(records) == 2, records
for path in records:
    parsed = json.load(open(path)).get("parsed") or {}
    anatomy = parsed.get("anatomy") or {}
    tile = anatomy.get("tile_pct")
    assert tile is not None and abs(tile - 100.0) <= 5.0, (path, tile)
    assert parsed.get("trend", {}).get("verdict"), path
print("campaign gate: resume completed only point 2; every record "
      "carries anatomy + trend provenance")
EOF
echo "== campaign gate: perf_report names the degraded streak =="
python scripts/perf_report.py --records-dir . \
    --campaign "$CP_TMP/records/campaign.json" > "$CP_TMP/report.txt"
python - "$CP_TMP/report.txt" <<'EOF'
import sys
text = open(sys.argv[1]).read()
assert "10 consecutive records without a real measurement" in text, text
assert "BENCH_r02.json" in text, text
assert "ci_campaign" in text, text
print("campaign gate OK")
EOF
rm -rf "$CP_TMP"

# Post-mortem gate (ISSUE 4): a 2-proc job crashed with action=abort on
# rank 1 must leave per-rank flight-recorder dumps and a launcher-written
# postmortem.json that is schema-valid and blames the injected rank; the
# clean-run path must write NO postmortem.  /healthz is probed instead of
# sleeping before the crash run starts (satellite: KVStoreServer liveness).
echo "== postmortem gate: crashed job leaves a black box + verdict =="
PM_TMP=$(mktemp -d)
cat > "$PM_TMP/worker.py" <<'EOF'
import numpy as np
import horovod_tpu as hvd

hvd.init()
for i in range(8):
    hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name=f"t{i}")
hvd.shutdown()
EOF
python - <<'EOF'
# healthz probe: a fresh KV server must answer before any job leans on it
import json, urllib.request
from horovod_tpu.run.rendezvous import KVStoreServer
s = KVStoreServer(); s.start()
doc = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{s.port}/healthz", timeout=5).read())
assert doc["status"] == "ok", doc
s.stop()
print("healthz OK")
EOF
mkdir -p "$PM_TMP/bb"
if JAX_PLATFORMS=cpu \
   PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
   HVDTPU_FAULT_SPEC="enqueue:rank=1:step=4:action=abort" \
       python -m horovod_tpu.run -np 2 --flightrec-dump "$PM_TMP/bb" \
       python "$PM_TMP/worker.py"; then
    echo "postmortem gate FAILED: crashed job reported success" >&2
    exit 1
fi
python - "$PM_TMP/bb" <<'EOF'
import glob, json, sys
d = sys.argv[1]
dumps = glob.glob(f"{d}/flightrec.*rank*.json")
assert len(dumps) == 2, f"expected 2 per-rank black boxes, got {dumps}"
report = json.load(open(f"{d}/postmortem.json"))
assert report["schema"] == "hvdtpu-postmortem-v1", report["schema"]
ff = report["first_failure"]
assert ff["rank"] == 1, f"verdict blamed {ff['rank']}, injected rank 1"
assert ff["trigger"] == "signal:SIGABRT", ff
assert ff["last_collective"] == "t2", ff
assert "ank 1" in report["verdict"], report["verdict"]
print("postmortem gate OK:", report["verdict"])
EOF
echo "== postmortem gate: clean run writes no postmortem =="
mkdir -p "$PM_TMP/clean"
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m horovod_tpu.run -np 2 --flightrec-dump "$PM_TMP/clean" \
    python "$PM_TMP/worker.py"
if [ -e "$PM_TMP/clean/postmortem.json" ]; then
    echo "postmortem gate FAILED: clean run wrote a postmortem" >&2
    exit 1
fi
rm -rf "$PM_TMP"

# Fastpath gate (ISSUE 6): on a stable 2-proc schedule the replay epoch
# must make ≥95% of steady-state cycles skip negotiation entirely —
# counter-based (engine.stats deltas after warmup), no timing flake —
# and a seeded fault-registry delay mid-replay must break the epoch on
# every rank instead of hanging.
echo "== fastpath gate: steady-state negotiation skip + chaos break =="
FP_TMP=$(mktemp -d)
cat > "$FP_TMP/worker.py" <<'EOF'
import json, os, sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import _engine_registry

hvd.init()
for i in range(30):  # warmup: negotiate, converge, enter replay
    hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name="grad")
eng = _engine_registry.get_engine()
warm = dict(eng.stats)
for i in range(200):  # steady state: must be negotiation-free
    hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name="grad")
steady = dict(eng.stats)
doc = {"warm": warm, "steady": steady, "rank": hvd.rank()}
with open(os.path.join(sys.argv[1], f"stats.rank{hvd.rank()}.json"), "w") as f:
    json.dump(doc, f)
hvd.shutdown()
EOF
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
HVDTPU_EAGER_ENGINE=python \
HVDTPU_EAGER_DEVICE=0 \
HVDTPU_SCHEDULE_REPLAY_CYCLES=5 \
HVDTPU_CYCLE_TIME=2 \
    timeout 180 python -m horovod_tpu.run -np 2 python "$FP_TMP/worker.py" "$FP_TMP"
python - "$FP_TMP" <<'EOF'
import glob, json, sys

dumps = sorted(glob.glob(f"{sys.argv[1]}/stats.rank*.json"))
assert len(dumps) == 2, dumps
for p in dumps:
    doc = json.load(open(p))
    warm, steady = doc["warm"], doc["steady"]
    assert steady["replay_epochs"] >= 1, steady
    d_cycles = steady["cycles"] - warm["cycles"]
    d_neg = steady["negotiated_cycles"] - warm["negotiated_cycles"]
    assert d_cycles > 0, (warm, steady)
    ratio = d_neg / d_cycles
    assert ratio <= 0.05, (
        f"rank {doc['rank']}: {d_neg}/{d_cycles} steady-state cycles "
        f"negotiated ({ratio:.1%} > 5%)")
    print(f"fastpath gate rank {doc['rank']}: {d_neg}/{d_cycles} "
          f"steady-state cycles negotiated ({ratio:.1%})")
EOF
echo "== fastpath gate: seeded delay breaks the epoch on every rank =="
cat > "$FP_TMP/chaos.py" <<'EOF'
import json, os, sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import _engine_registry

hvd.init()
for i in range(60):
    out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="grad")
    assert float(out[0]) == 2.0
eng = _engine_registry.get_engine()
doc = {"stats": dict(eng.stats), "rank": hvd.rank()}
with open(os.path.join(sys.argv[1], f"chaos.rank{hvd.rank()}.json"), "w") as f:
    json.dump(doc, f)
hvd.shutdown()
EOF
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
HVDTPU_EAGER_ENGINE=python \
HVDTPU_EAGER_DEVICE=0 \
HVDTPU_SCHEDULE_REPLAY_CYCLES=5 \
HVDTPU_CYCLE_TIME=2 \
HVDTPU_STALL_CHECK_TIME_SECONDS=1 \
HVDTPU_FAULT_SPEC="enqueue:rank=1:step=30:action=delay:2500" \
    timeout 120 python -m horovod_tpu.run -np 2 python "$FP_TMP/chaos.py" "$FP_TMP"
python - "$FP_TMP" <<'EOF'
import glob, json, sys

dumps = sorted(glob.glob(f"{sys.argv[1]}/chaos.rank*.json"))
assert len(dumps) == 2, dumps
for p in dumps:
    doc = json.load(open(p))
    s = doc["stats"]
    assert s["replay_epochs"] >= 1, s
    assert s["replay_breaks"] >= 1, (
        f"rank {doc['rank']} never broke its replay epoch: {s}")
    print(f"fastpath chaos rank {doc['rank']}: {s['replay_breaks']} "
          f"break(s), {s['replay_cycles']} replay cycles — no hang")
EOF
rm -rf "$FP_TMP"

# Checkpoint/recovery gate (ISSUE 7): the ckpt unit suite, hvdtpu-lint
# clean over the new subsystem specifically, and a 2-proc elastic chaos
# run — a seeded mid-epoch kill must be recovered by the respawned
# incarnation restoring from its peer's IN-MEMORY replica (provenance
# says peer, the replica specifically, never disk) inside the recovery
# budget, the job must finish with the right state, and the sharded
# manifest written along the way must be schema-valid.
echo "== ckpt gate: unit suite + lint over the subsystem =="
python -m pytest tests/test_ckpt.py -x -q
python -m horovod_tpu.analysis horovod_tpu/ckpt \
    --baseline horovod_tpu/analysis/baseline.json
echo "== ckpt gate: chaos — peer-sourced restore within budget =="
CK_TMP=$(mktemp -d)
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    timeout 180 python - "$CK_TMP" <<'EOF'
import sys

import numpy as np

import horovod_tpu.elastic as elastic
from horovod_tpu import ckpt

tmp = sys.argv[1]
ckpt_dir = f"{tmp}/shards"


def train(total_steps=8, directory=ckpt_dir):
    import numpy as np  # noqa: PLC0415

    import horovod_tpu.elastic as elastic  # noqa: PLC0415

    ctx = elastic.context()
    state = elastic.State(w=np.zeros(4, dtype=np.float64), step=0)

    @elastic.run
    def loop(state):
        while state.step < total_steps:
            grad = np.full(4, float(state.step + 1) * (ctx.rank + 1))
            state.w = state.w - 0.1 * ctx.allreduce(
                grad, name=f"g{state.step}")
            state.step += 1
            state.commit()
            if state.step == 2:
                # disk tier: every rank writes only its own shard,
                # rank 0 commits the manifest last
                state.save_sharded(directory).wait()
        return state.step, state.last_restore

    return loop(state)


env = {"JAX_PLATFORMS": "cpu", "HVDTPU_CKPT_REPLICA": "1",
       "HVDTPU_CKPT_DIR": ckpt_dir,
       "HVDTPU_FAULT_SPEC": "worker_exit:step=5:rank=1"}
results, job = elastic.launch(train, np=2, env=env, max_retries=2,
                              timeout=120)

assert sorted(results) == [0, 1], results
assert all(results[r][0] == 8 for r in results), results
assert [e[0] for e in job.trace].count("respawn") == 1, job.trace

prov = results[1][1]
assert prov and prov["source"] == "peer", (
    f"respawned rank restored from {prov}, expected the peer tier")
assert prov["replica_adopted"] is True, (
    f"restore did not come from the in-memory replica: {prov}")
assert prov["ms"] < 10_000, f"recovery took {prov['ms']:.0f} ms"

manifest = ckpt.load_manifest(ckpt_dir, 2)
assert manifest is not None, "no committed manifest at step 2"
assert manifest["schema"] == "hvdtpu-sharded-ckpt-v1", manifest["schema"]
assert manifest["world_size"] == 2, manifest
assert len(manifest["shards"]) == 2, manifest
for s in manifest["shards"]:
    assert len(s["checksum"]) == 64, s
owned = sorted(i for s in manifest["shards"] for i in s["leaves"])
assert owned == list(range(manifest["num_leaves"])), manifest
state = ckpt.restore_sharded(ckpt_dir, step=2)
print(f"ckpt gate OK: rank 1 restored from its peer replica in "
      f"{prov['ms']:.0f} ms; manifest valid "
      f"({manifest['num_leaves']} leaves over 2 shards)")
EOF
rm -rf "$CK_TMP"

# Multislice gate (ISSUE 8): a forced 2-slice world's engine allreduce
# must (a) actually run the hierarchical two-fabric path — per-fabric
# byte counters nonzero with dcn_bytes == ici_bytes / slice_procs,
# (b) produce results identical to a flat run of the same payloads
# (integer-valued floats sum exactly in any association order), and
# (c) turn a seeded slice-local delay into a slice-level straggler
# verdict through the shared blame merger.
echo "== multislice gate: hierarchical two-fabric collectives =="
MS_TMP=$(mktemp -d)
cat > "$MS_TMP/worker.py" <<'EOF'
import json, os, sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu._engine_registry import peek_engine
from horovod_tpu.obs import get_registry

hvd.init()
r = hvd.rank()
outs = []
for i in range(8):
    out = hvd.allreduce(np.arange(16, dtype=np.float32) * (i + 1) + r,
                        op=hvd.Sum, name=f"g{i}")
    outs.append(np.asarray(out).tolist())
eng = peek_engine()
counters = {m["name"]: m.get("value") for m in get_registry().snapshot()
            if not m.get("tags")}
doc = {
    "rank": r, "slice": hvd.slice_id(), "num_slices": hvd.num_slices(),
    "hier": bool(eng and eng.hierarchical), "outs": outs,
    "dcn": counters.get("engine.dcn_bytes", 0),
    "ici": counters.get("engine.ici_bytes", 0),
    "metrics": get_registry().snapshot(),
}
with open(os.path.join(sys.argv[2], f"{sys.argv[1]}.rank{r}.json"), "w") as f:
    json.dump(doc, f)
hvd.shutdown()
EOF
MS_COMMON_ENV="JAX_PLATFORMS=cpu HVDTPU_EAGER_ENGINE=python HVDTPU_CYCLE_TIME=2"
env $MS_COMMON_ENV \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    XLA_FLAGS="--xla_force_host_platform_device_count=1" \
    HVDTPU_SLICE_SIZE=2 HVDTPU_HIERARCHICAL_ALLREDUCE=1 \
    timeout 180 python -m horovod_tpu.run -np 4 \
    python "$MS_TMP/worker.py" hier "$MS_TMP"
# same forced partition, flat schedule: the multislice world the
# hierarchical run is judged against (and the full-tensor DCN cost)
env $MS_COMMON_ENV \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    XLA_FLAGS="--xla_force_host_platform_device_count=1" \
    HVDTPU_SLICE_SIZE=2 \
    timeout 180 python -m horovod_tpu.run -np 4 \
    python "$MS_TMP/worker.py" flat "$MS_TMP"
echo "== multislice gate: seeded slice-local delay -> slice verdict =="
env $MS_COMMON_ENV \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    XLA_FLAGS="--xla_force_host_platform_device_count=1" \
    HVDTPU_SLICE_SIZE=2 HVDTPU_HIERARCHICAL_ALLREDUCE=1 \
    HVDTPU_FAULT_SPEC="enqueue:rank=2:count=6:action=delay:400" \
    timeout 180 python -m horovod_tpu.run -np 4 \
    python "$MS_TMP/worker.py" chaos "$MS_TMP"
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" python - "$MS_TMP" <<'EOF'
import glob, json, sys

from horovod_tpu.obs import straggler as obs_straggler

tmp = sys.argv[1]


def load(tag):
    docs = [json.load(open(p))
            for p in sorted(glob.glob(f"{tmp}/{tag}.rank*.json"))]
    assert len(docs) == 4, (tag, docs)
    return sorted(docs, key=lambda d: d["rank"])


hier, flat, chaos = load("hier"), load("flat"), load("chaos")
for r in range(4):
    h = hier[r]
    assert h["num_slices"] == 2 and h["slice"] == r // 2, h
    assert h["hier"], "hierarchical path not selected"
    # (a) the two-fabric path executed, with the 1/slice_procs DCN story
    assert h["dcn"] > 0 and h["ici"] > 0, (h["dcn"], h["ici"])
    assert h["dcn"] * 2 == h["ici"], (h["dcn"], h["ici"])
    # (b) bitwise-identical to the flat run
    assert h["outs"] == flat[r]["outs"], f"rank {r}: hier != flat"
    # flat multislice pays full-tensor cost on the slow fabric
    assert flat[r]["dcn"] > 0 and flat[r]["ici"] == 0, flat[r]["dcn"]
# (c) slice-level straggler verdict from the seeded slice-1 delay
verdict = obs_straggler.merge_blames([d["metrics"] for d in chaos])
assert verdict is not None, "no straggler attribution recorded"
assert verdict["rank"] == 2, verdict
assert verdict.get("slice") == 1, verdict
print(f"multislice gate OK: dcn/ici = {hier[0]['dcn']}/{hier[0]['ici']} "
      f"(= 1/slice_procs), hier == flat bitwise, "
      f"slice verdict: slice {verdict['slice']} "
      f"({verdict['slice_blames']})")
EOF
rm -rf "$MS_TMP"

# Overlap gate (ISSUE 9): the backward-overlap gradient plane on a
# 4-device CPU mesh must (a) schedule per-bucket collectives INSIDE the
# backward — inspector-verified >=2 gradient collectives before the
# last backward compute op, while the off-mode module reads as one
# monolithic end-of-backward psum — (b) produce training bitwise-equal
# to off for both bucket and bucket+zero1, and (c) land a BENCH record
# (degraded allowed on CPU) with the overlap stats embedded.
echo "== overlap gate: in-backward bucketed collectives =="
OV_TMP=$(mktemp -d)
JAX_PLATFORMS=cpu \
XLA_FLAGS="--xla_force_host_platform_device_count=4" \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    timeout 300 python - <<'EOF'
import jax, jax.numpy as jnp, numpy as np, optax
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.optim import overlap
from horovod_tpu.ops.collectives import shard_map_compat

mesh = Mesh(np.asarray(jax.devices(), dtype=object).reshape(4),
            (hvd.DP_AXIS,))

def init_params(key):
    sizes = [32, 64, 37, 64, 10]
    params = []
    for i in range(4):
        k, key = jax.random.split(key)
        params.append({"w": jax.random.normal(k, (sizes[i], sizes[i+1])) * .1,
                       "b": jnp.zeros(sizes[i+1])})
    return params

def loss_fn(params, x, y):
    h = x
    for i, layer in enumerate(params):
        h = h @ layer["w"] + layer["b"]
        if i < 3:
            h = jax.nn.relu(h)
    return jnp.mean((h - y) ** 2)

params = init_params(jax.random.PRNGKey(0))
x = jax.random.normal(jax.random.PRNGKey(1), (16, 32))
y = jax.random.normal(jax.random.PRNGKey(2), (16, 10))
tx = optax.sgd(0.05, momentum=0.9)

results, reports = {}, {}
for mode in overlap.MODES:
    plan = overlap.OverlapPlan(params, tx, mode=mode, mesh=mesh,
                               bucket_mb=8 / 1024.0)
    spec = plan.state_spec()
    step = jax.jit(shard_map_compat(
        plan.local_step(loss_fn), mesh=mesh,
        in_specs=(spec, P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
        out_specs=(spec, P()),
    ), donate_argnums=(0,))
    state = plan.init(params)
    reports[mode] = overlap.inspect_schedule(step.lower(state, x, y))
    for _ in range(4):
        state, loss = step(state, x, y)
    results[mode] = jax.tree_util.tree_leaves(plan.materialize(state))

# (a) per-bucket collectives inside the backward, not one monolithic psum
rep, rep_off = reports["bucket"], reports["off"]
assert rep.gradient_collectives >= 3, rep.as_dict()
assert rep.in_backward >= 2, rep.as_dict()
assert rep_off.gradient_collectives == 1 and rep_off.monolithic, \
    rep_off.as_dict()
# (b) bitwise-equal training
for mode in ("bucket", "bucket+zero1"):
    for a, b in zip(results["off"], results[mode]):
        assert bool(jnp.all(a == b)), f"{mode} diverged from off"
print(f"overlap gate OK: bucket={rep.as_dict()} off={rep_off.as_dict()}, "
      f"bucket/bucket+zero1 bitwise == off over 4 steps")
EOF
# (c) a BENCH record lands with the overlap stats embedded
JAX_PLATFORMS=cpu \
XLA_FLAGS="--xla_force_host_platform_device_count=4" \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
HVDTPU_BENCH_RECORD_DIR="$OV_TMP" \
    timeout 540 python bench.py --cpu --model resnet18 --image-size 64 \
    --batch-size 2 --iters 2 --warmup 1 --overlap bucket \
    --grad-bucket-mb 4 > "$OV_TMP/bench.out"
python - "$OV_TMP" <<'EOF'
import glob, json, sys

recs = sorted(glob.glob(f"{sys.argv[1]}/BENCH_*.json"))
assert recs, "overlap bench landed no BENCH record"
doc = json.load(open(recs[-1]))
parsed = doc.get("parsed") or {}
gauges = parsed.get("engine_gauges") or {}
assert parsed.get("overlap_mode") == "bucket", parsed
assert gauges.get("overlap_mode") == "bucket", gauges
assert gauges.get("overlap.buckets", 0) >= 2, gauges
bb = gauges.get("overlap_bucket_bytes")
assert bb and len(bb) == int(gauges["overlap.buckets"]), gauges
assert parsed.get("donation", {}).get("ok") is True, parsed
print(f"overlap bench record OK: {len(bb)} buckets, "
      f"donation {parsed['donation']['donated']}/"
      f"{parsed['donation']['expected']}")
EOF
rm -rf "$OV_TMP"

# HLO schedule-diff gate (ISSUE 12): every rank must COMPILE the same
# collective sequence for the engine fused-allreduce, the overlap
# bucket train step, and the serve sequence-sharded decode step — the
# artifact-level form of the HVD001/HVD010 invariant.  Each simulated
# rank compiles in its own process with rank-specific env; the checker
# diffs op kinds, order, replica groups, and operand bytes.  The
# --seed-divergence self-test plants a rank-guarded collective and
# requires the gate to reject it, so "gate passed" can never mean
# "checker was blind".
echo "== hlo gate: cross-rank collective-schedule diff =="
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    timeout 580 python scripts/hlo_gate.py
echo "== hlo gate: seeded divergence self-test =="
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    timeout 580 python scripts/hlo_gate.py --seed-divergence

# Serve gate (ISSUE 10): the continuous-batching serving plane.  The
# unit suite + hvdtpu-lint over the new subsystem, then one 2-proc
# acceptance run: staggered mixed-length requests through a live fleet
# with live telemetry armed — continuous admission must be observable
# (a request admitted after step 0 completes), the serve gauges must
# appear in a mid-run /metrics scrape, a deterministically killed
# serving rank must respawn and replay its in-flight requests (zero
# dropped, tokens bitwise-equal to single-stream generate), and
# `bench.py --serve` must land a BENCH record with latency percentiles.
echo "== serve gate: unit suite + lint over the subsystem =="
# slow-marked multi-proc acceptances are excluded from tier-1's budget
# (-m 'not slow') and run HERE by node id — the gate is their home.
python -m pytest tests/test_serve.py -x -q -m "not slow"
python -m pytest \
    "tests/test_serve.py::test_serve_job_staggered_requests_and_rejection" \
    "tests/test_serve.py::test_serve_chaos_kill_leader_respawn_zero_dropped" \
    -x -q
python -m horovod_tpu.analysis horovod_tpu/serve \
    --baseline horovod_tpu/analysis/baseline.json
echo "== serve gate: 2-proc continuous batching + chaos respawn + scrape =="
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    timeout 300 python - <<'EOF'
import time
import urllib.request

import jax.numpy as jnp
import numpy as np

from horovod_tpu.models.decode import generate
from horovod_tpu.models.transformer import gpt
from horovod_tpu.serve import ServeJob

overrides = dict(num_layers=1, num_heads=2, emb_dim=32, max_len=64,
                 vocab_size=64, dtype=jnp.float32,
                 attention_impl="reference")
spec = {"size": "nano", "overrides": overrides, "seed": 3,
        "num_slots": 2, "idle_secs": 0.005}
model = gpt("nano", **overrides)
import jax
params = model.init(jax.random.PRNGKey(3), jnp.zeros((1, 8), jnp.int32))

rs = np.random.RandomState(7)
prompts = [rs.randint(0, 64, rs.randint(3, 9)).tolist() for _ in range(8)]
steps = [3, 4, 5, 6, 3, 4, 5, 6]
oracle = [np.asarray(generate(model.cfg, params,
                              jnp.asarray([p], jnp.int32), s))[0].tolist()
          for p, s in zip(prompts, steps)]

# Kill the LEADER mid-stream: rank 0 is the only rank that reads the
# ingest log and writes result streams, and its step 6 is
# deterministically mid-stream (8 requests x >=3 tokens through 2
# slots need far more busy steps than 6).
job = ServeJob(
    spec, np=2,
    env={"JAX_PLATFORMS": "cpu",
         "HVDTPU_FAULT_SPEC": "worker_exit:step=6:rank=0"},
    max_retries=2, live_stats_secs=0.2, timeout=240,
).start()
rids = []
for p, s in zip(prompts, steps):
    rids.append(job.client.submit(p, max_new_tokens=s))
    time.sleep(0.05)  # staggered arrivals -> admissions mid-stream

# mid-run /metrics scrape: serve gauges must be present while slots
# are still churning (they stream as deltas, so poll until all four
# series have landed)
WANT = ("hvdtpu_serve_queue_depth", "hvdtpu_serve_active_slots",
        "hvdtpu_serve_admitted", "hvdtpu_serve_tokens_per_sec",
        # Memory plane (ISSUE 14): KV occupancy must stream live —
        # the paged-attention baseline is read off a running fleet.
        "hvdtpu_serve_kv_waste_ratio",
        # Paged KV (ISSUE 15): the page pool the admission gate judges
        # capacity in must be observable mid-run.
        "hvdtpu_serve_kv_page_size", "hvdtpu_serve_kv_page_free",
        "hvdtpu_serve_kv_page_used")
deadline = time.monotonic() + 120
serve_series = []
while time.monotonic() < deadline:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{job.port}/metrics", timeout=5
    ).read().decode()
    serve_series = [l for l in body.splitlines()
                    if l.startswith("hvdtpu_serve_")]
    if all(any(l.startswith(w) for l in serve_series) for w in WANT):
        break
    time.sleep(0.3)
for want in WANT:
    assert any(l.startswith(want) for l in serve_series), (
        f"{want} missing from the mid-run /metrics scrape")

docs = [job.client.result(r, timeout=180) for r in rids]
results, ejob = job.stop()

# zero dropped, bitwise-equal tokens per request
for i, d in enumerate(docs):
    assert d["tokens"] == oracle[i], (
        f"request {i} tokens {d['tokens']} != oracle {oracle[i]}")
# continuous admission: some request entered after serving had begun
assert max(d["admitted_step"] for d in docs) > 1, docs
# the injected kill was recovered by respawn, and work finished in the
# post-recovery epoch
events = [e[0] for e in ejob.trace]
assert events.count("failure") == 1 and events.count("respawn") == 1, \
    ejob.trace
assert max(d["epoch"] for d in docs) >= 1, docs
assert sorted(results) == [0, 1], results
print(f"serve gate OK: 8/8 requests exact through the chaos run, "
      f"{len(serve_series)} serve series scraped, trace {ejob.trace}")
EOF
echo "== serve gate: bench --serve lands a latency-percentile record =="
SV_TMP=$(mktemp -d)
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
HVDTPU_BENCH_RECORD_DIR="$SV_TMP" \
    timeout 300 python bench.py --serve --cpu \
    --serve-requests 6 --serve-rate 6 > "$SV_TMP/bench.out"
python - "$SV_TMP" <<'EOF'
import glob, json, sys

recs = sorted(glob.glob(f"{sys.argv[1]}/BENCH_*.json"))
assert recs, "bench --serve landed no BENCH record"
doc = json.load(open(recs[-1]))
parsed = doc.get("parsed") or {}
serve = parsed.get("serve") or {}
assert parsed.get("metric") == "serve_nano_tokens_per_sec", parsed
for h in ("ttft_ms", "tpot_ms"):
    for q in ("p50", "p90", "p99"):
        assert isinstance(serve.get(h, {}).get(q), (int, float)), (h, q)
assert serve.get("requests") == 6, serve
assert doc.get("degraded") is True  # CPU numbers are placeholders
# Paged KV waste gate (ISSUE 15): on the bench's mixed-length workload
# the paged pool's busy-step waste must stay within the partial-last-
# page bound — against a PR-14 contiguous baseline of ~0.85 recomputed
# on the same traffic (embedded alongside it in the record).
kv = serve.get("kv") or {}
assert kv.get("mode") == "paged", kv
assert kv.get("waste_ratio_mean") is not None \
    and kv["waste_ratio_mean"] <= 0.15, kv
assert kv.get("contiguous_equiv_waste_mean", 0) > 0.3, kv
print(f"serve bench record OK: {parsed['value']} tok/s, "
      f"ttft p50 {serve['ttft_ms']['p50']}ms, "
      f"kv waste {kv['waste_ratio_mean']} "
      f"(contiguous-equivalent {kv['contiguous_equiv_waste_mean']})")
EOF
rm -rf "$SV_TMP"

# Paged KV + width-sharded fleet gate (ISSUE 15): unit suite for the
# allocator/paged-decode/width/sampling planes, the slow-marked fleet
# acceptance by node id (np=2 width=1 -> two serving groups over the
# log partition, leader of group 1 killed mid-stream, greedy AND
# sampled streams 8/8 bitwise vs the single-engine oracle), and the
# compiled-HLO schedule diff across simulated ranks for the width-
# sharded paged decode program (scripts/hlo_gate.py runs in the full
# tier's hlo gate; the width program rides it).
echo "== paged gate: allocator + paged decode + width + sampling =="
python -m pytest tests/test_paged.py -x -q
echo "== paged gate: width-fleet chaos acceptance (by node id) =="
python -m pytest \
    "tests/test_serve.py::test_serve_width_fleet_partition_chaos_and_sampling" \
    -x -q

# Autoscale + hot-swap gate (ISSUE 13): the train→serve loop closed
# without a restart.  hvdtpu-lint clean over the new serve files (the
# poll-and-flip decision must derive from shared data only —
# HVD001/HVD010-013), the pure decision-table suite, then the two
# chaos acceptances: (1) load-driven grow through a re-minted epoch
# with in-flight requests bitwise-equal to an uninterrupted run,
# followed by a drain-driven release (cooldown respected in the
# decision trace, zero drops, no flapping); (2) a rank killed between
# shard prefetch and version flip (swap_commit/action=swap_abort) —
# the fleet converges on exactly ONE weight version (the durable flip
# record), 8/8 requests complete with oracle-exact tokens.
echo "== autoscale_swap gate: lint + decision-table suite =="
python -m horovod_tpu.analysis \
    horovod_tpu/serve/autoscale.py horovod_tpu/serve/hotswap.py \
    horovod_tpu/serve/service.py horovod_tpu/serve/frontend.py \
    --baseline horovod_tpu/analysis/baseline.json
JAX_PLATFORMS=cpu \
    timeout 300 python -m pytest tests/test_autoscale_swap.py \
    -x -q -m "not multiprocess"
echo "== autoscale_swap gate: grow-under-load + drain-release =="
JAX_PLATFORMS=cpu \
    timeout 400 python -m pytest \
    "tests/test_autoscale_swap.py::test_autoscale_grow_under_load_then_drain_release" \
    -x -q
echo "== autoscale_swap gate: mid-swap kill -> one version, 8/8 =="
JAX_PLATFORMS=cpu \
    timeout 400 python -m pytest \
    "tests/test_autoscale_swap.py::test_chaos_kill_mid_swap_converges_on_one_version" \
    "tests/test_autoscale_swap.py::test_log_compaction_bounds_store_and_replay" \
    -x -q

# Front-door gate (ISSUE 16): the sharded, supervised request plane +
# tenant-aware QoS.  hvdtpu-lint stays clean over the scheduler (the
# tenant pick must be a pure fold over the ordered log — HVD001/012),
# the fast decision-table suite (QoS table incl. the FCFS-degenerate
# byte-identity, machine-readable rejection codes, FrontDoor takeover
# on a bare KV store with no drop and no double-ingest, multi-shard
# recovery interleave, client poll backoff), then the two chaos
# acceptances by node id: (1) F=2 mixed-tenant fleet, frontend 0
# killed abruptly mid-stream — the survivor adopts its shard, the
# elastic monitor re-mints the epoch, and 8/8 requests complete
# bitwise-equal to the single-stream oracle; (2) a flooding batch
# tenant is budget-throttled (throttle counter lands in the drain
# summary) while its interactive victims all complete promptly with
# oracle tokens.
echo "== frontdoor gate: lint + decision-table suite =="
python -m horovod_tpu.analysis \
    horovod_tpu/serve/scheduler.py horovod_tpu/serve/frontend.py \
    horovod_tpu/serve/service.py \
    --baseline horovod_tpu/analysis/baseline.json
JAX_PLATFORMS=cpu \
    timeout 300 python -m pytest tests/test_frontdoor.py \
    -x -q -m "not slow"
echo "== frontdoor gate: kill-a-frontend chaos -> zero drops, bitwise =="
JAX_PLATFORMS=cpu \
    timeout 400 python -m pytest \
    "tests/test_frontdoor.py::test_frontdoor_kill_frontend_mid_stream_zero_drops_bitwise" \
    -x -q
echo "== frontdoor gate: noisy tenant throttled, victims complete =="
JAX_PLATFORMS=cpu \
    timeout 400 python -m pytest \
    "tests/test_frontdoor.py::test_frontdoor_noisy_tenant_throttled_victims_complete" \
    -x -q

# Trace gate (ISSUE 11): request-level tracing + the live MFU
# profiler.  The unit suite + hvdtpu-lint over the new obs files, a
# 2-proc training smoke through the real launcher CLI with --trace
# (engine negotiate/execute spans from BOTH ranks must land on the
# merged waterfall, and the launcher's end-of-job merge must write a
# schema-valid decomposition report), and the 2-proc serve chaos
# acceptance: leader killed mid-stream, the replayed request's spans
# from both incarnations appear stitched by epoch, every decomposed
# ttft's components sum to the histogram's sample within 5%, and the
# per-rank record embeds a cost_analysis()-derived perf.mfu
# (estimate-flagged on CPU).
echo "== trace gate: unit suite + lint over the tracing/profiler surface =="
python -m pytest tests/test_trace.py -x -q -m "not multiprocess"
python -m horovod_tpu.analysis horovod_tpu/obs/trace.py \
    horovod_tpu/obs/trace_merge.py horovod_tpu/obs/profile.py \
    --baseline horovod_tpu/analysis/baseline.json
echo "== trace gate: 2-proc launcher smoke with --trace -> engine lanes =="
TR_TMP=$(mktemp -d)
cat > "$TR_TMP/worker.py" <<'EOF'
import numpy as np
import horovod_tpu as hvd

hvd.init()
for i in range(4):
    hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name=f"t{i}")
hvd.shutdown()
EOF
# both engines must land engine-lane spans: the python engine records
# the negotiate/execute split, the native engine per-op
# enqueue->completion spans (its negotiation runs inside the C++ lib)
for ENGINE in python auto; do
    rm -f "$TR_TMP"/spans.*.json "$TR_TMP"/trace_*.json
    JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    HVDTPU_EAGER_ENGINE="$ENGINE" \
        timeout 300 python -m horovod_tpu.run -np 2 --trace "$TR_TMP/" \
        python "$TR_TMP/worker.py"
    python - "$TR_TMP" "$ENGINE" <<'EOF'
import glob, json, sys

d, engine = sys.argv[1], sys.argv[2]
rank_files = glob.glob(f"{d}/spans.*rank*.json")
assert len(rank_files) >= 2, f"expected 2 per-rank span files: {rank_files}"
wf = json.load(open(f"{d}/trace_waterfall.json"))
xs = [e for e in wf if e.get("ph") == "X"]
assert {e["args"]["rank"] for e in xs} >= {"0", "1"}, (
    "waterfall is missing a rank's spans")
lanes = {m["args"]["name"] for m in wf
         if m.get("ph") == "M" and m["name"] == "process_name"}
assert "engine" in lanes, f"no engine step lane, lanes={lanes}"
want = ("negotiate", "execute") if engine == "python" else \
    ("negotiate", "execute", "collective")
assert any(e["name"] in want for e in xs), "no engine-lane spans"
rep = json.load(open(f"{d}/trace_report.json"))
assert rep["schema"] == "hvdtpu-trace-report-v1", rep["schema"]
assert rep["missing_ranks"] == [], rep["missing_ranks"]
print(f"trace gate OK ({engine} engine): {len(xs)} spans across "
      f"lanes {sorted(lanes)}")
EOF
done
rm -rf "$TR_TMP"
echo "== trace gate: 2-proc serve chaos -> stitched waterfall + ttft decomposition + mfu =="
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    timeout 420 python -m pytest \
    "tests/test_trace.py::test_trace_acceptance_leader_kill_waterfall_and_mfu" \
    -x -q

# Memory gate (ISSUE 14): the HBM memory plane.  hvdtpu-lint clean
# over the new surface, the unit suite, then the two artifact gates:
# every collective-bearing program's per-device footprint must stay
# under the committed memory_budget.json ceiling (and a seeded 64x
# oversized program must be rejected — a budget that cannot fail is
# decorative), the PR-9 ZeRO-1 claim is asserted from the compiled
# programs' input buffers (optimizer-state bytes under bucket+zero1
# <= 1/world + eps of bucket mode on the 8-device mesh), and the OOM
# chaos acceptance: a seeded backend-shaped RESOURCE_EXHAUSTED on one
# rank must leave a postmortem whose verdict names the dying rank AND
# its dominant memory owner.
echo "== mem gate: lint + unit suite =="
python -m horovod_tpu.analysis horovod_tpu/obs/memplane.py \
    scripts/mem_gate.py \
    --baseline horovod_tpu/analysis/baseline.json
JAX_PLATFORMS=cpu \
    timeout 300 python -m pytest tests/test_memplane.py -q \
    -m "not multiprocess and not slow"
echo "== mem gate: compile-heavy coverage (slot-engine kv + 8-dev zero1) =="
JAX_PLATFORMS=cpu \
    timeout 400 python -m pytest \
    "tests/test_memplane.py::test_slot_engine_kv_stats_match_hand_computed" \
    "tests/test_memplane.py::test_zero1_budget_math_on_8_device_mesh" \
    -x -q
echo "== mem gate: per-program budget + zero1 ratio from the artifact =="
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    timeout 580 python scripts/mem_gate.py
echo "== mem gate: seeded budget violation must fail =="
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    timeout 580 python scripts/mem_gate.py --seed-violation
echo "== mem gate: OOM chaos -> postmortem names rank + dominant owner =="
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    timeout 400 python -m pytest \
    "tests/test_memplane.py::test_oom_chaos_postmortem_names_rank_and_owner" \
    -x -q

# Health gate (ISSUE 18): the training-health plane must (a) pass its
# unit suite, (b) leave the compiled step HLO byte-identical when
# --health is off, and (c) survive the SDC chaos proof — a seeded
# single-bit exponent flip on rank 1's copy of the 6th reduced gradient
# (training step 2, leaf w2 — bucket 0 in the reverse-topological
# layout) must be localized by the divergence
# sentinel to that exact rank + bucket + leaf within one check interval,
# halt every rank, and be named in the postmortem verdict.  A clean run
# of the same worker must alert nothing and write no postmortem.
echo "== health gate: unit suite =="
JAX_PLATFORMS=cpu \
    timeout 300 python -m pytest tests/test_health.py -x -q
echo "== health gate: --health off leaves compiled HLO unchanged =="
JAX_PLATFORMS=cpu \
    timeout 300 python -m pytest \
    "tests/test_health.py::test_health_off_leaves_compiled_hlo_byte_identical" \
    -x -q
echo "== health gate: SDC chaos -> sentinel names rank 1 + leaf w2 =="
HL_TMP=$(mktemp -d)
cat > "$HL_TMP/worker.py" <<'EOF'
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.obs import divergence
from horovod_tpu.obs.health import HealthConfig
from horovod_tpu.optim.overlap import build_layout

hvd.init()
params = {"w0": np.zeros(4, np.float32),
          "w1": np.zeros(4, np.float32),
          "w2": np.zeros(4, np.float32)}
names = sorted(params)                 # tree_flatten order: w0, w1, w2
leaves = [params[n] for n in names]
layout = build_layout(params, 16)      # 16B buckets: one leaf per bucket
cfg = HealthConfig.from_env()
sentinel = divergence.DivergenceSentinel(
    layout, rank=hvd.rank(), check_steps=cfg.check_steps,
    action=cfg.divergence_action, leaf_names=names)
for step in range(1, 9):
    # grad_ready collective seq: step 1 -> 1,2,3; step 2 -> 4,5,6, so
    # the seeded seq-6 flip lands on rank 1's copy of w2 — bucket 0,
    # since build_layout packs in reverse flatten order.
    for i, leaf in enumerate(leaves):
        leaf += np.asarray(
            hvd.allreduce(np.full(4, 0.1, np.float32), op=hvd.Sum,
                          name=f"g{i}"))
    sentinel.maybe_check(step, leaves)
hvd.shutdown()
EOF
mkdir -p "$HL_TMP/bb"
if JAX_PLATFORMS=cpu \
   PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
   HVDTPU_HEALTH=on HVDTPU_HEALTH_CHECK_STEPS=4 \
   HVDTPU_DIVERGENCE_ACTION=halt \
   HVDTPU_FAULT_SPEC="grad_ready:rank=1:step=6:action=flip_bits" \
       timeout 300 python -m horovod_tpu.run -np 2 \
       --flightrec-dump "$HL_TMP/bb" python "$HL_TMP/worker.py"; then
    echo "health gate FAILED: corrupted job reported success" >&2
    exit 1
fi
python - "$HL_TMP/bb" <<'EOF'
import glob, json, sys
d = sys.argv[1]
dumps = glob.glob(f"{d}/flightrec.*rank*.json")
assert len(dumps) == 2, f"expected 2 per-rank black boxes, got {dumps}"
events = [e for p in dumps for e in json.load(open(p))["events"]
          if e["kind"] == "health.divergence"]
assert events, "no health.divergence flightrec event recorded"
for ev in events:
    fields = dict(kv.split("=", 1) for kv in ev["detail"].split())
    assert fields["minority"] == "1", ev
    assert fields["bucket"] == "0", ev
    assert fields["leaf"] == "w2", ev
    assert ev["cycle"] == 4, ev  # first check interval after the flip
report = json.load(open(f"{d}/postmortem.json"))
v = report["verdict"]
assert "TRAINING-STATE DIVERGENCE" in v, v
assert "rank(s) 1" in v and "bucket0" in v and "w2" in v, v
print("health gate OK:", v.splitlines()[0])
EOF
echo "== health gate: clean run alerts nothing =="
mkdir -p "$HL_TMP/clean"
JAX_PLATFORMS=cpu \
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
HVDTPU_HEALTH=on HVDTPU_HEALTH_CHECK_STEPS=4 \
HVDTPU_DIVERGENCE_ACTION=halt \
    timeout 300 python -m horovod_tpu.run -np 2 \
    --flightrec-dump "$HL_TMP/clean" python "$HL_TMP/worker.py"
if [ -e "$HL_TMP/clean/postmortem.json" ]; then
    echo "health gate FAILED: clean run wrote a postmortem" >&2
    exit 1
fi
if grep -l "health.divergence\|health.alert" \
        "$HL_TMP"/clean/flightrec.*rank*.json 2>/dev/null; then
    echo "health gate FAILED: clean run recorded a health alert" >&2
    exit 1
fi
rm -rf "$HL_TMP"

# Elastic chaos smoke through the real launcher: a rank is killed
# deterministically mid-training (HVDTPU_FAULT_SPEC), the job must
# recover via rollback + respawn (the example asserts it did).
echo "== elastic chaos smoke: recovery after injected worker death =="
JAX_PLATFORMS=cpu python examples/elastic_train.py \
    --np 3 --fault worker_exit:step=4:rank=1
echo "== elastic chaos smoke: shrink when the respawn budget is spent =="
JAX_PLATFORMS=cpu python examples/elastic_train.py \
    --np 3 --fault worker_exit:step=4:rank=1 \
    --max-retries 0 --min-workers 2
echo "== elastic chaos smoke: deadlocked training thread caught by beat =="
JAX_PLATFORMS=cpu python examples/elastic_train.py \
    --np 3 --fault worker_exit:step=4:rank=1:action=hang \
    --progress-timeout 2
echo "matrix OK"
