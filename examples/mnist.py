#!/usr/bin/env python
"""Distributed MNIST training — the TPU equivalent of the reference's
examples/tensorflow2_mnist.py / pytorch_mnist.py four-line recipe:

    1. hvd.init()
    2. shard the data by rank
    3. wrap the optimizer in DistributedOptimizer
    4. broadcast initial state from rank 0

Run single-process (all local chips) or multi-process:

    python examples/mnist.py
    python -m horovod_tpu.run -np 2 python examples/mnist.py

Uses synthetic MNIST-shaped data (this environment has no dataset egress);
swap `synthetic_mnist` for a real loader in production.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import ConvNet
from horovod_tpu.ops.collectives import shard_map_compat


def synthetic_mnist(n=4096, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    # learnable structure: label = argmax of 10 fixed random projections
    w = np.random.RandomState(42).randn(28 * 28, 10).astype(np.float32)
    y = (x.reshape(n, -1) @ w).argmax(axis=1).astype(np.int32)
    return x, y


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--smoke", action="store_true",
        help="tiny run for the CI matrix (ci/test_matrix.sh)",
    )
    parser.add_argument("--epochs", type=int, default=3)
    args = parser.parse_args()
    if args.smoke:
        args.epochs = 1

    hvd.init()
    model = ConvNet()
    rng = jax.random.PRNGKey(1)

    x, y = synthetic_mnist(n=512 if args.smoke else 4096)
    # Step 2: shard the data across workers.  On TPU the mesh IS the data
    # sharding: every process builds the same global batch, and
    # P(DP_AXIS) hands each chip its distinct row block — rank-slicing the
    # dataset *as well* would shard twice and silently drop rows.
    per = len(x)

    params = model.init(rng, jnp.asarray(x[:1]))
    # Step 4: broadcast initial state so all workers start identically.
    params = hvd.broadcast_parameters(params, root_rank=0)

    # Step 3: wrap the optimizer; warmup schedule scales lr by world size
    # (reference LearningRateWarmupCallback semantics).
    steps_per_epoch = max(per // (32 * max(hvd.num_devices(), 1)), 1)
    lr = hvd.callbacks.warmup_schedule(
        0.001, warmup_epochs=2, steps_per_epoch=steps_per_epoch,
        scale=hvd.num_devices(),
    )
    tx = hvd.DistributedOptimizer(optax.adam(lr))
    opt_state = tx.init(params)

    def local_step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = model.apply(p, xb)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, hvd.allreduce(loss)

    mesh = hvd.mesh("flat")
    step = jax.jit(
        shard_map_compat(
            local_step,
            mesh=mesh,
            in_specs=(P(), P(), P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
            out_specs=(P(), P(), P()),
        ),
        # Donate the carried state: without aliasing, the input and
        # output params/opt_state copies are both live across every
        # step (hvdtpu-lint HVD009).
        donate_argnums=(0, 1),
    )

    batch = 32 * hvd.num_devices()
    for epoch in range(args.epochs):
        t0 = time.time()
        losses = []
        for i in range(0, len(x) - batch + 1, batch):
            params, opt_state, loss = step(
                params, opt_state, jnp.asarray(x[i : i + batch]),
                jnp.asarray(y[i : i + batch]),
            )
            losses.append(float(loss))
        if hvd.rank() == 0:
            print(
                f"epoch {epoch}: loss={np.mean(losses):.4f} "
                f"({time.time() - t0:.1f}s)"
            )

    hvd.shutdown()


if __name__ == "__main__":
    main()
