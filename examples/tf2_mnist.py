#!/usr/bin/env python
"""TF2 eager MNIST with DistributedGradientTape (≙ examples/
tensorflow2_mnist.py): the tape wraps gradient computation, grads are
averaged across ranks by the eager engine, and variables broadcast from
rank 0 on the first batch.

    python examples/tf2_mnist.py
    python -m horovod_tpu.run -np 2 python examples/tf2_mnist.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import tensorflow as tf

import horovod_tpu.interop.tf as hvd


def main() -> int:
    hvd.init()
    tf.keras.utils.set_random_seed(42 + hvd.rank())

    # synthetic MNIST-shaped data, sharded by rank like the reference
    # example shards via tf.data shard()
    rng = np.random.RandomState(hvd.rank())
    images = rng.rand(512, 28, 28, 1).astype(np.float32)
    labels = rng.randint(0, 10, size=(512,)).astype(np.int64)

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, activation="relu",
                               input_shape=(28, 28, 1)),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10),
    ])
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)
    # Scale LR by world size (reference recipe) — the averaged gradient
    # over N ranks represents an N-times-larger batch.
    opt = tf.keras.optimizers.SGD(0.001 * hvd.size())

    batch = 32
    for step in range(16):
        i = (step * batch) % len(images)
        x, y = images[i:i + batch], labels[i:i + batch]
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            logits = model(x, training=True)
            loss = loss_fn(y, logits)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if step == 0:
            # reference tensorflow2_mnist.py:first_batch broadcast.
            # `step == 0` is loop-uniform — every rank runs the first
            # iteration — so the branch cannot diverge across ranks.
            # hvdtpu: disable=HVD003
            hvd.broadcast_variables(model.variables, root_rank=0)
            opt_vars = opt.variables  # property in modern Keras,
            if callable(opt_vars):    # method on legacy optimizers
                opt_vars = opt_vars()
            # hvdtpu: disable=HVD003 — same loop-uniform branch
            hvd.broadcast_variables(opt_vars, root_rank=0)
        if step % 5 == 0 and hvd.rank() == 0:
            print(f"step {step:2d} loss {float(loss):.4f}")

    avg = hvd.allreduce(loss)
    if hvd.rank() == 0:
        print(f"final loss (rank-averaged): {float(avg):.4f}")
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
