#!/usr/bin/env python
"""Pipeline-parallel GPT training — the schedule family end to end.

The block stack splits into stages over a ``pp`` mesh axis built from
THIS process's local devices (pipeline parallelism rides ICI; use the
launcher's data-parallel axis across processes on top of it as in
docs/pipeline.md).  Demonstrates both training schedules:

* contiguous GPipe (``pp_gpt_loss``: stage-local head, scalar rejoin,
  per-tick remat), and
* circular interleaved groups (``pp_gpt_loss_circular``: bubble ÷
  circles).

No reference equivalent — Horovod 0.19.1 is data-parallel only
(SURVEY.md §2.9).

    python examples/pipeline_train.py --smoke             # TPU pod slice
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \\
        python examples/pipeline_train.py --smoke --cpu   # 4-dev CPU mesh

(``--cpu`` sets the platform in-process, like ``bench.py --cpu`` and
tests/conftest.py — more robust than ``JAX_PLATFORMS=cpu`` in the shell
when a TPU plugin is installed but its backend is unreachable.)
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (virtual multi-device "
                   "mesh via XLA_FLAGS=--xla_force_host_platform_"
                   "device_count=N)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--microbatches", type=int, default=4)
    p.add_argument("--circles", type=int, default=0,
                   help=">0 selects the circular schedule with this "
                   "many layer groups per stage")
    args = p.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.models.transformer import gpt
    from horovod_tpu.parallel import (
        pp_gpt_loss, pp_gpt_loss_circular, stack_pp_params,
        stack_pp_params_circular,
    )

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if args.smoke:
        args.steps, args.seq_len = 3, 32

    devices = jax.devices()
    pp = len(devices)
    if pp < 2:
        raise SystemExit(
            "pipeline example needs >=2 devices (e.g. XLA_FLAGS="
            "--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu)"
        )
    mesh = Mesh(np.asarray(devices), ("pp",))
    if args.steps <= 0:
        raise SystemExit("--steps must be positive")
    # the circular ring buffer needs microbatches >= pp; round the batch
    # UP to the next multiple so the requested workload is preserved
    args.microbatches = max(args.microbatches, pp)
    if args.batch_size % args.microbatches:
        rounded = -(-args.batch_size // args.microbatches) \
            * args.microbatches
        print(f"# batch {args.batch_size} -> {rounded} "
              f"(must divide microbatches={args.microbatches})")
        args.batch_size = rounded

    circles = args.circles or 1
    per_group = 1 if args.smoke else 2
    layers = per_group * pp * circles
    size_kw = (
        dict(num_heads=4, emb_dim=64, vocab_size=512) if args.smoke
        else {}
    )
    model = gpt("nano", num_layers=layers, max_len=args.seq_len,
                dtype=jnp.float32, attention_impl="reference",
                **size_kw)
    cfg = model.cfg

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(
        rng.randint(0, cfg.vocab_size, (args.batch_size, args.seq_len)),
        jnp.int32,
    )
    targets = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.PRNGKey(0), tokens[:1])
    if args.circles:
        staged, replicated = stack_pp_params_circular(
            params, cfg, pp, circles
        )
    else:
        staged, replicated = stack_pp_params(params, cfg, pp)
    # plain SGD: its state is empty, so the carried opt_state is
    # trivially replicated and the out_specs stay simple — a stateful
    # optimizer needs per-tree specs for its moment trees (the staged
    # moments are pp-sharded like the staged params)
    tx = optax.sgd(0.5)
    opt_state = tx.init((staged, replicated))

    def local_step(staged, replicated, opt_state, tok, tgt):
        def loss_fn(trees):
            st, rep = trees
            if args.circles:
                return pp_gpt_loss_circular(
                    st, rep, cfg, tok, tgt, "pp",
                    microbatches=args.microbatches, circles=circles,
                )
            return pp_gpt_loss(st, rep, cfg, tok, tgt, "pp",
                               microbatches=args.microbatches)

        loss, grads = jax.value_and_grad(loss_fn)((staged, replicated))
        updates, opt_state = tx.update(grads, opt_state,
                                       (staged, replicated))
        staged, replicated = optax.apply_updates(
            (staged, replicated), updates
        )
        return staged, replicated, opt_state, loss

    step = jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P("pp"), P(), P(), P(), P()),
            out_specs=(P("pp"), P(), P(), P()),
            check_vma=True,
        ),
        # Donate the carried state: the input and output copies of the
        # staged/replicated params and opt_state must not both stay
        # live across a step (hvdtpu-lint HVD009).
        donate_argnums=(0, 1, 2),
    )

    sched = f"circular x{circles}" if args.circles else "gpipe"
    for i in range(args.steps):
        staged, replicated, opt_state, loss = step(
            staged, replicated, opt_state, tokens, targets
        )
        print(f"[{sched} pp={pp} layers={layers}] "
              f"step {i} loss {float(loss):.4f}", flush=True)
    final = float(loss)
    assert np.isfinite(final), "non-finite loss"
    print(f"done: final loss {final:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
