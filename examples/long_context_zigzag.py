#!/usr/bin/env python
"""Long-context training with load-balanced zigzag sequence parallelism.

A RoPE GPT whose SEQUENCE (not batch) is sharded over the job mesh:
each process holds the zigzag chunk pair of every sample, attention runs
as the balanced causal ring (docs/long_context.md), and gradients
average over the same axis.  No reference equivalent — Horovod 0.19.1 is
data-parallel only (SURVEY.md §5.7); long context is a TPU-build
first-class feature.

    python examples/long_context_zigzag.py --smoke
    python -m horovod_tpu.run -np 2 python examples/long_context_zigzag.py --smoke
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.transformer import gpt
from horovod_tpu.parallel import zigzag_positions, zigzag_shard


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument("--seq-len", type=int, default=512)
    args = p.parse_args()
    if args.smoke:
        args.steps, args.seq_len = 3, 128

    hvd.init()
    r = hvd.rank()
    n = hvd.num_devices()
    if args.seq_len % (2 * n):
        raise SystemExit(f"--seq-len must divide by 2*{n}")
    s_local = args.seq_len // n

    model = gpt(
        "nano", max_len=args.seq_len, pos_embedding="rope",
        attention_impl="zigzag", sp_axis=hvd.DP_AXIS,
    )
    # every process builds the same global batch, zigzag-reordered once;
    # the mesh sharding below hands each chip its chunk pair
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(
        rng.randint(0, 1024, size=(args.batch_size, args.seq_len + 1))
    )
    inputs = zigzag_shard(tokens[:, :-1], n, axis=1)
    targets = zigzag_shard(tokens[:, 1:], n, axis=1)

    # init OUTSIDE shard_map needs an axis-free twin (identical param
    # structure; the attention schedule does not affect parameter shapes)
    init_model = gpt("nano", max_len=args.seq_len, pos_embedding="rope",
                     attention_impl="reference")
    params = init_model.init(jax.random.PRNGKey(0), inputs[:, :s_local])
    params = hvd.broadcast_parameters(params, root_rank=0)
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    def local_step(params, opt_state, tok, tgt):
        pos = zigzag_positions(jax.lax.axis_index(hvd.DP_AXIS), n, s_local)

        def loss_fn(p):
            logits = model.apply(p, tok, positions=pos)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # sequence-sharded loss: grads and loss both average over the axis
        grads = jax.lax.pmean(grads, hvd.DP_AXIS)
        loss = jax.lax.pmean(loss, hvd.DP_AXIS)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    mesh = hvd.mesh("flat")
    step = jax.jit(
        shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(), P(None, hvd.DP_AXIS), P(None, hvd.DP_AXIS)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0, 1),
    )

    losses = []
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, inputs, targets)
        losses.append(float(loss))
        if r == 0:
            print(f"step {i}: loss {losses[-1]:.4f}", flush=True)
    assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0], f"did not train: {losses}"
    if r == 0:
        print(f"OK zigzag SP over {n} chips: "
              f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
