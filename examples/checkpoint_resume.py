#!/usr/bin/env python
"""Checkpoint/resume pattern (SURVEY.md §5.4): rank 0 saves through orbax,
every rank resumes by broadcast — no shared filesystem required.

    python examples/checkpoint_resume.py
    python -m horovod_tpu.run -np 2 python examples/checkpoint_resume.py
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.checkpoint import (
    latest_checkpoint_step,
    restore_checkpoint,
    save_checkpoint,
)
from horovod_tpu.models import MLP


def main():
    hvd.init()
    ckpt_dir = os.environ.get(
        "CKPT_DIR", os.path.join(tempfile.gettempdir(), "hvdtpu_ckpt_demo")
    )

    model = MLP(features=(32,), num_classes=10)
    rng = jax.random.PRNGKey(0)
    x = np.random.RandomState(0).rand(256, 8).astype(np.float32)
    y = (x.sum(-1) * 1.25).astype(np.int32) % 10

    params = model.init(rng, jnp.asarray(x[:1]))
    tx = hvd.DistributedOptimizer(optax.adam(1e-2))
    opt_state = tx.init(params)
    start_step = 0

    # Resume if a checkpoint exists (rank 0 reads, everyone receives).
    # The existence check is decided ON RANK 0 and broadcast: the
    # filesystem is not guaranteed identical across hosts (local disks,
    # half-synced NFS), and ranks disagreeing here would send one subset
    # into restore_checkpoint and the rest into broadcast_parameters —
    # two different collective schedules, i.e. a hang.
    resume_step = hvd.broadcast_object(
        latest_checkpoint_step(ckpt_dir), root_rank=0
    )
    if resume_step is not None:
        state = restore_checkpoint(
            ckpt_dir, {"params": params, "step": 0}
        )
        params, start_step = state["params"], int(state["step"])
        if hvd.rank() == 0:
            print(f"resumed from step {start_step}")
    else:
        # branch is rank-uniform: decided by the broadcast above
        params = hvd.broadcast_parameters(  # hvdtpu: disable=HVD003
            params, root_rank=0
        )

    from jax.sharding import PartitionSpec as P

    def local_step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = model.apply(p, xb)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # DistributedOptimizer psums over the mesh axis, so the step runs under
    # shard_map with the batch sharded — hvd.distribute wires that up.
    step = hvd.distribute(
        local_step,
        in_specs=(P(), P(), P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
        out_specs=(P(), P(), P()),
    )

    for s in range(start_step, start_step + 50):
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(x), jnp.asarray(y)
        )
        if s % 20 == 0:
            save_checkpoint(
                ckpt_dir, {"params": params, "step": s}, step=s, keep=3
            )
            if hvd.rank() == 0:
                print(f"step {s}: loss {float(loss):.4f} (checkpointed)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
