#!/usr/bin/env python
"""MNIST with the horovod.torch-compatible interop frontend — the verbatim
port target for reference users (≙ examples/pytorch_mnist.py): same
hvd.init / DistributedOptimizer / broadcast_parameters recipe, torch
tensors on the host, collectives through the eager engine.

    python examples/torch_mnist.py
    python -m horovod_tpu.run -np 2 python examples/torch_mnist.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.interop.torch as hvd


class Net(nn.Module):
    """The reference example's two-conv MNIST net (pytorch_mnist.py Net)."""

    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = nn.Conv2d(10, 20, kernel_size=5)
        self.fc1 = nn.Linear(320, 50)
        self.fc2 = nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.view(-1, 320)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def synthetic_mnist(n=2048, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 1, 28, 28).astype(np.float32)
    w = np.random.RandomState(42).randn(28 * 28, 10).astype(np.float32)
    y = (x.reshape(n, -1) @ w).argmax(axis=1)
    return torch.from_numpy(x), torch.from_numpy(y)


def main():
    # 1. initialize
    hvd.init()
    torch.manual_seed(0)

    x, y = synthetic_mnist()
    # 2. shard the data by rank (reference DistributedSampler role)
    x = x[hvd.rank()::hvd.size()]
    y = y[hvd.rank()::hvd.size()]

    model = Net()
    # 3. broadcast initial state from rank 0
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    # 4. wrap the optimizer
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.01, momentum=0.5),
        named_parameters=model.named_parameters(),
    )
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    bs = 64
    for epoch in range(2):
        perm = torch.randperm(len(x))
        losses = []
        for s in range(len(x) // bs):
            idx = perm[s * bs:(s + 1) * bs]
            opt.zero_grad()
            loss = F.nll_loss(model(x[idx]), y[idx])
            loss.backward()
            opt.step()
            losses.append(float(loss))
        # metric averaging across ranks, eager path
        avg = float(hvd.allreduce(torch.tensor(np.mean(losses))))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {avg:.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
