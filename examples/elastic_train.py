#!/usr/bin/env python
"""Elastic fault-tolerant training demo (docs/elastic.md).

Runs a small data-parallel SGD loop under ``elastic.launch`` and — when
``--fault`` is given — proves the recovery path by deterministically
killing a rank mid-training with the fault-injection registry
(``HVDTPU_FAULT_SPEC``), then showing the job finish with the same
committed state a no-fault run reaches.

    # clean run
    python examples/elastic_train.py --np 3

    # chaos run: rank 1 is killed at its 4th step, respawned, and the
    # job recovers via rollback + re-rendezvous
    python examples/elastic_train.py --np 3 --fault worker_exit:step=4:rank=1

    # budget-spent shrink: no respawns allowed, world shrinks to 2
    python examples/elastic_train.py --np 3 --fault worker_exit:step=4:rank=1 \
        --max-retries 0 --min-workers 2
"""

import argparse

import numpy as np


def train(steps):
    import numpy as np

    import horovod_tpu.elastic as elastic

    ctx = elastic.context()
    state = elastic.State(w=np.zeros(8), step=0)

    @elastic.run
    def loop(state):
        while state.step < steps:
            # Toy "gradient": deterministic per (step, rank) so a
            # recovered run reproduces a no-fault run exactly.
            grad = np.full(8, float(state.step + 1) * (ctx.rank + 1))
            state.w = state.w - 0.01 * ctx.allreduce(
                grad, name=f"grad{state.step}")
            state.step += 1
            state.commit()
        return float(state.w[0]), state.step

    return loop(state)


def main() -> int:
    import horovod_tpu.elastic as elastic

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--np", type=int, default=3, dest="num_proc")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--fault", default="",
                   help="HVDTPU_FAULT_SPEC for the workers, e.g. "
                        "worker_exit:step=4:rank=1")
    p.add_argument("--max-retries", type=int, default=3)
    p.add_argument("--min-workers", type=int, default=None)
    p.add_argument("--progress-timeout", type=float, default=300.0,
                   help="steady-state progress-beat budget (secs); a "
                        "rank whose training thread hangs (e.g. --fault "
                        "...:action=hang) is killed and respawned after "
                        "this long without a completed collective")
    args = p.parse_args()

    env = {"JAX_PLATFORMS": "cpu"}
    if args.fault:
        env["HVDTPU_FAULT_SPEC"] = args.fault
        # A hang is only discovered by the progress beat; peer timeouts
        # must not be the rescue path in the demo either.
        if "action=hang" in args.fault:
            env["HVDTPU_ELASTIC_TIMEOUT"] = "600"
    results, job = elastic.launch(
        train, args=(args.steps,), np=args.num_proc, env=env,
        max_retries=args.max_retries, min_workers=args.min_workers,
        progress_timeout=args.progress_timeout,
        timeout=300,
    )
    print(f"final world: {job.world} (epoch {job.epoch})")
    for event in job.trace:
        print(f"  trace: {event}")
    w0 = {r: results[r][0] for r in sorted(results)}
    print(f"w[0] per rank: {w0}")
    assert len(set(w0.values())) == 1, "ranks disagree on final state"
    if args.fault and args.max_retries > 0:
        assert any(e[0] == "respawn" for e in job.trace), \
            "fault spec set but no respawn happened"
        print("recovered: rollback + respawn verified")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
