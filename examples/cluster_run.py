#!/usr/bin/env python
"""Run a horovod_tpu job inside cluster task slots (reference:
horovod.spark.run — examples/keras_spark_rossmann_run.py topology).

With Spark:

    import horovod_tpu.cluster as cluster
    results = cluster.run_on_cluster(
        train_fn, num_proc=sc.defaultParallelism,
        executor=cluster.spark_executor(sc))

This example uses the local subprocess executor so it runs anywhere:

    python examples/cluster_run.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.cluster import local_executor, run_on_cluster


def train_fn(steps: int):
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    total = 0.0
    for step in range(steps):
        g = np.full(8, float(r + 1 + step), np.float32)
        total += float(hvd.allreduce(g, op=hvd.Average).sum())
    hvd.shutdown()
    return {"rank": r, "metric": total}


def main() -> int:
    results = run_on_cluster(
        train_fn, (5,), num_proc=2,
        executor=local_executor(),
        env={"JAX_PLATFORMS": "cpu"},
    )
    for res in results:
        print(f"rank {res['rank']}: metric {res['metric']:.1f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
