#!/usr/bin/env python
"""BERT-base-style distributed training config (BASELINE.md's fourth
reference config): a transformer whose EMBEDDING gradients travel as
sparse IndexedSlices (allgather of values+indices, reference
tensorflow/__init__.py:74-89) while dense gradients allreduce with a
gradient predivide factor (reference gradient_predivide_factor: part of
the averaging happens before the sum, the rest after — numerically
gentler at large world sizes).

    python examples/bert_style.py --smoke
    python -m horovod_tpu.run -np 2 python examples/bert_style.py --smoke
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models.transformer import gpt
from horovod_tpu.ops.sparse import IndexedSlices, allreduce_sparse, to_dense


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    args = p.parse_args()
    if args.smoke:
        args.steps, args.seq_len = 3, 64

    hvd.init()
    r = hvd.rank()
    model = gpt("nano")
    n_chips = hvd.num_devices()

    # Same global batch on every process; P(DP_AXIS) hands each chip its
    # distinct row block (the mnist.py data convention).
    global_batch = args.batch_size * n_chips
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(
            0, 1024, size=(global_batch, args.seq_len)
        )
    )
    params = model.init(jax.random.PRNGKey(0), tokens[:2, :-1])
    params = hvd.broadcast_parameters(params, root_rank=0)
    tx = hvd.DistributedOptimizer(
        optax.adamw(1e-4),
        # predivide: grads /= factor before the cross-rank sum, the rest of
        # the averaging after (reference prescale/postscale split)
        gradient_predivide_factor=float(max(hvd.local_size(), 1)),
    )
    opt_state = tx.init(params)

    def local_step(params, opt_state, toks):
        def loss_fn(p):
            logits = model.apply(p, toks[:, :-1])
            # Embedding rows actually touched travel SPARSE in the
            # backward: allreduce_sparse allgathers (values, indices)
            # instead of dense-reducing the full vocab x d_model gradient
            # — the BERT embedding pattern (reference
            # tensorflow/__init__.py:74-89).  Demonstrated forward-side
            # here on the embedding table itself:
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, toks[:, 1:]
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # sparse embedding-gradient exchange, under tracing -> all_gather.
        # Only rows the batch actually touched travel: unique(size=K) keeps
        # the shape static under jit, K = min(batch tokens, vocab) — the
        # unique count can exceed neither, and real vocabularies dwarf a
        # batch; fill slots carry zero values so their scatter-add no-ops.
        for path, leaf in jax.tree_util.tree_flatten_with_path(grads)[0]:
            if "embed" in str(path).lower() and leaf.ndim == 2:
                used = jnp.unique(
                    toks, size=min(toks.size, leaf.shape[0]), fill_value=-1
                )
                valid = used >= 0
                rows = jnp.where(valid, used, 0)
                sparse = IndexedSlices(
                    values=leaf[rows] * valid[:, None],
                    indices=rows,
                    dense_shape=leaf.shape,
                )
                dense = to_dense(
                    allreduce_sparse(sparse, name="bert.embed.sparse")
                )
                del dense  # demonstration only: K-row traffic, and the
                #            dense reduce below owns the real update
                break
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, hvd.allreduce(loss)

    step = hvd.distribute(local_step, donate_argnums=(0, 1))

    t0 = time.time()
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens)
    jax.block_until_ready(params)
    if r == 0:
        steps_s = args.steps / (time.time() - t0)
        print(f"loss={float(loss):.4f} {steps_s:.2f} steps/s")
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
