#!/usr/bin/env python
"""Synthetic ResNet benchmark example — mirrors the reference's
examples/pytorch_synthetic_benchmark.py CLI (model, batch size, iteration
counts, fp16/bf16 allreduce flag) on the TPU stack.

    python examples/synthetic_benchmark.py --model resnet50 --batch-size 64
    python -m horovod_tpu.run -np 2 python examples/synthetic_benchmark.py

(bench.py at the repo root is the driver-facing single-line version.)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import models
from horovod_tpu.optim import DistributedOptimizer


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-chip batch size (reference default 32)")
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--bf16-allreduce", action="store_true",
                   help="≙ reference --fp16-allreduce: compress grads on the wire")
    args = p.parse_args()

    hvd.init()
    model = getattr(models, args.model.capitalize().replace("net", "Net"))(
        num_classes=1000
    )

    n = hvd.num_devices()
    global_batch = args.batch_size * n
    images = jnp.asarray(
        np.random.RandomState(0).randn(global_batch, 224, 224, 3), jnp.float32
    )
    labels = jnp.asarray(np.random.RandomState(1).randint(0, 1000, (global_batch,)))

    variables = model.init(jax.random.PRNGKey(0), images[:1], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    params = hvd.broadcast_parameters(params)

    compression = (
        hvd.Compression.bf16 if args.bf16_allreduce else hvd.Compression.none
    )
    tx = DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=compression
    )
    opt_state = tx.init(params)

    def local_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                images, train=True, mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, opt_state, loss

    mesh = hvd.mesh("flat")
    step = jax.jit(
        shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(), P(), P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
            out_specs=(P(), P(), P(), P()), check_vma=False,
        ),
        donate_argnums=(0, 1, 2),
    )

    def run_batches(k):
        nonlocal params, batch_stats, opt_state
        loss = None
        for _ in range(k):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, images, labels
            )
        jax.block_until_ready(loss)

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch size/chip: {args.batch_size}, "
              f"chips: {n}")
    run_batches(args.num_warmup_batches)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        run_batches(args.num_batches_per_iter)
        dt = time.perf_counter() - t0
        rate = global_batch * args.num_batches_per_iter / dt
        img_secs.append(rate)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate:.1f} img/sec total")

    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec per chip: {mean / n:.1f} +- {conf / n:.1f}")
        print(f"Total img/sec on {n} chip(s): {mean:.1f} +- {conf:.1f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
