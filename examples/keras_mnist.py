#!/usr/bin/env python
"""Distributed Keras MNIST — the migration target of the reference's
examples/keras_mnist_advanced.py:

    1. hvd.init()
    2. wrap the optimizer:  model.compile(optimizer=hvd.DistributedOptimizer(...))
    3. model.fit(callbacks=[BroadcastGlobalVariablesCallback(0),
                            MetricAverageCallback(),
                            LearningRateWarmupCallback(...)])

Run:  python -m horovod_tpu.run -np 2 python examples/keras_mnist.py --smoke
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def synthetic_mnist(n: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    w = np.random.RandomState(42).randn(28 * 28, 10).astype(np.float32)
    y = (x.reshape(n, -1) @ w).argmax(axis=1).astype(np.int32)
    return x, y


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true")
    parser.add_argument("--epochs", type=int, default=4)
    args = parser.parse_args()
    if args.smoke:
        args.epochs = 1

    import tensorflow as tf

    import horovod_tpu.interop.tf_keras as hvd

    hvd.init()
    x, y = synthetic_mnist(512 if args.smoke else 4096, seed=hvd.rank())

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, activation="relu",
                               input_shape=(28, 28, 1)),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10),
    ])
    # LR scaled by world size, warmed up over the first epochs (reference
    # keras_mnist_advanced.py recipe).
    model.compile(
        optimizer=hvd.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.01 * hvd.size(),
                                    momentum=0.9)
        ),
        loss=tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
    )
    hist = model.fit(
        x, y,
        batch_size=32,
        epochs=args.epochs,
        verbose=2 if hvd.rank() == 0 else 0,
        callbacks=[
            hvd.callbacks.BroadcastGlobalVariablesCallback(0),
            hvd.callbacks.MetricAverageCallback(),
            hvd.callbacks.LearningRateWarmupCallback(
                initial_lr=0.01 * hvd.size(), warmup_epochs=2
            ),
        ],
    )
    if hvd.rank() == 0:
        print(f"final loss {hist.history['loss'][-1]:.4f} "
              f"acc {hist.history['accuracy'][-1]:.3f}")
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
