#!/usr/bin/env python
"""Adasum on a tiny curve-fitting model (≙ examples/adasum_small_model.py):
each rank fits a polynomial on a different slice of the curve, and the
delta-reducing Adasum optimizer (``op=hvd.Adasum``) blends the per-rank
update *directions* with the VHDD projection instead of averaging raw
gradients — the regime Adasum was built for (large effective batches from
many disagreeing workers).

    python examples/adasum_small_model.py
    python -m horovod_tpu.run -np 2 python examples/adasum_small_model.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import torch

import horovod_tpu.interop.torch as hvd


def curve(x: torch.Tensor) -> torch.Tensor:
    return 2.0 * x * x - 20.0 * x + 50.0


class Quadratic(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.coef = torch.nn.Parameter(torch.tensor([1.0, -1.0, 1.0]))

    def forward(self, x):
        return self.coef[0] * x * x + self.coef[1] * x + self.coef[2]


def main() -> int:
    hvd.init()
    torch.manual_seed(1234)

    model = Quadratic()
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    # op=Adasum selects the delta-based optimizer: the SGD step runs
    # locally, the parameter delta rides the VHDD reduction
    # (reference torch/__init__.py:225-393 via the factory :443-449).
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=1e-3),
        named_parameters=model.named_parameters(),
        op=hvd.Adasum,
    )

    # Disjoint per-rank data slices -> genuinely disagreeing gradients.
    lo = -5.0 + 10.0 * hvd.rank() / hvd.size()
    xs = torch.linspace(lo, lo + 10.0 / hvd.size(), 64)
    ys = curve(xs)

    for step in range(200):
        opt.zero_grad()
        loss = ((model(xs) - ys) ** 2).mean()
        loss.backward()
        opt.step()
        if step % 50 == 0 and hvd.rank() == 0:
            print(f"step {step:3d} loss {float(loss):10.3f} "
                  f"coef {model.coef.detach().numpy().round(3)}")

    final = hvd.allreduce(((model(xs) - ys) ** 2).mean(), name="final_loss")
    if hvd.rank() == 0:
        print(f"final mean loss across ranks: {float(final):.3f}")
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
