#!/usr/bin/env python
"""Estimator API (≙ the reference's Spark estimator examples,
horovod/spark keras/torch estimators): configure model + optimizer +
store, fit on a data dict, get back a Model transformer.

    python examples/estimator_train.py
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MLP


def main():
    hvd.init()
    rng = np.random.RandomState(0)
    n = 2048
    x = np.concatenate([
        rng.randn(n // 2, 16).astype(np.float32) + 1.5,
        rng.randn(n // 2, 16).astype(np.float32) - 1.5,
    ])
    y = np.concatenate([
        np.zeros(n // 2, np.int32), np.ones(n // 2, np.int32)
    ])

    store = hvd.LocalStore(
        os.path.join(tempfile.gettempdir(), "hvdtpu_estimator_demo")
    )
    est = hvd.Estimator(
        MLP(features=(64,), num_classes=2),
        optax.adam(1e-3),
        batch_size=64,
        epochs=3,
        store=store,
        run_id="demo",
        verbose=True,
    )
    model = est.fit({"features": x, "label": y})

    out = model.transform({"features": x, "label": y})
    acc = (out["prediction"] == y).mean()
    print(f"train accuracy: {acc:.3f}")
    print(f"metadata: {store.read_metadata('demo')['history'][-1]}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
