"""Build hook: compile the native eager engine during package build.

Reference contrast (SURVEY.md §2.8): horovod's setup.py is 1,631 lines of
compiler/MPI/NCCL/CUDA probing because every framework x transport pair
needs its own extension.  Here the entire native surface is one shared
library with no external deps beyond a C++17 toolchain, built by the
plain Makefile in cpp/ and loaded via ctypes (horovod_tpu/runtime/native.py)
— no Python C extension, so no per-interpreter ABI builds.  If no C++
toolchain is available the build degrades gracefully: the pure-Python
engine is a full functional twin (HVDTPU_EAGER_ENGINE=python).
"""

import shutil
import subprocess
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        root = Path(__file__).parent
        if shutil.which("make") and shutil.which("g++"):
            try:
                subprocess.run(
                    ["make", "-C", str(root / "cpp")], check=True
                )
            except subprocess.CalledProcessError as e:
                print(f"warning: native engine build failed ({e}); "
                      "falling back to the pure-Python engine")
        else:
            print("warning: make/g++ not found; packaging without the "
                  "native engine (pure-Python engine will be used)")
        super().run()


setup(cmdclass={"build_py": BuildWithNative})
