"""Sparse (embedding) gradient collectives.

TPU-native re-design of the reference's IndexedSlices allreduce path
(horovod/tensorflow/__init__.py:74-89): a sparse gradient is never summed
elementwise — instead every rank allgathers its (values, indices) pair and
the optimizer applies the concatenated slices.  The reference also offers
``sparse_as_dense`` on DistributedOptimizer (horovod/tensorflow/__init__.py,
ctor arg) to densify before reduction; both paths exist here.

On TPU the allgather compiles to an XLA all-gather over ICI; under jit the
per-rank row count must be uniform (static shapes), which holds for the
usual embedding-gradient case (same batch shape on every rank).  The eager
path tolerates ragged per-rank counts — the engine's allgather negotiates
dim-0 sizes exactly like the reference controller does
(horovod/common/controller.cc:453-518).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..basics import DP_AXIS
from .collectives import Average, ReduceOp, Sum, _is_traced

__all__ = [
    "IndexedSlices",
    "allreduce_sparse",
    "to_dense",
]


class IndexedSlices(NamedTuple):
    """A sparse tensor as (values, indices) row slices of a dense shape.

    Mirrors tf.IndexedSlices (the type the reference special-cases).
    ``values`` has shape ``(n, *dense_shape[1:])``; ``indices`` has shape
    ``(n,)`` indexing dim 0 of ``dense_shape``.
    """

    values: jax.Array
    indices: jax.Array
    dense_shape: Tuple[int, ...]


def to_dense(slices: IndexedSlices):
    """Scatter-add the slices into a dense array (XLA scatter, MXU-friendly
    for the downstream update)."""
    dense = jnp.zeros(slices.dense_shape, jnp.asarray(slices.values).dtype)
    return dense.at[slices.indices].add(slices.values)


def allreduce_sparse(
    slices: IndexedSlices,
    op: ReduceOp = Average,
    *,
    axis_name: str = DP_AXIS,
    name: Optional[str] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> IndexedSlices:
    """Allreduce an IndexedSlices by allgathering values and indices.

    Reference semantics (horovod/tensorflow/__init__.py:74-89): the result
    is the concatenation of every rank's slices, with values divided by
    world size when averaging; duplicate indices are NOT combined (the
    optimizer's scatter-add does that), exactly as in the reference.
    """
    if op not in (Average, Sum):
        raise NotImplementedError(
            "sparse allreduce supports Average/Sum only (reference parity: "
            "horovod/tensorflow/__init__.py:74-89)"
        )
    values = jnp.asarray(slices.values)
    indices = jnp.asarray(slices.indices)
    if prescale_factor != 1.0:
        values = values * prescale_factor
    if _is_traced(values):
        n = lax.psum(1, axis_name)
        g_values = lax.all_gather(values, axis_name, tiled=True)
        g_indices = lax.all_gather(indices, axis_name, tiled=True)
        if op == Average:
            g_values = g_values / n
    else:
        from . import eager  # noqa: PLC0415
        from ..basics import size  # noqa: PLC0415

        g_values = eager.allgather(
            values, name=(f"{name}.values" if name else None)
        )
        g_indices = eager.allgather(
            indices, name=(f"{name}.indices" if name else None)
        )
        if op == Average:
            g_values = g_values / size()
    if postscale_factor != 1.0:
        g_values = g_values * postscale_factor
    return IndexedSlices(g_values, g_indices, tuple(slices.dense_shape))


def apply_sparse_update(params, slices: IndexedSlices, step_size):
    """Apply ``params[indices] += step_size * values`` (scatter-add), the
    optimizer-side half of the sparse path."""
    return params.at[slices.indices].add(
        step_size * jnp.asarray(slices.values, params.dtype)
    )
