"""Rotary position embedding (RoPE), TPU-shaped.

Positions are an explicit int vector (one global position per local row),
NOT an offset + arange — that is what makes RoPE compose with arbitrary
sequence layouts: contiguous shards pass ``offset + arange``, zigzag
shards pass :func:`horovod_tpu.parallel.zigzag_positions`, and the
rotation is correct either way because it only ever looks at the
per-token position value.

Angles are computed in fp32 regardless of activation dtype (bf16 angles
destroy long-range phase accuracy), rotation output casts back.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_tables(positions: jax.Array, head_dim: int,
                theta: float = 10000.0):
    """Precompute ``(cos, sin)`` ``[seq, head_dim//2]`` for
    :func:`apply_rope_tables`.  Angles depend only on positions and theta,
    so a model computes them ONCE and threads them to every block —
    under remat the per-block recompute would otherwise re-run the
    transcendentals in the backward pass too."""
    if head_dim % 2:
        raise ValueError(f"RoPE requires an even head_dim, got {head_dim}")
    half = head_dim // 2
    freqs = 1.0 / (
        theta ** (jnp.arange(half, dtype=jnp.float32) / half)
    )
    ang = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope_tables(x: jax.Array, cos: jax.Array,
                      sin: jax.Array) -> jax.Array:
    """Rotate ``x`` ``[batch, seq, heads, head_dim]`` by precomputed
    tables from :func:`rope_tables`."""
    half = x.shape[-1] // 2
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
    )
    return out.astype(x.dtype)


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float = 10000.0) -> jax.Array:
    """One-shot spelling: rotate ``x`` by per-token angles from
    ``positions`` (int ``[seq]`` global token positions)."""
    cos, sin = rope_tables(positions, x.shape[-1], theta)
    return apply_rope_tables(x, cos, sin)
