"""Eager per-op collective API with async handles.

Reference surface: horovod/torch/mpi_ops.py — ``allreduce[_async][_]``,
``allgather[_async]``, ``broadcast[_async][_]``, ``poll``, ``synchronize``,
``join``, ``barrier``.  Handles map to futures resolved by the background
engine (reference HandleManager, horovod/torch/handle_manager.cc).

Use this path for host-driven, out-of-jit collectives: metric averaging,
parameter broadcast at startup, ragged allgathers, uneven-data Join.  The
training hot loop belongs on the jit path (ops/collectives.py) where XLA
fuses and schedules everything ahead of time.
"""

from __future__ import annotations

import concurrent.futures
from typing import Optional

import jax
import numpy as np

from .._engine_registry import get_engine
from ..runtime.messages import RequestType
from .collectives import Average, ReduceOp

__all__ = [
    "allreduce",
    "allreduce_",
    "allreduce_async",
    "allreduce_async_",
    "allgather",
    "allgather_async",
    "reducescatter",
    "reducescatter_async",
    "broadcast",
    "broadcast_",
    "broadcast_async",
    "broadcast_async_",
    "alltoall",
    "alltoall_async",
    "synchronize",
    "poll",
    "join",
    "barrier",
]

_name_counter = 0


def _auto_name(prefix: str) -> str:
    """Reference behavior: unnamed tensors get a sequence name
    (torch/mpi_ops.py handle naming 'allreduce.noname.N')."""
    global _name_counter
    _name_counter += 1
    return f"{prefix}.noname.{_name_counter}"


# Count of times _uncommit's zero-copy fast path failed and the host-copy
# fallback ran.  The fast path reaches into jax._src.array.ArrayImpl; if a
# jax upgrade moves that internal, results silently degrade to a host
# round-trip — the exact quiet regression the device-plane tests exist to
# catch.  So the degradation is LOUD: counted here (asserted zero by
# tests/test_eager.py and the multiprocess no-host-copy test) and warned
# once per process.
_uncommit_fallbacks = 0
_uncommit_warned = False


def _array_impl_cls():
    """The pinned jax internal, isolated so tests can simulate it moving."""
    from jax._src.array import ArrayImpl  # noqa: PLC0415

    return ArrayImpl


def _uncommit(x):
    """Rebuild a single-device jax.Array WITHOUT device commitment.

    Collective results built by the device plane are committed to their
    device; a caller that passed an UNCOMMITTED array (the normal state of
    model.init output) must get an uncommitted array back, or feeding the
    result into a jit over a multi-device mesh fails with "incompatible
    devices" — the exact broadcast_parameters -> jit train-step flow.
    Uses the ArrayImpl constructor (pinned by tests/test_eager.py on this
    jax version); falls back to one host round-trip — loudly — if the
    internals move."""
    global _uncommit_fallbacks, _uncommit_warned
    if not isinstance(x, jax.Array) or not getattr(x, "_committed", False):
        return x
    try:
        ArrayImpl = _array_impl_cls()
        shards = x.addressable_shards
        if len(shards) != 1:
            return x
        return ArrayImpl(
            x.aval,
            jax.sharding.SingleDeviceSharding(next(iter(x.devices()))),
            [shards[0].data],
            committed=False,
        )
    except Exception as exc:
        _uncommit_fallbacks += 1
        if not _uncommit_warned:
            _uncommit_warned = True
            from ..utils.logging import get_logger  # noqa: PLC0415

            get_logger("eager").warning(
                "zero-copy uncommit fast path failed (%s: %s); results now "
                "pay a host round-trip — the jax ArrayImpl internal moved",
                type(exc).__name__, exc,
            )
        return jax.device_put(np.asarray(x))


def _ingest(engine, tensor):
    """Hand a payload to the engine without gratuitous copies.

    Returns ``(payload, device)``; ``device`` non-None marks a device-
    resident caller whose result must come back as a committed
    ``jax.Array`` (reference: the GPU path keeps tensors on device end to
    end, operations.cc:266-291).

    * Python engine + single-device jax.Array: passed through untouched —
      the engine executes the negotiated op on the XLA device data plane
      (runtime/device_plane.py), zero host round-trips.
    * Native engine + jax.Array: the TCP wire needs host bytes; a CPU-
      backed array is ingested as a **zero-copy dlpack view** (the analog
      of the reference registering the framework buffer directly with the
      collective, no staging copy); an accelerator array pays exactly one
      D2H transfer.
    * Everything else (numpy, torch, lists): ``np.asarray`` as before.
    """
    if tensor is None:
        return None, None
    if isinstance(tensor, jax.Array):
        try:
            devices = tensor.devices()
        except Exception:  # deleted/donated
            devices = set()
        dev = next(iter(devices)) if len(devices) == 1 else None
        committed = bool(getattr(tensor, "_committed", True))
        if getattr(engine, "accepts_device_arrays", False) and dev is not None:
            return tensor, (dev, committed)
        try:
            return np.from_dlpack(tensor), (dev, committed)
        except Exception:  # non-host backing (TPU): one explicit transfer
            return np.asarray(tensor), (dev, committed)
    return np.asarray(tensor), None


def _tag(fut: concurrent.futures.Future, dev) -> concurrent.futures.Future:
    if dev is not None:
        fut._hvdtpu_device = dev  # consumed by synchronize()
    return fut


def allreduce_async(
    tensor,
    op: ReduceOp = Average,
    name: Optional[str] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> concurrent.futures.Future:
    """reference: hvd.allreduce_async (torch/mpi_ops.py:94-129)."""
    engine = get_engine()
    rtype = (
        RequestType.ADASUM if op == ReduceOp.ADASUM else RequestType.ALLREDUCE
    )
    payload, dev = _ingest(engine, tensor)
    return _tag(
        engine.enqueue(
            rtype,
            name or _auto_name("allreduce"),
            payload,
            reduce_op=int(op),
            prescale=prescale_factor,
            postscale=postscale_factor,
        ),
        dev,
    )


def allreduce(tensor, op: ReduceOp = Average, name: Optional[str] = None, **kw):
    """Blocking allreduce (reference torch/mpi_ops.py:131-155)."""
    result = synchronize(allreduce_async(tensor, op, name, **kw))
    return _grad_ready_fault(result, name)


def _grad_ready_fault(result, name: Optional[str]):
    """Chaos hook for the divergence sentinel (testing/faults.py,
    point ``grad_ready``): fired AFTER the reduction so an injected
    bit flip lands on this rank's copy of the agreed result — the SDC
    shape that makes exactly one rank diverge.  Corrupting the input
    instead would spread identically through the reduce to every rank
    and diverge nothing."""
    from ..testing import faults  # noqa: PLC0415

    if not faults.active():
        return result
    action = faults.maybe_fail("grad_ready", name=name)
    if action not in ("flip_bits", "nan_inject"):
        return result
    from ..utils.env import resolve_rank  # noqa: PLC0415

    corrupted = faults.corrupt_grad(
        np.asarray(result), action,
        rank=resolve_rank(0),
        step=faults.point_count("grad_ready"),
        name=name,
    )
    if isinstance(result, np.ndarray):
        return corrupted
    return jax.numpy.asarray(corrupted)


# In-place spellings: JAX arrays are immutable, so these return the result;
# they exist so reference call sites port one-to-one.
allreduce_async_ = allreduce_async
allreduce_ = allreduce


def allgather_async(tensor, name: Optional[str] = None) -> concurrent.futures.Future:
    """reference: hvd.allgather_async (torch/mpi_ops.py:231-260).  Ragged
    dim-0 across ranks is supported — sizes are negotiated (controller
    Response::tensor_sizes)."""
    engine = get_engine()
    payload, dev = _ingest(engine, tensor)
    return _tag(
        engine.enqueue(
            RequestType.ALLGATHER, name or _auto_name("allgather"), payload
        ),
        dev,
    )


def allgather(tensor, name: Optional[str] = None):
    return synchronize(allgather_async(tensor, name))


def broadcast_async(
    tensor, root_rank: int, name: Optional[str] = None
) -> concurrent.futures.Future:
    """reference: hvd.broadcast_async (torch/mpi_ops.py:330-360)."""
    engine = get_engine()
    payload, dev = _ingest(engine, tensor)
    return _tag(
        engine.enqueue(
            RequestType.BROADCAST,
            name or _auto_name("broadcast"),
            payload,
            root_rank=root_rank,
        ),
        dev,
    )


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    return synchronize(broadcast_async(tensor, root_rank, name))


broadcast_async_ = broadcast_async
broadcast_ = broadcast


def reducescatter_async(
    tensor,
    op: ReduceOp = Average,
    name: Optional[str] = None,
) -> concurrent.futures.Future:
    """Sum across ranks, keep this rank's dim-0 rows (the first leg of the
    reference's hierarchical allreduce, nccl_operations.cc:218-229, as the
    user op later Horovod versions exposed).  Uneven dim0: the first
    (dim0 % world) ranks receive one extra row."""
    from .collectives import ReduceOp as _R  # noqa: PLC0415

    if op not in (_R.AVERAGE, _R.SUM):
        raise ValueError(f"reducescatter supports Sum/Average, got {op!r}")
    engine = get_engine()
    payload, dev = _ingest(engine, tensor)
    return _tag(
        engine.enqueue(
            RequestType.REDUCESCATTER,
            name or _auto_name("reducescatter"),
            payload,
            reduce_op=int(op),
        ),
        dev,
    )


def reducescatter(tensor, op: ReduceOp = Average, name: Optional[str] = None):
    return synchronize(reducescatter_async(tensor, op, name))


def alltoall_async(tensor, name: Optional[str] = None) -> concurrent.futures.Future:
    engine = get_engine()
    payload, dev = _ingest(engine, tensor)
    return _tag(
        engine.enqueue(
            RequestType.ALLTOALL, name or _auto_name("alltoall"), payload
        ),
        dev,
    )


def alltoall(tensor, name: Optional[str] = None):
    return synchronize(alltoall_async(tensor, name))


def poll(handle: concurrent.futures.Future) -> bool:
    """True if the op has completed (reference torch/mpi_ops.py:458-472)."""
    return handle.done()


def synchronize(handle: concurrent.futures.Future):
    """Block until completion and return the result (reference
    torch/mpi_ops.py:475-491; raises the negotiated error on mismatch,
    like the reference's ErrorOp -> exception path).

    Device-resident callers get a ``jax.Array`` back on the device their
    input lived on, with the input's commitment preserved: device-plane
    results arrive as device arrays already; host-plane results (native
    engine's TCP wire, ADASUM) are placed back with one H2D transfer.  An
    uncommitted input (model.init's normal state) yields an uncommitted
    result so it flows into any downstream jit/mesh placement."""
    result = handle.result()
    tag = getattr(handle, "_hvdtpu_device", None)
    if tag is None or result is None:
        return result
    dev, committed = tag
    if not isinstance(result, jax.Array):
        result = (
            jax.device_put(result, dev) if committed and dev is not None
            else jax.device_put(result)
        )
    elif committed and dev is not None:
        # Device-plane results live on the plane's device (the lowest-id
        # local device); a caller committed elsewhere gets its result moved
        # back — "on the device their input lived on", literally.
        try:
            if next(iter(result.devices())) != dev:
                result = jax.device_put(result, dev)
        except Exception:
            pass
    if not committed:
        result = _uncommit(result)
    return result


def join() -> int:
    """Block until every rank has joined (reference hvd.join,
    torch/mpi_ops.py:494-508; semantics at controller.cc:263-307).  While
    blocked, this rank participates in peers' collectives with zero
    tensors.  Returns the last rank to join (best-effort)."""
    return get_engine().join().result()


def barrier() -> None:
    """All-rank barrier on the eager engine."""
    get_engine().barrier().result()
