"""Eager per-op collective API with async handles.

Reference surface: horovod/torch/mpi_ops.py — ``allreduce[_async][_]``,
``allgather[_async]``, ``broadcast[_async][_]``, ``poll``, ``synchronize``,
``join``, ``barrier``.  Handles map to futures resolved by the background
engine (reference HandleManager, horovod/torch/handle_manager.cc).

Use this path for host-driven, out-of-jit collectives: metric averaging,
parameter broadcast at startup, ragged allgathers, uneven-data Join.  The
training hot loop belongs on the jit path (ops/collectives.py) where XLA
fuses and schedules everything ahead of time.
"""

from __future__ import annotations

import concurrent.futures
from typing import Optional

import numpy as np

from .._engine_registry import get_engine
from ..runtime.messages import RequestType
from .collectives import Average, ReduceOp

__all__ = [
    "allreduce",
    "allreduce_",
    "allreduce_async",
    "allreduce_async_",
    "allgather",
    "allgather_async",
    "reducescatter",
    "reducescatter_async",
    "broadcast",
    "broadcast_",
    "broadcast_async",
    "broadcast_async_",
    "alltoall",
    "alltoall_async",
    "synchronize",
    "poll",
    "join",
    "barrier",
]

_name_counter = 0


def _auto_name(prefix: str) -> str:
    """Reference behavior: unnamed tensors get a sequence name
    (torch/mpi_ops.py handle naming 'allreduce.noname.N')."""
    global _name_counter
    _name_counter += 1
    return f"{prefix}.noname.{_name_counter}"


def _to_host(tensor) -> np.ndarray:
    # The eager path owns host<->device movement; jax arrays come to the
    # host once, the engine's data plane puts fused buffers back on device.
    return np.asarray(tensor)


def allreduce_async(
    tensor,
    op: ReduceOp = Average,
    name: Optional[str] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
) -> concurrent.futures.Future:
    """reference: hvd.allreduce_async (torch/mpi_ops.py:94-129)."""
    engine = get_engine()
    rtype = (
        RequestType.ADASUM if op == ReduceOp.ADASUM else RequestType.ALLREDUCE
    )
    return engine.enqueue(
        rtype,
        name or _auto_name("allreduce"),
        _to_host(tensor),
        reduce_op=int(op),
        prescale=prescale_factor,
        postscale=postscale_factor,
    )


def allreduce(tensor, op: ReduceOp = Average, name: Optional[str] = None, **kw):
    """Blocking allreduce (reference torch/mpi_ops.py:131-155)."""
    return synchronize(allreduce_async(tensor, op, name, **kw))


# In-place spellings: JAX arrays are immutable, so these return the result;
# they exist so reference call sites port one-to-one.
allreduce_async_ = allreduce_async
allreduce_ = allreduce


def allgather_async(tensor, name: Optional[str] = None) -> concurrent.futures.Future:
    """reference: hvd.allgather_async (torch/mpi_ops.py:231-260).  Ragged
    dim-0 across ranks is supported — sizes are negotiated (controller
    Response::tensor_sizes)."""
    return get_engine().enqueue(
        RequestType.ALLGATHER, name or _auto_name("allgather"), _to_host(tensor)
    )


def allgather(tensor, name: Optional[str] = None):
    return synchronize(allgather_async(tensor, name))


def broadcast_async(
    tensor, root_rank: int, name: Optional[str] = None
) -> concurrent.futures.Future:
    """reference: hvd.broadcast_async (torch/mpi_ops.py:330-360)."""
    return get_engine().enqueue(
        RequestType.BROADCAST,
        name or _auto_name("broadcast"),
        _to_host(tensor),
        root_rank=root_rank,
    )


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    return synchronize(broadcast_async(tensor, root_rank, name))


broadcast_async_ = broadcast_async
broadcast_ = broadcast


def reducescatter_async(
    tensor,
    op: ReduceOp = Average,
    name: Optional[str] = None,
) -> concurrent.futures.Future:
    """Sum across ranks, keep this rank's dim-0 rows (the first leg of the
    reference's hierarchical allreduce, nccl_operations.cc:218-229, as the
    user op later Horovod versions exposed).  Uneven dim0: the first
    (dim0 % world) ranks receive one extra row."""
    from .collectives import ReduceOp as _R  # noqa: PLC0415

    if op not in (_R.AVERAGE, _R.SUM):
        raise ValueError(f"reducescatter supports Sum/Average, got {op!r}")
    return get_engine().enqueue(
        RequestType.REDUCESCATTER,
        name or _auto_name("reducescatter"),
        _to_host(tensor),
        reduce_op=int(op),
    )


def reducescatter(tensor, op: ReduceOp = Average, name: Optional[str] = None):
    return synchronize(reducescatter_async(tensor, op, name))


def alltoall_async(tensor, name: Optional[str] = None) -> concurrent.futures.Future:
    return get_engine().enqueue(
        RequestType.ALLTOALL, name or _auto_name("alltoall"), _to_host(tensor)
    )


def alltoall(tensor, name: Optional[str] = None):
    return synchronize(alltoall_async(tensor, name))


def poll(handle: concurrent.futures.Future) -> bool:
    """True if the op has completed (reference torch/mpi_ops.py:458-472)."""
    return handle.done()


def synchronize(handle: concurrent.futures.Future):
    """Block until completion and return the result (reference
    torch/mpi_ops.py:475-491; raises the negotiated error on mismatch,
    like the reference's ErrorOp -> exception path)."""
    return handle.result()


def join() -> int:
    """Block until every rank has joined (reference hvd.join,
    torch/mpi_ops.py:494-508; semantics at controller.cc:263-307).  While
    blocked, this rank participates in peers' collectives with zero
    tensors.  Returns the last rank to join (best-effort)."""
    return get_engine().join().result()


def barrier() -> None:
    """All-rank barrier on the eager engine."""
    get_engine().barrier().result()
