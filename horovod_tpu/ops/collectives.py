"""SPMD collectives with Horovod's autodiff rules.

These are the jit-path primitives: call them inside ``shard_map`` / ``pjit``
over a named mesh axis (default :data:`horovod_tpu.basics.DP_AXIS`).  XLA
lowers them to ICI/DCN collectives; there is no runtime controller on this
path (SPMD program order already guarantees every chip issues the same
collectives in the same order, which is the invariant the reference's rank-0
negotiation protocol exists to enforce — horovod/common/controller.h:62-97).

Autodiff rules are ported from the reference's autograd Functions
(horovod/torch/mpi_ops.py):

* allreduce  backward = allreduce of the cotangent        (mpi_ops.py:158-171)
* allgather  backward = reduce, then slice own rank chunk (mpi_ops.py:289-307)
* broadcast  backward = reduce to root, zero elsewhere    (mpi_ops.py:371-385)

``Average`` is implemented as Sum + divide, exactly as the reference does in
framework code because its core rejects AVERAGE
(horovod/common/operations.cc:812-819, horovod/torch/mpi_ops.py:94-129).
"""

from __future__ import annotations

import enum
import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..basics import DP_AXIS

__all__ = [
    "ReduceOp",
    "Average",
    "Sum",
    "Adasum",
    "Min",
    "Max",
    "allreduce",
    "allreduce_",
    "grouped_allreduce",
    "allgather",
    "broadcast",
    "broadcast_",
    "alltoall",
    "reducescatter",
    "reduce_scatter_flat",
    "all_gather_flat",
    "axis_rank",
    "axis_size",
]


class ReduceOp(enum.IntEnum):
    """Reduction ops (reference: horovod_reduce_op_{average,sum,adasum},
    horovod/common/operations.cc:726-799)."""

    AVERAGE = 1
    SUM = 2
    ADASUM = 3
    MIN = 4
    MAX = 5


Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX


def _check_eager_axis(axis_name: str) -> None:
    """The eager engine always reduces over the whole process world; a
    non-default axis_name on a concrete array would silently mean something
    else, so reject it loudly (sub-axis eager collectives belong under
    shard_map)."""
    if axis_name != DP_AXIS:
        raise ValueError(
            f"axis_name={axis_name!r} is only meaningful under tracing "
            f"(shard_map/pjit); the eager path always operates over the "
            f"full process world."
        )


def _is_traced(tensor) -> bool:
    """True when we're under jit/shard_map tracing — the SPMD path.

    Concrete arrays outside a trace take the eager engine instead, so a
    single ``hvd.allreduce`` spelling serves both worlds (the reference has
    one eager spelling; its graph mode is the framework's tracer doing the
    same dispatch)."""
    return any(
        isinstance(leaf, jax.core.Tracer)
        for leaf in jax.tree_util.tree_leaves(tensor)
    )


def axis_rank(axis_name: str = DP_AXIS):
    """This shard's index along the collective axis (trace-time value)."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: str = DP_AXIS) -> int:
    """Static width of the collective axis.  ``lax.axis_size`` across
    the jax version drift: older releases lack it, where ``psum(1, axis)``
    constant-folds to the same static width (the documented pre-axis_size
    idiom)."""
    size = getattr(lax, "axis_size", None)
    if size is not None:
        return size(axis_name)
    return lax.psum(1, axis_name)


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """shard_map across the jax version drift: newer jax exposes
    ``jax.shard_map`` (replication check kwarg ``check_vma``), older
    releases only ``jax.experimental.shard_map.shard_map``
    (``check_rep``).  The ONE shim the data plane, the jit optimizer
    path, and the bench all build their shard_maps through — without it
    every one of those paths is dead on the older interpreter."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as sm  # noqa: PLC0415

    return sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _allreduce_sum(x, axis_name, average):
    y = lax.psum(x, axis_name)
    if average:
        y = y / axis_size(axis_name)
    return y


def _allreduce_fwd(x, axis_name, average):
    return _allreduce_sum(x, axis_name, average), None


def _allreduce_bwd(axis_name, average, _, g):
    # Reference rule: backward of allreduce is allreduce with the same op
    # (horovod/torch/mpi_ops.py:158-171).
    return (_allreduce_sum(g, axis_name, average),)


_allreduce_sum.defvjp(_allreduce_fwd, _allreduce_bwd)


def _eager_tree(tensor, name, call):
    """Flatten a pytree, derive per-leaf negotiation names (suffix ``.i``
    only for multi-leaf pytrees), call, unflatten — the ONE definition of
    the eager naming convention shared by every collective, so the keys
    that pair tensors across ranks can never drift between ops."""
    leaves, treedef = jax.tree_util.tree_flatten(tensor)
    outs = [
        call(leaf, f"{name}.{i}" if name and len(leaves) > 1 else name)
        for i, leaf in enumerate(leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, outs)


def allreduce(
    tensor,
    op: ReduceOp = Average,
    *,
    axis_name: str = DP_AXIS,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    name: Optional[str] = None,
):
    """Allreduce across the mesh axis (reference: hvd.allreduce,
    horovod/torch/mpi_ops.py:94-155; EnqueueTensorAllreduce,
    horovod/common/operations.cc:803).

    Works on a single array or an arbitrary pytree (each leaf reduced).
    Under tracing this is a psum over ``axis_name``; on concrete arrays it
    routes through the eager engine (named-tensor negotiation).  An
    ``IndexedSlices`` input takes the sparse allgather path (reference:
    horovod/tensorflow/__init__.py:74-89).
    """
    from .sparse import IndexedSlices, allreduce_sparse  # noqa: PLC0415

    def _sparse(s, suffix=""):
        return allreduce_sparse(
            s,
            op,
            axis_name=axis_name,
            name=(f"{name}{suffix}" if name else None),
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )

    if isinstance(tensor, IndexedSlices):
        return _sparse(tensor)
    s_leaves, s_treedef = jax.tree_util.tree_flatten(
        tensor, is_leaf=lambda x: isinstance(x, IndexedSlices)
    )
    if any(isinstance(l, IndexedSlices) for l in s_leaves):
        # Mixed pytree: sparse leaves take the allgather path, dense leaves
        # recurse onto the ordinary reduce (an IndexedSlices is itself a
        # NamedTuple pytree, so without is_leaf it would be flattened and
        # its integer indices psum'd into garbage).
        outs = [
            _sparse(l, suffix=f".{i}")
            if isinstance(l, IndexedSlices)
            else allreduce(
                l,
                op,
                axis_name=axis_name,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                name=(f"{name}.{i}" if name else None),
            )
            for i, l in enumerate(s_leaves)
        ]
        return jax.tree_util.tree_unflatten(s_treedef, outs)
    if not _is_traced(tensor):
        _check_eager_axis(axis_name)
        from . import eager  # noqa: PLC0415

        return _eager_tree(
            tensor, name,
            lambda leaf, nm: eager.allreduce(
                leaf, op, name=nm,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
            ),
        )
    del name
    if op == Adasum:
        from .adasum import adasum_allreduce  # noqa: PLC0415

        return adasum_allreduce(tensor, axis_name=axis_name)

    def one(x):
        x = jnp.asarray(x)
        if prescale_factor != 1.0:
            x = x * prescale_factor
        if op in (Average, Sum):
            y = _allreduce_sum(x, axis_name, op == Average)
        elif op == Min:
            y = lax.pmin(x, axis_name)
        elif op == Max:
            y = lax.pmax(x, axis_name)
        else:
            raise ValueError(f"unsupported reduce op {op!r}")
        if postscale_factor != 1.0:
            y = y * postscale_factor
        return y

    return jax.tree_util.tree_map(one, tensor)


def allreduce_(tensor, op: ReduceOp = Average, **kwargs):
    """In-place-spelled alias (JAX arrays are immutable; returns the result).

    Exists so reference call sites (``hvd.allreduce_``) port mechanically."""
    return allreduce(tensor, op, **kwargs)


def grouped_allreduce(
    tensors: Sequence,
    op: ReduceOp = Average,
    *,
    axis_name: str = DP_AXIS,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    fusion_threshold_bytes: Optional[int] = None,
):
    """Fused allreduce of a list of tensors via flat buffers.

    TPU-native tensor fusion: the reference memcpys entries into a 64 MB
    fusion buffer around one NCCL call
    (horovod/common/fusion_buffer_manager.cc,
    collective_operations.cc:159-210); here we flatten+concat into 1-D
    buffers, issue one psum per buffer, and split back.  Like the
    reference's FuseResponses (controller.cc:640-761), fused bins are
    capped at the fusion threshold (HVDTPU_FUSION_THRESHOLD, default
    64 MB) per dtype, so the flat buffer never materializes an unbounded
    extra copy of the gradients at peak memory.  A single leaf larger
    than the threshold gets its own bin (the reference likewise never
    splits one tensor across fusion buffers).
    """
    leaves, treedef = jax.tree_util.tree_flatten(list(tensors))
    if not leaves:
        return tensors
    if fusion_threshold_bytes is None:
        from ..utils import env as envmod  # noqa: PLC0415

        fusion_threshold_bytes = envmod.env_int(
            envmod.FUSION_THRESHOLD, envmod.DEFAULT_FUSION_BYTES
        )
    # Fuse only same-dtype runs (the reference fuses per dtype too —
    # controller.cc:676-689 look-ahead keeps dtypes homogeneous per
    # fusion), then chunk each dtype's leaves into <=threshold bins.
    out = [None] * len(leaves)
    by_dtype: dict = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.asarray(leaf).dtype, []).append(i)

    def _reduce_bin(idxs):
        if len(idxs) == 1:
            i = idxs[0]
            out[i] = allreduce(
                jnp.asarray(leaves[i]),
                op,
                axis_name=axis_name,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
            )
            return
        flat = jnp.concatenate(
            [jnp.ravel(jnp.asarray(leaves[i])) for i in idxs]
        )
        reduced = allreduce(
            flat,
            op,
            axis_name=axis_name,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )
        offset = 0
        for i in idxs:
            n = jnp.asarray(leaves[i]).size
            out[i] = lax.dynamic_slice_in_dim(reduced, offset, n).reshape(
                jnp.shape(leaves[i])
            )
            offset += n

    for dtype, idxs in by_dtype.items():
        itemsize = jnp.dtype(dtype).itemsize
        bin_idxs: list = []
        bin_bytes = 0
        for i in idxs:
            nbytes = jnp.asarray(leaves[i]).size * itemsize
            if bin_idxs and bin_bytes + nbytes > fusion_threshold_bytes:
                _reduce_bin(bin_idxs)
                bin_idxs, bin_bytes = [], 0
            bin_idxs.append(i)
            bin_bytes += nbytes
        if bin_idxs:
            _reduce_bin(bin_idxs)
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _allgather(x, axis_name):
    return lax.all_gather(x, axis_name, axis=0, tiled=True)


def _allgather_fwd(x, axis_name):
    return _allgather(x, axis_name), jnp.shape(x)[0]


def _allgather_bwd(axis_name, dim0, g):
    # Reference rule: reduce the gathered cotangent, then every rank keeps
    # its own slice (horovod/torch/mpi_ops.py:289-307).  psum_scatter does
    # both in one collective (reduce-scatter), which is strictly cheaper
    # than the reference's allreduce + narrow.
    del dim0
    return (lax.psum_scatter(g, axis_name, scatter_dimension=0, tiled=True),)


_allgather.defvjp(_allgather_fwd, _allgather_bwd)


def allgather(tensor, *, axis_name: str = DP_AXIS, name: Optional[str] = None):
    """Concatenate each shard's tensor along dim 0 (reference: hvd.allgather,
    horovod/torch/mpi_ops.py:231-307; EnqueueTensorAllgather,
    operations.cc:856).

    The jit path requires equal dim-0 sizes (static shapes; XLA constraint).
    Ragged gathers — the reference negotiates per-rank sizes at runtime
    (controller.cc:453-518) — are served by the eager path, which pads to
    the negotiated max and slices on the host.
    """
    if not _is_traced(tensor):
        _check_eager_axis(axis_name)
        from . import eager  # noqa: PLC0415

        return _eager_tree(
            tensor, name, lambda leaf, nm: eager.allgather(leaf, name=nm)
        )
    del name
    return jax.tree_util.tree_map(
        lambda x: _allgather(jnp.asarray(x), axis_name), tensor
    )


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _broadcast(x, root_rank, axis_name):
    # One psum of a masked value: every non-root contributes zeros, so the
    # sum is exactly the root's tensor.  XLA lowers this to a single
    # all-reduce; on TPU this beats gather-then-index.
    mask = (lax.axis_index(axis_name) == root_rank).astype(x.dtype)
    return lax.psum(x * mask, axis_name)


def _broadcast_fwd(x, root_rank, axis_name):
    return _broadcast(x, root_rank, axis_name), None


def _broadcast_bwd(root_rank, axis_name, _, g):
    # Reference rule: sum cotangents to the root, zeros elsewhere
    # (horovod/torch/mpi_ops.py:371-385).
    summed = lax.psum(g, axis_name)
    mask = (lax.axis_index(axis_name) == root_rank).astype(g.dtype)
    return (summed * mask,)


_broadcast.defvjp(_broadcast_fwd, _broadcast_bwd)


def broadcast(
    tensor, root_rank: int, *, axis_name: str = DP_AXIS, name: Optional[str] = None
):
    """Broadcast the root shard's value to every shard (reference:
    hvd.broadcast, horovod/torch/mpi_ops.py:330-406; EnqueueTensorBroadcast,
    operations.cc:891)."""
    if not _is_traced(tensor):
        _check_eager_axis(axis_name)
        from . import eager  # noqa: PLC0415

        return _eager_tree(
            tensor, name,
            lambda leaf, nm: eager.broadcast(leaf, root_rank, name=nm),
        )
    del name
    return jax.tree_util.tree_map(
        lambda x: _broadcast(jnp.asarray(x), root_rank, axis_name), tensor
    )


def broadcast_(tensor, root_rank: int, **kwargs):
    """In-place-spelled alias; see :func:`allreduce_`."""
    return broadcast(tensor, root_rank, **kwargs)


# ---------------------------------------------------------------------------
# flat reduce-scatter / all-gather pair (the ZeRO-shape building blocks)
# ---------------------------------------------------------------------------
#
# 1-D tiled scatter/gather with each other as VJP: the backward of
# gathering shards into a full buffer is reduce-scattering the cotangent
# (and vice versa).  This is what lets the overlap plane
# (horovod_tpu.optim.overlap) express a ZeRO-1 step as "all-gather the
# parameter shards in the forward" and get the per-bucket gradient
# reduce-scatter emitted *inside the backward graph* for free — the
# cotangent of each bucket's gather fires the moment that bucket's last
# gradient materializes, which is the position XLA's latency-hiding
# scheduler needs to overlap the wire with remaining backward compute.


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _reduce_scatter_flat(x, axis_name):
    return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)


def _reduce_scatter_flat_fwd(x, axis_name):
    return _reduce_scatter_flat(x, axis_name), None


def _reduce_scatter_flat_bwd(axis_name, _, g):
    # d(reduce_scatter)/dx: every rank's contribution to every element is
    # weighted 1, so the cotangent of the owned shard broadcasts back to
    # the full buffer — one tiled all-gather.
    return (lax.all_gather(g, axis_name, axis=0, tiled=True),)


_reduce_scatter_flat.defvjp(_reduce_scatter_flat_fwd, _reduce_scatter_flat_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _all_gather_flat(x, axis_name):
    return lax.all_gather(x, axis_name, axis=0, tiled=True)


def _all_gather_flat_fwd(x, axis_name):
    return _all_gather_flat(x, axis_name), None


def _all_gather_flat_bwd(axis_name, _, g):
    # Reference allgather rule (mpi_ops.py:289-307) on the flat buffer:
    # reduce the gathered cotangent and keep the own-rank chunk —
    # psum_scatter does both in one collective.
    return (lax.psum_scatter(g, axis_name, scatter_dimension=0, tiled=True),)


_all_gather_flat.defvjp(_all_gather_flat_fwd, _all_gather_flat_bwd)


def reduce_scatter_flat(flat, op: ReduceOp = Sum, *,
                        axis_name: str = DP_AXIS):
    """Reduce a 1-D buffer across the axis, keep this shard's tiled chunk
    (``dim0`` must divide the axis size — pad first).  The element-wise
    result is bitwise-identical to the matching slice of a full ``psum``,
    which is what makes a reduce-scatter-sharded optimizer update provably
    equivalent to the replicated one (tests/test_overlap.py)."""
    if op not in (Sum, Average):
        raise ValueError(f"reduce_scatter_flat supports Sum/Average, got {op!r}")
    y = _reduce_scatter_flat(jnp.asarray(flat), axis_name)
    if op == Average:
        y = y / axis_size(axis_name)
    return y


def all_gather_flat(shard, *, axis_name: str = DP_AXIS):
    """Concatenate each rank's 1-D shard along dim 0 (tiled), the exact
    inverse of :func:`reduce_scatter_flat`'s slicing.  Its VJP is the
    reduce-scatter of the cotangent, so gathering parameter shards in a
    forward pass plants the gradient reduce-scatter inside the backward."""
    return _all_gather_flat(jnp.asarray(shard), axis_name)


# ---------------------------------------------------------------------------
# alltoall / reducescatter (TPU-first extensions)
# ---------------------------------------------------------------------------


def alltoall(tensor, *, axis_name: str = DP_AXIS,
             name: Optional[str] = None):
    """Scatter dim-0 chunks to each shard and gather their chunks (the
    primitive behind Ulysses-style sequence parallelism).  Not present in
    the reference at 0.19.1 (SURVEY.md §2.9); provided because all-to-all is
    first-class on the ICI torus and later Horovod grew it.  ``name`` keys
    the eager negotiation, like allreduce's."""
    if not _is_traced(tensor):
        _check_eager_axis(axis_name)
        from . import eager  # noqa: PLC0415

        return _eager_tree(
            tensor, name, lambda leaf, nm: eager.alltoall(leaf, name=nm)
        )

    def one(x):
        x = jnp.asarray(x)
        n = axis_size(axis_name)
        if x.shape[0] % n != 0:
            raise ValueError(
                f"alltoall dim0 ({x.shape[0]}) must divide the axis size ({n})"
            )
        return lax.all_to_all(
            x.reshape((n, x.shape[0] // n) + x.shape[1:]),
            axis_name,
            split_axis=0,
            concat_axis=0,
            tiled=False,
        ).reshape(x.shape)

    return jax.tree_util.tree_map(one, tensor)


def reducescatter(tensor, op: ReduceOp = Average, *,
                  axis_name: str = DP_AXIS, name: Optional[str] = None):
    """Sum across shards, keep only this shard's dim-0 slice — the first leg
    of the reference's hierarchical allreduce (nccl_operations.cc:218-229)
    exposed as a user op.  Under tracing this is ``lax.psum_scatter``
    (dim0 must divide the axis size — XLA static shapes); on concrete
    arrays the eager engine serves it with the uneven-dim0 convention
    (first ``dim0 % world`` ranks get one extra row).  ``name`` keys the
    eager negotiation, like allreduce's."""
    if not _is_traced(tensor):
        _check_eager_axis(axis_name)
        from . import eager  # noqa: PLC0415

        return _eager_tree(
            tensor, name,
            lambda leaf, nm: eager.reducescatter(leaf, op, name=nm),
        )

    def one(x):
        x = jnp.asarray(x)
        y = lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
        if op == Average:
            y = y / axis_size(axis_name)
        elif op != Sum:
            raise ValueError(f"reducescatter supports Sum/Average, got {op!r}")
        return y

    return jax.tree_util.tree_map(one, tensor)
