"""Collective ops for horovod_tpu.

Two execution paths, mirroring the design split in SURVEY.md §7:

* :mod:`horovod_tpu.ops.collectives` -- the **jit/SPMD path**: per-device
  collectives (psum / all_gather / ppermute) with Horovod's autodiff rules,
  usable inside ``pjit`` / ``shard_map`` over a named mesh axis.  XLA
  schedules and fuses these; no runtime controller is involved (the
  reference needed one because NCCL kernels are invisible to the framework
  compiler; XLA collectives are not).
* :mod:`horovod_tpu.ops.eager` -- the **eager per-op path**: Horovod-style
  named-tensor enqueue (``allreduce_async_`` / ``synchronize``) coordinated
  by the native background engine, for API parity with the reference's
  horovod/torch/mpi_ops.py surface.
"""

from .collectives import (  # noqa: F401
    ReduceOp,
    Average,
    Sum,
    Adasum,
    allreduce,
    allreduce_,
    grouped_allreduce,
    allgather,
    broadcast,
    broadcast_,
    alltoall,
    reducescatter,
)
