"""Pallas TPU flash attention.

The single-chip hot kernel under the transformer model family (and the
per-shard block compute of :mod:`horovod_tpu.parallel.ring_attention`).
The reference framework has no kernels of its own — its FLOPs live in
cuDNN via TF/torch; on TPU the idiomatic equivalent is a Pallas kernel
that keeps the (S, S) score matrix out of HBM entirely.

Design (the standard flash recurrence, TPU-shaped):

* Grid ``(batch*heads, S/block_q, S/block_k)``; each program owns one Q
  tile and one (1, block_k, d) K/V tile in VMEM — the online-softmax
  state rides VMEM scratch across the sequential K grid dimension, so
  peak memory is O(block_q*d + block_k*d), independent of S.
* fp32 accumulators regardless of input dtype (bf16 in, bf16 out, fp32
  softmax state — the MXU-native mixed precision).
* Causal programs stop their K loop at the diagonal tile — the upper
  triangle is never computed, not just masked.
* Backward is a blockwise recompute from the saved logsumexp (scan over
  K tiles, O(S * block_k) live), wired via ``jax.custom_vjp`` so the op
  drops into training.
* Off-TPU (the CPU test mesh) the same kernel runs through the Pallas
  interpreter, so correctness tests don't need TPU hardware.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min) / 2


def _pick_block(seq: int, want: int) -> int:
    """Largest power-of-two block <= want that divides seq."""
    b = min(want, seq)
    while seq % b:
        b //= 2
    return max(b, 1)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 256,
    window: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over ``[batch, seq, heads, head_dim]`` inputs.

    Differentiable; numerically matches
    :func:`horovod_tpu.parallel.local_attention` to fp32 tolerance.
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU.

    ``window=W`` (requires ``causal=True``) restricts each position to
    its last ``W`` keys (self included) — Mistral-style sliding-window
    attention.  Tiles entirely outside the band are SKIPPED in forward
    and backward (the same mechanism as the causal upper-triangle skip),
    so compute scales with ``S*W``, not ``S^2``; ``W >= S`` degenerates
    to plain causal.
    """
    b, s, h, d = q.shape
    if k.shape != v.shape:
        raise ValueError(
            f"flash_attention requires matching k/v shapes, got "
            f"{k.shape}/{v.shape}"
        )
    hkv = k.shape[2]
    if k.shape[0] != b or k.shape[1] != s or k.shape[3] != d or h % hkv:
        raise ValueError(
            f"flash_attention q {q.shape} incompatible with k/v {k.shape}: "
            "batch/seq/head_dim must match and num_heads must be a "
            "multiple of num_kv_heads (MQA/GQA)"
        )
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if window >= s:
            window = None  # full causal; skip/mask logic not needed
    scale_ = scale if scale is not None else d ** -0.5
    bq = _pick_block(s, block_q)
    bk = _pick_block(s, block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # [B,S,H,D] -> [B*H, S, D]: one grid row per (batch, head).  GQA/MQA:
    # k/v fold to [B*HKV, S, D] and the kernels' index maps route each q
    # head to its kv group — no broadcast materialization.
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(
        b * x.shape[2], s, d
    )
    out = _flash(fold(q), fold(k), fold(v), causal, scale_, bq, bk,
                 h, hkv, window, bool(interpret))
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, causal, scale, bq, bk, h, hkv, window, interpret):
    o, _ = _flash_fwd_kernel(q, k, v, causal, scale, bq, bk, h, hkv,
                             window, interpret)
    return o


def _flash_fwd(q, k, v, causal, scale, bq, bk, h, hkv, window,
               interpret):
    o, lse = _flash_fwd_kernel(q, k, v, causal, scale, bq, bk, h, hkv,
                               window, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, bq, bk, h, hkv, window, interpret, res,
               do):
    q, k, v, o, lse = res
    return _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale, bq, bk,
                             h, hkv, window, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _kv_row(zi, h: int, hkv: int):
    """Grid row (b*h + head) -> folded kv row (b*hkv + head//group)."""
    return (zi // h) * hkv + (zi % h) // (h // hkv)


def _flash_fwd_kernel(q, k, v, causal, scale, bq, bk, h, hkv, window,
                      interpret):
    """Returns (o [Z,S,D], lse [Z,S]) with Z = batch*heads.

    K tiles live on the innermost grid dimension, so only (1, bk, d) of K
    and V are resident per step — VMEM peak is O(bq*d + bk*d), independent
    of S (the long-context requirement).  The online-softmax state (acc,
    m, l) persists across the sequential K dimension in VMEM scratch and
    is flushed to the output block at the last K tile.  GQA/MQA: k/v have
    Z_kv = batch*hkv rows; the index map routes each q head to its group.
    """
    z, s, d = q.shape
    nq, nk = s // bq, s // bk

    # Mosaic requires the last two block dims to be (8k, 128k) or full —
    # scalars-per-row state therefore rides a broadcast 128-lane dim, the
    # same layout the public jax TPU flash kernel uses (MIN_BLOCK_SIZE).
    LANES = 128

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref):
        i = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)

        # Causal: K tiles strictly above the diagonal contribute
        # nothing; with a window, tiles entirely below the band are dead
        # too — skip both (their DMA is pipelined regardless).
        needed = (j * bk <= (i + 1) * bq - 1) if causal else (j >= 0)
        if window is not None:
            needed = jnp.logical_and(
                needed, (j + 1) * bk - 1 >= i * bq - (window - 1)
            )

        @pl.when(needed)
        def _compute():
            qb = q_ref[0].astype(jnp.float32) * scale  # [bq, d]
            kb = k_ref[0].astype(jnp.float32)          # [bk, d]
            vb = v_ref[0].astype(jnp.float32)
            st = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)
            if causal:
                q_pos = i * bq + lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0
                )
                k_pos = j * bk + lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1
                )
                st = jnp.where(k_pos > q_pos, NEG_INF, st)
                if window is not None:
                    st = jnp.where(k_pos < q_pos - (window - 1),
                                   NEG_INF, st)
            m_prev = m_ref[...]                       # [bq, LANES], lanes equal
            m_new = jnp.maximum(m_prev, st.max(-1)[:, None])
            p = jnp.exp(st - m_new[:, :1])
            corr = jnp.exp(m_prev - m_new)
            l_ref[...] = l_ref[...] * corr + p.sum(-1)[:, None]
            acc_ref[...] = acc_ref[...] * corr[:, :1] + jnp.dot(
                p, vb, preferred_element_type=jnp.float32
            )
            m_ref[...] = m_new

        @pl.when(j == nk - 1)
        def _flush():
            o_ref[0] = (acc_ref[...] / l_ref[:, :1]).astype(o_ref.dtype)
            lse_ref[0] = m_ref[...] + jnp.log(l_ref[...])

    o, lse_wide = pl.pallas_call(
        kernel,
        grid=(z, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda zi, qi, ki: (zi, qi, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda zi, qi, ki: (_kv_row(zi, h, hkv), ki, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda zi, qi, ki: (_kv_row(zi, h, hkv), ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda zi, qi, ki: (zi, qi, 0)),
            pl.BlockSpec((1, bq, LANES), lambda zi, qi, ki: (zi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((z, s, d), q.dtype),
            jax.ShapeDtypeStruct((z, s, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),       # acc
            pltpu.VMEM((bq, LANES), jnp.float32),   # running max m
            pltpu.VMEM((bq, LANES), jnp.float32),   # running sum l
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse_wide[:, :, 0]


def _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale, bq, bk,
                      h, hkv, window, interpret):
    """Fused Pallas flash backward: two passes, both tiled, both skipping
    fully-masked causal blocks (the scan fallback below computes the whole
    upper triangle and streams O(S*bk) score tiles through HBM — on a
    causal LM that is ~2x wasted FLOPs and the dominant HBM stream).

    Pass A (grid z_kv, nk, nq*group): K tile fixed, (q-head-in-group, Q
    tile) pairs stream sequentially; dk/dv accumulate in VMEM scratch —
    under GQA the whole group's contribution folds into one kv row — and
    flush at the last pair.
    Pass B (grid z, nq, nk): Q tile fixed, K tiles stream; dq accumulates.
    Both recompute P from the forward's saved logsumexp; ``delta`` =
    rowsum(do*o) is the standard softmax-backward correction.
    """
    z, s, d = q.shape
    z_kv = k.shape[0]
    group = h // hkv
    nq, nk = s // bq, s // bk
    f32 = jnp.float32
    LANES = 128
    # lse rides the same broadcast 128-lane layout as the forward's
    # softmax state (and the public jax TPU kernel's l/m blocks): Mosaic
    # requires the last two block dims to be (8k, 128k) or full, which a
    # narrow (1, bq) block over [Z, S] violates on hardware.  delta
    # (rowsum(do*o)) needs no such array: it is recomputed per tile from
    # the o tile, which is cheaper than streaming a (Z, S, 128) f32
    # broadcast through HBM twice.
    lse_w = jnp.broadcast_to(lse[:, :, None], (z, s, LANES))

    def _recompute_p_ds(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, i, j):
        """The shared backward recurrence: rebuild this tile's softmax P
        from the saved logsumexp and form dS = P * (dP - delta).  One
        definition for both passes so the mask/scale math cannot drift."""
        qb = q_ref[0].astype(f32)
        kb = k_ref[0].astype(f32)
        vb = v_ref[0].astype(f32)
        dob = do_ref[0].astype(f32)
        delta_col = (dob * o_ref[0].astype(f32)).sum(-1)[:, None]
        st = jnp.dot(qb, kb.T, preferred_element_type=f32) * scale
        p = jnp.exp(st - lse_ref[0][:, :1])
        if causal:
            q_pos = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = jnp.where(k_pos > q_pos, 0.0, p)
            if window is not None:
                p = jnp.where(k_pos < q_pos - (window - 1), 0.0, p)
        dp = jnp.dot(dob, vb.T, preferred_element_type=f32)
        ds = p * (dp - delta_col)
        return qb, kb, dob, p, ds

    def kernel_dkdv(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc):
        j = pl.program_id(1)
        t = pl.program_id(2)          # (q head in group) * nq + (q tile)
        i = t % nq

        @pl.when(t == 0)
        def _init():
            dk_acc[...] = jnp.zeros_like(dk_acc)
            dv_acc[...] = jnp.zeros_like(dv_acc)

        # Q tiles entirely above the diagonal see only masked scores;
        # with a window, Q tiles entirely past the band do too.
        needed = ((i + 1) * bq - 1 >= j * bk) if causal else (i >= 0)
        if window is not None:
            needed = jnp.logical_and(
                needed, (j + 1) * bk - 1 >= i * bq - (window - 1)
            )

        @pl.when(needed)
        def _compute():
            qb, _, dob, p, ds = _recompute_p_ds(
                q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, i, j
            )
            dv_acc[...] += jnp.dot(p.T, dob, preferred_element_type=f32)
            dk_acc[...] += jnp.dot(ds.T, qb,
                                   preferred_element_type=f32) * scale

        @pl.when(t == nq * group - 1)
        def _flush():
            dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
            dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)

    def kernel_dq(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                  dq_ref, dq_acc):
        i = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            dq_acc[...] = jnp.zeros_like(dq_acc)

        needed = (j * bk <= (i + 1) * bq - 1) if causal else (j >= 0)
        if window is not None:
            needed = jnp.logical_and(
                needed, (j + 1) * bk - 1 >= i * bq - (window - 1)
            )

        @pl.when(needed)
        def _compute():
            _, kb, _, _, ds = _recompute_p_ds(
                q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, i, j
            )
            dq_acc[...] += jnp.dot(ds, kb,
                                   preferred_element_type=f32) * scale

        @pl.when(j == nk - 1)
        def _flush():
            dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)

    qkv_spec = lambda tile, which: pl.BlockSpec((1, tile, d), which)
    lane_spec = lambda which: pl.BlockSpec((1, bq, LANES), which)

    def _qrow(zi, ti):
        """Pass-A q row for kv row ``zi`` and inner step ``ti``."""
        return (zi // hkv) * h + (zi % hkv) * group + ti // nq

    dk, dv = pl.pallas_call(
        kernel_dkdv,
        grid=(z_kv, nk, nq * group),
        in_specs=[
            qkv_spec(bq, lambda zi, ji, ti: (_qrow(zi, ti), ti % nq, 0)),
            qkv_spec(bk, lambda zi, ji, ti: (zi, ji, 0)),   # k
            qkv_spec(bk, lambda zi, ji, ti: (zi, ji, 0)),   # v
            qkv_spec(bq, lambda zi, ji, ti: (_qrow(zi, ti), ti % nq, 0)),
            qkv_spec(bq, lambda zi, ji, ti: (_qrow(zi, ti), ti % nq, 0)),
            lane_spec(lambda zi, ji, ti: (_qrow(zi, ti), ti % nq, 0)),
        ],
        out_specs=[
            qkv_spec(bk, lambda zi, ji, ti: (zi, ji, 0)),
            qkv_spec(bk, lambda zi, ji, ti: (zi, ji, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((z_kv, s, d), k.dtype),
            jax.ShapeDtypeStruct((z_kv, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), f32),
            pltpu.VMEM((bk, d), f32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, o, do, lse_w)
    (dq,) = pl.pallas_call(
        kernel_dq,
        grid=(z, nq, nk),
        in_specs=[
            qkv_spec(bq, lambda zi, ii, ji: (zi, ii, 0)),
            qkv_spec(bk, lambda zi, ii, ji: (_kv_row(zi, h, hkv), ji, 0)),
            qkv_spec(bk, lambda zi, ii, ji: (_kv_row(zi, h, hkv), ji, 0)),
            qkv_spec(bq, lambda zi, ii, ji: (zi, ii, 0)),
            qkv_spec(bq, lambda zi, ii, ji: (zi, ii, 0)),
            lane_spec(lambda zi, ii, ji: (zi, ii, 0)),
        ],
        out_specs=[qkv_spec(bq, lambda zi, ii, ji: (zi, ii, 0))],
        out_shape=[jax.ShapeDtypeStruct((z, s, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, d), f32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, o, do, lse_w)
    return dq, dk, dv


def _flash_bwd_blockwise(q, k, v, o, lse, do, causal, scale, bk,
                         window=None):
    """Blockwise flash backward (pure JAX scan over K tiles) — kept as the
    differential reference for the Pallas backward (tests pin equality)
    and as a debugging fallback.
    """
    z, s, d = q.shape
    nk = s // bk
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    dof, of = do.astype(jnp.float32), o.astype(jnp.float32)
    delta = (dof * of).sum(-1)  # [Z,S]
    q_pos = jnp.arange(s)

    def body(dq, j):
        kb = lax.dynamic_slice_in_dim(kf, j * bk, bk, axis=1)
        vb = lax.dynamic_slice_in_dim(vf, j * bk, bk, axis=1)
        st = jnp.einsum("zqd,zkd->zqk", qf, kb) * scale
        p = jnp.exp(st - lse[..., None])  # exact softmax: exp(s-m)/l
        if causal:
            k_pos = j * bk + jnp.arange(bk)
            p = jnp.where(k_pos[None, :] > q_pos[:, None], 0.0, p)
            if window is not None:
                p = jnp.where(
                    k_pos[None, :] < q_pos[:, None] - (window - 1),
                    0.0, p,
                )
        dp = jnp.einsum("zqd,zkd->zqk", dof, vb)
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("zqk,zkd->zqd", ds, kb) * scale
        dk_j = jnp.einsum("zqk,zqd->zkd", ds, qf) * scale
        dv_j = jnp.einsum("zqk,zqd->zkd", p, dof)
        return dq, (dk_j, dv_j)

    dq, (dks, dvs) = lax.scan(
        body, jnp.zeros_like(qf), jnp.arange(nk)
    )
    # stacked [nk, Z, bk, D] -> [Z, S, D]
    unfold = lambda t: t.transpose(1, 0, 2, 3).reshape(z, s, d)
    return (
        dq.astype(q.dtype),
        unfold(dks).astype(k.dtype),
        unfold(dvs).astype(v.dtype),
    )
