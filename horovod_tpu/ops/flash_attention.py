"""Pallas TPU flash attention.

The single-chip hot kernel under the transformer model family (and the
per-shard block compute of :mod:`horovod_tpu.parallel.ring_attention`).
The reference framework has no kernels of its own — its FLOPs live in
cuDNN via TF/torch; on TPU the idiomatic equivalent is a Pallas kernel
that keeps the (S, S) score matrix out of HBM entirely.

Design (the standard flash recurrence, TPU-shaped):

* Grid ``(batch*heads, S/block_q)``; each program owns one Q tile in VMEM
  and streams K/V tiles through the MXU with an online softmax, so peak
  memory is O(block_q * block_k) instead of O(S^2).
* fp32 accumulators regardless of input dtype (bf16 in, bf16 out, fp32
  softmax state — the MXU-native mixed precision).
* Causal programs stop their K loop at the diagonal tile — the upper
  triangle is never computed, not just masked.
* Backward is a blockwise recompute from the saved logsumexp (scan over
  K tiles, O(S * block_k) live), wired via ``jax.custom_vjp`` so the op
  drops into training.
* Off-TPU (the CPU test mesh) the same kernel runs through the Pallas
  interpreter, so correctness tests don't need TPU hardware.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = float(jnp.finfo(jnp.float32).min) / 2


def _pick_block(seq: int, want: int) -> int:
    """Largest power-of-two block <= want that divides seq."""
    b = min(want, seq)
    while seq % b:
        b //= 2
    return max(b, 1)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over ``[batch, seq, heads, head_dim]`` inputs.

    Differentiable; numerically matches
    :func:`horovod_tpu.parallel.local_attention` to fp32 tolerance.
    ``interpret=None`` auto-selects the Pallas interpreter off-TPU.
    """
    b, s, h, d = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(
            f"flash_attention requires matching q/k/v shapes, got "
            f"{q.shape}/{k.shape}/{v.shape} (MQA/GQA: broadcast k/v first)"
        )
    scale_ = scale if scale is not None else d ** -0.5
    bq = _pick_block(s, block_q)
    bk = _pick_block(s, block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # [B,S,H,D] -> [B*H, S, D]: one grid row per (batch, head)
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    out = _flash(fold(q), fold(k), fold(v), causal, scale_, bq, bk,
                 bool(interpret))
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, bq, bk, interpret):
    o, _ = _flash_fwd_kernel(q, k, v, causal, scale, bq, bk, interpret)
    return o


def _flash_fwd(q, k, v, causal, scale, bq, bk, interpret):
    o, lse = _flash_fwd_kernel(q, k, v, causal, scale, bq, bk, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, bq, bk, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd_blockwise(q, k, v, o, lse, do, causal, scale, bk)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _flash_fwd_kernel(q, k, v, causal, scale, bq, bk, interpret):
    """Returns (o [Z,S,D], lse [Z,S]) with Z = batch*heads."""
    z, s, d = q.shape
    nq, nk = s // bq, s // bk

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref):
        i = pl.program_id(1)
        qb = q_ref[0].astype(jnp.float32) * scale  # [bq, d]
        q_pos = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

        def body(j, carry):
            acc, m, l = carry
            kb = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
            vb = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
            st = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)
            if causal:
                k_pos = j * bk + lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1
                )
                st = jnp.where(k_pos > q_pos, NEG_INF, st)
            m_new = jnp.maximum(m, st.max(-1))
            p = jnp.exp(st - m_new[:, None])
            corr = jnp.exp(m - m_new)
            l = l * corr + p.sum(-1)
            acc = acc * corr[:, None] + jnp.dot(
                p, vb, preferred_element_type=jnp.float32
            )
            return acc, m_new, l

        # Causal: K tiles strictly above the diagonal contribute nothing —
        # stop the loop at the diagonal tile instead of masking them.
        if causal:
            n_iter = lax.min(nk, ((i + 1) * bq + bk - 1) // bk)
        else:
            n_iter = nk
        acc0 = jnp.zeros((bq, d), jnp.float32)
        m0 = jnp.full((bq,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((bq,), jnp.float32)
        acc, m, l = lax.fori_loop(0, n_iter, body, (acc0, m0, l0))
        o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
        lse_ref[0] = m + jnp.log(l)

    o, lse = pl.pallas_call(
        kernel,
        grid=(z, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda zi, qi: (zi, qi, 0)),
            pl.BlockSpec((1, s, d), lambda zi, qi: (zi, 0, 0)),
            pl.BlockSpec((1, s, d), lambda zi, qi: (zi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda zi, qi: (zi, qi, 0)),
            pl.BlockSpec((1, bq), lambda zi, qi: (zi, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((z, s, d), q.dtype),
            jax.ShapeDtypeStruct((z, s), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _flash_bwd_blockwise(q, k, v, o, lse, do, causal, scale, bk):
    """Blockwise flash backward (pure JAX scan over K tiles).

    Recomputes P tile-by-tile from the saved logsumexp — the standard
    flash-attention backward — so live memory stays O(S * bk) per (b,h)
    rather than O(S^2).  XLA maps the einsums onto the MXU directly; a
    hand-fused Pallas backward is a later optimization, the math and
    memory behavior here already match flash semantics.
    """
    z, s, d = q.shape
    nk = s // bk
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    dof, of = do.astype(jnp.float32), o.astype(jnp.float32)
    delta = (dof * of).sum(-1)  # [Z,S]
    q_pos = jnp.arange(s)

    def body(dq, j):
        kb = lax.dynamic_slice_in_dim(kf, j * bk, bk, axis=1)
        vb = lax.dynamic_slice_in_dim(vf, j * bk, bk, axis=1)
        st = jnp.einsum("zqd,zkd->zqk", qf, kb) * scale
        p = jnp.exp(st - lse[..., None])  # exact softmax: exp(s-m)/l
        if causal:
            k_pos = j * bk + jnp.arange(bk)
            p = jnp.where(k_pos[None, :] > q_pos[:, None], 0.0, p)
        dp = jnp.einsum("zqd,zkd->zqk", dof, vb)
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("zqk,zkd->zqd", ds, kb) * scale
        dk_j = jnp.einsum("zqk,zqd->zkd", ds, qf) * scale
        dv_j = jnp.einsum("zqk,zqd->zkd", p, dof)
        return dq, (dk_j, dv_j)

    dq, (dks, dvs) = lax.scan(
        body, jnp.zeros_like(qf), jnp.arange(nk)
    )
    # stacked [nk, Z, bk, D] -> [Z, S, D]
    unfold = lambda t: t.transpose(1, 0, 2, 3).reshape(z, s, d)
    return (
        dq.astype(q.dtype),
        unfold(dks).astype(k.dtype),
        unfold(dvs).astype(v.dtype),
    )
