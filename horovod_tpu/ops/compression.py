"""Gradient compression (reference: horovod/torch/compression.py:20-74 and
horovod/tensorflow/compression.py — identical shape).

The reference halves allreduce bytes by casting fp32 grads to fp16 before
the wire and back after.  On TPU the natural wire dtype is **bfloat16**
(same exponent range as fp32, native MXU/ICI support), so that is the
default compressor; fp16 is kept for parity.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["Compressor", "NoneCompressor", "BFloat16Compressor", "FP16Compressor", "Compression"]


class Compressor:
    """Interface (reference compression.py:20-31)."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context-for-decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference compression.py:34-44)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        del ctx
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype

    @classmethod
    def compress(cls, tensor):
        dtype = jnp.asarray(tensor).dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return jnp.asarray(tensor, cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else jnp.asarray(tensor, ctx)


class BFloat16Compressor(_CastCompressor):
    """Cast floats to bf16 on the wire — the TPU-native halving."""

    wire_dtype = jnp.bfloat16


class FP16Compressor(_CastCompressor):
    """Reference-parity fp16 compressor (compression.py:47-63)."""

    wire_dtype = jnp.float16


class Compression:
    """Namespace matching ``hvd.Compression`` (reference compression.py:66-74)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BFloat16Compressor
