"""Gradient compression (reference: horovod/torch/compression.py:20-74 and
horovod/tensorflow/compression.py — identical shape).

The reference halves allreduce bytes by casting fp32 grads to fp16 before
the wire and back after.  On TPU the natural wire dtype is **bfloat16**
(same exponent range as fp32, native MXU/ICI support), so that is the
default compressor; fp16 is kept for parity.

Multi-slice jobs use these compressors on the DCN leg of hierarchical
allreduce (``--dcn-compression``): only the 1/local_size shard that
crosses the slow fabric is cast, the ICI phases stay exact.  For
optimizer-level compression of the whole wire,
:class:`ErrorFeedbackCompressor` carries the quantization residual
forward so the bias does not accumulate across steps.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "Compressor",
    "NoneCompressor",
    "BFloat16Compressor",
    "FP16Compressor",
    "ErrorFeedbackCompressor",
    "Compression",
]


class Compressor:
    """Interface (reference compression.py:20-31).

    Contract: ``compress`` preserves the tensor's SHAPE and may narrow
    its dtype (the wire dtype); ``decompress`` restores the original
    dtype exactly and never changes the shape.  Round-tripping is lossy
    for values a narrower wire cannot represent — bounded by the wire
    format's relative precision (bf16: 2^-8, fp16: 2^-11 for in-range
    values), never by more.
    """

    @staticmethod
    def compress(tensor):
        """tensor -> ``(wire_tensor, ctx)``: same shape, possibly
        narrower dtype; ``ctx`` is whatever ``decompress`` needs to
        restore the original dtype (``None`` = nothing to undo)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        """``(wire_tensor, ctx)`` -> tensor in the original dtype; the
        shape is returned untouched."""
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference compression.py:34-44)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        del ctx
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype

    @classmethod
    def compress(cls, tensor):
        dtype = jnp.asarray(tensor).dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return jnp.asarray(tensor, cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else jnp.asarray(tensor, ctx)


class BFloat16Compressor(_CastCompressor):
    """Cast floats to bf16 on the wire — the TPU-native halving."""

    wire_dtype = jnp.bfloat16


class FP16Compressor(_CastCompressor):
    """Reference-parity fp16 compressor (compression.py:47-63)."""

    wire_dtype = jnp.float16


class ErrorFeedbackCompressor(Compressor):
    """Residual-carrying (error-feedback) compressor for the DCN leg.

    A plain cast compressor throws its quantization error away every
    step; over many steps the *bias* of that error accumulates in the
    model (the EF-SGD observation — Seide et al. 2014, Karimireddy et
    al. 2019).  This wrapper keeps the residual ``x - dec(enc(x))`` per
    tensor key and adds it back before the next compression, so every
    quantized bit eventually reaches the wire: the error is carried, not
    compounded.

    Stateful (a residual per ``key``), so it lives OUTSIDE jit: the
    residual dict is ordinary Python state, and calling ``compress``
    under a trace would leak tracers into it — a guard below raises
    instead.  That also means it is NOT a drop-in for the
    ``DistributedGradientTransform(compression=...)`` hook (which runs
    inside the jitted step AND compresses many leaves with no key —
    same-shape leaves would cross-contaminate residuals through the
    shared default).  Use it at the eager layer, bracketing the reduce
    yourself, with an explicit ``key`` per tensor stream.

    Contract refinements over :class:`Compressor`: ``compress`` takes a
    stable ``key`` identifying the tensor stream (the default is only
    safe for a SINGLE stream); shapes must be stable per key — a shape
    change resets that key's residual.
    """

    def __init__(self, inner=BFloat16Compressor):
        self._inner = inner
        self._residuals: dict = {}

    def compress(self, tensor, *, key: str = "default"):
        import jax.core as _core  # noqa: PLC0415

        if isinstance(tensor, _core.Tracer):
            raise TypeError(
                "ErrorFeedbackCompressor is stateful (residual carried "
                "across calls) and cannot run inside jit/shard_map "
                "tracing; compress eagerly, or use a pure cast "
                "compressor (Compression.bf16/fp16) on the wire"
            )
        t = jnp.asarray(tensor)
        prev = self._residuals.get(key)
        if prev is not None and prev.shape == t.shape:
            t = t + prev.astype(t.dtype)
        wire, ctx = self._inner.compress(t)
        # Residual in the ORIGINAL dtype: what the wire failed to carry.
        restored = self._inner.decompress(wire, ctx)
        self._residuals[key] = (t - jnp.asarray(restored, t.dtype))
        return wire, ctx

    def decompress(self, tensor, ctx):
        return self._inner.decompress(tensor, ctx)

    def reset(self) -> None:
        """Drop all residual state (elastic rendezvous / new stream)."""
        self._residuals.clear()


class Compression:
    """Namespace matching ``hvd.Compression`` (reference compression.py:66-74).

    Every member is a stateless class usable directly as a
    ``compression=`` argument.  :class:`ErrorFeedbackCompressor` is
    deliberately NOT here: it is stateful (a residual per stream) and
    must be instantiated — passing a namespace member where an instance
    is required would fail deep inside a trace instead of at the call
    site."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BFloat16Compressor
