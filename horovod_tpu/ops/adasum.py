"""Adasum reduction, TPU-native.

The reference implements Adasum as a Vector-Halving Distance-Doubling
(VHDD) fused allreduce in C++ (horovod/common/ops/adasum/adasum.h:167-299):
log2(N) levels, partner = rank ^ 2^level (adasum.h:230), each pair exchanges
buffer halves point-to-point and combines them with a projection formula
computed from pairwise dot products and squared norms
(DispatchComputeDotAndNormSqrds, adasum.h:101-120).

The pairwise rule for contributions ``a`` and ``b``::

    adasum(a, b) = (1 - a.b / (2 |a|^2)) * a  +  (1 - a.b / (2 |b|^2)) * b

which reduces to a+b when orthogonal and to the average when identical —
an automatic interpolation between summing and averaging gradients.

TPU design: instead of hand-scheduled point-to-point halves, each of the
log2(N) levels is one ``lax.ppermute`` exchanging the *current combined
vector* with the XOR partner, followed by local projection math.  XLA
schedules the permutes over ICI; the butterfly pattern maps onto the torus
links the same way recursive halving does.  Bandwidth is 2x VHDD's (whole
vector per level rather than a shrinking half), traded for zero host
choreography and full compiler visibility; see parallel/hierarchical.py for
the 2-level composition that keeps DCN traffic to one level.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..basics import DP_AXIS
from .collectives import axis_size

__all__ = ["adasum_allreduce", "adasum_combine"]


def _numpy_adasum_rows(rows):
    """Host-side recursive adasum of ``rows[i]`` = rank i's flat buffer —
    the eager engine's reduction kernel.

    Combination order mirrors the native engine's distributed scheme
    (cpp/hvdtpu/ops.cc AdasumImpl): for non-power-of-2 worlds, extra rank
    ``p + j`` (p = largest power of 2 <= n) folds into rank ``j`` first,
    then the balanced VHDD binary tree (reference adasum.h:167-299) runs
    over the p-group — so both engines agree bit-for-bit at any world size.
    """
    import numpy as np

    vecs = [np.asarray(r, np.float64) for r in rows]

    def combine(a, b):
        dot = float(np.dot(a, b))
        na2 = max(float(np.dot(a, a)), 1e-30)
        nb2 = max(float(np.dot(b, b)), 1e-30)
        return (1.0 - dot / (2 * na2)) * a + (1.0 - dot / (2 * nb2)) * b

    p = 1
    while p * 2 <= len(vecs):
        p *= 2
    extras = len(vecs) - p
    vecs = [
        combine(vecs[j], vecs[p + j]) if j < extras else vecs[j]
        for j in range(p)
    ]

    def rec(vs):
        if len(vs) == 1:
            return vs[0]
        half = len(vs) // 2
        return combine(rec(vs[:half]), rec(vs[half:]))

    return rec(vecs)


def adasum_combine(a, b, dot, na2, nb2, eps=1e-30):
    """Combine two contributions given their inner products (the math of
    reference adasum.h:239-263, per-pair scalar coefficients)."""
    a_coef = 1.0 - dot / (2.0 * jnp.maximum(na2, eps))
    b_coef = 1.0 - dot / (2.0 * jnp.maximum(nb2, eps))
    return a_coef * a + b_coef * b


def adasum_allreduce(tensor, *, axis_name: str = DP_AXIS):
    """Adasum-allreduce a pytree across the mesh axis.

    Matches the reference's recursive binary-tree semantics
    (adasum.h:167-299): level k combines each rank's running result with
    partner ``rank ^ 2^k``.  Requires a power-of-2 axis size, as the
    reference's VHDD does (docs/adasum_user_guide.rst; the torch API
    enforces power-of-2 at horovod/torch/mpi_ops.py:104-119).

    All math runs in fp32 regardless of input dtype (the reference keeps
    fp16 inputs but accumulates dots in double; bf16 inputs here would lose
    the projection's precision), casting back at the end.
    """
    n = axis_size(axis_name)
    if n & (n - 1) != 0:
        raise ValueError(f"Adasum requires a power-of-2 world size, got {n}")

    def one(x):
        x = jnp.asarray(x)
        orig_dtype = x.dtype
        flat = jnp.ravel(x).astype(jnp.float32)

        level = 1
        while level < n:
            # Butterfly exchange: every rank swaps its running vector with
            # rank ^ level in one ppermute (bidirectional on ICI).  Both
            # sides of a pair compute the identical combined vector because
            # adasum_combine is symmetric under swapping (a,|a|^2)<->(b,|b|^2).
            perm = [(r, r ^ level) for r in range(n)]
            other = lax.ppermute(flat, axis_name, perm)
            dot = jnp.dot(flat, other)
            na2 = jnp.dot(flat, flat)
            nb2 = jnp.dot(other, other)
            flat = adasum_combine(flat, other, dot, na2, nb2)
            level <<= 1
        return flat.reshape(x.shape).astype(orig_dtype)

    import jax

    return jax.tree_util.tree_map(one, tensor)
