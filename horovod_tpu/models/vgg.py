"""VGG, TPU-first.

One of the reference's three headline benchmark families
(/root/reference/docs/benchmarks.rst:13-14: VGG-16 at ~68% scaling on 512
GPUs — the hardest of the three to scale because its parameter volume is
dominated by the giant FC matmuls, which stress the allreduce).

TPU-first choices: NHWC layout, bf16 compute / fp32 params, channel
counts multiples of 64 (MXU tiling), no BN (classic VGG geometry, as in
tf_cnn_benchmarks' vgg16).  The classifier flattens (canonical geometry),
so the first FC's parameter shape follows the input resolution: init and
apply at the same size.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Classic configuration D (VGG-16): 13 convs, 'M' = 2x2 max pool.
_VGG16 = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
          512, 512, 512, "M", 512, 512, 512, "M")
_VGG19 = (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M")


class VGG(nn.Module):
    cfg: Sequence = _VGG16
    num_classes: int = 1000
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train  # no BN/dropout state in the benchmark geometry
        x = x.astype(self.compute_dtype)
        for spec in self.cfg:
            if spec == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(spec, (3, 3), padding="SAME",
                            dtype=self.compute_dtype)(x)
                x = nn.relu(x)
        # 224 input -> 7x7x512. Flatten feeds the 25088x4096 FC, the
        # parameter giant that makes VGG the allreduce stress test.
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.compute_dtype)(x))
        x = nn.relu(nn.Dense(4096, dtype=self.compute_dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)


def VGG16(num_classes: int = 1000, compute_dtype: Any = jnp.bfloat16,
          **_ignored) -> VGG:
    return VGG(_VGG16, num_classes, compute_dtype)


def VGG19(num_classes: int = 1000, compute_dtype: Any = jnp.bfloat16,
          **_ignored) -> VGG:
    return VGG(_VGG19, num_classes, compute_dtype)
