"""Model zoo for benchmarks and examples.

The reference treats models as external (tf_cnn_benchmarks, torchvision's
resnet50 in examples/pytorch_synthetic_benchmark.py:19-37); this package
carries TPU-first flax implementations so the framework's benchmarks and
examples are self-contained: NHWC layouts, bfloat16 compute with fp32
params, channel sizes that tile onto the 128x128 MXU."""

from .inception import InceptionV3  # noqa: F401
from .resnet import ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152  # noqa: F401
from .simple import MLP, ConvNet  # noqa: F401
from .decode import (  # noqa: F401
    assign_slot, decode_step, generate, init_cache, prefill,
    prefill_scan, reset_slot,
)
from .transformer import GPT, GPT_CONFIGS, TransformerConfig, gpt  # noqa: F401
from .vgg import VGG16, VGG19  # noqa: F401
