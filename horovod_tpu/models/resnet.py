"""ResNet v1.5 family, TPU-first.

The benchmark model of both the reference's headline numbers
(docs/benchmarks.rst: ResNet-101 @ 512 GPUs ~90% scaling;
examples/pytorch_synthetic_benchmark.py defaults to torchvision resnet50)
and this repo's BASELINE.md target (ResNet-50 images/sec/chip).

TPU-first choices:
* NHWC layout (XLA:TPU's native conv layout; NCHW forces transposes).
* ``compute_dtype=bfloat16`` runs convs/matmuls on the MXU at full rate
  while parameters and batch-norm statistics stay fp32.
* v1.5 stride placement (stride in the 3x3, not the 1x1) — the variant the
  reference benchmarks actually run (torchvision's resnet50).
* Optional cross-replica batch norm via horovod_tpu.parallel.SyncBatchNorm.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1 with projection shortcut (v1.5)."""

    features: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.features, (1, 1), use_bias=False, name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(
            self.features, (3, 3), self.strides, use_bias=False, name="conv2"
        )(y)
        y = self.norm(name="bn2")(y)
        y = self.act(y)
        y = self.conv(
            self.features * 4, (1, 1), use_bias=False, name="conv3"
        )(y)
        # zero-init the last BN scale: identity residual at init (the
        # standard trick the reference's Keras example enables via
        # resnet50's `zero_gamma`; helps large-batch warmup)
        y = self.norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.features * 4,
                (1, 1),
                self.strides,
                use_bias=False,
                name="proj_conv",
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 (ResNet-18/34)."""

    features: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(
            self.features, (3, 3), self.strides, use_bias=False, name="conv1"
        )(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.features, (3, 3), use_bias=False, name="conv2")(y)
        y = self.norm(name="bn2", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.features,
                (1, 1),
                self.strides,
                use_bias=False,
                name="proj_conv",
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return self.act(residual + y)


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """NHWC space-to-depth: (N, H, W, C) -> (N, H/b, W/b, C*b*b).

    The MLPerf-era TPU stem trick: folding 2x2 spatial patches into channels
    turns the 7x7/s2 stem conv (3 input channels — 3/128ths of an MXU column)
    into a 4x4/s1 conv over 12 channels, quadrupling stem MXU utilization.
    """
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, c * block * block)


class ResNet(nn.Module):
    """Configurable ResNet (stage sizes select 18/34/50/101/152)."""

    stage_sizes: Sequence[int]
    block: ModuleDef = BottleneckBlock
    num_classes: int = 1000
    num_filters: int = 64
    compute_dtype: jnp.dtype = jnp.bfloat16
    axis_name: Optional[str] = None  # set for cross-replica batch norm
    # Space-to-depth stem (MLPerf TPU ResNet recipe): same receptive-field
    # family as the 7x7/s2 stem but MXU-dense. Off by default so the
    # headline model matches the reference architecture exactly.
    s2d_stem: bool = False
    # Inter-block activation storage dtype (e.g. jnp.float8_e4m3fn): the
    # step is HBM-bandwidth-bound (docs/performance.md), so storing the
    # block-boundary activations at 1 B/elt halves the dominant traffic.
    # Lossy — changes the numerics contract — so opt-in only.
    act_store_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.axis_name is not None:
            from ..parallel.sync_batch_norm import SyncBatchNorm  # noqa: PLC0415

            norm = partial(
                SyncBatchNorm,
                axis_name=self.axis_name,
                use_running_average=not train,
                momentum=0.9,
            )
        else:
            # dtype=compute_dtype keeps the normalize/scale/shift elementwise
            # chain in bf16 (half the HBM traffic of f32 activations, and it
            # fuses with the surrounding convs); flax still computes the
            # batch statistics in f32 internally and stores running stats in
            # f32, so numerics match the reference's fp32-stats BN.
            norm = partial(
                nn.BatchNorm,
                use_running_average=not train,
                momentum=0.9,
                dtype=self.compute_dtype,
            )
        conv = partial(nn.Conv, dtype=self.compute_dtype, param_dtype=jnp.float32)
        if self.act_store_dtype is not None:
            # Quantized ReLU: every conv input (= every ReLU output) is
            # materialized at 1 B/elt in HBM; convs read f8 and widen
            # in-register to the compute dtype.  (Quantizing the backward
            # cotangent to e5m2 via a custom VJP was tried and rejected:
            # it stalled XLA:TPU compilation for >9 minutes.)
            def act(y):
                return jnp.asarray(
                    jnp.asarray(nn.relu(y), self.act_store_dtype),
                    self.compute_dtype,
                )
        else:
            act = nn.relu

        x = jnp.asarray(x, self.compute_dtype)
        if self.s2d_stem:
            x = space_to_depth(x, 2)
            x = conv(
                self.num_filters,
                (4, 4),
                (1, 1),
                padding=[(1, 2), (1, 2)],
                use_bias=False,
                name="conv_init",
            )(x)
        else:
            x = conv(
                self.num_filters,
                (7, 7),
                (2, 2),
                padding=[(3, 3), (3, 3)],
                use_bias=False,
                name="conv_init",
            )(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(
                    self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                    name=f"stage{i+1}_block{j+1}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
            name="head",
        )(jnp.asarray(x, jnp.float32))
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block=BottleneckBlock)
