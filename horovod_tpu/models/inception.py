"""Inception V3, TPU-first.

The reference's top headline benchmark model
(/root/reference/docs/benchmarks.rst:13-14: Inception V3 at ~90% scaling
on 512 GPUs; README.rst:79).  Canonical V3 geometry (stem, 3x InceptionA,
B-reduction, 4x InceptionC, D-reduction, 2x InceptionE, global pool, FC)
with conv+BN+relu everywhere.

TPU-first choices: NHWC, bf16 compute / fp32 params and BN statistics,
a global mean instead of the fixed 8x8 average pool so any input size
(299 canonical, smaller in tests) compiles statically, no aux head (the
benchmark measures the main tower, as tf_cnn_benchmarks does by default).
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        # BN in fp32 (stats must not accumulate in bf16), output back in
        # compute dtype so the next conv's operand stays MXU-native.
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=jnp.float32)(x)
        return nn.relu(x).astype(self.dtype)


def _pool_avg(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        d = self.dtype
        b1 = ConvBN(64, (1, 1), dtype=d)(x, train)
        b5 = ConvBN(48, (1, 1), dtype=d)(x, train)
        b5 = ConvBN(64, (5, 5), dtype=d)(b5, train)
        b3 = ConvBN(64, (1, 1), dtype=d)(x, train)
        b3 = ConvBN(96, (3, 3), dtype=d)(b3, train)
        b3 = ConvBN(96, (3, 3), dtype=d)(b3, train)
        bp = ConvBN(self.pool_features, (1, 1), dtype=d)(_pool_avg(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):  # 17x17 reduction
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        d = self.dtype
        b3 = ConvBN(384, (3, 3), strides=(2, 2), padding="VALID",
                    dtype=d)(x, train)
        bd = ConvBN(64, (1, 1), dtype=d)(x, train)
        bd = ConvBN(96, (3, 3), dtype=d)(bd, train)
        bd = ConvBN(96, (3, 3), strides=(2, 2), padding="VALID",
                    dtype=d)(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        d, c7 = self.dtype, self.channels_7x7
        b1 = ConvBN(192, (1, 1), dtype=d)(x, train)
        b7 = ConvBN(c7, (1, 1), dtype=d)(x, train)
        b7 = ConvBN(c7, (1, 7), dtype=d)(b7, train)
        b7 = ConvBN(192, (7, 1), dtype=d)(b7, train)
        bb = ConvBN(c7, (1, 1), dtype=d)(x, train)
        bb = ConvBN(c7, (7, 1), dtype=d)(bb, train)
        bb = ConvBN(c7, (1, 7), dtype=d)(bb, train)
        bb = ConvBN(c7, (7, 1), dtype=d)(bb, train)
        bb = ConvBN(192, (1, 7), dtype=d)(bb, train)
        bp = ConvBN(192, (1, 1), dtype=d)(_pool_avg(x), train)
        return jnp.concatenate([b1, b7, bb, bp], axis=-1)


class InceptionD(nn.Module):  # 8x8 reduction
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        d = self.dtype
        b3 = ConvBN(192, (1, 1), dtype=d)(x, train)
        b3 = ConvBN(320, (3, 3), strides=(2, 2), padding="VALID",
                    dtype=d)(b3, train)
        b7 = ConvBN(192, (1, 1), dtype=d)(x, train)
        b7 = ConvBN(192, (1, 7), dtype=d)(b7, train)
        b7 = ConvBN(192, (7, 1), dtype=d)(b7, train)
        b7 = ConvBN(192, (3, 3), strides=(2, 2), padding="VALID",
                    dtype=d)(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        d = self.dtype
        b1 = ConvBN(320, (1, 1), dtype=d)(x, train)
        b3 = ConvBN(384, (1, 1), dtype=d)(x, train)
        b3 = jnp.concatenate([
            ConvBN(384, (1, 3), dtype=d)(b3, train),
            ConvBN(384, (3, 1), dtype=d)(b3, train),
        ], axis=-1)
        bb = ConvBN(448, (1, 1), dtype=d)(x, train)
        bb = ConvBN(384, (3, 3), dtype=d)(bb, train)
        bb = jnp.concatenate([
            ConvBN(384, (1, 3), dtype=d)(bb, train),
            ConvBN(384, (3, 1), dtype=d)(bb, train),
        ], axis=-1)
        bp = ConvBN(192, (1, 1), dtype=d)(_pool_avg(x), train)
        return jnp.concatenate([b1, b3, bb, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        d = self.compute_dtype
        x = x.astype(d)
        # stem (299 -> 35)
        x = ConvBN(32, (3, 3), strides=(2, 2), padding="VALID", dtype=d)(x, train)
        x = ConvBN(32, (3, 3), padding="VALID", dtype=d)(x, train)
        x = ConvBN(64, (3, 3), dtype=d)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = ConvBN(80, (1, 1), padding="VALID", dtype=d)(x, train)
        x = ConvBN(192, (3, 3), padding="VALID", dtype=d)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # 35x35
        x = InceptionA(32, dtype=d)(x, train)
        x = InceptionA(64, dtype=d)(x, train)
        x = InceptionA(64, dtype=d)(x, train)
        x = InceptionB(dtype=d)(x, train)
        # 17x17
        x = InceptionC(128, dtype=d)(x, train)
        x = InceptionC(160, dtype=d)(x, train)
        x = InceptionC(160, dtype=d)(x, train)
        x = InceptionC(192, dtype=d)(x, train)
        x = InceptionD(dtype=d)(x, train)
        # 8x8
        x = InceptionE(dtype=d)(x, train)
        x = InceptionE(dtype=d)(x, train)
        # global mean (size-agnostic stand-in for the fixed 8x8 pool)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)
