"""GPT-style transformer family, TPU-first.

The long-context flagship of the model zoo (the reference's zoo is conv
nets via tf_cnn_benchmarks; transformers are where TPU-native design —
MXU-shaped matmuls, bf16 compute, flash/ring attention — pays off most).

TPU-first choices:
* bf16 compute / fp32 params and layer norms (MXU-native mixed precision).
* Attention impl is pluggable per config:
    - ``"flash"``     — the Pallas kernel (ops/flash_attention.py);
    - ``"reference"`` — plain softmax attention (parallel/ring_attention.py
      ``local_attention``), for tests and tiny shapes;
    - ``"ring"``      — ring attention over a sequence-parallel mesh axis
      (call the model inside shard_map with tokens sharded along seq);
    - ``"zigzag"``    — the load-balanced causal ring (zigzag layout;
      requires an explicit ``positions`` vector from
      ``zigzag_positions``);
    - ``"ulysses"``   — all-to-all head-parallel attention over that axis.
* Positions: ``pos_offset`` (scalar, contiguous shards) or an explicit
  per-token ``positions`` vector (required for zigzag); both the learned
  table (gather) and RoPE rotate/index by position VALUE, so the
  embeddings are layout-agnostic.
* GQA/MQA via ``num_kv_heads``: native in the flash kernel; ring/zigzag
  carry narrow k/v through the ppermute and broadcast after.
* Head dim and MLP width default to multiples of 128 (MXU lane width) at
  the named sizes.
* No data-dependent Python control flow — the whole forward is one traced
  graph.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..parallel.moe import DEFAULT_GROUP_SIZE as MOE_DEFAULT_GROUP_SIZE


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    num_heads: int = 12
    # GQA/MQA: fewer K/V heads than query heads (None = MHA).  The flash
    # kernel routes q heads to kv groups natively (no broadcast); other
    # attention impls repeat k/v to full heads before attending.
    num_kv_heads: Optional[int] = None
    emb_dim: int = 768
    mlp_ratio: int = 4
    max_len: int = 1024
    dtype: Any = jnp.bfloat16
    attention_impl: str = "flash"  # flash | reference | ring | ulysses | zigzag
    sp_axis: Optional[str] = None  # mesh axis for ring/ulysses/zigzag
    # Mistral-style sliding window (each position sees its last W keys,
    # self included).  Flash-kernel-only: the banded tiles are skipped in
    # fwd AND bwd, so attention compute scales with S*W instead of S^2.
    attention_window: Optional[int] = None
    # "learned" = wpe table (GPT-2 style); "rope" = rotary, driven by the
    # explicit per-token position vector, so it composes with ANY sequence
    # layout (contiguous or zigzag shards).
    pos_embedding: str = "learned"
    rope_theta: float = 10000.0
    # Measured on TPU v5e (docs/performance.md round-5 sweep): q512 x k256
    # tiles lift gpt-small from MFU 0.193 (128 x 128) to 0.325 — the
    # dominant single-chip lever.  _pick_block shrinks them to divide
    # short sequences, so the large default is shape-safe.
    flash_block_q: int = 512
    flash_block_k: int = 256
    # Rematerialize each block in the backward pass, keeping only matmul
    # outputs with no batch dims (the standard TPU transformer remat
    # policy): trades HBM for recomputed elementwise FLOPs, buying larger
    # per-chip batches — the MFU lever when activations bound the batch.
    remat: bool = False
    # Mixture-of-experts MLP (parallel/moe.py): >0 replaces every block's
    # dense MLP with moe_experts experts (GShard one-hot dispatch, static
    # capacity).  The auxiliary load-balancing loss is sowed into the
    # "losses" collection: apply with mutable=["losses"] and add
    # sum(losses) * your coefficient to the training loss.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    # routing group (keeps dispatch O(n*group)); default tracks the one
    # source of truth in parallel/moe.py
    moe_group_size: int = MOE_DEFAULT_GROUP_SIZE
    # Activation storage dtype (e.g. jnp.float8_e4m3fn) for the big saved
    # activations backward re-reads: the residual-branch deltas (attention
    # and MLP outputs), the pre-proj attention context, and the gelu
    # intermediate (the 4x-wide one) materialize at 1 B/elt; matmuls widen
    # in-register to the compute dtype.  Lossy — changes the numerics
    # contract (tests/test_fp8.py pins how far it may drift) — so opt-in,
    # mirroring models/resnet.py act_store_dtype.
    act_store_dtype: Optional[Any] = None

    def __post_init__(self):
        if self.num_kv_heads is not None:
            if self.num_kv_heads <= 0 or self.num_heads % self.num_kv_heads:
                raise ValueError(
                    f"num_heads={self.num_heads} must be a positive "
                    f"multiple of num_kv_heads={self.num_kv_heads}"
                )
        if self.pos_embedding not in ("learned", "rope"):
            raise ValueError(
                f"pos_embedding must be 'learned' or 'rope', got "
                f"{self.pos_embedding!r}"
            )

    @property
    def head_dim(self) -> int:
        return self.emb_dim // self.num_heads

    @property
    def kv_heads(self) -> int:
        return (self.num_kv_heads if self.num_kv_heads is not None
                else self.num_heads)


def _attend(cfg: TransformerConfig, q, k, v, positions):
    """Dispatch to the configured attention schedule (always causal).
    ``positions``: int [s_local] global positions of the local rows —
    used by schedules that mask in global coordinates."""
    if cfg.attention_impl == "flash":
        from ..ops.flash_attention import flash_attention  # noqa: PLC0415

        return flash_attention(
            q, k, v, causal=True,
            block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
            window=cfg.attention_window,
        )
    if cfg.attention_window is not None:
        raise ValueError(
            "attention_window is flash-only; "
            f"attention_impl={cfg.attention_impl!r} does not support it"
        )
    if cfg.kv_heads != cfg.num_heads and cfg.attention_impl in (
        "reference", "ulysses"
    ):
        # these schedules attend at full heads; ring/zigzag carry narrow
        # k/v through the ppermute and broadcast after (so GQA's
        # interconnect saving survives sequence parallelism)
        rep = cfg.num_heads // cfg.kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if cfg.attention_impl == "ring":
        from ..parallel.ring_attention import ring_attention  # noqa: PLC0415

        if cfg.sp_axis is None:
            raise ValueError("attention_impl='ring' requires sp_axis")
        return ring_attention(q, k, v, cfg.sp_axis, causal=True)
    if cfg.attention_impl == "zigzag":
        from ..parallel.ring_attention import (  # noqa: PLC0415
            ring_attention_zigzag,
        )

        if cfg.sp_axis is None:
            raise ValueError("attention_impl='zigzag' requires sp_axis")
        return ring_attention_zigzag(q, k, v, cfg.sp_axis)
    if cfg.attention_impl == "ulysses":
        from ..parallel.ring_attention import ulysses_attention  # noqa: PLC0415

        if cfg.sp_axis is None:
            raise ValueError("attention_impl='ulysses' requires sp_axis")
        return ulysses_attention(q, k, v, cfg.sp_axis, causal=True)
    if cfg.attention_impl != "reference":
        raise ValueError(
            f"unknown attention_impl {cfg.attention_impl!r}; expected "
            f"'flash', 'reference', 'ring', 'zigzag', or 'ulysses'"
        )
    from ..parallel.ring_attention import local_attention  # noqa: PLC0415

    # local_attention masks from scalar offsets: valid because every
    # non-zigzag layout is contiguous per shard (zigzag never routes here)
    return local_attention(
        q, k, v, causal=True, q_offset=positions[0], kv_offset=positions[0]
    )


def act_store(y, cfg: TransformerConfig):
    """The opt-in lossy activation-storage round-trip: materialize ``y``
    at ``cfg.act_store_dtype`` (1 B/elt for e4m3) and widen back to the
    compute dtype — a no-op when the knob is off.  Shared by block_math
    and every MLP closure so the fp8 story has one definition."""
    if cfg.act_store_dtype is None:
        return y
    return jnp.asarray(jnp.asarray(y, cfg.act_store_dtype), cfg.dtype)


def block_math(cfg: TransformerConfig, x, positions, rope_tabs, *,
               ln1, qkv, proj, ln2, mlp,
               num_heads: Optional[int] = None,
               num_kv_heads: Optional[int] = None,
               attend=None):
    """THE pre-LN transformer block wiring — the single source of truth.

    ``LN → qkv → split-heads → rope → attend → proj(+res) → LN →
    mlp(+res)``, shared by the flax :class:`Block`, the raw-weights
    pipeline-parallel block (:func:`raw_block_forward`), and the
    Megatron tensor-parallel block (``parallel/tensor_parallel.py``) so
    a change to the block (a bias flag, a norm variant, the head
    split) is made exactly once.

    Callers supply the five parameterized layer applications as
    callables (flax modules, raw-weight closures, or psum-rejoined
    tensor-parallel closures); ``proj`` and ``mlp`` return the residual
    DELTA (this function adds it to the stream).  ``num_heads`` /
    ``num_kv_heads`` override the config's head counts for callers
    operating on a per-rank head shard (TP).  ``attend`` overrides the
    attention schedule itself: a callable ``(q, k, v) -> att`` over the
    rope-applied ``[b, s, heads, head_dim]`` tensors — the KV-cache
    decode path (models/decode.py) supplies one that appends to its
    cache and attends the single query against the prefix, so decoding
    reuses THIS wiring instead of a third copy.
    """
    b, s, _ = x.shape
    nh = num_heads if num_heads is not None else cfg.num_heads
    nkv = num_kv_heads if num_kv_heads is not None else cfg.kv_heads
    hd = cfg.head_dim
    q_dim = nh * hd
    kv_dim = nkv * hd

    h = ln1(x)
    fused = qkv(h)
    q = fused[..., :q_dim].reshape(b, s, nh, hd)
    k = fused[..., q_dim:q_dim + kv_dim].reshape(b, s, nkv, hd)
    v = fused[..., q_dim + kv_dim:].reshape(b, s, nkv, hd)
    if rope_tabs is not None:
        from ..ops.rope import apply_rope_tables  # noqa: PLC0415

        q = apply_rope_tables(q, *rope_tabs)
        k = apply_rope_tables(k, *rope_tabs)
    if attend is None:
        attend_cfg = cfg
        if nh != cfg.num_heads or nkv != cfg.kv_heads:
            # per-rank head shard: _attend sees the LOCAL head geometry
            attend_cfg = replace(cfg, num_heads=nh, num_kv_heads=nkv,
                                 emb_dim=q_dim)
        att_4d = _attend(attend_cfg, q, k, v, positions)
    else:
        att_4d = attend(q, k, v)
    att = act_store(att_4d.reshape(b, s, q_dim), cfg)
    x = x + act_store(proj(att), cfg)
    return x + act_store(mlp(ln2(x)), cfg)


def raw_layer_norm(x, scale, bias, eps: float = 1e-6):
    """LayerNorm from raw weights, fp32 math (matches flax's
    ``nn.LayerNorm(dtype=jnp.float32)`` as the models use it)."""
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return y * scale + bias


def raw_dense(sub, dtype):
    """The dense-layer application from a raw ``{kernel, bias}`` subtree
    in the given compute dtype — the one definition of "apply a Dense
    from raw weights" shared by the pipeline and tensor-parallel block
    closures."""
    return lambda h: h.astype(dtype) @ sub["kernel"].astype(dtype) \
        + sub["bias"].astype(dtype)


def raw_block_forward(cfg: TransformerConfig, p, x, positions, rope_tabs,
                      attend=None):
    """One dense transformer block from a raw ``Block`` weight subtree
    ``p`` (keys ``ln1/qkv/proj/ln2/fc1/fc2``) — :func:`block_math` with
    plain-matmul closures.  Used by the pipeline-parallel stage body
    (``parallel/pipeline.py``) and, with an ``attend`` override, the
    KV-cache decode path (models/decode.py); numerically equivalent to
    the flax :class:`Block` (pinned by tests/test_pipeline.py)."""
    dt = cfg.dtype

    def mlp(h):
        m = act_store(jax.nn.gelu(raw_dense(p["fc1"], dt)(h)), cfg)
        return raw_dense(p["fc2"], dt)(m)

    return block_math(
        cfg, x, positions, rope_tabs,
        ln1=lambda h: raw_layer_norm(h, p["ln1"]["scale"], p["ln1"]["bias"]),
        qkv=raw_dense(p["qkv"], dt),
        proj=raw_dense(p["proj"], dt),
        ln2=lambda h: raw_layer_norm(h, p["ln2"]["scale"], p["ln2"]["bias"]),
        mlp=mlp,
        attend=attend,
    )


class Block(nn.Module):
    """Pre-LN transformer block: LN → attn → +res, LN → MLP → +res.

    The wiring lives in :func:`block_math`; this module only declares
    the flax parameters and hands their applications in as callables.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, rope_tabs=None):
        cfg = self.cfg
        kv_dim = cfg.kv_heads * cfg.head_dim

        def mlp(h):
            if cfg.moe_experts > 0:
                from ..parallel.moe import (  # noqa: PLC0415
                    moe_flax_params, moe_mlp,
                )

                moe_p = moe_flax_params(
                    self, cfg.emb_dim, cfg.mlp_ratio * cfg.emb_dim,
                    cfg.moe_experts,
                )
                y, aux = moe_mlp(
                    h, moe_p, top_k=cfg.moe_top_k,
                    capacity_factor=cfg.moe_capacity_factor,
                    group_size=cfg.moe_group_size, dtype=cfg.dtype,
                    act_store_dtype=cfg.act_store_dtype,
                )
                self.sow("losses", "moe_aux", aux)
                # y inherits ln2's fp32; keep the residual stream in the
                # compute dtype like the dense-MLP path does
                return y.astype(cfg.dtype)
            m = nn.Dense(cfg.mlp_ratio * cfg.emb_dim, dtype=cfg.dtype,
                         name="fc1")(h)
            return nn.Dense(cfg.emb_dim, dtype=cfg.dtype,
                            name="fc2")(act_store(nn.gelu(m), cfg))

        return block_math(
            cfg, x, positions, rope_tabs,
            ln1=nn.LayerNorm(dtype=jnp.float32, name="ln1"),
            qkv=nn.Dense(cfg.emb_dim + 2 * kv_dim, dtype=cfg.dtype,
                         name="qkv"),
            proj=nn.Dense(cfg.emb_dim, dtype=cfg.dtype, name="proj"),
            ln2=nn.LayerNorm(dtype=jnp.float32, name="ln2"),
            mlp=mlp,
        )


class GPT(nn.Module):
    """Decoder-only causal LM.

    ``tokens``: int32 ``[batch, seq]`` (local shard under sequence
    parallelism).  Positions, either/or:

    * ``pos_offset``: global position of ``tokens[:, 0]`` for CONTIGUOUS
      shards — pass ``axis_index(sp_axis) * local_seq`` inside shard_map;
    * ``positions``: explicit int ``[seq]`` global positions — REQUIRED
      (and only supported) non-contiguous layout is the zigzag schedule:
      ``attention_impl="zigzag"`` with positions from
      ``zigzag_positions(axis_index, P, s_local)``.  The position
      *embeddings* (learned gather, RoPE rotation) are layout-agnostic,
      but the flash/reference/ring attention impls mask assuming
      contiguous per-shard rows.

    Returns logits ``[batch, seq, vocab]`` in fp32.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, pos_offset=0, positions=None):
        cfg = self.cfg
        tok = nn.Embed(
            cfg.vocab_size, cfg.emb_dim, dtype=cfg.dtype, name="wte"
        )(tokens)
        s = tokens.shape[1]
        if s > cfg.max_len:
            raise ValueError(
                f"sequence length {s} exceeds max_len={cfg.max_len}"
            )
        if positions is None:
            if cfg.attention_impl == "zigzag":
                # contiguous default positions can NEVER match the zigzag
                # layout: silently wrong on every rank — fail at trace time
                raise ValueError(
                    "attention_impl='zigzag' requires explicit positions "
                    "(zigzag_positions(axis_index, P, s_local))"
                )
            positions = pos_offset + jnp.arange(s)
        x = tok
        if cfg.pos_embedding == "learned":
            pos_table = self.param(
                "wpe",
                nn.initializers.normal(0.02),
                (cfg.max_len, cfg.emb_dim),
                jnp.float32,
            )
            # Gather (not dynamic_slice): position layouts need not be
            # contiguous (zigzag shards).  mode="fill" + NaN makes an
            # out-of-range position (e.g. global S > max_len under SP,
            # which the local s<=max_len check can't see) poison the loss
            # LOUDLY instead of silently reusing the clamped last row.
            pos = jnp.take(pos_table, positions, axis=0,
                           mode="fill", fill_value=jnp.nan)
            x = x + pos.astype(cfg.dtype)[None]
        rope_tabs = None
        if cfg.pos_embedding == "rope":
            from ..ops.rope import rope_tables  # noqa: PLC0415

            # once for ALL blocks: under remat a per-block recompute would
            # re-run the transcendentals in the backward pass too
            rope_tabs = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(
                Block,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"block{i}")(x, positions, rope_tabs)
        x = nn.LayerNorm(dtype=jnp.float32, name="lnf")(x)
        logits = nn.Dense(
            cfg.vocab_size, dtype=cfg.dtype, use_bias=False, name="head"
        )(x)
        return logits.astype(jnp.float32)


# Named sizes (GPT-2 family geometry; head_dim 64, MXU-friendly widths).
GPT_CONFIGS = {
    "nano": TransformerConfig(num_layers=3, num_heads=4, emb_dim=128,
                              max_len=256, vocab_size=1024),
    "small": TransformerConfig(num_layers=12, num_heads=12, emb_dim=768),
    "medium": TransformerConfig(num_layers=24, num_heads=16, emb_dim=1024),
    "large": TransformerConfig(num_layers=36, num_heads=20, emb_dim=1280),
}


def gpt(size: str = "small", **overrides) -> GPT:
    """``gpt("small", attention_impl="ring", sp_axis="sp")`` etc."""
    cfg = GPT_CONFIGS[size]
    if overrides:
        cfg = replace(cfg, **overrides)
    return GPT(cfg)
