"""Slot-based KV-cache incremental decoding for the GPT family.

This is the model half of the serving plane (``horovod_tpu/serve/``):
the cache is a fixed pool of *slots* (batch rows) with **per-slot write
positions**, so a continuous-batching scheduler can admit a new request
into one slot — overwriting it via :func:`assign_slot` — while the
other slots keep decoding, all through ONE compiled ``decode_step``
shape (Orca-style iteration-level scheduling needs exactly this: the
batch never changes shape, only which rows are live).

* :func:`init_cache` — per-layer K/V buffers ``[L, b, max_len, kv_heads,
  head_dim]`` plus per-slot write positions ``pos [b]``.
* :func:`decode_step` — one token for every slot: append its K/V at
  that slot's own position, attend the single query against the slot's
  prefix, return next-token logits.  ``write_mask [b]`` freezes rows
  (no K/V write, no position advance) — finished or free slots ride
  along for free.
* :func:`prefill` — single-forward prefill: ONE full causal forward
  writes every position's K/V into the cache in one shot (the scanned
  token-by-token path survives as :func:`prefill_scan`, and the two are
  pinned bitwise against each other by tests/test_decode.py).
* :func:`generate` — greedy/sampled continuation; ``eos_id=`` freezes
  finished rows (masked writes, repeated pad) and exits the loop early
  once every row is done, so short completions in a batch don't pay for
  the longest.
* :func:`reset_slot` / :func:`assign_slot` — the serving primitives:
  clear one slot; prefill one request into one slot while the other
  slots' caches stay bitwise untouched.

The block wiring is NOT re-implemented here: each step runs
``raw_block_forward`` (the single-source :func:`block_math`) with an
``attend`` override that appends to the cache and attends against the
prefix — so GQA head routing, fp8 activation storage, and any future
block change flow into decoding automatically.  RoPE is applied inside
the override (per-slot positions need per-row angle tables, which the
shared ``[s, half]`` broadcast in ``block_math`` cannot express), with
the same fp32 rotation math as ``ops/rope.py``.

Dense blocks only (MoE is training-path-only, parallel/moe.py).
Decoding past a slot's cache end drops the write and poisons that
slot's logits with NaN (the same loud-failure contract as the
out-of-range wpe gather in ``GPT.__call__``) instead of silently
overwriting the last position.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .transformer import TransformerConfig, raw_block_forward

__all__ = [
    "init_cache",
    "decode_step",
    "prefill",
    "prefill_scan",
    "generate",
    "reset_slot",
    "assign_slot",
    "init_paged_pool",
    "decode_step_paged",
    "assign_slot_paged",
]


def _params(params):
    if set(params.keys()) == {"params"}:
        params = params["params"]
    return params


def init_cache(cfg: TransformerConfig, batch: int, max_len=None):
    """Empty slot pool: per-layer K/V at the cache dtype + per-slot
    write positions ``pos [batch]``."""
    if cfg.moe_experts > 0:
        raise ValueError("decode cache supports dense blocks only")
    s = max_len or cfg.max_len
    kv = (cfg.num_layers, batch, s, cfg.kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(kv, cfg.dtype),
        "v": jnp.zeros(kv, cfg.dtype),
        "pos": jnp.zeros((batch,), jnp.int32),
    }


def _slot_pos(cache, batch: int):
    """Per-slot positions ``[b]``; legacy scalar-``pos`` caches (pre-slot
    refactor pytrees restored from disk) broadcast to the batch."""
    pos = cache["pos"]
    if pos.ndim == 0:
        pos = jnp.broadcast_to(pos, (batch,))
    return pos


def _rope_rows(x, cos, sin):
    """Rotate ``x [b, 1, heads, hd]`` by PER-ROW tables ``[b, hd//2]``
    — the same fp32 math as ``ops.rope.apply_rope_tables``, with the
    broadcast moved from the sequence axis to the batch axis (each slot
    sits at its own position)."""
    half = x.shape[-1] // 2
    c = cos[:, None, None, :]
    s = sin[:, None, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
    )
    return out.astype(x.dtype)


def _attend_cached(cfg, q, k_cache, v_cache, pos):
    """One query per slot against that slot's cache prefix: ``q [b, h,
    hd]``, ``k/v_cache [b, S, hkv, hd]``, ``pos [b]`` -> ``[b, h, hd]``.
    Positions beyond each slot's own ``pos`` are masked; with
    ``cfg.attention_window`` the band's lower edge is masked too (parity
    with the flash kernel's sliding window); GQA queries fold onto their
    kv group via reshape, no K/V broadcast.  The kv-head count is read
    off the CACHE shape, not the config, so a width-sharded caller
    (heads split over a mesh axis) reuses this math bitwise on its
    shard."""
    b, h, hd = q.shape
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    group = h // hkv
    qg = q.reshape(b, hkv, group, hd).astype(jnp.float32)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)
    st = jnp.einsum("bkgd,bskd->bkgs", qg, kf) * (hd ** -0.5)
    idx = jnp.arange(s)[None, None, None, :]
    pb = pos[:, None, None, None]
    mask = idx > pb
    if cfg.attention_window is not None:
        mask = mask | (idx < pb - (cfg.attention_window - 1))
    st = jnp.where(mask, jnp.finfo(jnp.float32).min / 2, st)
    p = jax.nn.softmax(st, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", p, vf)
    return out.reshape(b, h, hd)


def _attend_prefix(cfg, q, k_cache, v_cache):
    """All prompt queries at once against the (just-written) cache:
    ``q [b, s, h, hd]``, ``k/v_cache [b, S, hkv, hd]`` -> ``[b, s, h,
    hd]``.  Query position ``t`` sees exactly the mask the scanned path
    applies at ``pos == t`` (future positions min-filled, window lower
    edge too), so the two prefills softmax over identical score rows.
    kv-head count comes from the cache shape (width-shard-reusable,
    like :func:`_attend_cached`)."""
    b, s, h, hd = q.shape
    big = k_cache.shape[1]
    hkv = k_cache.shape[2]
    group = h // hkv
    qg = q.reshape(b, s, hkv, group, hd).astype(jnp.float32)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)
    st = jnp.einsum("btkgd,bskd->btkgs", qg, kf) * (hd ** -0.5)
    idx = jnp.arange(big)[None, None, None, None, :]
    t = jnp.arange(s)[None, :, None, None, None]
    mask = idx > t
    if cfg.attention_window is not None:
        mask = mask | (idx < t - (cfg.attention_window - 1))
    st = jnp.where(mask, jnp.finfo(jnp.float32).min / 2, st)
    p = jax.nn.softmax(st, axis=-1)
    out = jnp.einsum("btkgs,bskd->btkgd", p, vf)
    return out.reshape(b, s, h, hd)


def decode_step(cfg: TransformerConfig, params, cache, tokens_t,
                write_mask=None):
    """Decode one token per slot: ``tokens_t [b]`` -> ``(logits
    [b, vocab], cache)`` with each slot's K/V appended at its OWN
    ``cache["pos"][slot]``.

    ``write_mask [b]`` (bool, default all-true): rows where it is False
    are frozen — their K/V write is dropped and their position does not
    advance — so evicted/finished slots ride the compiled step without
    touching their cache.  Frozen rows still produce (meaningless)
    logits; callers ignore them.
    """
    p = _params(params)
    b = tokens_t.shape[0]
    pos = _slot_pos(cache, b)
    s_cache = cache["k"].shape[2]

    # Per-slot embedding scaffold (the shared _gpt_embed broadcasts one
    # position vector across the batch, which per-slot decode cannot
    # use): same gather/cast/add math per row, including the loud NaN
    # fill past max_len on the learned table.  Keep in lockstep with
    # parallel/tensor_parallel._gpt_embed — it is the contract source,
    # and the bitwise prefill-vs-scan pin in tests/test_decode.py is
    # what catches drift between the two.
    x = jnp.take(
        p["wte"]["embedding"], tokens_t[:, None], axis=0
    ).astype(cfg.dtype)
    if cfg.pos_embedding == "learned":
        pe = jnp.take(p["wpe"], pos, axis=0,
                      mode="fill", fill_value=jnp.nan)
        x = x + pe.astype(cfg.dtype)[:, None]
    rope_tabs = None
    if cfg.pos_embedding == "rope":
        from ..ops.rope import rope_tables  # noqa: PLC0415

        rope_tabs = rope_tables(pos, cfg.head_dim, cfg.rope_theta)

    if write_mask is None:
        write_pos = pos
        advance = jnp.ones((b,), jnp.int32)
    else:
        # Masked rows write at index s_cache — out of bounds, which
        # scatter-with-mode="drop" discards — and stay put.
        write_pos = jnp.where(write_mask, pos, s_cache)
        advance = write_mask.astype(jnp.int32)

    rows = jnp.arange(b)
    k_new, v_new = cache["k"], cache["v"]
    for i in range(cfg.num_layers):

        def attend(q, k_t, v_t, _i=i):
            # q [b, 1, nh, hd]; k_t/v_t [b, 1, nkv, hd].  RoPE applies
            # HERE (per-row tables); block_math skipped it because we
            # passed rope_tabs=None.  Append at each slot's own
            # position, then attend against that slot's prefix.
            nonlocal k_new, v_new
            if rope_tabs is not None:
                q = _rope_rows(q, *rope_tabs)
                k_t = _rope_rows(k_t, *rope_tabs)
            k_new = k_new.at[_i, rows, write_pos].set(
                k_t[:, 0].astype(cfg.dtype), mode="drop"
            )
            v_new = v_new.at[_i, rows, write_pos].set(
                v_t[:, 0].astype(cfg.dtype), mode="drop"
            )
            att = _attend_cached(cfg, q[:, 0], k_new[_i], v_new[_i], pos)
            return att[:, None]

        x = raw_block_forward(cfg, p[f"block{i}"], x, pos[:, None],
                              None, attend=attend)

    from ..parallel.tensor_parallel import _gpt_head  # noqa: PLC0415

    logits = _gpt_head(p, cfg, x)[:, 0]
    # A slot writing past its cache end would CLAMP in the old
    # dynamic-update spelling (silently overwriting the last position);
    # here the write is dropped AND that slot's logits are poisoned —
    # per slot, so one full request never corrupts its batch peers.
    overrun = pos >= s_cache
    if write_mask is not None:
        overrun = overrun & write_mask
    logits = jnp.where(overrun[:, None], jnp.nan, logits)
    return logits, {"k": k_new, "v": v_new, "pos": pos + advance}


def prefill(cfg: TransformerConfig, params, tokens, max_len=None,
            lengths=None):
    """Single-forward prefill: feed prompts ``[b, s]`` through ONE full
    causal forward, writing every position's K/V into a fresh cache in
    one shot — O(1) dispatches where :func:`prefill_scan` pays O(s)
    sequential ``decode_step`` launches.  Returns per-position logits
    ``[b, s, vocab]`` and the filled cache.

    ``lengths [b]`` (optional): true per-row prompt lengths for
    right-padded batches — each slot's ``pos`` is set to its own length
    so pad positions stay masked and the next decode overwrites them.
    Pinned bitwise against the scanned path by tests/test_decode.py.

    One divergence from :func:`prefill_scan`: prompts longer than
    ``cfg.max_len`` fed into an enlarged cache (rope models only — no
    table to run off) trip the full forward's max_len guard here; use
    the scanned path for that corner.
    """
    from ..parallel.tensor_parallel import (  # noqa: PLC0415
        _gpt_embed, _gpt_head,
    )

    b, s = tokens.shape
    cache = init_cache(cfg, b, max_len)
    s_cache = cache["k"].shape[2]
    if s > s_cache:
        raise ValueError(
            f"prompt length {s} exceeds the {s_cache}-token cache; "
            f"raise max_len"
        )
    p = _params(params)
    # Explicit contiguous positions: prompts entering a decode cache are
    # always contiguous, and passing them explicitly keeps zigzag-layout
    # models decodable (their forward demands explicit positions; the
    # attend override below replaces the zigzag schedule anyway) — the
    # scanned path always drove decode_step with explicit positions too.
    x, positions, rope_tabs = _gpt_embed(p, cfg, tokens, 0,
                                         jnp.arange(s))

    k_new, v_new = cache["k"], cache["v"]
    for i in range(cfg.num_layers):

        def attend(q, k_t, v_t, _i=i):
            # k_t/v_t [b, s, nkv, hd], rope-applied by block_math (the
            # shared [s, half] tables are exactly right here: every row
            # sits at positions 0..s-1) — write the whole prompt's K/V
            # in one shot, then attend every query against the prefix.
            nonlocal k_new, v_new
            k_new = lax.dynamic_update_slice(
                k_new, k_t.astype(cfg.dtype)[None], (_i, 0, 0, 0, 0)
            )
            v_new = lax.dynamic_update_slice(
                v_new, v_t.astype(cfg.dtype)[None], (_i, 0, 0, 0, 0)
            )
            return _attend_prefix(cfg, q, k_new[_i], v_new[_i])

        x = raw_block_forward(cfg, p[f"block{i}"], x, positions,
                              rope_tabs, attend=attend)

    logits = _gpt_head(p, cfg, x)
    if lengths is None:
        pos = jnp.full((b,), s, jnp.int32)
    else:
        pos = jnp.asarray(lengths, jnp.int32)
    return logits, {"k": k_new, "v": v_new, "pos": pos}


def prefill_scan(cfg: TransformerConfig, params, tokens, max_len=None):
    """Token-by-token prefill: the prompt scanned through
    ``decode_step`` (one compiled loop, O(s) sequential dispatches).
    Kept as the bitwise oracle for :func:`prefill` — the incremental
    dataflow this module exists to get right."""
    b, s = tokens.shape
    cache = init_cache(cfg, b, max_len)

    def step(cache, tok_t):
        logits, cache = decode_step(cfg, params, cache, tok_t)
        return cache, logits

    cache, logits = lax.scan(step, cache, tokens.T)
    return jnp.transpose(logits, (1, 0, 2)), cache


def reset_slot(cache, slot):
    """Clear slot ``slot``: zero its K/V rows, rewind its position.
    The other slots' buffers are bitwise untouched."""
    return {
        "k": cache["k"].at[:, slot].set(0),
        "v": cache["v"].at[:, slot].set(0),
        "pos": cache["pos"].at[slot].set(0),
    }


def assign_slot(cfg: TransformerConfig, params, cache, slot, tokens,
                length=None):
    """Prefill ONE request into slot ``slot`` of a multi-slot cache
    while every other slot's K/V stays bitwise untouched — the
    admission primitive of the continuous-batching scheduler.

    ``tokens [s]`` may be right-padded to a bucket length; ``length``
    (dynamic scalar, default ``s``) is the true prompt length.  Returns
    ``(cache, last_logits [vocab])`` where ``last_logits`` is the
    prediction at the prompt's final real position (the request's first
    generated token is its argmax/sample).  ``slot`` and ``length`` are
    trace-time dynamic, so one compiled assign per prompt-length bucket
    serves every admission.
    """
    s = tokens.shape[0]
    s_cache = cache["k"].shape[2]
    if s > s_cache:
        raise ValueError(
            f"assign_slot: {s} prompt tokens exceed the {s_cache}-token "
            f"slot cache"
        )
    if length is None:
        length = s
    length = jnp.asarray(length, jnp.int32)
    # Prefill into a BUCKET-length cache, not the slot length: the
    # admission then pays O(s^2) attention and writes only [0:s) of the
    # slot.  Positions >= s keep the evicted predecessor's K/V — masked
    # by pos until the advancing decode overwrites them, so they never
    # attend; zeroing them would cost a full-slot write per admit.
    logits, one = prefill(cfg, params, tokens[None], max_len=s,
                          lengths=length[None])
    k = lax.dynamic_update_slice(cache["k"], one["k"], (0, slot, 0, 0, 0))
    v = lax.dynamic_update_slice(cache["v"], one["v"], (0, slot, 0, 0, 0))
    pos = cache["pos"].at[slot].set(length)
    last = jnp.take(logits[0], length - 1, axis=0)
    return {"k": k, "v": v, "pos": pos}, last


def init_paged_pool(cfg: TransformerConfig, num_pages: int,
                    page_size: int, num_slots: int,
                    kv_heads: Optional[int] = None):
    """Paged KV pool: per-layer K/V in fixed-size PAGES (``page_size``
    token rows each) shared by every slot, plus per-slot write
    positions.  A slot's cache is whatever pages its block table
    (serve/paged.py) names, so resident KV bytes scale with tokens
    actually written instead of ``slots x max_len`` — the vLLM block-
    table idea on top of :func:`decode_step`'s masked-write machinery.

    ``kv_heads`` overrides the per-pool head count for width-sharded
    pools (each device of the width axis holds only ITS heads' pages).
    """
    if cfg.moe_experts > 0:
        raise ValueError("decode cache supports dense blocks only")
    hkv = kv_heads if kv_heads is not None else cfg.kv_heads
    kv = (cfg.num_layers, num_pages, page_size, hkv, cfg.head_dim)
    return {
        "k": jnp.zeros(kv, cfg.dtype),
        "v": jnp.zeros(kv, cfg.dtype),
        "pos": jnp.zeros((num_slots,), jnp.int32),
    }


def decode_step_paged(cfg: TransformerConfig, params, pool, tables,
                      tokens_t, write_mask=None, *, tp_axis=None,
                      rep=None):
    """One decode step through the BLOCK TABLE: ``tokens_t [b]`` ->
    ``(logits [b, vocab], pool)`` where each slot's K/V append lands in
    page ``tables[slot, pos // page_size]`` at row ``pos % page_size``,
    and attention gathers the slot's pages back into its virtually
    contiguous prefix — logical position ``t`` maps to gathered index
    ``t`` exactly, so the math (and the tokens) are BITWISE the
    contiguous :func:`decode_step`'s whenever the virtual length
    matches (pinned by tests/test_paged.py).

    ``tables [b, max_pages]`` int32: page ids into the pool; entries
    past a slot's allocated prefix carry ``num_pages`` (the null page)
    — out of bounds, so scatter-``drop`` discards writes there and the
    gather zero-fills (masked by ``pos`` regardless).  Decoding past
    the virtual capacity drops the write and NaN-poisons that slot's
    logits, the same loud-failure contract as the contiguous path.

    ``tp_axis``/``rep``: width sharding (Megatron TP inside the
    serving fleet).  When set, ``params`` is this shard's block tree
    and ``rep`` the replicated tree (both from
    ``tensor_parallel.stack_tp_params``), the pool holds only this
    shard's ``kv_heads // width`` heads' pages, and each block rejoins
    through the two row-parallel psums over ``tp_axis`` — call inside
    ``shard_map`` (serve/engine.py does).
    """
    if tp_axis is None:
        p = _params(params)
        rep = p
        tp = 1
    else:
        from ..ops.collectives import axis_size  # noqa: PLC0415

        p = params
        tp = axis_size(tp_axis)
    b = tokens_t.shape[0]
    pos = _slot_pos(pool, b)
    num_pages, ps = pool["k"].shape[1], pool["k"].shape[2]
    mp = tables.shape[1]
    virt = mp * ps

    # Per-slot embedding scaffold — same math as decode_step (the
    # bitwise pin between the two paths is what catches drift).
    x = jnp.take(
        rep["wte"]["embedding"], tokens_t[:, None], axis=0
    ).astype(cfg.dtype)
    if cfg.pos_embedding == "learned":
        pe = jnp.take(rep["wpe"], pos, axis=0,
                      mode="fill", fill_value=jnp.nan)
        x = x + pe.astype(cfg.dtype)[:, None]
    rope_tabs = None
    if cfg.pos_embedding == "rope":
        from ..ops.rope import rope_tables  # noqa: PLC0415

        rope_tabs = rope_tables(pos, cfg.head_dim, cfg.rope_theta)

    # Write coordinates: page id of each slot's next position (null
    # page for frozen rows and overruns -> scatter drops them).
    page_of = jnp.take_along_axis(
        tables, jnp.minimum(pos // ps, mp - 1)[:, None], axis=1
    )[:, 0]
    in_range = pos < virt
    if write_mask is None:
        advance = jnp.ones((b,), jnp.int32)
        w_page = jnp.where(in_range, page_of, num_pages)
    else:
        advance = write_mask.astype(jnp.int32)
        w_page = jnp.where(write_mask & in_range, page_of, num_pages)
    w_off = pos % ps

    k_new, v_new = pool["k"], pool["v"]
    for i in range(cfg.num_layers):

        def attend(q, k_t, v_t, _i=i):
            # q [b, 1, nh, hd]; k_t/v_t [b, 1, nkv, hd].  RoPE per-row
            # here (block_math got rope_tabs=None), append into the
            # slot's current page, then gather the block table back
            # into the virtually contiguous [b, virt, nkv, hd] prefix.
            nonlocal k_new, v_new
            if rope_tabs is not None:
                q = _rope_rows(q, *rope_tabs)
                k_t = _rope_rows(k_t, *rope_tabs)
            k_new = k_new.at[_i, w_page, w_off].set(
                k_t[:, 0].astype(cfg.dtype), mode="drop"
            )
            v_new = v_new.at[_i, w_page, w_off].set(
                v_t[:, 0].astype(cfg.dtype), mode="drop"
            )
            kc = jnp.take(k_new[_i], tables, axis=0,
                          mode="fill", fill_value=0)
            vc = jnp.take(v_new[_i], tables, axis=0,
                          mode="fill", fill_value=0)
            kc = kc.reshape(b, virt, kc.shape[-2], kc.shape[-1])
            vc = vc.reshape(b, virt, vc.shape[-2], vc.shape[-1])
            att = _attend_cached(cfg, q[:, 0], kc, vc, pos)
            return att[:, None]

        if tp_axis is None:
            x = raw_block_forward(cfg, p[f"block{i}"], x, pos[:, None],
                                  None, attend=attend)
        else:
            from ..parallel.tensor_parallel import _tp_block  # noqa: PLC0415

            x = _tp_block(cfg, p[f"block{i}"], rep[f"block{i}"], x,
                          pos[:, None], None, tp_axis, tp,
                          attend=attend)

    from ..parallel.tensor_parallel import _gpt_head  # noqa: PLC0415

    logits = _gpt_head(rep, cfg, x)[:, 0]
    overrun = pos >= virt
    if write_mask is not None:
        overrun = overrun & write_mask
    logits = jnp.where(overrun[:, None], jnp.nan, logits)
    return logits, {"k": k_new, "v": v_new, "pos": pos + advance}


def _prefill_shard(cfg, p, rep, tokens, lengths, tp_axis):
    """Width-sharded single-forward prefill: :func:`prefill`'s math on
    this shard's heads — the mini-cache holds ``kv_heads // width``
    heads, blocks rejoin through the row-parallel psums.  Returns
    ``(logits [b, s, vocab], {"k", "v", "pos"})`` like prefill."""
    from ..parallel.tensor_parallel import (  # noqa: PLC0415
        _gpt_embed, _gpt_head, _tp_block,
    )

    from ..ops.collectives import axis_size  # noqa: PLC0415

    tp = axis_size(tp_axis)
    nkv = cfg.kv_heads // tp
    b, s = tokens.shape
    x, positions, rope_tabs = _gpt_embed(rep, cfg, tokens, 0,
                                         jnp.arange(s))
    k_new = jnp.zeros((cfg.num_layers, b, s, nkv, cfg.head_dim),
                      cfg.dtype)
    v_new = jnp.zeros_like(k_new)
    for i in range(cfg.num_layers):

        def attend(q, k_t, v_t, _i=i):
            nonlocal k_new, v_new
            k_new = lax.dynamic_update_slice(
                k_new, k_t.astype(cfg.dtype)[None], (_i, 0, 0, 0, 0)
            )
            v_new = lax.dynamic_update_slice(
                v_new, v_t.astype(cfg.dtype)[None], (_i, 0, 0, 0, 0)
            )
            return _attend_prefix(cfg, q, k_new[_i], v_new[_i])

        x = _tp_block(cfg, p[f"block{i}"], rep[f"block{i}"], x,
                      positions, rope_tabs, tp_axis, tp, attend=attend)

    logits = _gpt_head(rep, cfg, x)
    pos = jnp.asarray(lengths, jnp.int32)
    return logits, {"k": k_new, "v": v_new, "pos": pos}


def assign_slot_paged(cfg: TransformerConfig, params, pool, tables,
                      slot, tokens, length=None, *, tp_axis=None,
                      rep=None):
    """Admit ONE request into the paged pool: prefill the prompt into a
    contiguous mini-cache (the exact :func:`prefill` math, so the
    contiguous bitwise pins carry over), then scatter its rows into the
    slot's pages.  Positions past the slot's allocated prefix hit the
    null page and are dropped; every other slot's pages are bitwise
    untouched.  Returns ``(pool, last_logits [vocab])``.

    ``tp_axis``/``rep``: width-sharded admission — the mini-cache and
    the pool both hold only this shard's heads (see
    :func:`decode_step_paged`).
    """
    s = tokens.shape[0]
    ps = pool["k"].shape[2]
    mp = tables.shape[1]
    if s > mp * ps:
        raise ValueError(
            f"assign_slot_paged: {s} prompt tokens exceed the "
            f"{mp * ps}-row virtual slot capacity"
        )
    if length is None:
        length = s
    length = jnp.asarray(length, jnp.int32)
    if tp_axis is None:
        logits, one = prefill(cfg, params, tokens[None], max_len=s,
                              lengths=length[None])
    else:
        logits, one = _prefill_shard(cfg, params, rep, tokens[None],
                                     length[None], tp_axis)
    pidx = jnp.arange(s)
    row = jnp.take(tables, slot, axis=0)
    pages = jnp.take(row, pidx // ps)
    offs = pidx % ps
    k = pool["k"].at[:, pages, offs].set(one["k"][:, 0], mode="drop")
    v = pool["v"].at[:, pages, offs].set(one["v"][:, 0], mode="drop")
    pos = pool["pos"].at[slot].set(length)
    last = jnp.take(logits[0], length - 1, axis=0)
    return {"k": k, "v": v, "pos": pos}, last


def generate(cfg: TransformerConfig, params, prompt, steps: int,
             max_len=None, temperature: float = 0.0, top_k: int = 0,
             key=None, eos_id: Optional[int] = None):
    """Continuation: ``prompt [b, s]`` -> ``[b, steps]`` tokens.

    ``temperature == 0`` (default) is greedy argmax.  ``temperature > 0``
    samples ``softmax(logits / temperature)`` (requires ``key``);
    ``top_k > 0`` additionally truncates to the k most likely tokens
    before sampling.

    ``eos_id``: rows that emit it are FROZEN — their cache writes are
    masked, their position stops advancing, and they repeat ``eos_id``
    as pad — and the decode loop exits as soon as every row is done, so
    a batch of short completions stops paying for its longest member.
    """
    if temperature > 0 and key is None:
        raise ValueError("temperature > 0 requires a PRNG key")

    def pick(logits, k):
        if temperature <= 0:
            return jnp.argmax(logits, axis=-1)
        lt = logits / temperature
        if top_k > 0:
            kth = lax.top_k(lt, top_k)[0][..., -1:]
            lt = jnp.where(lt < kth, -jnp.inf, lt)
        return jax.random.categorical(k, lt, axis=-1)

    b = prompt.shape[0]
    if steps <= 0:
        return jnp.zeros((b, 0), jnp.int32)
    keys = (
        jax.random.split(key, steps) if key is not None
        else jnp.zeros((steps, 2), jnp.uint32)
    )
    logits, cache = prefill(cfg, params, prompt, max_len)
    first = pick(logits[:, -1], keys[0]).astype(jnp.int32)

    if eos_id is None:
        # Emit the NEWLY picked token from the scan (seeded with
        # ``first``): token i+1 costs exactly one decode_step on token
        # i, so ``steps`` tokens take ``steps - 1`` scan iterations.
        def step(carry, k):
            cache, tok = carry
            logits, cache = decode_step(cfg, params, cache, tok)
            new = pick(logits, k).astype(jnp.int32)
            return (cache, new), new

        (_, _), toks = lax.scan(step, (cache, first), keys[1:])
        return jnp.concatenate([first[:, None], toks.T], axis=1)

    # eos-aware path: same per-row math as the scan above (frozen rows
    # only freeze THEMSELVES — rows are independent), with a while_loop
    # so the batch stops as soon as its last row finishes.
    done0 = first == eos_id
    out0 = jnp.full((b, steps), eos_id, jnp.int32).at[:, 0].set(first)

    def cond(carry):
        step_i, _, _, done, _ = carry
        return (step_i < steps) & ~jnp.all(done)

    def body(carry):
        step_i, cache, tok, done, out = carry
        logits, cache = decode_step(cfg, params, cache, tok,
                                    write_mask=~done)
        new = pick(logits, keys[step_i]).astype(jnp.int32)
        new = jnp.where(done, eos_id, new)
        out = out.at[:, step_i].set(new)
        done = done | (new == eos_id)
        return step_i + 1, cache, new, done, out

    _, _, _, _, out = lax.while_loop(
        cond, body, (jnp.asarray(1, jnp.int32), cache, first, done0, out0)
    )
    return out
