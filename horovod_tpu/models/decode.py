"""KV-cache incremental decoding for the GPT family.

The reference framework is not in the serving path (docs/inference.md,
≙ ref docs/inference.rst) — but its model zoo still has to be *usable*
for generation, so the GPT family ships a functional decode path:

* :func:`init_cache` — per-layer K/V buffers ``[L, b, max_len, kv_heads,
  head_dim]`` plus the write position.
* :func:`decode_step` — one token for every sequence in the batch:
  append its K/V, attend the single query against the cache prefix,
  return next-token logits.  O(max_len) per step instead of the
  O(S^2) full forward.
* :func:`prefill` — feed a prompt through ``decode_step`` under
  ``lax.scan`` (one compiled loop), returning per-position logits and
  the filled cache.
* :func:`generate` — greedy continuation, one ``lax.scan`` over steps.

The block wiring is NOT re-implemented here: each step runs
``raw_block_forward`` (the single-source :func:`block_math`) with an
``attend`` override that appends to the cache and attends the single
query against the prefix — so rope, GQA head routing, fp8 activation
storage, and any future block change flow into decoding automatically.
Equivalence with the full (training) forward — logits at every prompt
position and greedy continuations token-for-token — is pinned by
tests/test_decode.py.

Dense blocks only (MoE is training-path-only, parallel/moe.py).
Decoding past the cache end poisons the logits with NaN (the same
loud-failure contract as the out-of-range wpe gather in
``GPT.__call__``) instead of silently overwriting the last slot.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .transformer import TransformerConfig, raw_block_forward

__all__ = ["init_cache", "decode_step", "prefill", "generate"]


def _params(params):
    if set(params.keys()) == {"params"}:
        params = params["params"]
    return params


def init_cache(cfg: TransformerConfig, batch: int, max_len=None):
    """Empty decode state: per-layer K/V at the cache dtype + position."""
    if cfg.moe_experts > 0:
        raise ValueError("decode cache supports dense blocks only")
    s = max_len or cfg.max_len
    kv = (cfg.num_layers, batch, s, cfg.kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(kv, cfg.dtype),
        "v": jnp.zeros(kv, cfg.dtype),
        "pos": jnp.zeros((), jnp.int32),
    }


def _attend_cached(cfg, q, k_cache, v_cache, pos):
    """One query against the cache prefix: ``q [b, h, hd]``,
    ``k/v_cache [b, S, hkv, hd]`` -> ``[b, h, hd]``.  Unwritten
    positions (> pos) are masked; with ``cfg.attention_window`` the
    band's lower edge is masked too (parity with the flash kernel's
    sliding window); GQA queries fold onto their kv group via reshape,
    no K/V broadcast."""
    b, h, hd = q.shape
    s = k_cache.shape[1]
    group = h // cfg.kv_heads
    qg = q.reshape(b, cfg.kv_heads, group, hd).astype(jnp.float32)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)
    st = jnp.einsum("bkgd,bskd->bkgs", qg, kf) * (hd ** -0.5)
    idx = jnp.arange(s)[None, None, None, :]
    mask = idx > pos
    if cfg.attention_window is not None:
        mask = mask | (idx < pos - (cfg.attention_window - 1))
    st = jnp.where(mask, jnp.finfo(jnp.float32).min / 2, st)
    p = jax.nn.softmax(st, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", p, vf)
    return out.reshape(b, h, hd)


def decode_step(cfg: TransformerConfig, params, cache, tokens_t):
    """Decode one token per sequence: ``tokens_t [b]`` ->
    ``(logits [b, vocab], cache)`` with the token's K/V appended at
    ``cache["pos"]``."""
    from ..parallel.tensor_parallel import (  # noqa: PLC0415
        _gpt_embed, _gpt_head,
    )

    p = _params(params)
    pos = cache["pos"]
    s_cache = cache["k"].shape[2]
    # shared scaffold: wte + wpe (NaN fill past max_len) / rope tables
    # at the explicit position
    x, positions, rope_tabs = _gpt_embed(
        p, cfg, tokens_t[:, None], 0, pos[None]
    )

    k_new, v_new = cache["k"], cache["v"]
    for i in range(cfg.num_layers):

        def attend(q, k_t, v_t, _i=i):
            # q [b, 1, nh, hd]; k_t/v_t [b, 1, nkv, hd], rope-applied by
            # block_math — append, then attend against the prefix
            nonlocal k_new, v_new
            k_new = lax.dynamic_update_slice(
                k_new, k_t.astype(cfg.dtype)[None], (_i, 0, pos, 0, 0)
            )
            v_new = lax.dynamic_update_slice(
                v_new, v_t.astype(cfg.dtype)[None], (_i, 0, pos, 0, 0)
            )
            att = _attend_cached(cfg, q[:, 0], k_new[_i], v_new[_i], pos)
            return att[:, None]

        x = raw_block_forward(cfg, p[f"block{i}"], x, positions,
                              rope_tabs, attend=attend)

    logits = _gpt_head(p, cfg, x)[:, 0]
    # past the cache end the write index would CLAMP (silently
    # overwriting the last slot) — poison instead, like the wpe gather
    logits = jnp.where(pos >= s_cache, jnp.nan, logits)
    return logits, {"k": k_new, "v": v_new, "pos": pos + 1}


def prefill(cfg: TransformerConfig, params, tokens, max_len=None):
    """Feed a prompt ``[b, s]``: per-position logits ``[b, s, vocab]``
    and the filled cache, as one scanned decode loop."""
    b, s = tokens.shape
    cache = init_cache(cfg, b, max_len)

    def step(cache, tok_t):
        logits, cache = decode_step(cfg, params, cache, tok_t)
        return cache, logits

    cache, logits = lax.scan(step, cache, tokens.T)
    return jnp.transpose(logits, (1, 0, 2)), cache


def generate(cfg: TransformerConfig, params, prompt, steps: int,
             max_len=None, temperature: float = 0.0, top_k: int = 0,
             key=None):
    """Continuation: ``prompt [b, s]`` -> ``[b, steps]`` tokens.

    ``temperature == 0`` (default) is greedy argmax.  ``temperature > 0``
    samples ``softmax(logits / temperature)`` (requires ``key``);
    ``top_k > 0`` additionally truncates to the k most likely tokens
    before sampling."""
    if temperature > 0 and key is None:
        raise ValueError("temperature > 0 requires a PRNG key")

    def pick(logits, k):
        if temperature <= 0:
            return jnp.argmax(logits, axis=-1)
        lt = logits / temperature
        if top_k > 0:
            kth = lax.top_k(lt, top_k)[0][..., -1:]
            lt = jnp.where(lt < kth, -jnp.inf, lt)
        return jax.random.categorical(k, lt, axis=-1)

    if steps <= 0:
        return jnp.zeros((prompt.shape[0], 0), jnp.int32)
    keys = (
        jax.random.split(key, steps) if key is not None
        else jnp.zeros((steps, 2), jnp.uint32)
    )
    logits, cache = prefill(cfg, params, prompt, max_len)
    first = pick(logits[:, -1], keys[0])

    # Emit the NEWLY picked token from the scan (seeded with ``first``):
    # token i+1 costs exactly one decode_step on token i, so ``steps``
    # tokens take ``steps - 1`` scan iterations — the old shape emitted
    # the input token and burned a final decode_step whose pick was
    # discarded.
    def step(carry, k):
        cache, tok = carry
        logits, cache = decode_step(cfg, params, cache, tok)
        new = pick(logits, k)
        return (cache, new), new

    (_, _), toks = lax.scan(step, (cache, first), keys[1:])
    return jnp.concatenate([first[:, None], toks.T], axis=1)
