"""Small models for examples and smoke tests (≙ the nets in the
reference's examples/*_mnist.py)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (128, 64)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.relu(nn.Dense(f)(x))
        return nn.Dense(self.num_classes)(x)


class ConvNet(nn.Module):
    """The two-conv MNIST net of the reference examples
    (examples/pytorch_mnist.py Net / tensorflow2_mnist.py model)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.relu(nn.Conv(32, (3, 3))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(self.num_classes)(x)
