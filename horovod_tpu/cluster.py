"""Cluster-manager integration: run a horovod_tpu job inside task slots a
cluster scheduler already allocated.

Reference: ``horovod.spark.run`` (horovod/spark/runner.py:100-189) — one
Horovod process per Spark task: a driver service waits for every task to
register, assigns ranks grouped by host hash (barrel-shifted so rank 0
lands on the first host), ships the pickled function to each task, and
collects per-rank results.

TPU redesign: the driver is this package's HMAC-signed HTTP KV store (the
same rendezvous the launcher uses, run/rendezvous.py ≙ the reference's
RendezvousServer), and the coordination service is ``jax.distributed``
bootstrapped by whichever task is assigned rank 0.  The cluster manager
only has to run ``task_main(index, driver, secret)`` in each of its task
slots — adapters:

* :func:`local_executor` — subprocess slots on this machine (the test
  topology, and a correctness reference for any adapter).
* :func:`spark_executor` — one task per Spark partition, exactly the
  reference's ``_make_spark_thread`` shape (imports pyspark lazily).

Any other scheduler (k8s indexed Jobs, Slurm steps, Ray actors) integrates
by invoking ``python -m horovod_tpu.cluster --task <i> --driver <addr>
--secret <key>`` in each slot.
"""

from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

import cloudpickle

from .run.allocate import routable_ip
from .run.rendezvous import KVStoreClient, KVStoreServer, make_secret

START_TIMEOUT_DEFAULT = 600.0


# ---------------------------------------------------------------------------
# rank assignment (reference spark/runner.py:186-205: host-hash grouping +
# barrel shift so index 0's host holds rank 0)
# ---------------------------------------------------------------------------


def assign_ranks(task_hosts: Dict[int, str]) -> List[dict]:
    """task index -> host hash, to per-task slot dicts (rank, local_rank,
    local_size, cross_rank, cross_size, size)."""
    by_host: Dict[str, List[int]] = {}
    for idx in sorted(task_hosts):
        by_host.setdefault(task_hosts[idx], []).append(idx)
    hosts = sorted(by_host)
    # Barrel shift until task index 0 is in the first host.
    first = task_hosts[0]
    while hosts[0] != first:
        hosts = hosts[1:] + hosts[:1]
    slots = [None] * len(task_hosts)
    rank = 0
    for cross_rank, h in enumerate(hosts):
        for local_rank, idx in enumerate(by_host[h]):
            slots[idx] = {
                "rank": rank,
                "local_rank": local_rank,
                "local_size": len(by_host[h]),
                "cross_rank": cross_rank,
                "cross_size": len(hosts),
                "size": len(task_hosts),
            }
            rank += 1
    return slots


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def run_on_cluster(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    *,
    num_proc: int,
    executor: Callable[[int, str, str], object],
    start_timeout: float = START_TIMEOUT_DEFAULT,
    job_timeout: Optional[float] = None,
    env: Optional[Dict[str, str]] = None,
    driver_host: Optional[str] = None,
    min_workers: Optional[int] = None,
    max_retries: int = 0,
):
    """Run ``fn`` as a ``num_proc``-rank horovod_tpu job inside cluster
    task slots; returns the per-rank results in rank order (reference
    horovod.spark.run contract).

    ``start_timeout`` bounds task START-UP (scheduling + registration —
    the reference's start_timeout semantics, spark/runner.py); the
    training function itself may run as long as it likes unless
    ``job_timeout`` is set.  ``driver_host`` overrides the advertised
    driver address for networks where the outbound-interface probe picks
    the wrong NIC.

    Elastic knobs (matching the launcher's, run/runner.py):
    ``min_workers`` — when the registration deadline passes with at
    least this many tasks checked in, the job proceeds with the reduced
    world instead of failing start-up (unregistered slots are released
    with a ``None`` rank assignment); default ``None`` keeps the strict
    all-``num_proc`` contract.  ``max_retries`` — re-run the whole
    attempt (fresh rendezvous + executor invocation) up to this many
    times when a task fails; default 0 keeps fail-fast.

    ``executor(num_tasks, driver_addr, secret)`` must arrange for
    :func:`task_main`-equivalent execution in each slot; returning an
    object with ``failed()`` / ``join()`` / ``terminate()`` gives the
    driver fast failure detection and cleanup.
    """
    attempts = 0
    while True:
        try:
            return _run_cluster_attempt(
                fn, args, kwargs,
                num_proc=num_proc, executor=executor,
                start_timeout=start_timeout, job_timeout=job_timeout,
                env=env, driver_host=driver_host,
                min_workers=min_workers,
            )
        except (RuntimeError, TimeoutError) as exc:
            attempts += 1
            if attempts > max_retries:
                raise
            print(
                f"horovod_tpu.cluster: attempt {attempts} failed "
                f"({exc}); retrying ({max_retries - attempts + 1} "
                f"retries left)",
                file=sys.stderr,
            )


def _run_cluster_attempt(
    fn: Callable,
    args: tuple,
    kwargs: Optional[dict],
    *,
    num_proc: int,
    executor: Callable[[int, str, str], object],
    start_timeout: float,
    job_timeout: Optional[float],
    env: Optional[Dict[str, str]],
    driver_host: Optional[str],
    min_workers: Optional[int],
):
    """One rendezvous + execution attempt (the pre-elastic
    run_on_cluster body)."""
    # Bind every interface and advertise the outbound-interface address:
    # task slots generally live on OTHER hosts (same logic as the
    # launcher's KV server, run/api.py bind_all=not all_local; the probe
    # address is never contacted — routable_ip uses a connected UDP
    # socket only to pick the interface).
    server = KVStoreServer(secret=(secret := make_secret()), bind_all=True)
    port = server.start()
    advertised = f"{driver_host or routable_ip('192.0.2.1')}:{port}"
    from .run.api import _pickle_func  # noqa: PLC0415

    kv = KVStoreClient(f"127.0.0.1:{port}", secret)
    kv.put("job", "program", _pickle_func(fn, args, kwargs or {}))
    kv.put("job", "env", pickle.dumps(env or {}))

    handle = executor(num_proc, advertised, secret)

    def posted_failure():
        """A task that raised posts its traceback BEFORE exiting; that
        diagnostic must win over the generic died-without-result error."""
        for j in range(num_proc):
            raw = kv.get("result", str(j))
            if raw is not None:
                ok, value = pickle.loads(raw)
                if not ok:
                    return j, value
        return None

    def check_executor_failure(what: str) -> None:
        """Fail the job promptly when a slot died: surface its posted
        traceback when one exists, else a generic death notice."""
        failed = getattr(handle, "failed", None)
        if failed is None or not failed():
            return
        post = posted_failure()
        if post is not None:
            j, tb = post
            raise RuntimeError(f"cluster task {j} raised:\n{tb}")
        raise RuntimeError(
            f"a cluster task died during {what} without reporting "
            "a result (see its slot's logs)"
        )

    def wait_kv(scope: str, key: str, deadline, what: str) -> bytes:
        """Poll the KV in short slices, interleaving executor-death checks
        so a crashed slot fails the job promptly instead of burning the
        whole timeout."""
        while True:
            try:
                return kv.wait(scope, key, timeout=5.0)
            except TimeoutError:
                pass
            check_executor_failure(what)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"cluster {what} timed out waiting for {scope}/{key}"
                )

    try:
        # 1. registration (reference: driver.task_host_hash_indices).
        # With min_workers set, a deadline pass with at least that many
        # registrants proceeds on the reduced world (the cluster-level
        # analog of the elastic launcher's shrink path) instead of
        # failing start-up on stragglers the scheduler never placed.
        start_deadline = time.monotonic() + start_timeout
        task_hosts: Dict[int, str] = {}
        pending = set(range(num_proc))
        while pending:
            # Block server-side on ONE representative key (so the common
            # fast path has sub-second latency without hammering the
            # single-threaded KV store at poll rate), then sweep the
            # rest with cheap gets once per wakeup.
            probe = min(pending)
            try:
                raw = kv.wait("register", str(probe), timeout=1.0)
                task_hosts[probe] = pickle.loads(raw)["host_hash"]
            except TimeoutError:
                pass
            for i in sorted(pending - set(task_hosts)):
                raw = kv.get("register", str(i))
                if raw is not None:
                    task_hosts[i] = pickle.loads(raw)["host_hash"]
            pending -= set(task_hosts)
            if not pending:
                break
            check_executor_failure("start-up")
            if time.monotonic() > start_deadline:
                if (min_workers is not None
                        and len(task_hosts) >= min_workers):
                    break
                raise TimeoutError(
                    f"cluster start-up timed out with "
                    f"{len(task_hosts)}/{num_proc} tasks registered"
                    + (f" (min_workers={min_workers})"
                       if min_workers is not None else "")
                )
        # 2. rank assignment, published per task.  assign_ranks wants
        # dense indexes, so a reduced world is densified first; slots
        # that never registered get an explicit None so a late-arriving
        # task releases its slot cleanly instead of hanging on the key.
        registered = sorted(task_hosts)
        dense = assign_ranks(
            {pos: task_hosts[i] for pos, i in enumerate(registered)}
        )
        slots = {i: dense[pos] for pos, i in enumerate(registered)}
        for i in range(num_proc):
            kv.put("slot", str(i), pickle.dumps(slots.get(i)))
        # 3. results, in rank order (bounded only by job_timeout; a task
        # that died without posting is detected through the executor
        # handle rather than a timeout)
        job_deadline = (
            time.monotonic() + job_timeout if job_timeout else None
        )
        results = [None] * len(registered)
        for i in registered:
            ok, value = pickle.loads(
                wait_kv("result", str(i), job_deadline, "job")
            )
            if not ok:
                raise RuntimeError(
                    f"cluster task {i} (rank {slots[i]['rank']}) raised:\n"
                    f"{value}"
                )
            results[slots[i]["rank"]] = pickle.loads(value)
    except BaseException:
        # Error path: peers may be blocked mid-negotiation on the dead
        # rank — tear the slots down rather than joining forever.
        terminate = getattr(handle, "terminate", None)
        if terminate is not None:
            try:
                terminate()
            except Exception:
                pass
        raise
    finally:
        joiner = getattr(handle, "join", None)
        if joiner is not None:
            try:
                joiner()
            except Exception:
                pass
        server.stop()
    return results


# ---------------------------------------------------------------------------
# task side
# ---------------------------------------------------------------------------


def task_main(index: int, driver_addr: str, secret: str) -> None:
    """Body of one cluster task slot: register, learn the rank, bootstrap
    the coordination service, run the user function, report the result
    (reference horovod/spark/task/__init__.py + task_service)."""
    kv = KVStoreClient(driver_addr, secret)
    try:
        kv.put(
            "register", str(index),
            pickle.dumps({"host_hash": socket.gethostname(),
                          "pid": os.getpid()}),
        )
        slot = pickle.loads(kv.wait("slot", str(index), timeout=600))
        if slot is None:
            # Reduced world (driver proceeded with min_workers before
            # this task registered): release the slot without error so
            # the executor's handle never reads it as a failure.
            kv.put("result", str(index), pickle.dumps((True, pickle.dumps(None))))
            return
        extra_env = pickle.loads(kv.wait("job", "env", timeout=60))
        os.environ.update(extra_env)
        os.environ.update({
            "HVDTPU_RANK": str(slot["rank"]),
            "HVDTPU_SIZE": str(slot["size"]),
            "HVDTPU_LOCAL_RANK": str(slot["local_rank"]),
            "HVDTPU_LOCAL_SIZE": str(slot["local_size"]),
            "HVDTPU_CROSS_RANK": str(slot["cross_rank"]),
            "HVDTPU_CROSS_SIZE": str(slot["cross_size"]),
        })
        # rank 0 hosts the jax.distributed coordinator; everyone else
        # learns its address through the driver KV (≙ the reference's
        # task-to-task address registration, spark/runner.py:193-199).
        # The reserving socket stays OPEN (SO_REUSEADDR) until just before
        # the user fn runs, shrinking the port-reuse window to the init
        # prologue rather than the whole fan-out of the address.
        reserve = None
        if slot["rank"] == 0:
            reserve = socket.socket()
            reserve.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            reserve.bind(("", 0))
            coord = f"{routable_ip(driver_addr.rsplit(':', 1)[0])}:" \
                    f"{reserve.getsockname()[1]}"
            kv.put("job", "coordinator", coord.encode())
        else:
            coord = kv.wait("job", "coordinator", timeout=600).decode()
        os.environ["HVDTPU_COORDINATOR"] = coord

        fn, args, kwargs = cloudpickle.loads(
            kv.wait("job", "program", timeout=60)
        )
        if reserve is not None:
            reserve.close()
        result = fn(*args, **kwargs)
        kv.put("result", str(index),
               pickle.dumps((True, pickle.dumps(result))))
    except BaseException:  # noqa: BLE001 — report, then re-raise
        import traceback

        try:
            kv.put("result", str(index),
                   pickle.dumps((False, traceback.format_exc())))
        except Exception:
            pass
        raise


def _main() -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="horovod_tpu cluster task entry (run one per slot)"
    )
    parser.add_argument("--task", type=int, required=True)
    parser.add_argument("--driver", required=True)
    parser.add_argument("--secret", required=True)
    a = parser.parse_args()
    task_main(a.task, a.driver, a.secret)
    return 0


# ---------------------------------------------------------------------------
# executors
# ---------------------------------------------------------------------------


class _LocalHandle:
    def __init__(self, procs: List[subprocess.Popen]):
        self.procs = procs

    def join(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        for p in self.procs:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def terminate(self) -> None:
        """Tear down surviving slots (error path: peers may be blocked
        mid-negotiation on a dead rank forever)."""
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 5.0
        for p in self.procs:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()

    def failed(self) -> bool:
        """True when any slot process exited non-zero (a task that died
        without posting its result — the driver stops waiting)."""
        return any(
            p.poll() is not None and p.poll() != 0 for p in self.procs
        )


def local_executor(base_env: Optional[Dict[str, str]] = None):
    """Task slots as local subprocesses — the test topology, and the
    template for writing adapters (every slot just needs to exec the
    module entry with its index)."""

    def launch(num_tasks: int, driver_addr: str, secret: str) -> _LocalHandle:
        procs = []
        for i in range(num_tasks):
            env = dict(os.environ)
            env.update(base_env or {})
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-m", "horovod_tpu.cluster",
                     "--task", str(i), "--driver", driver_addr,
                     "--secret", secret],
                    env=env,
                )
            )
        return _LocalHandle(procs)

    return launch


def spark_executor(spark_context=None):
    """One horovod_tpu process per Spark task, the reference's topology
    (spark/runner.py _make_spark_thread + mapPartitionsWithIndex).  Lazily
    imports pyspark; raises a clear error when Spark is absent."""

    def launch(num_tasks: int, driver_addr: str, secret: str):
        try:
            import pyspark  # noqa: PLC0415
        except ImportError as exc:
            raise RuntimeError(
                "spark_executor requires pyspark; install it or use "
                "local_executor / a custom adapter"
            ) from exc
        sc = spark_context or pyspark.SparkContext._active_spark_context
        if sc is None:
            raise RuntimeError(
                "no active SparkContext; create one before spark_executor"
            )

        def _task(index, _iterator):
            task_main(index, driver_addr, secret)
            yield index

        class _SparkHandle:
            """Exposes failed()/join() like _LocalHandle so the driver
            detects Spark-side task death (stage failure, executor OOM)
            instead of polling forever."""

            def __init__(self):
                self.exc: Optional[BaseException] = None
                self.thread = threading.Thread(target=self._run, daemon=True)
                self.thread.start()

            def _run(self):
                try:
                    sc.parallelize(
                        range(num_tasks), num_tasks
                    ).mapPartitionsWithIndex(_task).collect()
                except BaseException as e:  # noqa: BLE001
                    self.exc = e

            def failed(self) -> bool:
                return self.exc is not None

            def join(self, timeout: float = 30.0) -> None:
                self.thread.join(timeout)

        return _SparkHandle()

    return launch


if __name__ == "__main__":
    sys.exit(_main())
