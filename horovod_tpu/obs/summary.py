"""End-of-job aggregation of per-rank metrics dumps.

The launcher's ``--stats-summary`` flag reads every
``HVDTPU_METRICS_DUMP`` file the job's ranks wrote (obs/registry.py dump
schema) and renders one table — metrics as rows, ranks as columns — so
cross-rank skew (one rank's cycle p99, one rank's cache hit rate) is
visible without grepping per-rank logs.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional

from . import pathspec

__all__ = [
    "collect_dumps",
    "format_summary_table",
    "straggler_section",
    "fabric_section",
    "autoscale_section",
    "perf_section",
    "mem_section",
    "goodput_section",
    "slo_section",
    "health_section",
    "summarize",
]


def _dump_glob(raw: str) -> str:
    return pathspec.glob_pattern(raw, "metrics")


class DumpSet(Dict[str, dict]):
    """collect_dumps result: a plain ``{label -> dump doc}`` mapping
    plus ``.warnings`` — one line per dump that was found on disk but
    skipped (truncated mid-write, corrupt JSON, wrong schema).  A
    half-written dump must not sink the summary, but it must not
    vanish silently either: a missing column that LOOKS like "rank
    never dumped" when the file is sitting right there is exactly the
    kind of misdirection a post-mortem can't afford."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.warnings: List[str] = []


def collect_dumps(raw: str) -> DumpSet:
    """Read every per-rank dump derived from the ``HVDTPU_METRICS_DUMP``
    value; returns {column label -> dump document}.  Elastic epoch tags
    become part of the label so incarnations stay distinguishable.
    Unreadable/corrupt dumps are skipped but named in ``.warnings`` so
    the table header can say which columns are missing and why."""
    out = DumpSet()
    for path in sorted(glob.glob(_dump_glob(raw))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            out.warnings.append(
                f"skipped corrupt metrics dump {os.path.basename(path)}"
                f" ({type(exc).__name__}: truncated or unreadable)"
            )
            continue
        if not isinstance(doc, dict) or "metrics" not in doc:
            out.warnings.append(
                f"skipped metrics dump {os.path.basename(path)} "
                f"(valid JSON but not a metrics dump document)"
            )
            continue
        label = str(doc.get("rank", "?"))
        epoch = pathspec.epoch_of_path(path)
        if epoch:
            label = f"{label}@e{epoch}"
        out[label] = doc
    return out


def _cell(metric: dict) -> str:
    if metric["type"] in ("counter", "gauge"):
        v = metric["value"]
        if isinstance(v, float) and not v.is_integer():
            return f"{v:.3g}"
        return str(int(v))
    # histogram: the three numbers that matter at a glance
    if not metric["count"]:
        return "-"
    return (f"n={metric['count']} p50={metric['p50']:.3g} "
            f"p99={metric['p99']:.3g}")


def _metric_label(metric: dict) -> str:
    tags = metric.get("tags") or {}
    if not tags:
        return metric["name"]
    tag_s = ",".join(f"{k}={v}" for k, v in sorted(tags.items()))
    return f"{metric['name']}{{{tag_s}}}"


def format_summary_table(dumps: Dict[str, dict]) -> str:
    """Metrics as rows, ranks as columns, plain monospace table.
    collect_dumps warnings (corrupt/truncated dumps that were skipped)
    lead the header so a missing column reads as "dump was corrupt",
    never as "rank never dumped"."""
    warn_lines = [
        f"WARNING: {w}" for w in getattr(dumps, "warnings", [])
    ]
    if not dumps:
        return "\n".join(warn_lines + ["(no metrics dumps found)"])

    columns = sorted(dumps, key=_rank_sort_key)
    rows: Dict[str, Dict[str, str]] = {}
    for label in columns:
        for metric in dumps[label].get("metrics", []):
            rows.setdefault(_metric_label(metric), {})[label] = _cell(metric)

    name_w = max([len(r) for r in rows] + [len("metric")])
    col_w = {
        c: max([len(rows[r].get(c, "-")) for r in rows]
               + [len(f"rank {c}")])
        for c in columns
    }
    header = "metric".ljust(name_w) + "".join(
        f"  {f'rank {c}':>{col_w[c]}}" for c in columns
    )
    sep = "-" * len(header)
    lines = warn_lines + [header, sep]
    for r in sorted(rows):
        lines.append(
            r.ljust(name_w)
            + "".join(f"  {rows[r].get(c, '-'):>{col_w[c]}}" for c in columns)
        )
    return "\n".join(lines)


def straggler_section(dumps: Dict[str, dict]) -> Optional[str]:
    """The end-of-job straggler verdict: per-rank last-arrival counts
    (with shares), the skew distribution, and a one-line conclusion
    naming the lagging rank.  None when no rank recorded attribution
    (healthy jobs blame nobody).  The merge semantics are the live
    digest's — one shared implementation, obs/straggler.py
    merge_blames, so the two can never name different stragglers."""
    from . import straggler as obs_straggler  # noqa: PLC0415

    verdict = obs_straggler.merge_blames(
        [doc.get("metrics", []) for doc in dumps.values()]
    )
    if verdict is None:
        return None
    blames = verdict["blames"]
    skew = verdict["skew"]
    total = sum(blames.values())
    lines = []
    for rank in sorted(blames, key=lambda r: (-blames[r], r)):
        share = blames[rank] / total if total else 0.0
        mark = "  <- likely straggler" if rank == verdict["rank"] else ""
        lines.append(
            f"rank {rank}: last to arrive in {blames[rank]} "
            f"collectives ({share:.0%}){mark}"
        )
    if skew["count"]:
        lines.append(
            f"arrival skew: n={skew['count']} p50={skew['p50']:.3g}ms "
            f"p99={skew['p99']:.3g}ms max={skew['max']:.3g}ms"
        )
    if verdict["alerts"]:
        lines.append(f"alerts past --alert-skew-ms: {verdict['alerts']}")
    if "slice" in verdict:
        lines.append(
            f"slice {verdict['slice']} is the straggler "
            f"({verdict['slice_share']:.0%} of blame; per-slice "
            + " ".join(
                f"{s}={c}"
                for s, c in sorted(verdict["slice_blames"].items())
            )
            + ")"
        )
    return "\n".join(lines)


def fabric_section(dumps: Dict[str, dict]) -> Optional[str]:
    """End-of-job two-fabric byte report (multislice jobs): per-rank
    DCN vs ICI bytes the data plane moved and the DCN wire compression
    factor.  None when no rank touched the fabric counters — single-
    slice jobs see no new output."""
    rows = []
    for label in sorted(dumps, key=_rank_sort_key):
        dcn = ici = 0.0
        ratio = None
        for m in dumps[label].get("metrics", []):
            name = m.get("name")
            if name == "engine.dcn_bytes":
                dcn = float(m["value"])
            elif name == "engine.ici_bytes":
                ici = float(m["value"])
            elif name == "engine.dcn_compression_ratio":
                ratio = float(m["value"])
        if not dcn and not ici:
            continue
        row = (
            f"rank {label}: dcn {dcn:.3g} B, ici {ici:.3g} B"
            + (f", dcn/ici {dcn / ici:.3f}" if ici else "")
        )
        if ratio and ratio > 1.0:
            row += f", dcn wire compressed x{ratio:.1f}"
        rows.append(row)
    return "\n".join(rows) if rows else None


def ckpt_section(dumps: Dict[str, dict]) -> Optional[str]:
    """End-of-job checkpoint/recovery verdict: per-rank restore
    provenance (peer / disk / none), shard and replica-push volume,
    and the restore-time distribution.  None when no rank touched the
    checkpoint tier — jobs without it see no new output."""
    rows = []
    restore_ms = []
    for label in sorted(dumps, key=_rank_sort_key):
        metrics = dumps[label].get("metrics", [])
        sources = {}
        pushes = dropped = 0
        shard_bytes = 0.0
        for m in metrics:
            name = m.get("name")
            if name == "ckpt.restore_source":
                src = (m.get("tags") or {}).get("source", "?")
                sources[src] = sources.get(src, 0) + int(m["value"])
            elif name == "ckpt.replica_pushes":
                pushes += int(m["value"])
            elif name == "ckpt.replica_dropped":
                dropped += int(m["value"])
            elif name == "ckpt.shard_bytes" and m.get("count"):
                shard_bytes += float(m.get("sum") or 0.0)
            elif name == "ckpt.restore_ms" and m.get("count"):
                restore_ms.append(m)
        if not sources and not pushes and not shard_bytes:
            continue
        src_s = (" ".join(f"{k}={v}" for k, v in sorted(sources.items()))
                 or "-")
        row = (f"rank {label}: restores {src_s}, replica pushes {pushes}"
               + (f" (dropped {dropped})" if dropped else ""))
        if shard_bytes:
            row += f", shard bytes {shard_bytes:.3g}"
        rows.append(row)
    if not rows:
        return None
    if restore_ms:
        n = sum(m["count"] for m in restore_ms)
        worst = max(m["max"] for m in restore_ms)
        p50s = [m["p50"] for m in restore_ms if m.get("p50") is not None]
        rows.append(
            f"restore time: n={n} p50~{(sum(p50s) / len(p50s)):.3g}ms "
            f"max={worst:.3g}ms" if p50s else f"restore time: n={n}"
        )
    return "\n".join(rows)


def serve_section(dumps: Dict[str, dict]) -> Optional[str]:
    """End-of-job serving-plane report: per-rank admission/eviction
    traffic, replay count, and the latency distributions the SLO
    conversation needs (ttft/tpot percentiles, tokens/sec).  None when
    no rank served — training jobs see no new output."""
    rows = []
    for label in sorted(dumps, key=_rank_sort_key):
        vals = {}
        hists = {}
        tenants: Dict[str, Dict[str, float]] = {}
        for m in dumps[label].get("metrics", []):
            name = m.get("name")
            if name in ("serve.admitted", "serve.evicted",
                        "serve.rejected", "serve.replayed",
                        "serve.steps", "serve.tokens_per_sec",
                        "serve.admitted_while_busy", "serve.frontends",
                        "serve.kv.waste_ratio", "serve.kv.page_size",
                        "serve.kv.page_free", "serve.kv.page_used"):
                vals[name] = float(m["value"])
            elif name in ("serve.tenant.throttled",
                          "serve.tenant.admitted_tokens"):
                t = (m.get("tags") or {}).get("tenant", "?")
                short = ("throttled" if name.endswith("throttled")
                         else "tokens")
                bucket = tenants.setdefault(t, {})
                bucket[short] = bucket.get(short, 0.0) + float(m["value"])
            elif name in ("serve.ttft_ms", "serve.tpot_ms") \
                    and m.get("count"):
                hists[name] = m
        if not vals and not hists:
            continue
        row = (
            f"rank {label}: admitted {int(vals.get('serve.admitted', 0))}"
            f" (mid-decode "
            f"{int(vals.get('serve.admitted_while_busy', 0))})"
            f", evicted {int(vals.get('serve.evicted', 0))}"
            f", rejected {int(vals.get('serve.rejected', 0))}"
        )
        if vals.get("serve.replayed"):
            row += f", replayed {int(vals['serve.replayed'])}"
        if vals.get("serve.frontends", 0) > 1:
            # Sharded front door (PR-16): only worth a word when the
            # log actually had more than one producer.
            row += f", frontends {int(vals['serve.frontends'])}"
        if vals.get("serve.steps"):
            row += f", steps {int(vals['serve.steps'])}"
        if vals.get("serve.tokens_per_sec"):
            row += f", {vals['serve.tokens_per_sec']:.1f} tok/s"
        for name, short in (("serve.ttft_ms", "ttft"),
                            ("serve.tpot_ms", "tpot")):
            m = hists.get(name)
            if m is not None:
                row += (
                    f", {short} p50 {m.get('p50') or 0:.3g}ms "
                    f"p99 {m.get('p99') or 0:.3g}ms"
                )
        if "serve.kv.page_size" in vals:
            # Paged-pool line (absent on contiguous pools): what the
            # admission gate saw at the final snapshot.
            row += (
                f", kv pages {int(vals.get('serve.kv.page_used', 0))}"
                f"u/{int(vals.get('serve.kv.page_free', 0))}f"
                f" x{int(vals['serve.kv.page_size'])}rows"
            )
            if "serve.kv.waste_ratio" in vals:
                row += (
                    f" waste {vals['serve.kv.waste_ratio']:.2f}"
                )
        rows.append(row)
        if tenants:
            # Tenant-QoS sub-row (PR-16): who got throttled and how
            # many decode tokens each tenant was admitted — the
            # "one tenant is starving the others" runbook starts here.
            bits = []
            for t in sorted(tenants):
                b = tenants[t]
                bits.append(
                    f"{t} tok={int(b.get('tokens', 0))}"
                    f" throttled={int(b.get('throttled', 0))}"
                )
            rows.append(f"rank {label} tenants: " + ", ".join(bits))
    return "\n".join(rows) if rows else None


def goodput_section(dumps: Dict[str, dict]) -> Optional[str]:
    """End-of-job goodput ledger verdict (obs/goodput.py gauges):
    per-rank productive fraction with the wall-clock class breakdown
    (init/compile/productive/collective_wait/checkpoint/recovery/...)
    and, when any time was lost to elastic events, the per-cause
    attribution (rendezvous / respawn / stall).  Serving ranks add the
    token-goodput line.  None when no rank armed the ledger."""
    rows = []
    for label in sorted(dumps, key=_rank_sort_key):
        frac = None
        secs: Dict[str, float] = {}
        lost: Dict[str, float] = {}
        tok_frac = tok_rate = None
        for m in dumps[label].get("metrics", []):
            name = m.get("name")
            if name == "goodput.fraction":
                frac = float(m["value"])
            elif name == "goodput.secs":
                cls = (m.get("tags") or {}).get("class", "?")
                secs[cls] = float(m["value"])
            elif name == "goodput.lost_secs":
                cause = (m.get("tags") or {}).get("cause", "?")
                lost[cause] = float(m["value"])
            elif name == "serve.goodput.token_fraction":
                tok_frac = float(m["value"])
            elif name == "serve.goodput.tokens_per_slot_sec":
                tok_rate = float(m["value"])
        if frac is None and tok_frac is None:
            continue
        bits = []
        if frac is not None:
            bits.append(f"goodput {frac:.1%}")
            breakdown = " ".join(
                f"{cls}={secs[cls]:.3g}s"
                for cls in sorted(secs, key=lambda c: -secs[c])
                if secs[cls]
            )
            if breakdown:
                bits.append(breakdown)
            if any(lost.values()):
                bits.append("lost " + " ".join(
                    f"{cause}={lost[cause]:.3g}s"
                    for cause in sorted(lost, key=lambda c: -lost[c])
                    if lost[cause]
                ))
        if tok_frac is not None:
            tok = f"token goodput {tok_frac:.1%} of slot capacity"
            if tok_rate is not None:
                tok += f" ({tok_rate:.3g} tok/slot-s)"
            bits.append(tok)
        rows.append(f"rank {label}: " + ", ".join(bits))
    return "\n".join(rows) if rows else None


def slo_section(dumps: Dict[str, dict]) -> Optional[str]:
    """End-of-job SLO burn-rate verdict (obs/slo.py gauges): per
    (tenant, slo class, metric) series the latency digest, breach
    count, fast/slow-window burn rates, and whether an alert ever fired
    — the number the capacity conversation actually needs.  None when
    no rank digested SLO traffic."""
    # (tenant, slo, metric) -> merged view across ranks: digests are
    # per-rank so we show the worst rank's percentiles, and sum the
    # breach/alert counters (they are disjoint per rank).
    series: Dict[tuple, Dict[str, float]] = {}
    for label in sorted(dumps, key=_rank_sort_key):
        for m in dumps[label].get("metrics", []):
            name = m.get("name")
            if not name or not name.startswith("serve.slo."):
                continue
            tags = m.get("tags") or {}
            key = (tags.get("tenant", "?"), tags.get("slo", "?"),
                   tags.get("metric", "?"))
            bucket = series.setdefault(key, {})
            short = name[len("serve.slo."):]
            if short in ("p50_ms", "p99_ms"):
                bucket[short] = max(bucket.get(short, 0.0),
                                    float(m["value"]))
            elif short == "burn":
                win = tags.get("window", "?")
                bucket[f"burn_{win}"] = max(
                    bucket.get(f"burn_{win}", 0.0), float(m["value"]))
            elif short in ("breaches", "alerts"):
                bucket[short] = bucket.get(short, 0.0) + float(m["value"])
    if not series:
        return None
    rows = []
    for (tenant, slo, metric) in sorted(series):
        b = series[(tenant, slo, metric)]
        row = (f"{tenant}/{slo} {metric}: "
               f"p50 {b.get('p50_ms', 0):.3g}ms "
               f"p99 {b.get('p99_ms', 0):.3g}ms")
        if b.get("breaches"):
            row += f", breaches {int(b['breaches'])}"
        if "burn_fast" in b or "burn_slow" in b:
            row += (f", burn fast {b.get('burn_fast', 0.0):.2f}x"
                    f" slow {b.get('burn_slow', 0.0):.2f}x")
        if b.get("alerts"):
            row += (f", ALERTS FIRED {int(b['alerts'])}"
                    f" (see docs/troubleshooting.md burn-rate runbook)")
        rows.append(row)
    return "\n".join(rows)


def health_section(dumps: Dict[str, dict]) -> Optional[str]:
    """End-of-job training-health verdict (obs/health.py +
    obs/divergence.py gauges): anomaly alerts by class, the worst
    grad-norm z-score any rank saw, nonfinite counts, and the
    divergence sentinel's record — checks passed, last check step, and
    any confirmed divergence with its component/leaf.  None when no
    rank armed ``--health``."""
    alerts: Dict[str, float] = {}
    worst_z = None
    nonfinite = 0.0
    checks = 0.0
    last_check = None
    detected: Dict[str, float] = {}
    saw = False
    for label in sorted(dumps, key=_rank_sort_key):
        for m in dumps[label].get("metrics", []):
            name = m.get("name")
            if not name or not name.startswith("health."):
                continue
            saw = True
            tags = m.get("tags") or {}
            if "value" not in m:
                continue  # histograms carry quantiles, not a value
            value = float(m["value"])
            if name == "health.alerts":
                cls = tags.get("class", "?")
                alerts[cls] = alerts.get(cls, 0.0) + value
            elif name == "health.grad_norm_z":
                worst_z = value if worst_z is None else max(worst_z,
                                                            value)
            elif name == "health.nonfinite_total":
                nonfinite += value
            elif name == "health.divergence.checks":
                checks = max(checks, value)
            elif name == "health.divergence.last_check_step":
                last_check = (value if last_check is None
                              else max(last_check, value))
            elif name == "health.divergence.detected":
                where = tags.get("component", "?")
                if tags.get("leaf"):
                    where += f"/{tags['leaf']}"
                detected[where] = detected.get(where, 0.0) + value
    if not saw:
        return None
    rows = []
    fired = {c: int(n) for c, n in sorted(alerts.items()) if n}
    if fired:
        rows.append("alerts: " + ", ".join(
            f"{c} x{n}" for c, n in fired.items()))
    else:
        rows.append("alerts: none")
    if worst_z is not None:
        rows.append(f"worst grad-norm z-score: {worst_z:.2f}")
    if nonfinite:
        rows.append(f"nonfinite gradient elements: {int(nonfinite)}")
    div = f"divergence checks: {int(checks)}"
    if last_check is not None:
        div += f" (last at step {int(last_check)})"
    rows.append(div)
    for where, n in sorted(detected.items()):
        rows.append(
            f"DIVERGENCE DETECTED x{int(n)} in {where} "
            f"(see docs/health.md runbook)"
        )
    return "\n".join(rows)


def autoscale_section(dumps: Dict[str, dict]) -> Optional[str]:
    """End-of-job autoscale / weight hot-swap report: the world/version
    the fleet converged on (every rank must agree — a disagreement here
    is a single-version-guarantee violation worth reading twice), swap
    outcomes per rank, and the launcher's resize decisions/backoffs.
    None when the job neither autoscaled nor armed hot-swap."""
    worlds: Dict[str, int] = {}
    versions: Dict[str, int] = {}
    released_labels = set()
    swap_rows = []
    launcher_bits = []
    for label in sorted(dumps, key=_rank_sort_key):
        vals: Dict[str, float] = {}
        swaps: Dict[str, int] = {}
        for m in dumps[label].get("metrics", []):
            name = m.get("name")
            if name in ("serve.world_size", "serve.weight_version",
                        "serve.released", "serve.log_watermark",
                        "serve.swap_prefetch_failures",
                        "autoscale.world", "autoscale.backoffs"):
                vals[name] = float(m["value"])
            elif name == "serve.swaps":
                outcome = (m.get("tags") or {}).get("outcome", "?")
                swaps[outcome] = swaps.get(outcome, 0) + int(m["value"])
            elif name == "autoscale.decisions":
                d = (m.get("tags") or {}).get("direction", "?")
                launcher_bits.append(f"scale-{d} {int(m['value'])}")
        if vals.get("serve.released"):
            released_labels.add(label)
        if "serve.world_size" in vals:
            worlds[label] = int(vals["serve.world_size"])
        if "serve.weight_version" in vals:
            versions[label] = int(vals["serve.weight_version"])
        if "autoscale.backoffs" in vals and vals["autoscale.backoffs"]:
            launcher_bits.append(
                f"grow-backoffs {int(vals['autoscale.backoffs'])}")
        if swaps or vals.get("serve.swap_prefetch_failures") \
                or vals.get("serve.released"):
            row = f"rank {label}: " + ", ".join(
                [f"swaps {o}={n}" for o, n in sorted(swaps.items())]
                + ([f"prefetch-failures "
                    f"{int(vals['serve.swap_prefetch_failures'])}"]
                   if vals.get("serve.swap_prefetch_failures") else [])
                + (["released"] if vals.get("serve.released") else [])
            )
            swap_rows.append(row)
    if not worlds and not versions and not launcher_bits \
            and not swap_rows:
        return None
    from ..serve.autoscale import world_token  # noqa: PLC0415

    def _newest(per_label: Dict[str, int]) -> Dict[str, int]:
        """One value per rank: the newest incarnation's (labels are
        ``rank`` or ``rank@eN``).  A dead incarnation's stale version
        is evidence elsewhere, not a convergence violation."""
        best: Dict[str, tuple] = {}
        for label, v in per_label.items():
            base, _, etag = label.partition("@e")
            e = int(etag) if etag.isdigit() else 0
            if base not in best or e > best[base][0]:
                best[base] = (e, label, v)
        return {lbl: v for _, lbl, v in best.values()}

    lines = []
    if worlds or versions:
        # A released rank's end-of-life gauges describe the world it
        # was dropped FROM; the surviving ranks' dumps carry the final
        # truth.  Filter by BASE rank (every incarnation of a released
        # rank, not just the one whose dump carries serve.released),
        # and fall back to everything only when the whole fleet was
        # released (shrink-to-zero never happens, but dumps can be
        # partial).
        released_bases = {lbl.partition("@e")[0]
                          for lbl in released_labels}

        def _survivors(per_label: Dict[str, int]) -> Dict[str, int]:
            kept = {lbl: v for lbl, v in per_label.items()
                    if lbl.partition("@e")[0] not in released_bases}
            return kept or per_label

        newest_versions = _newest(_survivors(versions))
        # Worlds get the same newest-incarnation dedup: after a grow
        # then shrink, a survivor's stale earlier-incarnation dump
        # must not keep reporting the pre-shrink peak as "final".
        newest_worlds = _newest(_survivors(worlds))
        world = max(newest_worlds.values()) if newest_worlds else 0
        version = (max(newest_versions.values())
                   if newest_versions else None)
        lines.append("final " + world_token(None, world, version))
        stray_v = {label: v for label, v in newest_versions.items()
                   if version is not None and v != version}
        if stray_v:
            lines.append(
                "WARNING: weight-version disagreement across final "
                "incarnations (violates the single-version "
                f"guarantee): {stray_v}"
            )
    if launcher_bits:
        lines.append("launcher: " + ", ".join(sorted(set(launcher_bits))))
    lines.extend(swap_rows)
    return "\n".join(lines)


def perf_section(dumps: Dict[str, dict]) -> Optional[str]:
    """End-of-job MFU report (obs/profile.py gauges): per-rank model
    FLOP/s utilization, achieved TFLOP/s and step time — estimate-
    marked when the device peak was a guess (CPU dev mode), so a
    placeholder number can never read like a hardware claim.  None when
    no rank armed a profiler."""
    rows = []
    for label in sorted(dumps, key=_rank_sort_key):
        vals = {}
        for m in dumps[label].get("metrics", []):
            name = m.get("name")
            if name in ("perf.mfu", "perf.model_tflops", "perf.step_ms",
                        "perf.mfu_estimate"):
                vals[name] = float(m["value"])
        if "perf.mfu" not in vals:
            continue
        est = bool(vals.get("perf.mfu_estimate"))
        row = (f"rank {label}: mfu {'~' if est else ''}"
               f"{vals['perf.mfu']:.3f}"
               + (" (peak is an estimate — not a hardware claim)"
                  if est else ""))
        if vals.get("perf.model_tflops") is not None:
            row += f", {vals['perf.model_tflops']:.3g} TFLOP/s"
        if vals.get("perf.step_ms") is not None:
            row += f", step {vals['perf.step_ms']:.3g}ms"
        rows.append(row)
    return "\n".join(rows) if rows else None


def _fmt_bytes(b: float) -> str:
    """Human bytes for the memory rows (binary units, one decimal)."""
    b = float(b)
    for unit, div in (("GiB", 2.0 ** 30), ("MiB", 2.0 ** 20),
                      ("KiB", 2.0 ** 10)):
        if b >= div:
            return f"{b / div:.1f}{unit}"
    return f"{int(b)}B"


def mem_section(dumps: Dict[str, dict]) -> Optional[str]:
    """End-of-job device-memory report (obs/memplane.py gauges):
    per-rank HBM in-use/peak/limit (census live-bytes fallback on
    backends that report no stats — CPU dev mode says so instead of
    inventing an HBM), the owner breakdown (params / optimizer_state /
    kv_cache / …), KV-cache occupancy, and the per-program compiled
    breakdowns.  None when no rank armed the memory plane."""
    rows = []
    programs: Dict[str, Dict[str, float]] = {}
    for label in sorted(dumps, key=_rank_sort_key):
        vals: Dict[str, float] = {}
        owners: Dict[str, float] = {}
        for m in dumps[label].get("metrics", []):
            name = m.get("name")
            if name in ("mem.hbm_bytes_in_use", "mem.hbm_peak_bytes",
                        "mem.hbm_limit_bytes", "mem.headroom_bytes",
                        "mem.live_bytes", "serve.kv.allocated_bytes",
                        "serve.kv.live_bytes", "serve.kv.waste_ratio"):
                vals[name] = float(m["value"])
            elif name == "mem.owner_bytes":
                owner = (m.get("tags") or {}).get("owner", "?")
                owners[owner] = float(m["value"])
            elif name and name.startswith("mem.compiled."):
                prog = (m.get("tags") or {}).get("program", "?")
                programs.setdefault(prog, {})[
                    name[len("mem.compiled."):]
                ] = float(m["value"])
        if not vals and not owners:
            continue
        if "mem.hbm_bytes_in_use" in vals:
            row = f"rank {label}: hbm {_fmt_bytes(vals['mem.hbm_bytes_in_use'])}"
            if vals.get("mem.hbm_limit_bytes"):
                row += f"/{_fmt_bytes(vals['mem.hbm_limit_bytes'])}"
            if vals.get("mem.hbm_peak_bytes"):
                row += f" (peak {_fmt_bytes(vals['mem.hbm_peak_bytes'])})"
        else:
            row = (f"rank {label}: live "
                   f"{_fmt_bytes(vals.get('mem.live_bytes', 0))} "
                   f"(no backend memory stats — census only)")
        total = sum(owners.values())
        if total:
            shares = " ".join(
                f"{k}={owners[k] / total:.0%}"
                for k in sorted(owners, key=lambda k: -owners[k])
                if owners[k]
            )
            row += f", owners {shares}"
        if vals.get("serve.kv.allocated_bytes"):
            row += (
                f", kv {_fmt_bytes(vals.get('serve.kv.live_bytes', 0))}"
                f"/{_fmt_bytes(vals['serve.kv.allocated_bytes'])} live "
                f"(waste {vals.get('serve.kv.waste_ratio', 0.0):.0%})"
            )
        rows.append(row)
    if not rows:
        return None
    for prog in sorted(programs):
        b = programs[prog]
        rows.append(
            f"program {prog}: total "
            f"{_fmt_bytes(b.get('total_bytes', 0))} "
            f"(arg {_fmt_bytes(b.get('argument_bytes', 0))}, "
            f"temp {_fmt_bytes(b.get('temp_bytes', 0))}, "
            f"out {_fmt_bytes(b.get('output_bytes', 0))}, "
            f"alias {_fmt_bytes(b.get('alias_bytes', 0))})"
        )
    return "\n".join(rows)


def trend_section(dumps: Dict[str, dict]) -> Optional[str]:
    """Perf-trend verdict (obs/trend.py) over the checkout's committed
    BENCH records.  Unlike the other sections this reads the record
    directory, not the dumps: the trajectory is a property of the repo,
    and a dark streak ("N records without a real measurement") must
    reach the operator at end-of-job even when the job itself produced
    no perf gauges.  None on a fresh checkout (no records) so dev runs
    stay quiet."""
    del dumps  # same call shape as the other sections
    from . import trend as obs_trend  # noqa: PLC0415

    stamp = obs_trend.trend_stamp()
    if stamp is None:
        return None
    lines = [
        f"records {stamp['records']} "
        f"(real {stamp['real']}, degraded {stamp['degraded']}, "
        f"failed {stamp['failed']})",
    ]
    if stamp["verdict"]:
        lines.append(stamp["verdict"])
    return "\n".join(lines)


def _rank_sort_key(label: str):
    """Rank-label ordering shared by the summary table's columns and
    the ckpt section's rows: numeric ranks first (numerically, with
    ``@e<N>`` incarnation tags ignored), everything else after."""
    head = label.split("@", 1)[0]
    return (0, int(head), label) if head.isdigit() else (1, label, "")


def summarize(raw: str) -> Optional[str]:
    """Collect + format in one call; None when nothing was dumped."""
    dumps = collect_dumps(raw)
    if not dumps:
        return None
    return format_summary_table(dumps)
