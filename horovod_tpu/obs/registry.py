"""Tagged metrics registry: the quantitative side of the observability
plane.

The reference ships three debugging pillars — the Chrome-trace timeline
(timeline.cc), the stall inspector (stall_inspector.cc) and
HOROVOD_LOG_LEVEL — but nothing *quantitative* survives a job: cycle
times, fusion efficiency and cache hit rates die with the process.  This
registry is the container for those numbers: Counter / Gauge / Histogram
instruments keyed by (name, tags), cheap enough to update from the
engine's cycle loop, dumped as one JSON document per rank at process
exit when ``HVDTPU_METRICS_DUMP`` is set (the launcher aggregates the
per-rank dumps into the ``--stats-summary`` table, obs/summary.py).

Thread model: instruments are updated from the single-producer engine
thread (plus occasional updates from checkpoint/elastic call sites).
Updates are plain int/float mutations — atomic enough under the GIL and
deliberately lock-free so a 100 Hz cycle loop pays nanoseconds, not a
mutex, per sample.  ``snapshot()`` may observe a value mid-train; that
is fine for monitoring data.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

METRICS_DUMP_ENV = "HVDTPU_METRICS_DUMP"

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "CollectorRetired",
    "get_registry",
    "reset_registry",
    "dump_metrics",
    "resolve_dump_path",
    "METRICS_DUMP_ENV",
]


class CollectorRetired(Exception):
    """Raised by a collector whose owner is gone; the registry prunes it
    (other exceptions are swallowed but the collector is kept)."""


# Geometric bucket bounds shared by every histogram (prometheus-style
# 1/2.5/5 per decade, µs-to-hours span): fixed and global so per-rank
# dumps aggregate without bound negotiation.
_BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    m * (10.0 ** e) for e in range(-3, 8) for m in (1.0, 2.5, 5.0)
)


class _Instrument:
    kind = "instrument"

    def __init__(self, name: str, tags: Dict[str, str]):
        self.name = name
        self.tags = dict(tags)

    def as_dict(self) -> dict:
        raise NotImplementedError


class Counter(_Instrument):
    """Monotonic count (events, bytes, errors)."""

    kind = "counter"

    def __init__(self, name: str, tags: Dict[str, str]):
        super().__init__(name, tags)
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def as_dict(self) -> dict:
        return {"name": self.name, "type": self.kind, "tags": self.tags,
                "value": self.value}


class Gauge(_Instrument):
    """Last-written value (queue depth, current fusion threshold)."""

    kind = "gauge"

    def __init__(self, name: str, tags: Dict[str, str]):
        super().__init__(name, tags)
        self.value: float = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def as_dict(self) -> dict:
        return {"name": self.name, "type": self.kind, "tags": self.tags,
                "value": self.value}


class Histogram(_Instrument):
    """Streaming distribution: exact count/sum/min/max plus fixed
    geometric buckets for approximate quantiles.  O(1) memory per
    instrument regardless of sample count — safe on the cycle loop."""

    kind = "histogram"

    def __init__(self, name: str, tags: Dict[str, str]):
        super().__init__(name, tags)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._buckets = [0] * (len(_BUCKET_BOUNDS) + 1)

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        # manual bisect over the fixed bounds (no per-call allocation)
        lo, hi = 0, len(_BUCKET_BOUNDS)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= _BUCKET_BOUNDS[mid]:
                hi = mid
            else:
                lo = mid + 1
        self._buckets[lo] += 1

    def quantile(self, q: float) -> Optional[float]:
        """Approximate q-quantile: the upper bound of the bucket holding
        the q-th sample (min/max clamp the ends)."""
        if self.count == 0:
            return None
        target = q * self.count
        seen = 0
        for i, c in enumerate(self._buckets):
            seen += c
            if seen >= target:
                if i >= len(_BUCKET_BOUNDS):
                    return self.max
                bound = _BUCKET_BOUNDS[i]
                return min(bound, self.max) if self.max is not None else bound
        return self.max

    def as_dict(self) -> dict:
        mean = (self.sum / self.count) if self.count else None
        return {
            "name": self.name, "type": self.kind, "tags": self.tags,
            "count": self.count, "sum": self.sum,
            "min": self.min, "max": self.max, "mean": mean,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }


def _key(name: str, tags: Dict[str, str]) -> Tuple:
    return (name, tuple(sorted(tags.items())))


class MetricsRegistry:
    """Process-local instrument store.  Instrument creation takes a lock
    (rare); updates on the returned instrument objects are lock-free."""

    def __init__(self):
        # REENTRANT: snapshot()/instrument creation run on the signal
        # death path (the registry dump is an on_death callback, and the
        # live stream's final delta snapshots from inside the fatal-
        # signal flush).  A signal landing while the owning thread is
        # mid-_get would self-deadlock on a plain Lock — the same shape
        # as PR-4's SIGTERM-inside-SIGUSR1 flush deadlock (hvdtpu-lint
        # HVDC103).
        self._lock = threading.RLock()
        self._instruments: Dict[Tuple, _Instrument] = {}
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []

    def _get(self, cls, name: str, tags: Dict[str, str]):
        key = _key(name, tags)
        inst = self._instruments.get(key)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(key)
                if inst is None:
                    inst = cls(name, tags)
                    self._instruments[key] = inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as {inst.kind}, "
                f"requested {cls.kind}"
            )
        return inst

    def counter(self, name: str, **tags: str) -> Counter:
        return self._get(Counter, name, tags)

    def gauge(self, name: str, **tags: str) -> Gauge:
        return self._get(Gauge, name, tags)

    def histogram(self, name: str, **tags: str) -> Histogram:
        return self._get(Histogram, name, tags)

    def remove_matching(self, prefix: str) -> int:
        """Drop every instrument whose name starts with ``prefix``
        (elastic incarnation resets — e.g. straggler attribution must
        start clean after a rendezvous).  Callers holding handles to a
        removed instrument keep a detached object; the next registry
        lookup under the same (name, tags) mints a fresh one."""
        with self._lock:
            doomed = [
                key for key, inst in self._instruments.items()
                if inst.name.startswith(prefix)
            ]
            for key in doomed:
                del self._instruments[key]
        return len(doomed)

    def register_collector(
        self, fn: Callable[["MetricsRegistry"], None]
    ) -> None:
        """Register a pre-snapshot hook that publishes externally-held
        state (e.g. the engine's ``stats`` dict) into instruments.  Runs
        inside :meth:`snapshot`, never on the hot path.  A collector
        whose owner is gone raises :class:`CollectorRetired` and is
        dropped — long-lived processes creating many engines must not
        accumulate dead hooks."""
        with self._lock:
            self._collectors.append(fn)

    def snapshot(self) -> List[dict]:
        retired = []
        for fn in list(self._collectors):
            try:
                fn(self)
            except CollectorRetired:
                retired.append(fn)
            except Exception:
                pass  # a broken collector must not lose the other metrics
        if retired:
            with self._lock:
                self._collectors = [
                    fn for fn in self._collectors if fn not in retired
                ]
        with self._lock:
            instruments = sorted(
                self._instruments.values(),
                key=lambda i: (i.name, tuple(sorted(i.tags.items()))),
            )
        return [i.as_dict() for i in instruments]

    def dump(self, path: str, *, rank) -> dict:
        """Write the dump-schema JSON document to ``path`` atomically.
        Returns the document."""
        doc = {
            "schema": "hvdtpu-metrics-v1",
            "rank": rank,
            "pid": os.getpid(),
            "wall_time": time.time(),
            "metrics": self.snapshot(),
        }
        from . import pathspec  # noqa: PLC0415

        pathspec.write_json_atomic(path, doc)
        return doc


# -- process-global registry + env-driven exit dump -------------------------

_registry: Optional[MetricsRegistry] = None
# Reentrant for the same reason as flightrec's module locks: the death
# flush calls get_registry()/dump_metrics() from signal context, and the
# interrupted thread may be inside this very lock (hvdtpu-lint HVDC103).
_registry_lock = threading.RLock()
_atexit_installed = False


def _resolve_rank() -> str:
    from ..utils.env import artifact_rank  # noqa: PLC0415

    return artifact_rank()


def resolve_dump_path(raw: str, rank: Optional[str] = None) -> str:
    """Map the ``HVDTPU_METRICS_DUMP`` value to this rank's file —
    shared template/dir/plain-path + epoch-tag rules in obs/pathspec.py
    (the aggregator globs with the same module, so they cannot drift)."""
    from . import pathspec  # noqa: PLC0415

    return pathspec.resolve(
        raw, "metrics", _resolve_rank() if rank is None else rank
    )


def dump_metrics(path: Optional[str] = None) -> Optional[str]:
    """Dump the global registry; ``path=None`` resolves from the env.
    Returns the written path, or None when dumping is not configured."""
    raw = path or os.environ.get(METRICS_DUMP_ENV)
    if not raw:
        return None
    resolved = resolve_dump_path(raw) if path is None else path
    get_registry().dump(resolved, rank=_resolve_rank())
    return resolved


def _atexit_dump() -> None:
    try:
        dump_metrics()
    except Exception:
        pass  # never let a metrics dump break interpreter teardown


def get_registry() -> MetricsRegistry:
    """The process-global registry.  First use arms the exit dump (a
    no-op unless ``HVDTPU_METRICS_DUMP`` is set at dump time) — routed
    through the shared death-path flush (obs/flightrec.py), so it fires
    not just at clean exit but on every catchable death: excepthooks
    and fatal signals included.  A signal-killed rank leaves its
    metrics dump alongside its flight-recorder ring."""
    global _registry, _atexit_installed
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = MetricsRegistry()
                if not _atexit_installed:
                    from .flightrec import on_death  # noqa: PLC0415

                    on_death(_atexit_dump)
                    _atexit_installed = True
    return _registry


def reset_registry() -> None:
    """Drop the global registry (tests)."""
    global _registry
    with _registry_lock:
        _registry = None
