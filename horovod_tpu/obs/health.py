"""Training-health plane: in-graph numerics telemetry + anomaly judge.

Ten PRs of observability watch *time and bytes*; this module watches
the *numbers*.  Three pieces:

* **The bundle** (:func:`health_bundle`) — a fused device-side scalar
  summary computed INSIDE the training step from values the step
  already has in registers (loss, grads, updates, params): global grad
  norm, per-bucket grad norms (the same ``build_layout`` buckets the
  overlap plan fuses), max |update|/|param| ratio, and nonfinite
  counts.  It is returned as an extra step output, so it rides the
  step's existing device→host sync — no extra round trip.  With
  ``--health off`` the step closure is *the same object as today's*
  and the compiled HLO is byte-identical (asserted in CI).

* **The judge** (:class:`AnomalyJudge`) — a pure decision table over
  the bundle stream.  Per-series EWMA mean + EWMA absolute deviation
  (a robust MAD-flavored scale, cheap and clock-free); alert classes
  ``loss-spike``, ``grad-explode``, ``grad-vanish``, ``dead-gradient``
  (a bucket's norm pinned at zero for ``dead_steps``), and
  ``nonfinite`` (absolute — no baseline needed to know NaN is bad).
  Alerts are edge-triggered: the counter increments once per episode,
  the gauge holds while the condition persists (the same discipline as
  obs/slo.py's burn-rate alerts).

* **The monitor** (:class:`HealthMonitor`) — host-side glue: feeds the
  judge, publishes ``health.*`` gauges/histograms into the registry
  (→ /metrics, live digest, history rows, ``--stats-summary``, bench
  records), records flightrec events on rising edges, and on the FIRST
  nonfinite runs the off-hot-path provenance bisection
  (:func:`nonfinite_provenance`) that names the first offending leaf.

Everything here is decision logic over small host scalars; the only
jax in the file is inside :func:`health_bundle`, which callers embed
in their own jitted step.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import env as envmod
from ..utils.logging import get_logger

LOG = get_logger("obs.health")

__all__ = [
    "HealthConfig",
    "ALERT_CLASSES",
    "health_bundle",
    "bundle_names",
    "nonfinite_provenance",
    "AnomalyJudge",
    "Alert",
    "HealthMonitor",
]

ALERT_CLASSES = (
    "loss-spike",
    "grad-explode",
    "grad-vanish",
    "dead-gradient",
    "nonfinite",
)


@dataclass(frozen=True)
class HealthConfig:
    """Knobs, resolved once from env (set by run/config_parser.py from
    ``--health`` / ``--health-check-steps`` / ``--divergence-action``)."""

    enabled: bool = False
    check_steps: int = 100
    divergence_action: str = "warn"

    @classmethod
    def from_env(cls) -> "HealthConfig":
        import os  # noqa: PLC0415

        raw = os.environ.get(envmod.HEALTH, "off").strip().lower()
        enabled = raw in ("on", "1", "true", "yes")
        return cls(
            enabled=enabled,
            check_steps=max(1, envmod.env_int(envmod.HEALTH_CHECK_STEPS,
                                              100)),
            divergence_action=os.environ.get(
                envmod.DIVERGENCE_ACTION, "warn").strip().lower() or "warn",
        )


# ---------------------------------------------------------------------------
# the in-graph bundle
# ---------------------------------------------------------------------------


def bundle_names(n_buckets: int) -> List[str]:
    """Stable component order of the bundle vector."""
    return (["loss", "grad_norm", "update_ratio_max", "nonfinite"]
            + [f"bucket{i}_grad_norm" for i in range(n_buckets)])


def health_bundle(loss, grads_flat: Sequence, layout,
                  updates_flat: Optional[Sequence] = None,
                  params_flat: Optional[Sequence] = None):
    """Build the fused health vector INSIDE a jitted step.

    ``grads_flat``/``updates_flat``/``params_flat`` are the step's flat
    leaves (``layout``'s flatten order).  Returns a float32 vector of
    ``4 + n_buckets`` scalars in :func:`bundle_names` order.  All
    reductions fuse into the step's existing HLO; the output is a few
    dozen bytes riding the loss fetch.
    """
    import jax.numpy as jnp  # noqa: PLC0415

    f32 = jnp.float32
    per_bucket = []
    nonfinite = jnp.zeros((), jnp.int32)
    total_sq = jnp.zeros((), f32)
    for b in layout.buckets:
        sq = jnp.zeros((), f32)
        for i in b.leaf_indices:
            g = grads_flat[i].astype(f32)
            sq = sq + jnp.sum(g * g)
            nonfinite = nonfinite + jnp.sum(
                (~jnp.isfinite(g)).astype(jnp.int32))
        per_bucket.append(jnp.sqrt(sq))
        total_sq = total_sq + sq
    ratio = jnp.zeros((), f32)
    if updates_flat is not None and params_flat is not None:
        eps = f32(1e-12)
        for u, p in zip(updates_flat, params_flat):
            u32 = u.astype(f32)
            p32 = p.astype(f32)
            r = jnp.max(jnp.abs(u32)) / (jnp.max(jnp.abs(p32)) + eps)
            ratio = jnp.maximum(ratio, r)
    return jnp.stack(
        [jnp.asarray(loss, f32).reshape(()),
         jnp.sqrt(total_sq),
         ratio,
         nonfinite.astype(f32)]
        + per_bucket
    )


def nonfinite_provenance(grads_flat: Sequence, layout,
                         leaf_names: Optional[Sequence[str]] = None
                         ) -> Optional[Tuple[int, int, str]]:
    """Off-hot-path bisection: name the FIRST leaf carrying a
    nonfinite value.  Host-side, runs only after the bundle has already
    reported ``nonfinite > 0`` — cost does not matter by then.  Returns
    ``(bucket_index, leaf_index, leaf_name)`` or None."""
    for b in layout.buckets:
        for i in b.leaf_indices:
            g = np.asarray(grads_flat[i])
            if not np.isfinite(g).all():
                name = (leaf_names[i]
                        if leaf_names and i < len(leaf_names)
                        else f"leaf{i}")
                return b.index, i, name
    return None


# ---------------------------------------------------------------------------
# the anomaly judge (pure)
# ---------------------------------------------------------------------------


@dataclass
class _Series:
    """EWMA mean + EWMA absolute deviation of one scalar stream."""

    alpha: float
    mean: float = 0.0
    dev: float = 0.0
    n: int = 0

    def z(self, x: float) -> float:
        """Robust z-score of ``x`` against the history BEFORE observing
        it.  The relative floor on the scale means a perfectly flat or
        smoothly ramping series (dev ~ 0) only alerts on a step change
        of >~ ``z_spike * 2%`` of the mean — not on sub-percent drift."""
        if self.n == 0:
            return 0.0
        scale = max(self.dev, 1e-9, 2e-2 * abs(self.mean))
        return (x - self.mean) / scale

    def observe(self, x: float) -> None:
        if self.n == 0:
            self.mean = x
            self.dev = 0.0
        else:
            a = self.alpha
            self.dev = (1 - a) * self.dev + a * abs(x - self.mean)
            self.mean = (1 - a) * self.mean + a * x
        self.n += 1


@dataclass(frozen=True)
class Alert:
    cls: str          # one of ALERT_CLASSES
    rising: bool      # True exactly once per episode
    detail: str = ""


class AnomalyJudge:
    """Pure decision table over bundle observations — no clocks, no
    I/O, fully deterministic, so it is testable as a table of (series
    in, alerts out).

    Rules (evaluated per :meth:`observe` call):

    * ``nonfinite``     — bundle's nonfinite count > 0 or loss not
                          finite.  Absolute: fires even before
                          ``min_samples``.
    * ``loss-spike``    — loss z-score > ``z_spike`` AND loss above its
                          EWMA mean (a downward "spike" is good news).
    * ``grad-explode``  — grad-norm z-score > ``z_spike``, norm above
                          mean.
    * ``grad-vanish``   — grad norm below ``vanish_frac`` of its EWMA
                          mean (scale-relative: an absolute threshold
                          would need per-model tuning).
    * ``dead-gradient`` — any bucket's grad norm exactly 0.0 for
                          ``dead_steps`` consecutive observations (a
                          detached/frozen subtree).

    Relative rules hold off until ``min_samples`` observations so a
    cold EWMA can't fire on warmup transients.
    """

    def __init__(self, *, alpha: float = 0.1, z_spike: float = 6.0,
                 vanish_frac: float = 1e-3, dead_steps: int = 10,
                 min_samples: int = 8):
        self.z_spike = float(z_spike)
        self.vanish_frac = float(vanish_frac)
        self.dead_steps = int(dead_steps)
        self.min_samples = int(min_samples)
        self.loss = _Series(alpha)
        self.grad = _Series(alpha)
        self._zero_streak: Dict[int, int] = {}
        self.firing: Dict[str, bool] = {c: False for c in ALERT_CLASSES}
        self.alerts_total: Dict[str, int] = {c: 0 for c in ALERT_CLASSES}
        self.last_loss_z = 0.0
        self.last_grad_z = 0.0

    def observe(self, *, loss: float, grad_norm: float,
                nonfinite: int = 0,
                bucket_norms: Sequence[float] = ()) -> List[Alert]:
        """Feed one step's bundle; returns the alerts active AFTER this
        observation (``rising=True`` on the first step of an episode)."""
        active: Dict[str, str] = {}

        finite = math.isfinite(loss) and math.isfinite(grad_norm)
        if nonfinite > 0 or not finite:
            active["nonfinite"] = f"count={int(nonfinite)}"

        warm = (self.loss.n >= self.min_samples and finite)
        self.last_loss_z = self.loss.z(loss) if finite else float("inf")
        self.last_grad_z = (self.grad.z(grad_norm) if finite
                            else float("inf"))
        if warm:
            if (self.last_loss_z > self.z_spike
                    and loss > self.loss.mean):
                active["loss-spike"] = f"z={self.last_loss_z:.1f}"
            if (self.last_grad_z > self.z_spike
                    and grad_norm > self.grad.mean):
                active["grad-explode"] = f"z={self.last_grad_z:.1f}"
            if (self.grad.mean > 0
                    and grad_norm < self.vanish_frac * self.grad.mean):
                active["grad-vanish"] = f"norm={grad_norm:.3g}"

        for i, bn in enumerate(bucket_norms):
            streak = self._zero_streak.get(i, 0)
            streak = streak + 1 if bn == 0.0 else 0
            self._zero_streak[i] = streak
            if streak >= self.dead_steps and "dead-gradient" not in active:
                active["dead-gradient"] = f"bucket={i} steps={streak}"

        # Only clean samples train the baseline — a NaN loss would
        # poison the EWMA and mask everything after it.
        if finite:
            self.loss.observe(loss)
            self.grad.observe(grad_norm)

        out: List[Alert] = []
        for cls in ALERT_CLASSES:
            now = cls in active
            rising = now and not self.firing[cls]
            if rising:
                self.alerts_total[cls] += 1
            self.firing[cls] = now
            if now:
                out.append(Alert(cls=cls, rising=rising,
                                 detail=active[cls]))
        return out


# ---------------------------------------------------------------------------
# the monitor (host-side glue)
# ---------------------------------------------------------------------------


class HealthMonitor:
    """Publishes the bundle + judge verdicts to every obs surface."""

    def __init__(self, n_buckets: int = 0, *, rank: int = 0,
                 judge: Optional[AnomalyJudge] = None,
                 leaf_names: Optional[Sequence[str]] = None,
                 registry=None):
        self.n_buckets = int(n_buckets)
        self.rank = int(rank)
        self.judge = judge or AnomalyJudge()
        self.leaf_names = list(leaf_names) if leaf_names else None
        if registry is None:
            from .registry import get_registry  # noqa: PLC0415

            registry = get_registry()
        self._reg = registry
        self.nonfinite_total = 0
        self.first_nonfinite: Optional[dict] = None

    # -- feeding ----------------------------------------------------------

    def observe_bundle(self, step: int, bundle,
                       grads_flat: Optional[Sequence] = None,
                       layout=None) -> List[Alert]:
        """Consume one step's bundle vector (:func:`health_bundle`
        order).  ``grads_flat``/``layout``, when provided, enable the
        first-nonfinite provenance bisection."""
        vec = np.asarray(bundle, dtype=np.float64).ravel()
        loss, grad_norm, ratio, nonfinite = (
            float(vec[0]), float(vec[1]), float(vec[2]), int(vec[3]))
        bucket_norms = [float(x) for x in vec[4:4 + self.n_buckets]]
        return self.observe(step, loss=loss, grad_norm=grad_norm,
                            update_ratio=ratio, nonfinite=nonfinite,
                            bucket_norms=bucket_norms,
                            grads_flat=grads_flat, layout=layout)

    def observe(self, step: int, *, loss: float, grad_norm: float,
                update_ratio: float = 0.0, nonfinite: int = 0,
                bucket_norms: Sequence[float] = (),
                grads_flat: Optional[Sequence] = None,
                layout=None) -> List[Alert]:
        alerts = self.judge.observe(loss=loss, grad_norm=grad_norm,
                                    nonfinite=nonfinite,
                                    bucket_norms=bucket_norms)
        self._publish(step, loss, grad_norm, update_ratio, nonfinite,
                      bucket_norms, alerts)
        if nonfinite > 0 or not math.isfinite(loss):
            self._first_nonfinite(step, nonfinite, grads_flat, layout)
        return alerts

    # -- publishing -------------------------------------------------------

    def _publish(self, step: int, loss: float, grad_norm: float,
                 ratio: float, nonfinite: int,
                 bucket_norms: Sequence[float],
                 alerts: List[Alert]) -> None:
        reg = self._reg
        if math.isfinite(loss):
            reg.gauge("health.loss").set(loss)
        if math.isfinite(grad_norm):
            reg.gauge("health.grad_norm").set(grad_norm)
            reg.histogram("health.grad_norm_hist").observe(grad_norm)
        reg.gauge("health.grad_norm_z").set(
            self.judge.last_grad_z
            if math.isfinite(self.judge.last_grad_z) else -1.0)
        reg.gauge("health.update_ratio_max").set(ratio)
        reg.gauge("health.nonfinite").set(nonfinite)
        if nonfinite > 0:
            self.nonfinite_total += nonfinite
            reg.counter("health.nonfinite_total").inc(int(nonfinite))
        for i, bn in enumerate(bucket_norms):
            reg.gauge("health.bucket_grad_norm", bucket=str(i)).set(
                bn if math.isfinite(bn) else -1.0)

        firing = {a.cls for a in alerts}
        for cls in ALERT_CLASSES:
            reg.gauge("health.alert", **{"class": cls}).set(
                1 if cls in firing else 0)
        for a in alerts:
            if not a.rising:
                continue
            reg.counter("health.alerts", **{"class": a.cls}).inc()
            detail = f"step={step} {a.detail}".strip()
            from . import flightrec  # noqa: PLC0415

            flightrec.record("health.alert", name=a.cls, cycle=step,
                             detail=detail)
            LOG.warning("health alert [%s] at step %d (%s)",
                        a.cls, step, a.detail)

    # -- provenance -------------------------------------------------------

    def _first_nonfinite(self, step: int, count: int,
                         grads_flat: Optional[Sequence],
                         layout) -> None:
        if self.first_nonfinite is not None:
            return
        info = {"step": int(step), "rank": self.rank,
                "count": int(count)}
        if grads_flat is not None and layout is not None:
            found = nonfinite_provenance(grads_flat, layout,
                                         self.leaf_names)
            if found is not None:
                bucket, leaf_index, leaf_name = found
                info.update(bucket=bucket, leaf_index=leaf_index,
                            leaf=leaf_name)
        self.first_nonfinite = info
        detail = " ".join(f"{k}={v}" for k, v in info.items())
        from . import flightrec  # noqa: PLC0415

        flightrec.record("health.nonfinite", name="first", cycle=step,
                         detail=detail)
        LOG.error("first nonfinite at step %d (%s)", step, detail)
