"""Launcher-side live telemetry: merged job view, digest, history,
Prometheus exposition.

The consumer half of the streaming plane (worker half: obs/stream.py).
The launcher's aggregator thread scans its own KV store for per-rank
snapshot deltas under ``obs/live/{epoch}/{rank}/{seq}``, applies them to
a merged job-level view keyed by (rank, elastic incarnation), and every
round:

* prints a one-line console digest (ranks reporting, total collectives,
  phase spread, and — the question this plane exists for — the current
  straggler with evidence);
* appends one JSON line to a crash-safe ``live_history.jsonl`` (append +
  flush per round: a killed launcher leaves every completed round
  parseable);
* serves the merged view as Prometheus text exposition from the
  read-only unauthenticated ``GET /metrics`` branch the aggregator
  registers on the ``KVStoreServer`` — an external scraper can attach to
  an in-flight job with nothing but the port (PUTs stay HMAC-gated; the
  exposition leaks only metric values).

Incarnation semantics: a rank respawned by the elastic launcher
publishes under its new spawn epoch; :meth:`LiveAggregator.merged`
surfaces each rank's *newest* incarnation while older incarnations stay
queryable (label ``epoch`` in the exposition) — a dead incarnation's
last snapshot is evidence, not noise.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from . import stream as obs_stream
from . import straggler as obs_straggler

LOG = get_logger("obs.live")

__all__ = ["LiveAggregator", "LivePlane", "prometheus_escape"]


class _RankView:
    """One (rank, epoch) incarnation's latest state."""

    def __init__(self, rank: int, epoch: int):
        self.rank = rank
        self.epoch = epoch
        self.metrics: Dict[str, dict] = {}
        self.seq = -1
        self.phase: Optional[str] = None
        self.progress = 0
        self.wall_time = 0.0
        self.seen_mono = 0.0


def prometheus_escape(value: str) -> str:
    return (
        str(value).replace("\\", r"\\").replace('"', r'\"')
        .replace("\n", r"\n")
    )


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    if not out or out[0].isdigit():
        out = "_" + out
    return "hvdtpu_" + out


# Exposition HELP text for the series operators actually alert on; the
# rest get an honest generic line.  Keyed by instrument name (pre-
# prom-mangling) so the map reads like the metric docs.
_METRIC_HELP = {
    "serve.ttft_ms": "Time to first token per request, milliseconds",
    "serve.tpot_ms": "Per-decode-step latency per emitted token, "
                     "milliseconds",
    "serve.tokens_per_sec": "Sliding wall-clock window token "
                            "throughput (shared timestamps with the "
                            "trace plane's decode spans)",
    "serve.queue_depth": "Requests admitted to the log but not yet in "
                         "a decode slot",
    "serve.active_slots": "Decode slots currently generating",
    "perf.mfu": "Model FLOP/s utilization: model FLOPs per step over "
                "measured step time over device peak (see "
                "perf.mfu_estimate)",
    "perf.mfu_estimate": "1 when perf.mfu's device peak is an "
                         "estimate (CPU/unknown chip), 0 on known TPUs",
    "perf.model_tflops": "Achieved model TFLOP/s from the compiled "
                         "artifact's cost analysis",
    "perf.step_ms": "Last measured step time, milliseconds",
    "engine.cycle_time_ms": "Background negotiation-loop cycle time, "
                            "milliseconds",
    "engine.negotiation_ms": "Control-plane exchange time per cycle, "
                             "milliseconds",
    "mem.hbm_bytes_in_use": "Backend-reported device bytes in use "
                            "(memory_stats; absent on CPU)",
    "mem.hbm_peak_bytes": "Backend-reported peak device bytes in use",
    "mem.hbm_limit_bytes": "Backend-reported device memory limit",
    "mem.headroom_bytes": "Device memory limit minus bytes in use",
    "mem.live_bytes": "Sum of live jax array bytes on this process "
                      "(host-triggered census, obs/memplane.py)",
    "mem.owner_bytes": "Live array bytes per logical owner (params / "
                       "optimizer_state / grad_buckets / kv_cache / "
                       "other)",
    "serve.kv.allocated_bytes": "KV bytes the fixed-row slot pool "
                                "reserves for the busy slots "
                                "(slots-in-use x max_len rows)",
    "serve.kv.live_bytes": "KV bytes the busy slots actually wrote "
                           "(sum of per-slot positions)",
    "serve.kv.waste_ratio": "1 - live/allocated KV bytes: the tail "
                            "paged attention would reclaim",
    "goodput.fraction": "Share of this rank's wall-clock spent in "
                        "productive steps (obs/goodput.py ledger)",
    "goodput.secs": "Wall-clock seconds per goodput class (init / "
                    "compile / productive_step / collective_wait / "
                    "checkpoint / recovery / idle / degraded)",
    "goodput.lost_secs": "Seconds lost to elastic events, attributed "
                         "by cause (rendezvous / respawn / stall)",
    "serve.goodput.token_fraction": "Decode tokens emitted over slot "
                                    "capacity (tokens / steps x slots)",
    "serve.goodput.tokens_per_slot_sec": "Decode tokens per slot per "
                                         "wall-clock second",
    "serve.slo.p50_ms": "Per-tenant/SLO-class sliding-window latency "
                        "median (metric label: ttft or tpot)",
    "serve.slo.p99_ms": "Per-tenant/SLO-class sliding-window latency "
                        "p99 (metric label: ttft or tpot)",
    "serve.slo.burn": "Error-budget burn rate over the labelled "
                      "window (fast=cliffs, slow=slow burns); 1.0 "
                      "spends the budget exactly at the objective",
    "serve.slo.alert": "1 while the labelled window's burn rate is "
                       "over its alerting threshold",
    "serve.slo.breaches": "Requests over their SLO ceiling, by "
                          "tenant/class/metric",
    "serve.slo.alerts": "Burn-rate alert rising edges, by "
                        "tenant/class/metric",
    "health.loss": "Per-step training loss from the in-graph health "
                   "bundle (obs/health.py)",
    "health.grad_norm": "Global gradient L2 norm per step",
    "health.grad_norm_z": "Robust z-score of the last grad norm "
                          "against its EWMA baseline (-1 = nonfinite)",
    "health.update_ratio_max": "Max per-leaf |update|/|param| ratio "
                               "this step",
    "health.nonfinite": "Nonfinite gradient elements this step",
    "health.nonfinite_total": "Cumulative nonfinite gradient elements",
    "health.bucket_grad_norm": "Gradient L2 norm per overlap bucket "
                               "(label: bucket index)",
    "health.alert": "1 while the labelled anomaly class is firing "
                    "(loss-spike / grad-explode / grad-vanish / "
                    "dead-gradient / nonfinite)",
    "health.alerts": "Anomaly alert rising edges, by class",
    "health.divergence.checks": "Cross-rank digest exchanges completed "
                                "by the divergence sentinel",
    "health.divergence.detected": "Confirmed cross-rank state "
                                  "divergences (labels: component, "
                                  "leaf)",
    "health.divergence.last_check_step": "Step of the sentinel's most "
                                         "recent digest exchange",
    "health.divergence.alert": "1 after a divergence was detected, 0 "
                               "while checks pass",
}


def _prom_help(name: str, kind: str) -> str:
    text = _METRIC_HELP.get(
        name, f"horovod_tpu {kind} {name} (per-rank instrument, "
              f"obs/registry.py)"
    )
    # Exposition escaping for HELP: backslash and newline only.
    return text.replace("\\", r"\\").replace("\n", r"\n")


class LiveAggregator:
    """Merged job-level view of every rank's streamed snapshots.
    Thread-safe: the HTTP handler renders from scraper threads while the
    aggregator thread ingests."""

    def __init__(self):
        # RLock: digest()/history_row() compose merged()+straggler(),
        # and every reader holds the lock for its WHOLE traversal — the
        # /metrics handler thread renders concurrently with ingest, and
        # iterating a view dict mid-apply_delta would raise.
        self._lock = threading.RLock()
        self._views: Dict[Tuple[int, int], _RankView] = {}
        self.rounds = 0
        # Last serving-world size the digest printed: the autoscale
        # token shows transitions ("world 4→6") across rounds.
        self._serve_world_prev: Optional[int] = None
        # Perf-trend token, computed once per process: the committed
        # BENCH trajectory can't change mid-job, and digest() runs on
        # every round — don't re-read the record dir each time.
        # False = not yet computed (None is a valid "no token" result).
        self._trend_token: object = False

    # ------------------------------------------------------------ ingest

    def ingest(self, doc: dict) -> None:
        """Apply one worker payload (obs/stream.py wire contract)."""
        rank, epoch = int(doc["rank"]), int(doc.get("epoch", 0))
        with self._lock:
            view = self._views.get((rank, epoch))
            if view is None:
                view = self._views[(rank, epoch)] = _RankView(rank, epoch)
            if doc.get("full"):
                # A full snapshot is authoritative: a publisher restarted
                # in-process (seq reset) must not leave phantom metrics.
                view.metrics = {}
            obs_stream.apply_delta(view.metrics, doc.get("metrics", []))
            view.seq = max(view.seq, int(doc.get("seq", 0)))
            view.phase = doc.get("phase") or view.phase
            view.progress = int(doc.get("progress", view.progress))
            view.wall_time = float(doc.get("t", view.wall_time))
            view.seen_mono = time.monotonic()

    # ------------------------------------------------------------ views

    def merged(self) -> Dict[int, _RankView]:
        """rank -> newest incarnation's view."""
        with self._lock:
            out: Dict[int, _RankView] = {}
            for (rank, _), view in sorted(self._views.items()):
                cur = out.get(rank)
                if cur is None or view.epoch > cur.epoch:
                    out[rank] = view
            return out

    def incarnations(self) -> List[_RankView]:
        with self._lock:
            return [self._views[k] for k in sorted(self._views)]

    # -------------------------------------------------------- straggler

    def straggler(self) -> Optional[dict]:
        """Current top straggler from the merged incarnation views —
        the SAME verdict ``--stats-summary`` computes over the exit
        dumps (shared implementation: obs/straggler.py merge_blames)."""
        with self._lock:
            verdict = obs_straggler.merge_blames(
                [list(v.metrics.values()) for v in self.merged().values()]
            )
        if verdict is None:
            return None
        out = {
            "rank": verdict["rank"],
            "last_arrivals": verdict["last_arrivals"],
            "share": verdict["share"],
            "worst_skew_ms": verdict["worst_skew_ms"],
            "ops_with_skew": int(verdict["skew"]["count"] or 0),
        }
        if "slice" in verdict:
            out["slice"] = verdict["slice"]
            out["slice_share"] = verdict["slice_share"]
        return out

    # ----------------------------------------------------------- digest

    def digest(self, expected_ranks: Optional[int] = None) -> str:
        with self._lock:
            views = self.merged()
            if not views:
                return "live: no rank has reported yet"
            total = "?" if expected_ranks is None else str(expected_ranks)
            progress = {r: v.progress for r, v in views.items()}
            lo_rank = min(progress, key=lambda r: (progress[r], r))
            phases = sorted({v.phase or "?" for v in views.values()})
            strag = self.straggler()
        parts = [
            f"ranks {len(views)}/{total}",
            f"collectives min {progress[lo_rank]} (rank {lo_rank}) "
            f"max {max(progress.values())}",
            "phase " + "/".join(phases),
        ]
        if strag is not None:
            token = (
                f"straggler rank {strag['rank']} "
                f"({strag['last_arrivals']} last-arrivals, "
                f"{strag['share']:.0%}, worst skew "
                f"{strag['worst_skew_ms']:.0f}ms)"
            )
            if "slice" in strag:
                token += (
                    f" — slice {strag['slice']} is the straggler "
                    f"({strag['slice_share']:.0%} of blame)"
                )
            parts.append(token)
        else:
            parts.append("straggler none")
        tuner = self._tuner_part(views)
        if tuner:
            parts.append(tuner)
        fabric = self._fabric_part(views)
        if fabric:
            parts.append(fabric)
        ckpt = self._ckpt_part(views)
        if ckpt:
            parts.append(ckpt)
        serve = self._serve_part(views)
        if serve:
            parts.append(serve)
        slo = self._slo_part(views)
        if slo:
            parts.append(slo)
        health = self._health_part(views)
        if health:
            parts.append(health)
        goodput = self._goodput_part(views)
        if goodput:
            parts.append(goodput)
        autoscale = self._autoscale_part(views)
        if autoscale:
            parts.append(autoscale)
        frontdoor = self._frontdoor_part()
        if frontdoor:
            parts.append(frontdoor)
        perf = self._perf_part(views)
        if perf:
            parts.append(perf)
        mem = self._mem_part(views)
        if mem:
            parts.append(mem)
        trend = self._trend_part()
        if trend:
            parts.append(trend)
        return "live[" + time.strftime("%H:%M:%S") + "] " + " | ".join(parts)

    def _trend_part(self) -> Optional[str]:
        """One digest token for the perf-trend verdict (obs/trend.py):
        only speaks when the committed BENCH trajectory is dark, so an
        operator babysitting a hardware window learns "the last N
        records were all degraded" before burning the window on another
        one.  Quiet on healthy or empty trajectories."""
        if self._trend_token is False:
            token = None
            try:
                from . import trend as obs_trend  # noqa: PLC0415

                stamp = obs_trend.trend_stamp()
                if stamp is not None and stamp["degraded_streak"]:
                    token = (
                        f"trend {stamp['degraded_streak']} records dark"
                        + (f", last real {stamp['last_real_record']}"
                           if stamp["last_real_record"] else "")
                    )
            except Exception:
                token = None
            self._trend_token = token
        return self._trend_token  # type: ignore[return-value]

    @staticmethod
    def _tuner_part(views) -> Optional[str]:
        """One digest token for the rank-0 autotuner + replay fast path
        (runtime/autotune.py gauges; absent when tuning is off), so an
        operator watching the console sees what the tuner is doing and
        how much negotiation the engine is skipping."""
        from ..runtime.autotune import STATE_NAMES  # noqa: PLC0415

        def metric(view, name):
            for m in view.metrics.values():
                if m.get("name") == name and not m.get("tags"):
                    return m.get("value")
            return None

        for view in views.values():
            state = metric(view, "autotune.state")
            if state is None:
                continue
            bits = [
                "tuner "
                + STATE_NAMES.get(int(state), str(int(state)))
                + f" f={metric(view, 'autotune.fusion_mb') or 0:.0f}MB"
                + f" c={metric(view, 'autotune.cycle_ms') or 0:.1f}ms"
            ]
            reopens = metric(view, "autotune.reopens")
            if reopens:
                bits.append(f"reopens {int(reopens)}")
            skip = metric(view, "engine.negotiation_skip_rate")
            if skip is not None:
                bits.append(f"neg-skip {skip:.0%}")
            return " ".join(bits)
        # no tuner: still surface the replay skip rate when present
        for view in views.values():
            skip = metric(view, "engine.negotiation_skip_rate")
            if skip is not None:
                return f"neg-skip {skip:.0%}"
        return None

    @staticmethod
    def _fabric_part(views) -> Optional[str]:
        """One digest token for the two-fabric data path (multislice
        jobs): bytes over DCN vs ICI and the DCN compression factor —
        absent on single-slice jobs, whose planes never touch these
        counters.  Worst (max) per-rank view: the counters are
        deterministic and near-identical across ranks, and max never
        under-reports a fabric."""
        dcn = ici = 0.0
        ratio = None
        for view in views.values():
            for m in view.metrics.values():
                name = m.get("name")
                if name == "engine.dcn_bytes":
                    dcn = max(dcn, float(m["value"]))
                elif name == "engine.ici_bytes":
                    ici = max(ici, float(m["value"]))
                elif name == "engine.dcn_compression_ratio":
                    v = float(m["value"])
                    ratio = v if ratio is None else max(ratio, v)
        if not dcn and not ici:
            return None
        token = f"fabric dcn {dcn / 1e6:.1f}MB ici {ici / 1e6:.1f}MB"
        if ici:
            token += f" (dcn/ici {dcn / ici:.2f})"
        if ratio and ratio > 1.0:
            token += f" wire x{ratio:.1f}"
        return token

    @staticmethod
    def _ckpt_part(views) -> Optional[str]:
        """One digest token for the checkpoint/replica tier (ckpt/):
        how many recoveries sourced from a live peer vs disk, and the
        replica-push latency — absent while the tier is idle, so quiet
        jobs stay quiet."""
        sources: Dict[str, int] = {}
        pushes = 0
        push_p50 = None
        for view in views.values():
            for m in view.metrics.values():
                name = m.get("name")
                if name == "ckpt.restore_source":
                    src = (m.get("tags") or {}).get("source", "?")
                    sources[src] = sources.get(src, 0) + int(m["value"])
                elif name == "ckpt.replica_pushes":
                    pushes += int(m["value"])
                elif name == "ckpt.replica_push_ms" and m.get("count"):
                    # Worst per-rank p50, not last-iterated: the digest
                    # exists to surface the slow rank, not to hide it
                    # behind dict iteration order.
                    p50 = m.get("p50")
                    if p50 is not None:
                        push_p50 = p50 if push_p50 is None \
                            else max(push_p50, p50)
        if not sources and not pushes:
            return None
        bits = []
        if sources:
            bits.append("restores " + " ".join(
                f"{k}={sources[k]}" for k in ("peer", "disk", "none")
                if k in sources
            ))
        if pushes:
            token = f"pushes {pushes}"
            if push_p50 is not None:
                token += f" (worst p50 {push_p50:.0f}ms)"
            bits.append(token)
        return "ckpt " + " ".join(bits)

    @staticmethod
    def _serve_part(views) -> Optional[str]:
        """One digest token for the serving plane (serve/): queue
        depth, live slots, throughput and first-token latency — the
        autoscaling quartet — absent on jobs that never served.  Worst
        (max) per-rank queue/latency: the digest exists to surface the
        pressure, not to average it away."""
        depth = slots = None
        ttft = None
        pages_free = pages_used = None
        # tokens/sec: groups of a width-sharded fleet are INDEPENDENT
        # capacity — sum the per-group rates (max within a group: its
        # replicated peers report the same stream).  Ranks without a
        # serve.group gauge (legacy replicated fleet) all fold into
        # one bucket, preserving the old max semantics.
        tps_by_group: dict = {}
        for view in views.values():
            group_id = None
            for m in view.metrics.values():
                if m.get("name") == "serve.group":
                    group_id = m.get("value")
                    break
            for m in view.metrics.values():
                name = m.get("name")
                if name == "serve.queue_depth":
                    v = float(m["value"])
                    depth = v if depth is None else max(depth, v)
                elif name == "serve.active_slots":
                    v = float(m["value"])
                    slots = v if slots is None else max(slots, v)
                elif name == "serve.tokens_per_sec":
                    v = float(m["value"])
                    tps_by_group[group_id] = max(
                        tps_by_group.get(group_id, 0.0), v)
                elif name == "serve.ttft_ms" and m.get("count"):
                    p50 = m.get("p50")
                    if p50 is not None:
                        ttft = p50 if ttft is None else max(ttft, p50)
                elif name == "serve.kv.page_free":
                    v = float(m["value"])
                    # Tightest (min-free) rank: page pressure is what
                    # gates admission, so surface the worst of it.
                    pages_free = v if pages_free is None \
                        else min(pages_free, v)
                elif name == "serve.kv.page_used":
                    v = float(m["value"])
                    pages_used = v if pages_used is None \
                        else max(pages_used, v)
        if depth is None and slots is None:
            return None
        tps = sum(tps_by_group.values())
        token = (f"serve q={int(depth or 0)} "
                 f"slots={int(slots or 0)} {tps:.0f} tok/s")
        if ttft is not None:
            token += f" ttft p50 {ttft:.0f}ms"
        if pages_free is not None or pages_used is not None:
            token += (f" pages {int(pages_used or 0)}u/"
                      f"{int(pages_free or 0)}f")
        return token

    @staticmethod
    def _slo_part(views) -> Optional[str]:
        """One digest token for the tenant SLO burn-rate plane
        (obs/slo.py): ``slo OK burn 0.4x`` while the budget holds,
        ``slo ALERT acme/interactive ttft fast 12.3x`` the moment a
        window's burn rate crosses its threshold — the alert an
        operator must see without opening /metrics.  Absent on jobs
        that never digested SLO traffic, so untagged fleets stay
        quiet."""
        firing: List[str] = []
        worst_burn = None
        saw_series = False
        for view in views.values():
            for m in view.metrics.values():
                name = m.get("name")
                if name == "serve.slo.burn":
                    saw_series = True
                    v = float(m["value"])
                    worst_burn = v if worst_burn is None \
                        else max(worst_burn, v)
                elif name == "serve.slo.alert" and float(m["value"]):
                    tags = m.get("tags") or {}
                    firing.append(
                        f"{tags.get('tenant', '?')}/"
                        f"{tags.get('slo', '?')} "
                        f"{tags.get('metric', '?')} "
                        f"{tags.get('window', '?')}"
                    )
        if not saw_series:
            return None
        if firing:
            return "slo ALERT " + ", ".join(sorted(set(firing))) + (
                f" (worst burn {worst_burn:.1f}x)"
                if worst_burn is not None else ""
            )
        return f"slo OK burn {worst_burn or 0.0:.1f}x"

    @staticmethod
    def _health_part(views) -> Optional[str]:
        """One digest token for the training-health plane
        (obs/health.py): ``health OK`` while the numerics are clean,
        ``health ALERT(loss-spike, divergence)`` when an anomaly class
        or the cross-rank sentinel is firing — silent corruption an
        operator must see without opening /metrics.  Absent on jobs
        that never armed ``--health``, so serving fleets stay quiet."""
        firing: List[str] = []
        saw_series = False
        for view in views.values():
            for m in view.metrics.values():
                name = m.get("name")
                if name == "health.alert":
                    saw_series = True
                    if float(m["value"]):
                        cls = (m.get("tags") or {}).get("class", "?")
                        firing.append(cls)
                elif name == "health.divergence.alert":
                    saw_series = True
                    if float(m["value"]):
                        firing.append("divergence")
                elif name in ("health.loss", "health.grad_norm"):
                    saw_series = True
        if not saw_series:
            return None
        if firing:
            return "health ALERT(" + ", ".join(sorted(set(firing))) + ")"
        return "health OK"

    @staticmethod
    def _goodput_part(views) -> Optional[str]:
        """One digest token for the goodput ledger (obs/goodput.py):
        the fleet's worst productive fraction (the fleet is only as
        good as its least-productive rank) plus that rank's dominant
        non-productive class — absent on jobs that never armed the
        ledger."""
        worst = None
        worst_view = None
        for view in views.values():
            for m in view.metrics.values():
                if m.get("name") == "goodput.fraction":
                    v = float(m["value"])
                    if worst is None or v < worst:
                        worst, worst_view = v, view
        if worst is None:
            return None
        token = f"goodput {worst:.0%}"
        if worst_view is not None:
            sinks = {
                (m.get("tags") or {}).get("class", "?"): float(m["value"])
                for m in worst_view.metrics.values()
                if m.get("name") == "goodput.secs"
                and (m.get("tags") or {}).get("class") != "productive_step"
            }
            if sinks and max(sinks.values()) > 0:
                top = max(sinks, key=lambda c: sinks[c])
                token += f" (top sink {top} {sinks[top]:.3g}s)"
        return token

    @staticmethod
    def _frontdoor_part() -> Optional[str]:
        """One digest token for the sharded front door (``frontdoor
        2/2 up``, ``1/2 up 1 takeover`` after a kill): frontend count,
        how many are alive, and the takeover total.  The FrontDoor runs
        in the launcher process — its gauges live in the LAUNCHER-local
        registry, not the rank views every other part merges — so this
        part reads :func:`~..obs.registry.get_registry` directly.
        Absent on training jobs and single-pump serving jobs that never
        published ``serve.frontend.count``."""
        from .registry import get_registry  # noqa: PLC0415

        count = alive = takeovers = None
        for m in get_registry().snapshot():
            name = m.get("name")
            if name == "serve.frontend.count":
                count = int(float(m["value"]))
            elif name == "serve.frontend.alive":
                alive = int(float(m["value"]))
            elif name == "serve.frontend.takeovers":
                takeovers = int(float(m["value"]))
        if count is None:
            return None
        token = f"frontdoor {alive if alive is not None else count}" \
                f"/{count} up"
        if takeovers:
            token += (f" {takeovers} takeover"
                      + ("s" if takeovers != 1 else ""))
        return token

    def _autoscale_part(self, views) -> Optional[str]:
        """One digest token for the autoscale/hot-swap plane (``world
        4→6 v=12``): current serving-world size (arrowed across rounds
        when it changed — a resize mid-flight reads as a transition)
        and the weight version every rank reports.  Absent on jobs that
        never set ``serve.world_size``, so training jobs and pre-swap
        fleets stay quiet.  Formatting is shared with the
        ``--stats-summary`` section (serve/autoscale.py world_token —
        the PR-3 single-source rule)."""
        world = version = None
        world_seen = version_seen = -1.0
        for view in views.values():
            for m in view.metrics.values():
                name = m.get("name")
                # Both gauges are fleet-global values every CURRENT
                # member republishes each round, so the freshest view
                # wins — a released rank's final (stale) snapshot must
                # not keep reporting the pre-shrink world forever.
                if name == "serve.world_size" \
                        and view.seen_mono > world_seen:
                    world, world_seen = int(float(m["value"])), \
                        view.seen_mono
                elif name == "serve.weight_version" \
                        and view.seen_mono > version_seen:
                    version, version_seen = int(float(m["value"])), \
                        view.seen_mono
        if world is None:
            return None
        # Imported here, not at module top: only serving jobs reach
        # this branch, and their launcher already imported the serve
        # package (ingest pump) — a training job's launcher never pays
        # for it.
        from ..serve.autoscale import world_token  # noqa: PLC0415

        token = world_token(self._serve_world_prev, world, version)
        self._serve_world_prev = world
        return token

    @staticmethod
    def _perf_part(views) -> Optional[str]:
        """One digest token for the MFU profiler (obs/profile.py):
        where the FLOPs are going, live — absent on jobs that never
        armed a profiler.  Min across ranks (the fleet is only as fast
        as its slowest chip), tilde-marked when the device peak is an
        estimate (CPU dev mode): an estimated MFU must never read like
        a measured one."""
        mfu = None
        estimate = False
        step_ms = None
        for view in views.values():
            for m in view.metrics.values():
                name = m.get("name")
                if name == "perf.mfu":
                    v = float(m["value"])
                    mfu = v if mfu is None else min(mfu, v)
                elif name == "perf.mfu_estimate" and float(m["value"]):
                    estimate = True
                elif name == "perf.step_ms":
                    v = float(m["value"])
                    step_ms = v if step_ms is None else max(step_ms, v)
        if mfu is None:
            return None
        token = f"mfu {'~' if estimate else ''}{mfu:.2f}"
        if estimate:
            token += " (est)"
        if step_ms is not None:
            token += f" step {step_ms:.0f}ms"
        return token

    @staticmethod
    def _mem_part(views) -> Optional[str]:
        """One digest token for the memory plane (obs/memplane.py):
        ``mem 11.2/16.0G kv 38% waste 62%`` — device bytes in use over
        the limit (worst rank: the fleet OOMs at its fullest chip),
        falling back to the census live-bytes total when the backend
        reports no HBM (CPU dev mode, suffix ``live``), plus KV-cache
        utilization/waste when the serving plane published occupancy.
        Absent on jobs that never armed the census."""
        in_use = limit = live = None
        kv_alloc = kv_live = waste = None
        for view in views.values():
            for m in view.metrics.values():
                name = m.get("name")
                if name == "mem.hbm_bytes_in_use":
                    v = float(m["value"])
                    in_use = v if in_use is None else max(in_use, v)
                elif name == "mem.hbm_limit_bytes":
                    v = float(m["value"])
                    limit = v if limit is None else max(limit, v)
                elif name == "mem.live_bytes":
                    v = float(m["value"])
                    live = v if live is None else max(live, v)
                elif name == "serve.kv.allocated_bytes":
                    v = float(m["value"])
                    kv_alloc = v if kv_alloc is None else max(kv_alloc, v)
                elif name == "serve.kv.live_bytes":
                    v = float(m["value"])
                    kv_live = v if kv_live is None else max(kv_live, v)
                elif name == "serve.kv.waste_ratio":
                    v = float(m["value"])
                    waste = v if waste is None else max(waste, v)
        if in_use is None and live is None and waste is None:
            return None
        gib = 2.0 ** 30
        bits = []
        if in_use is not None and limit:
            bits.append(f"mem {in_use / gib:.1f}/{limit / gib:.1f}G")
        elif in_use is not None:
            bits.append(f"mem {in_use / gib:.1f}G")
        elif live is not None:
            bits.append(f"mem {live / gib:.2f}G live")
        if kv_alloc:
            util = (kv_live or 0.0) / kv_alloc
            bits.append(f"kv {util:.0%} waste {waste or 0.0:.0%}")
        elif waste is not None:
            bits.append(f"kv waste {waste:.0%}")
        return " ".join(bits) if bits else None

    # ---------------------------------------------------------- history

    def history_row(self, expected_ranks: Optional[int] = None) -> dict:
        with self._lock:
            views = self.merged()
            row = {
                "t": time.time(),
                "round": self.rounds,
                "ranks_reporting": len(views),
                "ranks_expected": expected_ranks,
                "progress": {str(r): v.progress for r, v in views.items()},
                "phases": {str(r): v.phase for r, v in views.items()},
                "epochs": {str(r): v.epoch for r, v in views.items()},
                "straggler": self.straggler(),
            }
            # SLO burn-rate plane (obs/slo.py): windows currently over
            # threshold + cumulative rising edges, so the history file
            # answers "when did the alert fire" after the job is gone.
            firing = 0
            alerts = 0.0
            saw_slo = False
            for view in views.values():
                for m in view.metrics.values():
                    name = m.get("name")
                    if name == "serve.slo.alert":
                        saw_slo = True
                        firing += 1 if float(m["value"]) else 0
                    elif name == "serve.slo.alerts":
                        saw_slo = True
                        alerts += float(m["value"])
            if saw_slo:
                row["slo"] = {"firing": firing, "alerts": int(alerts)}
            # Training-health plane (obs/health.py): anomaly classes
            # currently firing + cumulative rising edges + divergence
            # checks, so the history file answers "when did the loss
            # spike / which step diverged" after the job is gone.
            h_firing = 0
            h_alerts = 0.0
            div_detected = 0.0
            saw_health = False
            for view in views.values():
                for m in view.metrics.values():
                    name = m.get("name")
                    if name in ("health.alert", "health.divergence.alert"):
                        saw_health = True
                        h_firing += 1 if float(m["value"]) else 0
                    elif name == "health.alerts":
                        saw_health = True
                        h_alerts += float(m["value"])
                    elif name == "health.divergence.detected":
                        saw_health = True
                        div_detected += float(m["value"])
            if saw_health:
                row["health"] = {"firing": h_firing,
                                 "alerts": int(h_alerts),
                                 "divergences": int(div_detected)}
            return row

    # ------------------------------------------------------- prometheus

    def prometheus(self) -> str:
        """Text exposition (format 0.0.4) of every incarnation's view,
        labelled ``rank``/``epoch`` plus the instrument's own tags.
        Histograms render as summaries (quantile label + _sum/_count).
        An instrument tag that collides with a reserved exposition
        label (``rank``, ``epoch``, ``quantile`` — e.g. the blamed-rank
        tag on ``engine.straggler.last_arrivals``) is emitted as
        ``tag_<name>``: duplicate label names are a hard parse error
        for real scrapers."""
        with self._lock:
            incarnations = self.incarnations()
            by_name: Dict[str, List[Tuple[dict, _RankView]]] = {}
            for view in incarnations:
                for m in view.metrics.values():
                    by_name.setdefault(m["name"], []).append((m, view))
            merged = self.merged()
            strag = self.straggler()
        lines: List[str] = []
        _RESERVED = ("rank", "epoch", "quantile")

        def labels(view: _RankView, tags: dict, extra: str = "") -> str:
            items = [f'rank="{view.rank}"', f'epoch="{view.epoch}"']
            for k, v in sorted(tags.items()):
                key = _prom_name(k)[len("hvdtpu_"):]
                if key in _RESERVED:
                    key = "tag_" + key
                items.append(f'{key}="{prometheus_escape(v)}"')
            if extra:
                items.append(extra)
            return "{" + ",".join(items) + "}"

        def num(v) -> str:
            if v is None:
                return "NaN"
            return repr(float(v))

        for name in sorted(by_name):
            entries = by_name[name]
            kind = entries[0][0]["type"]
            prom = _prom_name(name)
            # HELP before TYPE before samples, once per family: real
            # scrapers warn on bare samples, and a second HELP/TYPE for
            # the same name is a hard parse error.
            lines.append(f"# HELP {prom} " + _prom_help(name, kind))
            lines.append(
                f"# TYPE {prom} "
                + {"counter": "counter", "gauge": "gauge",
                   "histogram": "summary"}[kind]
            )
            for m, view in entries:
                tags = m.get("tags") or {}
                if kind == "histogram":
                    for q, field in (("0.5", "p50"), ("0.9", "p90"),
                                     ("0.99", "p99")):
                        lines.append(
                            prom + labels(view, tags, f'quantile="{q}"')
                            + " " + num(m.get(field))
                        )
                    lines.append(
                        f"{prom}_sum" + labels(view, tags)
                        + " " + num(m.get("sum", 0.0))
                    )
                    lines.append(
                        f"{prom}_count" + labels(view, tags)
                        + " " + str(int(m.get("count") or 0))
                    )
                else:
                    lines.append(
                        prom + labels(view, tags) + " " + num(m["value"])
                    )
        # Aggregator-level meta series: scrapers get liveness and the
        # straggler verdict without re-deriving them from raw counters.
        lines.append("# HELP hvdtpu_live_ranks_reporting Ranks whose "
                     "live stream has reported at least once")
        lines.append("# TYPE hvdtpu_live_ranks_reporting gauge")
        lines.append(f"hvdtpu_live_ranks_reporting {len(merged)}")
        lines.append("# HELP hvdtpu_live_straggler_rank Rank the "
                     "shared straggler attribution currently blames "
                     "(-1 = none)")
        lines.append("# TYPE hvdtpu_live_straggler_rank gauge")
        lines.append(
            "hvdtpu_live_straggler_rank "
            + (str(strag["rank"]) if strag else "-1")
        )
        now = time.monotonic()
        lines.append("# HELP hvdtpu_live_update_age_seconds Seconds "
                     "since each rank's newest incarnation last "
                     "streamed a snapshot")
        lines.append("# TYPE hvdtpu_live_update_age_seconds gauge")
        for rank, view in merged.items():
            lines.append(
                f'hvdtpu_live_update_age_seconds{{rank="{rank}"}} '
                + repr(round(now - view.seen_mono, 3))
            )
        return "\n".join(lines) + "\n"


class LivePlane:
    """The launcher's live-telemetry driver: owns the aggregator thread,
    consumes snapshot keys from the KV server, appends history, prints
    the digest, and serves ``/metrics``.

    ``server`` must be the in-process :class:`KVStoreServer` (the
    aggregator reads and prunes its store directly — zero HTTP overhead
    and listing for free, which the HTTP surface deliberately lacks)."""

    def __init__(
        self,
        server,
        *,
        interval: float,
        history_path: Optional[str] = None,
        expected_ranks: Optional[int] = None,
        print_digest: bool = True,
        announce_host: Optional[str] = None,
    ):
        self.server = server
        self.interval = max(float(interval), 0.05)
        self.history_path = history_path
        self.expected_ranks = expected_ranks
        self.print_digest = print_digest
        # The host scrapers should dial — the launcher's ROUTABLE
        # address for multi-host jobs (the announced line is the only
        # discoverable endpoint; 127.0.0.1 would be a lie off-box).
        self.announce_host = announce_host or "127.0.0.1"
        self.agg = LiveAggregator()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Launcher-local series appended to the exposition (e.g. the
        # autoscale controller's gauges — worker snapshots never carry
        # them).  Each callable returns complete exposition lines.
        self._extra_renders: List = []

    def add_render(self, fn) -> None:
        """Append a launcher-side exposition source to ``/metrics``."""
        self._extra_renders.append(fn)

    def _render(self) -> str:
        body = self.agg.prometheus()
        for fn in self._extra_renders:
            try:
                body += fn()
            except Exception as exc:  # pragma: no cover - defensive
                LOG.warning("extra /metrics render failed: %s", exc)
        return body

    def start(self) -> None:
        self.server.set_metrics_render(self._render)
        self._thread = threading.Thread(
            target=self._loop, name="hvdtpu_live_agg", daemon=True
        )
        self._thread.start()
        print(
            f"[live] scrape endpoint "
            f"http://{self.announce_host}:{self.server.port}/metrics "
            f"(every {self.interval:g}s"
            + (f", history -> {self.history_path}" if self.history_path
               else "") + ")",
            flush=True,
        )

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.round()
            except Exception as exc:  # pragma: no cover - defensive
                LOG.warning("live aggregation round failed: %s", exc)

    def round(self) -> int:
        """One aggregation round: consume every pending snapshot key (in
        (epoch, rank, seq) order), append history, print the digest.
        Returns the number of documents ingested."""
        pending = self.server.scan(obs_stream.LIVE_SCOPE + "/")
        docs: List[Tuple[Tuple[int, int, int], str, dict]] = []
        for key, raw in pending.items():
            tail = key[len(obs_stream.LIVE_SCOPE) + 1:].split("/")
            try:
                epoch, rank, seq = (int(t) for t in tail)
                doc = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                self.server.discard([key])  # junk key: drop, don't wedge
                continue
            docs.append(((epoch, rank, seq), key, doc))
        docs.sort(key=lambda item: item[0])
        for _, key, doc in docs:
            try:
                self.agg.ingest(doc)
            except Exception as exc:
                # JSON-valid but schema-invalid (a version-skewed
                # worker): log and fall through to the discard — a
                # poison doc must cost one warning, never wedge every
                # subsequent round on the same key.
                LOG.warning("unparseable live snapshot %s: %s", key, exc)
            self.server.discard([key])
        self.agg.rounds += 1
        if self.agg.merged():
            self._append_history()
            if self.print_digest:
                print("[live] " + self.agg.digest(self.expected_ranks),
                      flush=True)
        return len(docs)

    def _append_history(self) -> None:
        if not self.history_path:
            return
        row = self.agg.history_row(self.expected_ranks)
        try:
            d = os.path.dirname(self.history_path)
            if d:
                os.makedirs(d, exist_ok=True)
            # Append + flush per round: every completed round survives a
            # launcher kill; a torn final line is the reader's problem
            # (one json.loads failure), never the writer's.
            with open(self.history_path, "a") as f:
                f.write(json.dumps(row, separators=(",", ":")) + "\n")
                f.flush()
        except OSError as exc:  # pragma: no cover - disk full etc.
            LOG.warning("live history append failed: %s", exc)

    def stop(self) -> None:
        """Final round (drain what workers flushed at exit), then stop."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, self.interval * 2))
            self._thread = None
        try:
            self.round()
        except Exception:  # pragma: no cover - defensive
            pass
        self.server.set_metrics_render(None)
