"""Straggler attribution: who kept everyone waiting, with evidence.

The reference's answer to "which rank is the straggler?" is eyeballing
timeline lanes by hand (or the stall inspector's 60-second warnings,
which only fire for outright stalls).  This module turns the per-op
arrival data both collective paths already see into accumulated metrics:

* ``engine.straggler.last_arrivals{rank=K}`` — counter: how many
  collectives rank K was the *last* to arrive at (only ops whose
  arrivals spanned more than one negotiation cycle / a real wait count —
  same-cycle completion blames nobody).
* ``engine.straggler.skew_ms`` — histogram of first-to-last arrival skew.
* ``engine.straggler.worst_skew_ms`` / ``engine.straggler.last_rank`` —
  gauges for the live digest.
* ``engine.straggler.alerts`` — counter, one per skew past the
  ``--alert-skew-ms`` threshold (which also logs a warning naming the
  rank, the skew, and the tensor).

Producers: the eager controller (runtime/controller.py — deterministic,
so every rank accumulates the identical attribution) and the elastic
context's KV collectives (per-peer wait times; each rank blames the peer
it actually waited on).  Attribution is reset at elastic rendezvous so a
re-formed world — survivors included — starts its incarnation with clean
counts.

Consumers: the live aggregator's digest and ``/metrics`` exposition
(obs/live.py), and the ``--stats-summary`` straggler section
(obs/summary.py).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..utils.logging import get_logger
from .registry import get_registry

LOG = get_logger("obs.straggler")

PREFIX = "engine.straggler."

# Elastic KV waits shorter than this are polling noise, not stragglers
# (_POLL_SECS is 0.05; one or two sleeps happen even in a healthy step).
MIN_WAIT_SECS = 0.15

__all__ = [
    "PREFIX",
    "MIN_WAIT_SECS",
    "record",
    "record_waits",
    "merge_blames",
    "reset",
]


def _slice_tag(rank: int) -> dict:
    """``{"slice": str(k)}`` on multi-slice topologies, ``{}`` otherwise.
    Single-slice jobs keep the untagged counter identity so their dumps
    (and every existing consumer) are byte-compatible."""
    try:
        from .. import basics  # noqa: PLC0415

        if basics.is_initialized() and basics.num_slices() > 1:
            return {"slice": str(basics.slice_of_rank(rank))}
    except Exception:
        pass
    return {}


def record(
    rank: int,
    skew_ms: float,
    *,
    tensor: Optional[str] = None,
    timeline=None,
    alert_ms: float = 0.0,
) -> None:
    """Blame ``rank`` for one collective's arrival skew of ``skew_ms``.
    On multi-slice topologies the last-arrivals counter also carries the
    blamed rank's slice, so the merger can name the straggling SLICE —
    the actionable unit when a whole pod's DCN link is the problem."""
    reg = get_registry()
    reg.counter(
        PREFIX + "last_arrivals", rank=str(rank), **_slice_tag(rank)
    ).inc()
    reg.histogram(PREFIX + "skew_ms").observe(skew_ms)
    worst = reg.gauge(PREFIX + "worst_skew_ms")
    if skew_ms > worst.value:
        worst.set(skew_ms)
    reg.gauge(PREFIX + "last_rank").set(rank)
    if timeline is not None:
        timeline.counter(
            "straggler_skew_ms", {"skew_ms": round(skew_ms, 3)}
        )
    if alert_ms and skew_ms > alert_ms:
        reg.counter(PREFIX + "alerts").inc()
        LOG.warning(
            "straggler: rank %d arrived %.0f ms after the first rank%s "
            "(> alert threshold %.0f ms)",
            rank, skew_ms,
            f" for tensor {tensor!r}" if tensor else "",
            alert_ms,
        )


def record_waits(
    waits: Dict[int, float],
    self_rank: int,
    *,
    tensor: Optional[str] = None,
    alert_ms: float = 0.0,
    floor_secs: float = MIN_WAIT_SECS,
) -> Optional[int]:
    """Elastic-path attribution: ``waits`` maps peer rank -> seconds this
    rank spent blocked polling for that peer's contribution.  Blames the
    peer waited on longest when that wait is past the noise floor;
    returns the blamed rank (or None).  A delayed rank waits on nobody,
    so it never blames an innocent peer for its own lateness."""
    candidates = {r: w for r, w in waits.items() if r != self_rank}
    if not candidates:
        return None
    worst_rank = max(candidates, key=lambda r: (candidates[r], -r))
    worst_wait = candidates[worst_rank]
    if worst_wait < floor_secs:
        return None
    record(worst_rank, worst_wait * 1e3, tensor=tensor, alert_ms=alert_ms)
    return worst_rank


def merge_blames(metric_lists) -> Optional[dict]:
    """Merge ``engine.straggler.*`` instruments from several reporters
    (per-rank dumps, or live views) into one verdict — the SINGLE
    implementation behind both the live digest/exposition and the
    ``--stats-summary`` straggler section, so they can never name
    different stragglers for the same data.

    ``metric_lists``: iterable of per-reporter metric-dict iterables
    (dump-schema form).  Counts merge max-per-reporter: eager
    attribution is deterministic and identical on every rank (max ==
    the value), elastic attribution is each rank's personally-suffered
    waits (max keeps the strongest single witness instead of
    double-counting agreement).  Returns None when nobody was blamed,
    else ``{rank, last_arrivals, share, blames, skew, worst_skew_ms,
    alerts}`` with ``blames`` the full per-rank merged counts and
    ``skew`` the largest reporter's histogram fields.  When the counters
    carry slice tags (multi-slice jobs), the verdict also includes
    ``slice`` (the slice whose ranks drew the most blame) and
    ``slice_blames`` — the slice-level verdict the live digest and the
    summary print as "slice K is the straggler"."""
    blames: Dict[int, int] = {}
    rank_slice: Dict[int, int] = {}
    worst_skew = 0.0
    skew = {"count": 0, "p50": None, "p99": None, "max": None}
    alerts = 0
    for metrics in metric_lists:
        for m in metrics:
            name = m.get("name", "")
            if name == PREFIX + "last_arrivals":
                tags = m.get("tags") or {}
                try:
                    blamed = int(tags["rank"])
                except (KeyError, TypeError, ValueError):
                    continue
                blames[blamed] = max(blames.get(blamed, 0),
                                     int(m["value"]))
                if "slice" in tags:
                    try:
                        rank_slice[blamed] = int(tags["slice"])
                    except (TypeError, ValueError):
                        pass
            elif name == PREFIX + "worst_skew_ms":
                worst_skew = max(worst_skew, float(m["value"]))
            elif name == PREFIX + "skew_ms":
                if int(m.get("count") or 0) > skew["count"]:
                    skew = {k: m.get(k)
                            for k in ("count", "p50", "p99", "max")}
            elif name == PREFIX + "alerts":
                alerts = max(alerts, int(m["value"]))
    if not blames:
        return None
    top = max(blames, key=lambda r: (blames[r], -r))
    total = sum(blames.values())
    verdict = {
        "rank": top,
        "last_arrivals": blames[top],
        "share": blames[top] / total if total else 0.0,
        "blames": blames,
        "skew": skew,
        "worst_skew_ms": round(worst_skew, 3),
        "alerts": alerts,
    }
    if rank_slice:
        slice_blames: Dict[int, int] = {}
        for r, count in blames.items():
            s = rank_slice.get(r)
            if s is not None:
                slice_blames[s] = slice_blames.get(s, 0) + count
        if slice_blames:
            top_slice = max(
                slice_blames, key=lambda s: (slice_blames[s], -s)
            )
            verdict["slice"] = top_slice
            verdict["slice_blames"] = slice_blames
            verdict["slice_share"] = (
                slice_blames[top_slice] / total if total else 0.0
            )
    return verdict


def reset() -> None:
    """Drop every straggler instrument — called at elastic rendezvous so
    a re-formed world's attribution starts clean (a respawned rank is a
    fresh process anyway; this covers the surviving ranks)."""
    get_registry().remove_matching(PREFIX)
