"""Merge per-rank span dumps into a per-request waterfall + latency
decomposition report.

The launcher-side consumer of obs/trace.py.  Two outputs from one set
of ``spans.*rank*.json`` files (all ranks, all epochs, the launcher's
own ``spans.rank.launcher.json`` included):

* **Chrome-trace waterfall** — one ``pid`` lane per trace id (i.e. per
  request, plus the ``serve.steps`` / ``engine`` step lanes), one
  ``tid`` per (rank, epoch) incarnation inside the lane, reusing
  timeline_merge's epoch-lane-stride convention.  A replayed request's
  lane therefore shows its epoch-0 spans and its epoch-1 replay spans
  side by side — the recovery gap is the visible hole between them.
* **Latency-decomposition report** — per request: ttft broken into the
  named components that tile the [arrival, first-token] interval
  (``queue_wait + schedule_broadcast + admit_wait + prefill``; on the
  greedy slot engine the first token IS the prefill's argmax, so
  first-decode is folded into prefill), the recorded ttft they must sum
  to, epochs and ranks seen; plus fleet-level p50/p99 per component and
  the tpot decomposition (decode-compute / scheduler residual /
  stream-publish) from the per-step spans.

Missing ranks are reported, not fatal: a rank that died by SIGKILL (or
had its flush chaos-suppressed via ``trace_flush:action=trace_drop``)
leaves no file, and the merge proceeds on what exists — the absence is
itself named in the report (``missing_ranks``), mirroring the
post-mortem analyzer's "no black box" verdict.

Used by the launcher at job end (run/runner.py, ``--trace``) and
directly::

    python -m horovod_tpu.obs.trace_merge OUT_PREFIX SPAN_FILE [...]
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Sequence

from . import pathspec

REPORT_SCHEMA = "hvdtpu-trace-report-v1"

# (rank, epoch) -> tid inside a request's lane; same stride convention
# as timeline_merge's per-incarnation pid lanes.
_EPOCH_LANE_STRIDE = 100000

# ttft components, in waterfall order.  The report sums whatever subset
# a request actually recorded — a replayed request's second incarnation
# has replay_prefill instead of the full chain.
TTFT_COMPONENTS = ("queue_wait", "schedule_broadcast", "admit_wait",
                   "prefill")
TPOT_COMPONENTS = ("decode_compute", "scheduler", "stream_publish")

# Step-lane trace ids: aggregate timing lanes, not requests.
_STEP_TRACES = ("serve.steps", "engine", "overlap")

__all__ = ["load_docs", "merge", "report", "merge_glob", "main",
           "TTFT_COMPONENTS", "TPOT_COMPONENTS", "REPORT_SCHEMA"]


def load_docs(paths: Sequence[str]) -> List[dict]:
    """Load every span dump that parses; a torn file (rank killed
    mid-write never happens — the write is atomic — but a disk-full
    truncation can) costs that rank, never the merge."""
    docs = []
    for path in sorted(paths):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) or "spans" not in doc:
            continue
        doc["_path"] = path
        docs.append(doc)
    return docs


def _rank_key(doc: dict) -> str:
    """A dump's rank tag comes from the document itself (the launcher's
    dump says ``launcher``; filename parsing would read no rank there)."""
    return str(doc.get("rank", "?"))


def _rank_sort_key(r: str) -> tuple:
    """Numeric ranks first in numeric order, then labels
    (``launcher``) lexicographically — the one ordering every
    rank-label sort in this module uses (mirrors obs/summary.py)."""
    return (not r.isdigit(), int(r) if r.isdigit() else 0, r)


def _lane_ids(docs: List[dict]) -> Dict[str, int]:
    """Stable small pid per trace id: step lanes first (they are the
    context every request lane is read against), then requests sorted
    by their earliest span — the waterfall reads top-to-bottom in
    arrival order."""
    first_t: Dict[str, float] = {}
    for doc in docs:
        for s in doc.get("spans", []):
            tr = s.get("trace")
            if not tr:
                continue
            t0 = float(s.get("t0", 0.0))
            if tr not in first_t or t0 < first_t[tr]:
                first_t[tr] = t0
    steps = [t for t in _STEP_TRACES if t in first_t]
    requests = sorted(
        (t for t in first_t if t not in _STEP_TRACES),
        key=lambda t: (first_t[t], t),
    )
    return {t: i + 1 for i, t in enumerate(steps + requests)}


def merge(paths: Sequence[str], out_path: str) -> int:
    """Merge span dumps into one valid Chrome trace at ``out_path``;
    returns the number of events written.  ``ts`` is wall-clock
    microseconds rebased to the job's earliest span so Perfetto opens
    near t=0."""
    docs = load_docs(paths)
    lanes = _lane_ids(docs)
    base = None
    for doc in docs:
        for s in doc.get("spans", []):
            t0 = float(s.get("t0", 0.0))
            if base is None or t0 < base:
                base = t0
    base = base or 0.0

    events: List[dict] = []
    tids = set()
    for doc in docs:
        rank = _rank_key(doc)
        try:
            rank_n = int(rank)
        except ValueError:
            rank_n = -1  # the launcher's lane
        for s in doc.get("spans", []):
            tr = s.get("trace")
            if tr not in lanes:
                continue
            epoch = int(s.get("epoch", 0))
            tid = rank_n + 1 + epoch * _EPOCH_LANE_STRIDE
            ev = {
                "ph": "X",
                "name": s.get("name", "?"),
                "pid": lanes[tr],
                "tid": tid,
                "ts": round((float(s.get("t0", 0.0)) - base) * 1e6, 1),
                "dur": round(float(s.get("dur", 0.0)) * 1e6, 1),
                "args": dict(s.get("args") or {}, epoch=epoch,
                             rank=rank),
            }
            events.append(ev)
            tids.add((lanes[tr], tid, rank, epoch))
    meta = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": trace}}
        for trace, pid in sorted(lanes.items(), key=lambda kv: kv[1])
    ] + [
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
         "args": {"name": f"rank {rank}"
                  + (f" (epoch {epoch})" if epoch else "")}}
        for pid, tid, rank, epoch in sorted(tids)
    ]
    pathspec.write_json_atomic(out_path, meta + events, indent=None)
    return len(events)


def _pcts(values: List[float]) -> Optional[dict]:
    if not values:
        return None
    xs = sorted(values)

    def pick(q: float) -> float:
        return xs[min(int(q * len(xs)), len(xs) - 1)]

    return {"n": len(xs), "p50": round(pick(0.50), 3),
            "p99": round(pick(0.99), 3), "max": round(xs[-1], 3)}


def report(paths: Sequence[str],
           expected_ranks: Optional[int] = None) -> dict:
    """The latency-decomposition report over a set of span dumps.

    Per-request component sums use the LEADER's spans (the lowest
    numeric rank that recorded the request's prefill — the leader's
    clock is also the one the ttft histogram was measured on), so the
    sum-vs-ttft check compares timestamps from a single clock."""
    docs = load_docs(paths)
    ranks_present = sorted({_rank_key(d) for d in docs},
                           key=_rank_sort_key)
    missing = []
    if expected_ranks is not None:
        have = {r for r in ranks_present if r.isdigit()}
        missing = [r for r in range(expected_ranks) if str(r) not in have]

    # trace id -> rank -> name -> [span...]
    per_req: Dict[str, Dict[str, Dict[str, List[dict]]]] = {}
    step_spans: Dict[str, List[dict]] = {}
    for doc in docs:
        rank = _rank_key(doc)
        for s in doc.get("spans", []):
            tr = s.get("trace")
            if not tr:
                continue
            if tr in _STEP_TRACES:
                # Keep the source rank with the span: the scheduler
                # residual must subtract each rank's named phases from
                # ITS OWN whole-step span, not pool all ranks into one
                # (epoch, step) bucket N-fold.
                step_spans.setdefault(s.get("name", "?"), []) \
                    .append({**s, "_rank": rank})
                continue
            per_req.setdefault(tr, {}).setdefault(rank, {}) \
                .setdefault(s.get("name", "?"), []).append(s)

    requests: Dict[str, dict] = {}
    comp_samples: Dict[str, List[float]] = {}
    ttft_samples: List[float] = []
    for rid in sorted(per_req):
        by_rank = per_req[rid]
        # leader = lowest numeric rank that prefix-recorded the request
        leader = None
        for rank in sorted(by_rank, key=_rank_sort_key):
            names = by_rank[rank]
            if "prefill" in names or "replay_prefill" in names:
                leader = rank
                break
        if leader is None:
            leader = min(by_rank, key=_rank_sort_key)
        names = by_rank[leader]
        # The ttft-bearing incarnation: the NEWEST epoch whose prefill
        # recorded a ttft sample.  Under elastic replay one rank's
        # merged doc can hold several admission chains for a rid (a
        # request re-admitted as fresh after a world break records a
        # full second chain); mixing epochs would double-count the
        # earlier incarnation's components against the final ttft.
        ttft = None
        ttft_epoch = None
        for s in names.get("prefill", ()):
            v = (s.get("args") or {}).get("ttft_ms")
            ep = int(s.get("epoch", 0))
            if v is not None and (ttft_epoch is None or ep >= ttft_epoch):
                ttft = float(v)
                ttft_epoch = ep
        components = {}
        for comp in TTFT_COMPONENTS:
            spans = [s for s in names.get(comp, ())
                     if ttft_epoch is None
                     or int(s.get("epoch", 0)) == ttft_epoch]
            if spans:
                ms = sum(s["dur"] for s in spans) * 1e3
                components[comp] = round(ms, 3)
                comp_samples.setdefault(comp, []).append(ms)
        if ttft is not None:
            ttft_samples.append(ttft)
        epochs = sorted({int(s.get("epoch", 0))
                         for spans in by_rank.values()
                         for ss in spans.values() for s in ss})
        entry = {
            "components_ms": components,
            "component_sum_ms": round(sum(components.values()), 3),
            "ttft_ms": ttft,
            "epochs": epochs,
            "replayed": any("replay_prefill" in by_rank[r]
                            for r in by_rank),
            "ranks": sorted(by_rank),
        }
        requests[rid] = entry

    tpot = {}
    # Per-step scheduler residual: whole-iteration "step" spans minus
    # the named phases inside them, keyed by (rank, epoch, step) —
    # rank so each rank's residual is its own (every rank emits step
    # spans; pooling would inflate the residual N-fold), epoch so an
    # elastic replay's repeated step numbers stay distinct.
    named_by_step: Dict[tuple, float] = {}
    step_total: Dict[tuple, float] = {}
    for name, spans in step_spans.items():
        if name in ("decode_compute", "schedule_broadcast",
                    "stream_publish", "prefill"):
            for s in spans:
                key = (s.get("_rank"), s.get("epoch", 0),
                       (s.get("args") or {}).get("step"))
                named_by_step[key] = named_by_step.get(key, 0.0) + s["dur"]
        if name == "step":
            for s in spans:
                key = (s.get("_rank"), s.get("epoch", 0),
                       (s.get("args") or {}).get("step"))
                step_total[key] = step_total.get(key, 0.0) + s["dur"]
    sched_residual = [
        (step_total[k] - named_by_step.get(k, 0.0)) * 1e3
        for k in step_total
    ]
    for comp in TPOT_COMPONENTS:
        if comp == "scheduler":
            stats = _pcts([max(v, 0.0) for v in sched_residual])
        else:
            stats = _pcts([s["dur"] * 1e3 for s in step_spans.get(comp, ())])
        if stats is not None:
            tpot[comp] = stats

    return {
        "schema": REPORT_SCHEMA,
        "ranks_present": ranks_present,
        "missing_ranks": missing,
        "requests": requests,
        "ttft_components": {
            comp: _pcts(vals) for comp, vals in sorted(comp_samples.items())
        },
        "ttft_ms": _pcts(ttft_samples),
        "tpot_components": tpot,
    }


def per_rank_glob(raw: str) -> str:
    return pathspec.glob_pattern(raw, "spans")


def merged_output_paths(raw: str) -> tuple:
    """(waterfall path, report path) for a ``HVDTPU_TRACE`` value —
    named so the per-rank glob can never re-consume them."""
    if "{rank}" in raw:
        base, ext = os.path.splitext(raw.replace("{rank}", "merged"))
        return f"{base}{ext or '.json'}", f"{base}.report{ext or '.json'}"
    if raw.endswith(os.sep) or os.path.isdir(raw):
        return (os.path.join(raw, "trace_waterfall.json"),
                os.path.join(raw, "trace_report.json"))
    base, ext = os.path.splitext(raw)
    return (f"{base}.waterfall{ext or '.json'}",
            f"{base}.report{ext or '.json'}")


def merge_glob(raw: str, expected_ranks: Optional[int] = None
               ) -> Optional[dict]:
    """Merge every per-rank span file derived from the ``HVDTPU_TRACE``
    value ``raw``: writes the waterfall and the report, returns
    ``{"waterfall", "report", "events", "doc"}`` or None when no rank
    dumped spans."""
    wf_path, rep_path = merged_output_paths(raw)
    skip = {os.path.abspath(wf_path), os.path.abspath(rep_path)}
    paths = [p for p in glob.glob(per_rank_glob(raw))
             if os.path.abspath(p) not in skip]
    if not paths:
        return None
    n = merge(paths, wf_path)
    doc = report(paths, expected_ranks=expected_ranks)
    pathspec.write_json_atomic(rep_path, doc)
    return {"waterfall": wf_path, "report": rep_path, "events": n,
            "doc": doc}


def main(argv: Optional[List[str]] = None) -> int:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 2:
        print("usage: python -m horovod_tpu.obs.trace_merge "
              "OUT_PREFIX SPAN_FILE [SPAN_FILE ...]\n"
              "   or: python -m horovod_tpu.obs.trace_merge --glob RAW "
              "(the HVDTPU_TRACE value)", file=sys.stderr)
        return 2
    if argv[0] == "--glob":
        out = merge_glob(argv[1])
        if out is None:
            print("no span files found", file=sys.stderr)
            return 1
        print(f"merged {out['events']} spans -> {out['waterfall']}; "
              f"report -> {out['report']}")
        return 0
    out_prefix, paths = argv[0], argv[1:]
    n = merge(paths, out_prefix + ".waterfall.json")
    doc = report(paths)
    pathspec.write_json_atomic(out_prefix + ".report.json", doc)
    print(f"merged {n} spans from {len(paths)} files into "
          f"{out_prefix}.waterfall.json "
          f"({len(doc['requests'])} requests decomposed)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
