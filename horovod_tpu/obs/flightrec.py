"""Per-rank flight recorder: the black box that survives the crash.

Every dump the obs plane writes today is ``atexit``-armed — which is
exactly the path a *dying* rank never takes: a fatal signal (including
the launcher's own kill escalation on ``progress_lost`` /
``heartbeat_lost``) skips atexit entirely, so the rank that most needs
to leave evidence leaves none.  PyTorch's ProcessGroupNCCL flight
recorder and the reference's timeline story point at the same fix, and
this module is it:

* **An always-on, bounded, in-memory event ring per rank** —
  fixed-capacity, fully preallocated at construction, O(1) per event
  with zero steady-state growth (slots are mutated in place; old events
  are overwritten, never freed).  Recording takes one (reentrant) lock
  for a handful of scalar stores — cheap enough for the engine cycle
  loop.  Events are structured ``(seq, t, kind, name, cycle, detail)``
  tuples: collective enqueue/negotiate/execute/complete with op name and
  negotiation cycle, engine phase transitions, elastic rendezvous/epoch
  events, checkpoint begin/shard/commit plus the recovery tier's
  ``ckpt.replica_push`` / ``ckpt.restore`` (whose ``source=peer|disk|
  none`` detail is the restore-provenance record the post-mortem
  analyzer surfaces), fault injections, and the last exception.
* **A shared death-path flush** — :func:`flush` dumps the ring (when
  ``HVDTPU_FLIGHTREC_DUMP`` names a target) and then runs every
  registered :func:`on_death` callback (the metrics-registry dump and
  the live-stream final delta register here), LIFO like atexit.
  :func:`install_death_hooks` arms the flush on **every** death path a
  Python process has: ``sys.excepthook``, ``threading.excepthook``, and
  fatal-signal handlers for SIGTERM / SIGABRT / SIGUSR1 (SIGUSR1 is
  dump-only: the process keeps running, so an operator — or the
  launcher's kill escalation — can demand a black box from a live or
  deadlocked rank without killing it).  After flushing, fatal signals
  are re-delivered with the default disposition so exit statuses stay
  truthful.
* **Honest limits** — SIGKILL and a hard power loss cannot be caught:
  those ranks leave no dump (the post-mortem analyzer reports them as
  "no black box").  A main thread parked inside a C extension defers
  Python signal handlers until it next runs bytecode; the launcher's
  escalation covers that case with a SIGKILL after ``--dump-grace-secs``.

The launcher-side consumer is ``obs/postmortem.py``: it loads every
rank's ring dump, aligns them on (cycle, op), and names the root cause.
"""

from __future__ import annotations

import atexit
import os
import signal
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from ..utils import env as envmod

SCHEMA = "hvdtpu-flightrec-v1"
DEFAULT_CAPACITY = 512
MIN_CAPACITY = 8

# Fatal signals the death hooks intercept.  SIGUSR1 is the dump-only
# member: flush and keep running (the launcher's kill escalation sends
# it before SIGTERM so even the SIGTERM-ignoring die leave a ring).
_FATAL_SIGNALS = ("SIGTERM", "SIGABRT")
_DUMP_SIGNAL = "SIGUSR1"

__all__ = [
    "SCHEMA",
    "FlightRecorder",
    "get_recorder",
    "reset_recorder",
    "record",
    "record_exception",
    "dump_flight_recorder",
    "resolve_dump_path",
    "add_observer",
    "remove_observer",
    "on_death",
    "flush",
    "install_death_hooks",
]

# Event tap: consumers (the goodput ledger) that want every recorded
# event as it happens, without polling snapshots.  Observers run OUTSIDE
# the ring lock, exception-swallowed — a broken consumer must not cost
# the black box an event or deadlock a dying rank.
_observers: List[Callable[[str, str, int, float], None]] = []


def add_observer(fn: Callable[[str, str, int, float], None]) -> None:
    """Register ``fn(kind, name, cycle, t)`` to run after every recorded
    event (module-level and recorder-method paths both).  Idempotent."""
    with _recorder_lock:
        if fn not in _observers:
            _observers.append(fn)


def remove_observer(fn) -> None:
    with _recorder_lock:
        if fn in _observers:
            _observers.remove(fn)


def _notify(kind: str, name: str, cycle: int, t: float) -> None:
    for fn in list(_observers):
        try:
            fn(kind, name, cycle, t)
        except Exception:
            pass


class FlightRecorder:
    """Fixed-capacity ring of structured events.

    All slots are preallocated as mutable lists and overwritten in
    place, so steady-state recording allocates nothing that outlives the
    call (Python's transient float boxing aside) and the memory bound is
    exactly ``capacity`` slots regardless of job length.  The lock is
    reentrant: a fatal-signal handler interrupting the owning thread
    mid-:meth:`record` must still be able to :meth:`snapshot`."""

    _FIELDS = ("seq", "t", "kind", "name", "cycle", "detail")

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = envmod.env_int(
                envmod.FLIGHTREC_CAPACITY, DEFAULT_CAPACITY
            )
        self.capacity = max(int(capacity), MIN_CAPACITY)
        self._slots: List[list] = [
            [0, 0.0, "", "", -1, ""] for _ in range(self.capacity)
        ]
        self._seq = 0
        self._lock = threading.RLock()
        self._last_exc: Optional[dict] = None

    # ------------------------------------------------------------- record

    def record(self, kind: str, name: str = "", cycle: int = -1,
               detail: str = "") -> None:
        """O(1), allocation-free in steady state: reserve the next slot
        and overwrite its six fields in place."""
        t = time.time()
        with self._lock:
            slot = self._slots[self._seq % self.capacity]
            slot[0] = self._seq
            slot[1] = t
            slot[2] = kind
            slot[3] = name
            slot[4] = cycle
            slot[5] = detail
            self._seq += 1
        _notify(kind, name, cycle, t)

    def record_exception(self, exc: BaseException,
                         where: str = "") -> None:
        """Remember the last exception (full, outside the ring — it is
        the single most valuable record and must not be overwritten) and
        drop an ``exception`` event into the ring."""
        doc = {
            "type": type(exc).__name__,
            "message": str(exc)[:500],
            "where": where,
            "traceback": "".join(
                traceback.format_exception(type(exc), exc, exc.__traceback__)
            )[-4000:],
        }
        with self._lock:
            self._last_exc = doc
        self.record("exception", name=type(exc).__name__,
                    detail=str(exc)[:200])

    # ----------------------------------------------------------- inspect

    @property
    def recorded(self) -> int:
        return self._seq

    @property
    def overwritten(self) -> int:
        return max(0, self._seq - self.capacity)

    def snapshot(self) -> List[Dict]:
        """Chronological copy of the live window (oldest surviving event
        first).  Taken under the lock so a concurrent record cannot tear
        a slot mid-read."""
        with self._lock:
            n = min(self._seq, self.capacity)
            start = self._seq % self.capacity if self._seq > self.capacity \
                else 0
            out = []
            for i in range(n):
                slot = self._slots[(start + i) % self.capacity]
                out.append(dict(zip(self._FIELDS, slot)))
            return out

    def dump(self, path: str, *, rank, trigger: str) -> dict:
        """Write the dump-schema JSON document atomically; returns it."""
        with self._lock:
            last_exc = dict(self._last_exc) if self._last_exc else None
        doc = {
            "schema": SCHEMA,
            "rank": rank,
            "pid": os.getpid(),
            "wall_time": time.time(),
            "trigger": trigger,
            "epoch": envmod.env_int("HVDTPU_ELASTIC_EPOCH", 0),
            "capacity": self.capacity,
            "recorded": self.recorded,
            "overwritten": self.overwritten,
            "last_exception": last_exc,
            "events": self.snapshot(),
        }
        from . import pathspec  # noqa: PLC0415

        pathspec.write_json_atomic(path, doc)
        return doc


# -- process-global recorder -------------------------------------------------

# Both module locks are REENTRANT: a fatal signal interrupting the
# owning thread mid-critical-section re-enters flush()/get_recorder()
# from the handler on the SAME thread — a plain Lock would self-
# deadlock the dying rank exactly when its dump matters most (e.g. the
# launcher's SIGUSR1 immediately followed by SIGTERM).
_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.RLock()


def get_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def reset_recorder() -> None:
    """Drop the global recorder and sticky flush trigger (tests)."""
    global _recorder, _sticky_trigger
    with _recorder_lock:
        _recorder = None
    with _death_lock:
        _sticky_trigger = None


def record(kind: str, name: str = "", cycle: int = -1,
           detail: str = "") -> None:
    """Record one event on the process-global ring (always on)."""
    get_recorder().record(kind, name=name, cycle=cycle, detail=detail)


def record_exception(exc: BaseException, where: str = "") -> None:
    get_recorder().record_exception(exc, where=where)
    # The memory plane's OOM black box rides the same death path: a
    # RESOURCE_EXHAUSTED exception additionally drops a ``mem.oom``
    # event (last census + dominant owner) so the post-mortem can name
    # WHAT was resident when the allocator gave up, not just that it
    # did.  Defensive import: a stripped tree without the plane must
    # still record the exception itself.
    try:
        from . import memplane  # noqa: PLC0415

        memplane.maybe_record_oom(exc, where=where)
    except Exception:
        pass


def _resolve_rank() -> str:
    return envmod.artifact_rank()


def resolve_dump_path(raw: str, rank: Optional[str] = None) -> str:
    """``HVDTPU_FLIGHTREC_DUMP`` value -> this rank's file, via the same
    shared pathspec rules (dir / {rank} template / plain path, epoch
    tag) the metrics and timeline artifacts use."""
    from . import pathspec  # noqa: PLC0415

    return pathspec.resolve(
        raw, "flightrec", _resolve_rank() if rank is None else rank
    )


def dump_flight_recorder(path: Optional[str] = None,
                         trigger: str = "explicit") -> Optional[str]:
    """Dump the global ring; ``path=None`` resolves from the env.
    Returns the written path, or None when dumping is not configured."""
    raw = path or os.environ.get(envmod.FLIGHTREC_DUMP)
    if not raw:
        return None
    resolved = resolve_dump_path(raw) if path is None else path
    get_recorder().dump(resolved, rank=_resolve_rank(), trigger=trigger)
    return resolved


# -- shared death-path flush -------------------------------------------------

_death_callbacks: List[Callable[[], None]] = []
_death_lock = threading.RLock()  # reentrant: see _recorder_lock
_atexit_armed = False
_hooks_installed = False
_prev_signal_handlers: Dict[int, object] = {}
_sticky_trigger: Optional[str] = None

# Triggers that mean "this process is dying abnormally".  Once one of
# these flushed, a later routine flush (the atexit leg still runs after
# an excepthook, and after a caught-and-returned worker error) must not
# overwrite the dump's trigger with a benign-looking "atexit".
_DEATH_TRIGGER_PREFIXES = ("excepthook", "threading.excepthook",
                           "exception", "signal:")


def on_death(fn: Callable[[], None]) -> None:
    """Register a flusher to run on every death path (and at clean
    exit).  First registration arms the atexit leg; the signal and
    excepthook legs are armed by :func:`install_death_hooks`.  Callbacks
    run LIFO (atexit semantics: later-armed subsystems flush first) and
    exceptions are swallowed — one broken flusher must not cost the
    others their dump."""
    global _atexit_armed
    with _death_lock:
        if fn not in _death_callbacks:
            _death_callbacks.append(fn)
        if not _atexit_armed:
            atexit.register(_atexit_flush)
            _atexit_armed = True


def flush(trigger: str) -> None:
    """The one flush every death path converges on: ring dump first
    (the black box is the point), then every registered flusher.  Safe
    to call repeatedly — later flushes refresh the dump with newer
    events, but a death trigger is sticky: the atexit leg running after
    an excepthook must not relabel the dump as a routine exit."""
    global _sticky_trigger
    is_death = trigger.startswith(_DEATH_TRIGGER_PREFIXES) and \
        trigger != f"signal:{_DUMP_SIGNAL}"
    with _death_lock:
        if is_death and _sticky_trigger is None:
            _sticky_trigger = trigger
        effective = _sticky_trigger or trigger
    try:
        dump_flight_recorder(trigger=effective)
    except Exception:
        pass
    with _death_lock:
        callbacks = list(_death_callbacks)
    for fn in reversed(callbacks):
        try:
            fn()
        except Exception:
            pass


def _atexit_flush() -> None:
    flush("atexit")


def install_death_hooks() -> None:
    """Arm the flush on every catchable death path.  Idempotent; safe
    to call from any thread (signal handlers are skipped off the main
    thread — the excepthook and atexit legs still arm).  Previously
    installed hooks/handlers are chained, not clobbered."""
    global _hooks_installed, _atexit_armed
    with _death_lock:
        if _hooks_installed:
            return
        _hooks_installed = True
        if not _atexit_armed:
            atexit.register(_atexit_flush)
            _atexit_armed = True

    prev_excepthook = sys.excepthook

    def _excepthook(tp, value, tb):
        try:
            if isinstance(value, BaseException):
                record_exception(value, where="excepthook")
            flush("excepthook")
        except Exception:
            pass
        prev_excepthook(tp, value, tb)

    sys.excepthook = _excepthook

    prev_thread_hook = threading.excepthook

    def _thread_hook(args):
        try:
            if args.exc_value is not None:
                record_exception(
                    args.exc_value,
                    where=f"thread:{getattr(args.thread, 'name', '?')}",
                )
            flush("threading.excepthook")
        except Exception:
            pass
        prev_thread_hook(args)

    threading.excepthook = _thread_hook

    for sig_name in _FATAL_SIGNALS + (_DUMP_SIGNAL,):
        signum = getattr(signal, sig_name, None)
        if signum is None:  # pragma: no cover - platform without it
            continue
        try:
            prev = signal.signal(signum, _signal_handler)
        except (ValueError, OSError):
            # not the main thread, or an unblockable signal on this
            # platform — the excepthook/atexit legs still cover us
            continue
        _prev_signal_handlers[int(signum)] = prev


def _signal_handler(signum, frame):
    try:
        name = signal.Signals(signum).name
    except ValueError:  # pragma: no cover - unknown signal number
        name = str(signum)
    try:
        record("signal", name=name)
        flush(f"signal:{name}")
    except Exception:
        pass
    prev = _prev_signal_handlers.get(int(signum))
    if name == _DUMP_SIGNAL:
        # Dump-only: the rank keeps running (or hanging) — but a user
        # handler installed before ours (e.g. checkpoint-on-preemption:
        # SLURM delivers SIGUSR1 ahead of the kill) must still fire.
        if callable(prev) and prev is not _signal_handler:
            prev(signum, frame)
        return
    if callable(prev) and prev is not _signal_handler:
        # The real frame, not None: a prior handler inspecting
        # frame.f_lineno (a common diagnostic pattern) must not crash
        # inside signal delivery.
        prev(signum, frame)
        return
    # Default/ignored before us: restore the default disposition and
    # re-deliver so the exit status is the real signal, not a fake
    # sys.exit code (launchers and schedulers key off it).
    signal.signal(signum, signal.SIG_DFL)
    signal.raise_signal(signum)
