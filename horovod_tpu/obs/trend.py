"""Perf-trend observatory over the driver's benchmark trajectory.

The repo's ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` records are the
only durable perf evidence this project has, and they span every schema
era since r01 (bare parsed payloads without a ``device`` key, degraded
CPU fallbacks, serve records, failed dark rounds).  This module is the
ONE place that knows how to read them:

* **classification** — ``classify()`` partitions a record into
  ``real`` / ``degraded`` / ``failed``.  ``scripts/perf_gate.py``,
  ``bench.py``'s regression sentinel and ``scripts/perf_report.py``
  all import it from here, so "what counts as a real measurement" can
  never fork between the gate and the sentinel.
* **EWMA baselines** — ``ewma_baseline()`` folds the last K real
  records of a scenario ``(metric, device)`` into an exponentially
  weighted baseline, replacing the single-newest-record bar: one lucky
  (or unlucky) round no longer owns the regression threshold.
* **degraded-streak verdict** — ``degraded_streak()`` names the dark
  trajectory out loud ("N consecutive records without a real
  measurement; last real number is BENCH_r02.json ...") so it
  self-announces in every fresh record, the live digest and the
  ``--stats-summary`` table instead of needing a reviewer to notice.
* **rendering** — ``render_markdown()`` emits the trajectory +
  baseline tables ``scripts/perf_report.py`` writes into
  ``docs/performance.md``.

Everything here is stdlib-only and read-only over the record dir; every
public entry is total (returns empty/None on an unreadable dir) because
trend accounting must never sink the measurement or digest it rides in.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Tuple

__all__ = [
    "classify", "parsed_payload", "scenario_key",
    "load_bench_records", "load_multichip_records",
    "ewma_baseline", "degraded_streak", "trend_stamp",
    "trajectory", "render_markdown",
    "EWMA_K", "EWMA_ALPHA", "repo_record_dir",
]

# EWMA over the last K real records per scenario.  alpha=0.5 halves a
# record's weight per newer record: the newest real number dominates
# (weight 0.5) but a single outlier round can no longer own the bar.
EWMA_K = 5
EWMA_ALPHA = 0.5

# Record dir override for launchers/tests; default is the repo root,
# where the driver lands BENCH_r*.json.
RECORD_DIR_ENV = "HVDTPU_RECORD_DIR"


def repo_record_dir() -> str:
    env = os.environ.get(RECORD_DIR_ENV)
    if env:
        return env
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


# --------------------------------------------------------------- loading

def _load_glob(record_dir: str, pattern: str) -> List[Tuple[int, str, dict]]:
    """[(round n, filename, doc)] sorted by round; unreadable files are
    skipped (one corrupt record must not blind the observatory to the
    rest of the trajectory)."""
    records = []
    for path in sorted(glob.glob(os.path.join(record_dir, pattern))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        n = doc.get("n")
        records.append((n if isinstance(n, int) else 0,
                        os.path.basename(path), doc))
    records.sort(key=lambda t: (t[0], t[1]))
    return records


def load_bench_records(record_dir: Optional[str] = None
                       ) -> List[Tuple[int, str, dict]]:
    return _load_glob(record_dir or repo_record_dir(), "BENCH_*.json")


def load_multichip_records(record_dir: Optional[str] = None
                           ) -> List[Tuple[int, str, dict]]:
    return _load_glob(record_dir or repo_record_dir(), "MULTICHIP_*.json")


# -------------------------------------------------------- classification

def parsed_payload(doc: dict) -> Optional[dict]:
    """The measurement payload: bench.py main() embeds it under
    ``parsed`` in driver records; a bare bench stdout JSON (a fresh
    candidate) IS the payload."""
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        return parsed
    if "metric" in doc:
        return doc
    return None


def classify(doc: dict) -> str:
    """'real' | 'degraded' | 'failed' for one record document.

    real = rc 0, a parsed measurement with a numeric value, and no
    ``degraded`` stamp anywhere; degraded = the explicit stamp bench.py
    lands on CPU fallbacks and give-up records; failed = everything
    else (the r03-r05 dark rounds: a nonzero rc and no measurement)."""
    parsed = parsed_payload(doc)
    if doc.get("degraded") or (isinstance(parsed, dict)
                               and parsed.get("degraded")):
        return "degraded"
    if (doc.get("rc", 0) == 0 and isinstance(parsed, dict)
            and parsed.get("metric")
            and isinstance(parsed.get("value"), (int, float))):
        return "real"
    return "failed"


def scenario_key(parsed: dict) -> Tuple[Optional[str], Optional[str]]:
    """(metric, device) — the comparability unit.  r01-era payloads
    carry no ``device`` key and key as (metric, None), deliberately
    distinct from later device-stamped records: a CPU dev number must
    never baseline a TPU one."""
    return (parsed.get("metric"), parsed.get("device"))


# -------------------------------------------------------- EWMA baseline

def ewma_baseline(records: List[Tuple[int, str, dict]],
                  metric: Optional[str], device: Optional[str],
                  k: int = EWMA_K,
                  alpha: float = EWMA_ALPHA) -> Optional[dict]:
    """EWMA over the last ``k`` REAL records matching (metric, device),
    folded oldest-to-newest so the newest real number carries the most
    weight.  Returns None when the scenario has no real record —
    degraded records are trajectory evidence, never a bar."""
    matching = []
    for _, fname, doc in records:
        if classify(doc) != "real":
            continue
        parsed = parsed_payload(doc)
        if scenario_key(parsed) != (metric, device):
            continue
        matching.append((fname, parsed))
    if not matching:
        return None
    window = matching[-k:]
    value = None
    mfu = None
    for _, parsed in window:
        v = parsed.get("value")
        if isinstance(v, (int, float)):
            value = v if value is None else alpha * v + (1 - alpha) * value
        m = parsed.get("mfu")
        if isinstance(m, (int, float)):
            mfu = m if mfu is None else alpha * m + (1 - alpha) * mfu
    if value is None:
        return None
    return {
        "value": round(float(value), 4),
        "mfu": round(float(mfu), 6) if mfu is not None else None,
        "records": [fname for fname, _ in window],
        "count": len(window),
        "k": k,
        "alpha": alpha,
        "newest": window[-1][0],
    }


# ------------------------------------------------------ degraded streak

def degraded_streak(records: List[Tuple[int, str, dict]]) -> dict:
    """How long the trajectory has been dark, and what the last real
    number was.  ``verdict`` is the human sentence every record / live
    digest / summary embeds."""
    last_real = None  # (fname, parsed)
    streak = 0
    since = None
    for _, fname, doc in records:
        if classify(doc) == "real":
            last_real = (fname, parsed_payload(doc))
            streak = 0
            since = None
        else:
            if streak == 0:
                since = fname
            streak += 1
    out = {
        "streak": streak,
        "since": since,
        "last_real_record": last_real[0] if last_real else None,
        "last_real_metric": (last_real[1].get("metric")
                             if last_real else None),
        "last_real_value": (last_real[1].get("value")
                            if last_real else None),
        "last_real_device": (last_real[1].get("device")
                             if last_real else None),
    }
    if not records:
        out["verdict"] = "no benchmark records yet"
    elif streak == 0 and last_real is not None:
        out["verdict"] = (
            f"latest record {last_real[0]} is a real measurement "
            f"({out['last_real_metric']}={out['last_real_value']})"
        )
    elif last_real is None:
        out["verdict"] = (
            f"{streak} consecutive records without a real measurement; "
            f"no real number has ever landed"
        )
    else:
        out["verdict"] = (
            f"{streak} consecutive records without a real measurement "
            f"(since {since}); last real number is {last_real[0]} "
            f"({out['last_real_metric']}={out['last_real_value']}"
            + (f" on {out['last_real_device']}"
               if out["last_real_device"] else "") + ")"
        )
    return out


def trend_stamp(record_dir: Optional[str] = None) -> Optional[dict]:
    """The small trend/provenance block embedded in fresh records and
    digest tokens.  Total: returns None when the record dir is
    unreadable or empty (a missing trajectory must never sink a
    measurement)."""
    try:
        records = load_bench_records(record_dir)
        if not records:
            return None
        counts: Dict[str, int] = {"real": 0, "degraded": 0, "failed": 0}
        for _, _, doc in records:
            counts[classify(doc)] += 1
        streak = degraded_streak(records)
        return {
            "records": len(records),
            "real": counts["real"],
            "degraded": counts["degraded"],
            "failed": counts["failed"],
            "degraded_streak": streak["streak"],
            "last_real_record": streak["last_real_record"],
            "last_real_value": streak["last_real_value"],
            "verdict": streak["verdict"],
        }
    except Exception:
        return None


# ------------------------------------------------------------ rendering

def trajectory(records: List[Tuple[int, str, dict]]) -> List[dict]:
    """One row per record, oldest first, ready for tabulation."""
    rows = []
    for n, fname, doc in records:
        parsed = parsed_payload(doc) or {}
        rows.append({
            "n": n,
            "file": fname,
            "class": classify(doc),
            "metric": parsed.get("metric"),
            "device": parsed.get("device"),
            "value": parsed.get("value"),
            "unit": parsed.get("unit"),
            "mfu": parsed.get("mfu"),
            "rc": doc.get("rc"),
        })
    return rows


def _fmt(v, nd=2) -> str:
    if isinstance(v, bool) or v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render_markdown(record_dir: Optional[str] = None) -> str:
    """The auto-generated trajectory section for docs/performance.md:
    verdict, per-record table, EWMA baselines, multichip rounds."""
    record_dir = record_dir or repo_record_dir()
    records = load_bench_records(record_dir)
    lines = ["<!-- generated by scripts/perf_report.py --write-docs; "
             "do not edit by hand -->", ""]
    if not records:
        lines.append(f"_No BENCH records under {record_dir}._")
        return "\n".join(lines) + "\n"
    streak = degraded_streak(records)
    lines += [f"**Trajectory verdict:** {streak['verdict']}", ""]
    lines += ["| round | record | class | metric | device | value | MFU |",
              "|---|---|---|---|---|---|---|"]
    for row in trajectory(records):
        lines.append(
            f"| {row['n']} | {row['file']} | {row['class']} | "
            f"{_fmt(row['metric'])} | {_fmt(row['device'])} | "
            f"{_fmt(row['value'])} | {_fmt(row['mfu'], 4)} |"
        )
    scenarios = sorted(
        {scenario_key(parsed_payload(doc))
         for _, _, doc in records
         if classify(doc) == "real"},
        key=str,
    )
    if scenarios:
        lines += ["", f"**EWMA baselines** (last {EWMA_K} real records "
                      f"per scenario, alpha={EWMA_ALPHA}):", "",
                  "| metric | device | EWMA value | EWMA MFU | records |",
                  "|---|---|---|---|---|"]
        for metric, device in scenarios:
            base = ewma_baseline(records, metric, device)
            if base is None:
                continue
            lines.append(
                f"| {_fmt(metric)} | {_fmt(device)} | "
                f"{_fmt(base['value'])} | {_fmt(base['mfu'], 4)} | "
                f"{', '.join(base['records'])} |"
            )
    multichip = load_multichip_records(record_dir)
    if multichip:
        lines += ["", "**Multichip rounds:**", "",
                  "| round | record | devices | ok | skipped |",
                  "|---|---|---|---|---|"]
        for n, fname, doc in multichip:
            lines.append(
                f"| {n} | {fname} | {_fmt(doc.get('n_devices'))} | "
                f"{_fmt(doc.get('ok'))} | {_fmt(doc.get('skipped'))} |"
            )
    return "\n".join(lines) + "\n"
