"""Live MFU profiler: model-FLOPs accounting over measured step time.

The ROADMAP's item-5 campaign has machinery but no *measurement layer*:
MFU existed only as a line bench.py computed inline at the end of a
run.  This module is that layer, shared by every surface that times a
step:

* **Model FLOPs per step** — preferred source: XLA's own post-fusion
  cost analysis of the compiled artifact (:func:`flops_from_compiled`,
  the PR-9 HLO-inspector spirit: a property of the artifact, not a
  hand-derived guess).  Fallback when the executable cannot be
  inspected: analytic formulas keyed off the bench model builders
  (:func:`analytic_step_flops` — the 6N + 12·L·s·d transformer rule and
  a per-model conv table), flagged ``source: analytic``.
* **Device peak FLOP/s** — a small per-platform table
  (:data:`PEAK_FLOPS`, public TPU spec sheets).  CPU and unknown chips
  get a nominal order-of-magnitude entry marked **estimate-only**: a
  CPU MFU is a trajectory placeholder, never a perf claim, and every
  consumer carries the flag.
* **Live gauges** — :class:`MFUProfiler` divides FLOPs by measured step
  time and publishes ``perf.mfu``, ``perf.model_tflops``,
  ``perf.step_ms`` (plus ``perf.mfu_estimate`` when the peak is a
  guess) into the metrics registry — so the digest (``mfu 0.31``
  token), ``/metrics``, ``--stats-summary`` and every BENCH record see
  the same number, computed once.

No jax import at module scope: the launcher imports obs eagerly and
must not pay (or hang on) a backend handshake for it.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = [
    "PEAK_FLOPS",
    "CPU_PEAK_ESTIMATE",
    "peak_flops",
    "flops_from_compiled",
    "transformer_step_flops",
    "analytic_step_flops",
    "MFUProfiler",
]

# Peak dense-matmul FLOP/s per chip (bf16 on MXU; fp32 runs at ~1/4 via
# bf16x3 passes or worse).  Sources: public TPU spec sheets.  Shared
# with bench.py — ONE table, so the bench headline and the live gauge
# can never disagree about a chip's peak.
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# Order-of-magnitude stand-in for a few AVX cores — good enough to keep
# the MFU pipeline exercised end-to-end on the CPU dev path, useless as
# a perf claim, hence estimate-flagged everywhere it flows.
CPU_PEAK_ESTIMATE = 1e11


def peak_flops(device_kind: str, dtype: str = "bf16"
               ) -> Tuple[float, bool]:
    """``(peak FLOP/s, estimate_flag)`` for a device kind string
    (``jax.Device.device_kind``).  Known TPUs are authoritative;
    everything else (CPU dev mode, unknown chips) returns the nominal
    CPU estimate with the flag raised."""
    peak = PEAK_FLOPS.get(device_kind)
    if peak is None:
        return CPU_PEAK_ESTIMATE, True
    if dtype == "fp32":
        peak = peak / 4.0
    return peak, False


def flops_from_compiled(compiled) -> Optional[float]:
    """Per-device FLOPs of one execution of a compiled executable, as
    XLA counts them post-fusion (``cost_analysis()``).  Tolerates the
    per-version shape drift (dict vs single-element list) and returns
    None when the backend exposes no analysis — callers fall back to
    :func:`analytic_step_flops`."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    try:
        v = float(ca.get("flops", 0.0))
    except (AttributeError, TypeError, ValueError):
        return None
    return v if v > 0 else None


# -- analytic fallbacks ------------------------------------------------------

def _transformer_param_count(cfg) -> int:
    """Parameter count of models/transformer.py's GPT for a config —
    kept in lockstep with the flax module (wte + learned wpe + per-block
    qkv/proj/mlp/2LN + final LN + untied head)."""
    d = cfg.emb_dim
    kv_dim = cfg.kv_heads * cfg.head_dim
    mlp_hidden = cfg.mlp_ratio * d
    per_block = (
        d * (d + 2 * kv_dim) + (d + 2 * kv_dim)   # qkv (+bias)
        + d * d + d                                # proj
        + d * mlp_hidden + mlp_hidden              # mlp up
        + mlp_hidden * d + d                       # mlp down
        + 4 * d                                    # 2 x LayerNorm
    )
    n = cfg.vocab_size * d + cfg.num_layers * per_block
    n += 2 * d                                     # final LayerNorm
    n += d * cfg.vocab_size                        # untied head
    if cfg.pos_embedding == "learned":
        n += cfg.max_len * d
    return n


def transformer_step_flops(cfg, batch_size: int, seq_len: int,
                           training: bool = True) -> float:
    """Analytic model FLOPs for one step over ``batch_size`` sequences
    of ``seq_len`` tokens: the standard 6N-per-token rule (2N forward,
    4N backward) plus the attention term 12·L·s·d per token (4·s·d
    forward for QKᵀ and AV, tripled for training).  ``training=False``
    gives the forward-only 2N + 4·L·s·d (the decode-step shape)."""
    n = _transformer_param_count(cfg)
    tokens = batch_size * seq_len
    per_tok_mat = (6 if training else 2) * n
    per_tok_attn = (12 if training else 4) * cfg.num_layers * seq_len \
        * cfg.emb_dim
    return float(tokens) * (per_tok_mat + per_tok_attn)


# Forward FLOPs per image at 224x224 (published per-model numbers,
# 2 x MACs); training approximated as 3 x forward.
_CONV_FWD_FLOPS_224 = {
    "resnet18": 3.6e9,
    "resnet50": 8.2e9,
    "resnet101": 15.2e9,
    "vgg16": 31.0e9,
    "vgg19": 39.0e9,
    "inception3": 11.4e9,
}


def analytic_step_flops(model_name: str, batch_size: int,
                        seq_len: Optional[int] = None,
                        image_size: int = 224) -> Optional[float]:
    """Analytic per-step training FLOPs keyed off the bench model
    builders (``bench.py --model`` names).  None for a model the tables
    don't know — the caller then reports no MFU rather than a wrong
    one."""
    if model_name.startswith("gpt-"):
        from ..models.transformer import GPT_CONFIGS  # noqa: PLC0415

        cfg = GPT_CONFIGS.get(model_name[len("gpt-"):])
        if cfg is None or not seq_len:
            return None
        return transformer_step_flops(cfg, batch_size, seq_len)
    fwd = _CONV_FWD_FLOPS_224.get(model_name)
    if fwd is None:
        return None
    scale = (image_size / 224.0) ** 2
    return 3.0 * fwd * scale * batch_size


class MFUProfiler:
    """Publishes the live perf gauges for one measured step loop.

    ``flops_per_step`` is per-device (XLA's cost analysis is the
    post-SPMD-partitioning per-device module; analytic callers must
    divide by world size themselves).  ``observe(step_secs)`` is cheap
    enough for a serving decode loop: three float divisions and three
    gauge stores."""

    def __init__(self, flops_per_step: Optional[float],
                 device_kind: str, dtype: str = "bf16", *,
                 source: str = "cost_analysis", registry=None):
        from .registry import get_registry  # noqa: PLC0415

        self.flops_per_step = flops_per_step
        self.device_kind = device_kind
        self.peak, self.estimate = peak_flops(device_kind, dtype)
        self.source = source
        self.mfu: Optional[float] = None
        self.step_ms: Optional[float] = None
        reg = registry if registry is not None else get_registry()
        self._g_mfu = reg.gauge("perf.mfu")
        self._g_tflops = reg.gauge("perf.model_tflops")
        self._g_step_ms = reg.gauge("perf.step_ms")
        self._g_estimate = reg.gauge("perf.mfu_estimate")
        self._g_estimate.set(1.0 if self.estimate else 0.0)

    def observe(self, step_secs: float) -> Optional[float]:
        """One measured step (or the mean of a timed window): update
        the gauges, return the MFU (None when FLOPs are unknown)."""
        if step_secs <= 0:
            return self.mfu
        self.step_ms = step_secs * 1e3
        self._g_step_ms.set(self.step_ms)
        if not self.flops_per_step:
            return None
        achieved = self.flops_per_step / step_secs
        self.mfu = achieved / self.peak
        self._g_mfu.set(self.mfu)
        self._g_tflops.set(achieved / 1e12)
        return self.mfu

    def summary(self) -> dict:
        """The record-embeddable view — what BENCH/serve records carry
        so the moment a real TPU answers, item 5's sweep lands real MFU
        numbers with zero new code."""
        out = {
            "mfu": round(self.mfu, 4) if self.mfu is not None else None,
            "model_tflops": (
                round(self.flops_per_step / (self.step_ms / 1e3) / 1e12, 4)
                if self.flops_per_step and self.step_ms else None
            ),
            "step_ms": (round(self.step_ms, 3)
                        if self.step_ms is not None else None),
            "flops_per_step": self.flops_per_step,
            "flops_source": self.source,
            "device": self.device_kind,
            "peak_flops": self.peak,
            "estimate": bool(self.estimate),
        }
        return out
