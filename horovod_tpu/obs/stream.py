"""Per-rank live metrics streaming: the worker half of the telemetry
plane.

The PR-2 observability plane is post-mortem — per-rank JSON dumps at
process exit, aggregated once the job is over.  This module makes the
same registry inspectable *while the job runs*: a daemon thread snapshots
the metrics registry every ``HVDTPU_LIVE_STATS_SECS`` seconds, diffs it
against the previous snapshot, and publishes a compact delta document to
the launcher's KV store over the existing HMAC-signed PUT path
(run/rendezvous.py) — no new listening sockets on workers, and the same
trust model as every other KV payload.

Wire contract (consumed by obs/live.py's launcher aggregator):

* key: ``obs/live/{epoch}/{rank}/{seq}`` — one key per publish, so the
  aggregator never loses a delta to an overwrite; it deletes keys as it
  consumes them (the launcher owns the store's memory).
* value: JSON ``{"v": 1, "rank", "epoch", "seq", "t", "phase",
  "progress", "full", "metrics": [compact instruments...]}`` where
  ``metrics`` carries only the instruments that changed since the last
  publish (all of them on the first, ``full: true``).  Every entry
  carries the instrument's *current* value, never an increment, so a
  lost or reordered delta heals itself the next time the instrument
  moves.

Compact instrument encoding (≈60% smaller than the dump schema):

* counter/gauge: ``{"n", "k": "c"|"g", "g": tags?, "v": value}``
* histogram: ``{"n", "k": "h", "g": tags?, "c": count, "s": sum,
  "mn": min, "mx": max, "q50", "q90", "q99"}``
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils import env as envmod
from ..utils.logging import get_logger
from .registry import get_registry

LOG = get_logger("obs.stream")

LIVE_SCOPE = "obs/live"

__all__ = [
    "LIVE_SCOPE",
    "snapshot_map",
    "encode_delta",
    "expand_metric",
    "apply_delta",
    "StreamPublisher",
    "maybe_start_from_env",
    "stop_stream",
]

_KIND_SHORT = {"counter": "c", "gauge": "g", "histogram": "h"}
_KIND_LONG = {v: k for k, v in _KIND_SHORT.items()}


def metric_key(metric: dict) -> str:
    """Stable identity of one instrument inside a snapshot: name plus
    sorted tags (the same identity the registry itself keys on)."""
    tags = metric.get("tags") or {}
    if not tags:
        return metric["name"]
    return metric["name"] + "{" + ",".join(
        f"{k}={v}" for k, v in sorted(tags.items())
    ) + "}"


def snapshot_map(metrics: List[dict]) -> Dict[str, dict]:
    """Dump-schema snapshot list -> {identity: metric dict}."""
    return {metric_key(m): m for m in metrics}


def _compact(metric: dict) -> dict:
    out = {"n": metric["name"], "k": _KIND_SHORT[metric["type"]]}
    if metric.get("tags"):
        out["g"] = metric["tags"]
    if metric["type"] == "histogram":
        out.update(
            c=metric["count"], s=metric["sum"],
            mn=metric["min"], mx=metric["max"],
            q50=metric["p50"], q90=metric["p90"], q99=metric["p99"],
        )
    else:
        out["v"] = metric["value"]
    return out


def expand_metric(compact: dict) -> dict:
    """Compact wire form -> dump-schema form (the aggregator's working
    representation, so live views and end-of-job dumps compare 1:1)."""
    kind = _KIND_LONG[compact["k"]]
    out = {"name": compact["n"], "type": kind,
           "tags": dict(compact.get("g") or {})}
    if kind == "histogram":
        count = compact["c"]
        out.update(
            count=count, sum=compact["s"],
            min=compact["mn"], max=compact["mx"],
            mean=(compact["s"] / count) if count else None,
            p50=compact["q50"], p90=compact["q90"], p99=compact["q99"],
        )
    else:
        out["value"] = compact["v"]
    return out


def encode_delta(
    prev: Dict[str, dict], cur: Dict[str, dict]
) -> List[dict]:
    """The compact entries for every instrument that changed (or
    appeared) between two snapshot maps, plus a ``{"rm": key}``
    tombstone per instrument that *disappeared* — instrument removal
    (the elastic-rendezvous straggler reset) must reach the launcher
    view, or stale blame would survive a re-formed world forever."""
    out: List[dict] = [
        {"rm": key} for key in prev if key not in cur
    ]
    out.extend(_compact(m) for key, m in cur.items() if prev.get(key) != m)
    return out


def apply_delta(view: Dict[str, dict], delta: List[dict]) -> None:
    """Apply a wire delta onto an aggregator-side view map in place."""
    for compact in delta:
        if "rm" in compact:
            view.pop(compact["rm"], None)
            continue
        m = expand_metric(compact)
        view[metric_key(m)] = m


class StreamPublisher:
    """One worker's snapshot-diff-publish loop.  Publishes every
    ``interval`` seconds whether or not anything changed — an empty
    delta is the liveness signal the aggregator's "ranks reporting"
    count rests on.  Publish failures are swallowed: the launcher going
    away must never take the training process with it."""

    def __init__(self, kv, rank, epoch: int, interval: float):
        self.kv = kv
        self.rank = rank
        self.epoch = int(epoch)
        self.interval = float(interval)
        self._prev: Dict[str, dict] = {}
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def publish_once(self) -> Optional[bytes]:
        """Snapshot, diff, publish one document; returns the payload
        (tests), or None when the PUT failed."""
        from . import progress as obs_progress  # noqa: PLC0415

        cur = snapshot_map(get_registry().snapshot())
        full = self._seq == 0
        delta = encode_delta({} if full else self._prev, cur)
        doc = {
            "v": 1,
            "rank": int(self.rank),
            "epoch": self.epoch,
            "seq": self._seq,
            "t": time.time(),
            "phase": obs_progress.phase(),
            "progress": obs_progress.value(),
            "full": full,
            "metrics": delta,
        }
        payload = json.dumps(doc, separators=(",", ":")).encode()
        try:
            self.kv.put(
                f"{LIVE_SCOPE}/{self.epoch}", f"{self.rank}/{self._seq}",
                payload,
            )
        except Exception:
            return None  # launcher down/restarting; try again next beat
        self._prev = cur
        self._seq += 1
        return payload

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="hvdtpu_live_stream", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.publish_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        # Final flush: the last partial interval's metrics (often the
        # job's concluding straggler attributions) must reach the
        # launcher's end-of-job drain round.  Best-effort like every
        # other publish.
        self.publish_once()


_current: Optional[StreamPublisher] = None
# Reentrant: _death_flush runs on the fatal-signal path, and a second
# signal interrupting the owning thread inside this lock (the
# launcher's SIGUSR1-then-SIGTERM escalation) must not self-deadlock
# the dying rank — same rationale as flightrec.py's module locks.
_current_lock = threading.RLock()
_atexit_installed = False


def _env_config() -> Optional[Tuple[str, float, str, int]]:
    interval = envmod.env_float(envmod.LIVE_STATS, 0.0)
    if interval <= 0:
        return None
    addr = (os.environ.get(envmod.LIVE_KV)
            or os.environ.get("HVDTPU_ELASTIC_KV"))
    if not addr:
        return None
    rank = envmod.resolve_rank(0)
    epoch = envmod.env_int("HVDTPU_ELASTIC_EPOCH", 0)
    return addr, interval, str(rank), epoch


def maybe_start_from_env() -> Optional[StreamPublisher]:
    """Start (once per process) the live publisher when the launcher
    armed it: ``HVDTPU_LIVE_STATS_SECS > 0`` and a KV endpoint present.
    Called from ``hvd.init()`` and the elastic heartbeat start, so both
    launch modes stream without user code changes."""
    global _current, _atexit_installed
    # The memory plane's env opt-in rides the same worker-init hook:
    # HVDTPU_MEM_CENSUS=1 arms the census collector here regardless of
    # whether streaming itself is on (the exit dump consumes it too).
    try:
        from . import memplane  # noqa: PLC0415

        memplane.maybe_install_from_env()
    except Exception:
        pass
    cfg = _env_config()
    if cfg is None:
        return None
    with _current_lock:
        if _current is not None:
            return _current
        addr, interval, rank, epoch = cfg
        from ..run.rendezvous import KVStoreClient  # noqa: PLC0415

        pub = StreamPublisher(
            KVStoreClient(addr), rank=rank, epoch=epoch, interval=interval
        )
        pub.start()
        if not _atexit_installed:
            # Exit flush: publish the final partial interval.  Routed
            # through the shared death-path flush (obs/flightrec.py) so
            # the final live delta also survives excepthook/signal
            # deaths; registered after the registry's dump hook, so
            # (LIFO) it runs BEFORE the process's metrics dump.
            # Deliberately non-destructive — a dump-only flush (the
            # SIGUSR1 black-box request) happens MID-RUN and must not
            # stop the publisher; at real exit the daemon thread dies
            # with the process and the delta published here is the
            # flush that matters.
            from .flightrec import on_death  # noqa: PLC0415

            on_death(_death_flush)
            _atexit_installed = True
        LOG.debug("live stats streaming to %s every %.2fs", addr, interval)
        _current = pub
        return pub


def _death_flush() -> None:
    """Publish the current delta without tearing the publisher down
    (the shared death-path flush runs this on every flush trigger,
    including mid-run dump-only ones)."""
    with _current_lock:
        pub = _current
    if pub is not None:
        pub.publish_once()


def stop_stream() -> None:
    """Stop the process publisher (tests, or in-process re-launch)."""
    global _current
    # Detach under the lock, stop outside it: stop() joins the publisher
    # thread and issues the final (network) publish — holding the lock
    # through that would stall maybe_start_from_env()/_death_flush
    # callers, including the fatal-signal flush (hvdtpu-lint HVDC102).
    with _current_lock:
        pub, _current = _current, None
    if pub is not None:
        pub.stop()
