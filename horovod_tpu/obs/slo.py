"""Per-tenant / per-SLO-class latency objectives with burn-rate alerts.

PR 16 gave requests a tenant and an SLO class (interactive / standard /
batch) and made admission weight them 8:4:1 — but nothing ever said
what "interactive" *means* in milliseconds, so the class was a priority
hint, not an objective.  This module makes it one:

* **Targets** — per-class latency objectives (``ttft_ms`` /
  ``tpot_ms`` ceilings with an ``objective`` fraction, e.g. "99% of
  interactive first tokens under 500ms"), parsed from the serve spec
  (:func:`targets_from_spec`; fed by ``--slo-ttft-ms`` and friends).
* **Sliding-window digests** — per (tenant, SLO class, metric) sample
  windows with p50/p90/p99 on demand.  Bounded; old samples age out of
  the slow window.
* **Two-window error-budget burn rates** — the SRE alerting shape: the
  *fast* window (default 60s) with a *high* threshold catches cliffs
  within a window or two; the *slow* window (default 600s) with a
  *low* threshold catches slow burns a short window would dismiss as
  noise.  ``burn = observed error rate ÷ (1 − objective)``: burn 1.0
  spends the budget exactly at the objective's rate, burn 10 spends a
  day's budget in ~2.4 hours.
* **Pure clocks** — every method takes ``now`` from the caller.  The
  decision-table tests drive a fake clock through breach scenarios;
  production passes the serving loop's step timestamps.  Registry
  writes happen only in :meth:`SLOPlane.publish`.

Traffic whose SLO class has no configured target is digested (the
percentiles are still worth seeing) but can never alert: untagged
traffic trips nothing by construction.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

__all__ = [
    "SLOTarget",
    "SLOPlane",
    "targets_from_spec",
    "DEFAULT_FAST_WINDOW",
    "DEFAULT_SLOW_WINDOW",
    "DEFAULT_FAST_BURN",
    "DEFAULT_SLOW_BURN",
    "DEFAULT_OBJECTIVE",
]

DEFAULT_FAST_WINDOW = 60.0
DEFAULT_SLOW_WINDOW = 600.0
# Burn thresholds: fast/high pages on cliffs (14.4 is the classic
# 1h/5m pair's threshold; 8 suits our shorter windows), slow/low warns
# on sustained overspend.
DEFAULT_FAST_BURN = 8.0
DEFAULT_SLOW_BURN = 2.0
DEFAULT_OBJECTIVE = 0.99
# Minimum samples in a window before its burn rate is trusted: one
# unlucky request must not page anybody.
MIN_WINDOW_SAMPLES = 3
# Per-series sample cap (slow-window retention is the real bound; this
# is the memory backstop under pathological request rates).
MAX_SAMPLES = 4096

_METRICS = ("ttft", "tpot")


class SLOTarget:
    """One SLO class's latency objective."""

    def __init__(self, ttft_ms: Optional[float] = None,
                 tpot_ms: Optional[float] = None,
                 objective: float = DEFAULT_OBJECTIVE):
        self.ttft_ms = float(ttft_ms) if ttft_ms else None
        self.tpot_ms = float(tpot_ms) if tpot_ms else None
        objective = float(objective)
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {objective}")
        self.objective = objective

    def threshold_ms(self, metric: str) -> Optional[float]:
        return self.ttft_ms if metric == "ttft" else self.tpot_ms

    @property
    def budget(self) -> float:
        """The error budget: the fraction of requests ALLOWED to miss."""
        return 1.0 - self.objective

    def as_dict(self) -> dict:
        out = {"objective": self.objective}
        if self.ttft_ms is not None:
            out["ttft_ms"] = self.ttft_ms
        if self.tpot_ms is not None:
            out["tpot_ms"] = self.tpot_ms
        return out

    def __repr__(self):  # pragma: no cover - debug aid
        return f"SLOTarget({self.as_dict()})"


def targets_from_spec(spec: dict) -> Dict[str, SLOTarget]:
    """``spec['slo']`` → {slo class: :class:`SLOTarget`}.  The spec form
    is ``{"interactive": {"ttft_ms": 500, "tpot_ms": 80,
    "objective": 0.99}, ...}``; classes absent from the dict carry no
    objective and never alert."""
    raw = spec.get("slo") if isinstance(spec, dict) else None
    if not isinstance(raw, dict):
        return {}
    out: Dict[str, SLOTarget] = {}
    for cls, doc in raw.items():
        if not isinstance(doc, dict):
            continue
        tgt = SLOTarget(
            ttft_ms=doc.get("ttft_ms"),
            tpot_ms=doc.get("tpot_ms"),
            objective=doc.get("objective", DEFAULT_OBJECTIVE),
        )
        if tgt.ttft_ms is not None or tgt.tpot_ms is not None:
            out[str(cls)] = tgt
    return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


class _Series:
    """One (tenant, slo, metric) sample window: (t, ms, breach)."""

    __slots__ = ("samples", "breaches_total", "alerts_total", "firing")

    def __init__(self):
        self.samples: Deque[Tuple[float, float, bool]] = deque()
        self.breaches_total = 0
        self.alerts_total = 0
        # window -> currently firing (rising-edge alert counting)
        self.firing: Dict[str, bool] = {"fast": False, "slow": False}

    def observe(self, t: float, ms: float, breach: bool,
                keep_secs: float) -> None:
        self.samples.append((t, ms, breach))
        if breach:
            self.breaches_total += 1
        cut = t - keep_secs
        while self.samples and self.samples[0][0] < cut:
            self.samples.popleft()
        while len(self.samples) > MAX_SAMPLES:
            self.samples.popleft()

    def window(self, now: float, secs: float
               ) -> Tuple[int, int]:
        """(samples, breaches) within the trailing ``secs``."""
        cut = now - secs
        n = bad = 0
        for t, _, breach in self.samples:
            if t >= cut:
                n += 1
                bad += 1 if breach else 0
        return n, bad

    def percentiles(self, now: float, secs: float) -> dict:
        cut = now - secs
        vals = sorted(ms for t, ms, _ in self.samples if t >= cut)
        return {
            "n": len(vals),
            "p50": round(_percentile(vals, 0.50), 3),
            "p90": round(_percentile(vals, 0.90), 3),
            "p99": round(_percentile(vals, 0.99), 3),
        }


class SLOPlane:
    """The per-tenant SLO accountant for one serving rank.

    Feed it every ttft/tpot observation with its (tenant, slo) tag and
    a timestamp; ask it for burn rates, firing alerts, registry gauges
    and the drain summary.  No internal clocks, no sleeps."""

    def __init__(self, targets: Dict[str, SLOTarget],
                 fast_window: float = DEFAULT_FAST_WINDOW,
                 slow_window: float = DEFAULT_SLOW_WINDOW,
                 fast_burn: float = DEFAULT_FAST_BURN,
                 slow_burn: float = DEFAULT_SLOW_BURN,
                 min_samples: int = MIN_WINDOW_SAMPLES):
        self.targets = dict(targets or {})
        self.fast_window = float(fast_window)
        self.slow_window = max(float(slow_window), self.fast_window)
        self.thresholds = {"fast": float(fast_burn),
                           "slow": float(slow_burn)}
        self.windows = {"fast": self.fast_window,
                        "slow": self.slow_window}
        self.min_samples = max(int(min_samples), 1)
        self._series: Dict[Tuple[str, str, str], _Series] = {}

    @property
    def armed(self) -> bool:
        """Whether any class carries an objective (alerting possible)."""
        return bool(self.targets)

    @property
    def observed(self) -> bool:
        """Whether any sample has ever landed (summary worth printing)."""
        return bool(self._series)

    # --------------------------------------------------------- observing

    def _observe(self, metric: str, tenant: str, slo: str, ms: float,
                 now: float) -> None:
        tgt = self.targets.get(slo)
        threshold = tgt.threshold_ms(metric) if tgt else None
        breach = threshold is not None and float(ms) > threshold
        key = (str(tenant), str(slo), metric)
        series = self._series.get(key)
        if series is None:
            series = self._series[key] = _Series()
        series.observe(float(now), float(ms), breach, self.slow_window)

    def observe_ttft(self, tenant: str, slo: str, ms: float,
                     now: float) -> None:
        self._observe("ttft", tenant, slo, ms, now)

    def observe_tpot(self, tenant: str, slo: str, ms: float,
                     now: float) -> None:
        self._observe("tpot", tenant, slo, ms, now)

    # -------------------------------------------------------- evaluating

    def burn_rates(self, now: float) -> Dict[Tuple[str, str, str],
                                             Dict[str, float]]:
        """{(tenant, slo, metric): {window: burn}} for targeted series.
        Burn is error-rate over budget; 0.0 when the window is empty."""
        out = {}
        for key, series in self._series.items():
            tgt = self.targets.get(key[1])
            if tgt is None or tgt.threshold_ms(key[2]) is None:
                continue
            burns = {}
            for win, secs in self.windows.items():
                n, bad = series.window(now, secs)
                rate = bad / n if n else 0.0
                burns[win] = rate / tgt.budget
            out[key] = burns
        return out

    def evaluate(self, now: float) -> List[dict]:
        """Advance alert state and return the CURRENTLY-FIRING alerts.
        Rising edges increment the per-series alert total — re-asserting
        a still-firing alert is not a new page."""
        alerts = []
        for key, burns in self.burn_rates(now).items():
            series = self._series[key]
            for win, burn in burns.items():
                n, _ = series.window(now, self.windows[win])
                firing = (n >= self.min_samples
                          and burn >= self.thresholds[win])
                if firing and not series.firing[win]:
                    series.alerts_total += 1
                series.firing[win] = firing
                if firing:
                    tenant, slo, metric = key
                    alerts.append({
                        "tenant": tenant,
                        "slo": slo,
                        "metric": metric,
                        "window": win,
                        "burn": round(burn, 2),
                        "threshold": self.thresholds[win],
                        "samples": n,
                    })
        return alerts

    # -------------------------------------------------------- publishing

    def publish(self, reg, now: float) -> None:
        """Land the plane in a metrics registry as ``serve.slo.*``:
        burn-rate and alert gauges per (tenant, slo, metric, window),
        breach counters, and p50/p99 digests per series."""
        alerts = self.evaluate(now)
        firing = {(a["tenant"], a["slo"], a["metric"], a["window"])
                  for a in alerts}
        burns = self.burn_rates(now)
        for key, series in sorted(self._series.items()):
            tenant, slo, metric = key
            tags = {"tenant": tenant, "slo": slo, "metric": metric}
            pct = series.percentiles(now, self.slow_window)
            reg.gauge("serve.slo.p50_ms", **tags).set(pct["p50"])
            reg.gauge("serve.slo.p99_ms", **tags).set(pct["p99"])
            if key not in burns:
                continue  # undigested objective: no target, no alerting
            for win, burn in burns[key].items():
                reg.gauge("serve.slo.burn", window=win, **tags).set(
                    round(burn, 3))
                reg.gauge("serve.slo.alert", window=win, **tags).set(
                    1.0 if key + (win,) in firing else 0.0)
            breach_c = reg.counter("serve.slo.breaches", **tags)
            delta = series.breaches_total - int(breach_c.value)
            if delta > 0:
                breach_c.inc(delta)
            alert_c = reg.counter("serve.slo.alerts", **tags)
            delta = series.alerts_total - int(alert_c.value)
            if delta > 0:
                alert_c.inc(delta)

    def summary(self, now: float) -> dict:
        """The drain / ``--stats-summary`` document."""
        out: Dict[str, dict] = {}
        burns = self.burn_rates(now)
        for key, series in sorted(self._series.items()):
            tenant, slo, metric = key
            doc = series.percentiles(now, self.slow_window)
            doc["breaches"] = series.breaches_total
            if key in burns:
                doc["burn_fast"] = round(burns[key]["fast"], 2)
                doc["burn_slow"] = round(burns[key]["slow"], 2)
                doc["alerts"] = series.alerts_total
                doc["firing"] = any(series.firing.values())
            out.setdefault(f"{tenant}/{slo}", {})[metric] = doc
        return out
