"""Cross-rank divergence sentinel: the HVD001 invariant, verified at
runtime.

The repo's load-bearing invariant — "every rank derives the bitwise-
identical schedule/state" — is enforced statically by the PR-5/PR-12
lint and pinned by tests, but nothing watches the *running* job: data
skew, a nondeterministic kernel, or silent data corruption (an SDC bit
flip that survives the allreduce) can break bitwise replication
invisibly for thousands of steps, until a checkpoint poisons every
future restart.  This module is the runtime half of that proof
(O'Hearn's continuous-reasoning thesis, PAPERS.md: the property the
analyzer proves about the source, an always-on sentinel keeps proving
about the process).

Design:

* **Digest algebra** (:func:`bit_words`, :func:`digest_words`) — arrays
  are *bit-reinterpreted* into a uint32 word stream (one zero-extended
  word per element; float64 splits into lo/hi words) and folded through
  two independent position-mixed multiply-XOR lanes.  Equality of bit
  patterns ⟺ equality of digests for any single-site difference (odd
  multipliers are bijections mod 2^32), so the adversarial float pairs
  value-comparison would wave through — ``+0.0`` vs ``-0.0``, NaNs with
  different payloads, denormals — all produce distinct digests, and
  bitwise-identical state always digests identically.  The same algebra
  is implementable in-graph (:func:`jit_digest`) via
  ``lax.bitcast_convert_type``, byte-for-byte equal to the host path.

* **Per-bucket digest vector** (:func:`tree_digest_vector`) — params
  digest per overlap bucket (reusing ``optim/overlap.py``'s
  :class:`~..optim.overlap.BucketLayout`, the same deterministic
  grouping every rank already derives), plus one digest each for the
  optimizer state and the replicated PRNG key.  A mismatch therefore
  localizes to a component and a bucket from the FIRST exchange.

* **:class:`DivergenceSentinel`** — every ``--health-check-steps`` N,
  allgathers the tiny digest vector over the engine, compares all rows,
  and on mismatch names the minority-partition ranks, then descends:
  a second (equally tiny) exchange of the divergent bucket's per-leaf
  digests names the first divergent leaf.  Every rank runs the same
  comparison on the same gathered matrix, so every rank reaches the
  identical verdict and the identical ``--divergence-action`` — the
  sentinel obeys the very invariant it checks.

Cost, stated honestly: one ~(2·buckets+4)-word allgather every N steps.
Through the eager engine that is one extra negotiated collective per
check, which also breaks the schedule-replay epoch for ~2 cycles
(runtime/engine.py) — at the default N=100 that is noise; at N=1 it
would halve the replay skip rate.  See docs/health.md.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import get_logger

LOG = get_logger("obs.divergence")

__all__ = [
    "bit_words",
    "digest_words",
    "digest_array",
    "digest_leaves",
    "blob_digest",
    "tree_digest_vector",
    "leaf_digest_matrix",
    "jit_digest",
    "page_state_digest",
    "serve_state_digest",
    "DivergenceReport",
    "DivergenceHalt",
    "DivergenceSentinel",
    "ACTIONS",
]

ACTIONS = ("warn", "dump", "halt")

# Two independent mix lanes: (index stride, odd multiplier, seed).
# Odd multipliers are bijections mod 2^32, so a word that differs at one
# position always changes that lane's XOR fold; two lanes make a
# cross-position cancellation require a simultaneous collision in both.
_LANES = (
    (np.uint32(0x9E3779B9), np.uint32(0x85EBCA6B), np.uint32(0x02E1B213)),
    (np.uint32(0xC2B2AE35), np.uint32(0x27D4EB2F), np.uint32(0x165667B1)),
)
DIGEST_WIDTH = len(_LANES)  # uint32 words per digest


class DivergenceHalt(RuntimeError):
    """Raised on every rank when ``--divergence-action halt`` fires."""


def bit_words(arr) -> np.ndarray:
    """The canonical uint32 word stream of an array's BIT PATTERN: one
    zero-extended word per element for itemsize <= 4, two (lo, hi) words
    per element for itemsize 8.  Per-element (not a raw byte stream) so
    the identical stream is cheap to produce in-graph, where
    ``bitcast_convert_type`` yields one integer per element."""
    a = np.ascontiguousarray(arr)
    size = a.dtype.itemsize
    if size == 1:
        return a.view(np.uint8).ravel().astype(np.uint32)
    if size == 2:
        return a.view(np.uint16).ravel().astype(np.uint32)
    if size == 4:
        return a.view(np.uint32).ravel().copy()
    if size == 8:
        w = a.view(np.uint64).ravel()
        out = np.empty(w.size * 2, dtype=np.uint32)
        out[0::2] = (w & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        out[1::2] = (w >> np.uint64(32)).astype(np.uint32)
        return out
    raise TypeError(f"no bit_words rule for itemsize {size} ({a.dtype})")


def digest_words(words: np.ndarray) -> np.ndarray:
    """Fold a uint32 word stream into the ``(DIGEST_WIDTH,)`` digest.
    Length is mixed in, so a zero-padded stream never digests equal to
    its unpadded prefix."""
    w = np.asarray(words, dtype=np.uint32)
    n = np.uint32(w.size)
    idx = np.arange(w.size, dtype=np.uint32)
    out = np.empty(DIGEST_WIDTH, dtype=np.uint32)
    for lane, (c, m, seed) in enumerate(_LANES):
        if w.size:
            mixed = np.multiply(
                np.bitwise_xor(w, np.multiply(idx, c, dtype=np.uint32)
                               + seed),
                m, dtype=np.uint32,
            )
            acc = np.bitwise_xor.reduce(mixed)
        else:
            acc = np.uint32(0)
        length_mix = np.uint32((int(n) * int(m) + int(c)) & 0xFFFFFFFF)
        out[lane] = np.bitwise_xor(acc, length_mix)
    return out


def digest_array(arr) -> np.ndarray:
    """Digest of one array's bit pattern (host side)."""
    return digest_words(bit_words(arr))


def digest_leaves(leaves: Sequence) -> np.ndarray:
    """Digest of several arrays' concatenated word streams — the
    per-bucket digest is over the bucket's leaves in bucket order, the
    same concatenation order ``_bucket_concat`` fuses gradients in."""
    if not leaves:
        return digest_words(np.empty(0, dtype=np.uint32))
    return digest_words(np.concatenate([bit_words(l) for l in leaves]))


def blob_digest(raw: bytes) -> np.ndarray:
    """Digest of an opaque byte payload (zero-padded to whole words) —
    the serving twin's schedule-doc digest."""
    buf = np.frombuffer(raw, dtype=np.uint8)
    pad = (-buf.size) % 4
    if pad:
        buf = np.concatenate([buf, np.zeros(pad, dtype=np.uint8)])
    return digest_words(buf.view(np.uint32))


# ---------------------------------------------------------------------------
# pytree / bucket digests
# ---------------------------------------------------------------------------


def tree_digest_vector(leaves: Sequence, layout,
                       extras: Sequence[Tuple[str, Sequence]] = ()
                       ) -> Tuple[np.ndarray, List[str]]:
    """The exchange vector: per-bucket digests of ``leaves`` (flattened
    params, in ``layout``'s flatten order) followed by one digest per
    named extra component (optimizer state, PRNG key, ...).  Returns
    ``(uint32 vector, component names)`` where component ``i`` owns
    words ``[i*DIGEST_WIDTH, (i+1)*DIGEST_WIDTH)`` — the first
    mismatching word indexes straight into a component."""
    parts: List[np.ndarray] = []
    names: List[str] = []
    for b in layout.buckets:
        parts.append(digest_leaves([np.asarray(leaves[i])
                                    for i in b.leaf_indices]))
        names.append(f"bucket{b.index}")
    for name, arrs in extras:
        parts.append(digest_leaves([np.asarray(a) for a in arrs]))
        names.append(name)
    return np.concatenate(parts), names


def leaf_digest_matrix(leaves: Sequence, bucket) -> np.ndarray:
    """Per-leaf digests of one bucket, shape ``(n_leaves,
    DIGEST_WIDTH)`` — the descent exchange that turns "bucket 3
    diverged" into "leaf mlp/kernel diverged"."""
    return np.stack([digest_array(np.asarray(leaves[i]))
                     for i in bucket.leaf_indices])


def jit_digest(layout):
    """Compile the IN-GRAPH digest: a jitted function mapping the
    params' flat leaves to the ``(n_buckets, DIGEST_WIDTH)`` uint32
    digest matrix, byte-for-byte equal to :func:`tree_digest_vector`'s
    bucket prefix.  Runs on device — the host fetches 8 bytes per
    bucket instead of the parameters themselves."""
    import jax  # noqa: PLC0415
    import jax.numpy as jnp  # noqa: PLC0415
    from jax import lax  # noqa: PLC0415

    def words_of(leaf):
        size = jnp.dtype(leaf.dtype).itemsize
        if size == 2:
            return lax.bitcast_convert_type(
                leaf, jnp.uint16).ravel().astype(jnp.uint32)
        if size == 4:
            return lax.bitcast_convert_type(leaf, jnp.uint32).ravel()
        raise TypeError(
            f"no in-graph bit_words rule for itemsize {size} "
            f"({leaf.dtype}); use the host digest"
        )

    def one_lane(w, c, m, seed):
        n = w.shape[0]
        idx = jnp.arange(n, dtype=jnp.uint32)
        if n:
            mixed = (w ^ (idx * c + seed)) * m
            acc = lax.reduce(mixed, jnp.uint32(0),
                             lambda a, b: lax.bitwise_xor(a, b), (0,))
        else:
            acc = jnp.uint32(0)
        return acc ^ (jnp.uint32(n) * m + c)

    def digests(*leaves):
        rows = []
        for b in layout.buckets:
            w = jnp.concatenate(
                [words_of(leaves[i]) for i in b.leaf_indices]
            )
            rows.append(jnp.stack([
                one_lane(w, jnp.uint32(c), jnp.uint32(m), jnp.uint32(s))
                for (c, m, s) in _LANES
            ]))
        return jnp.stack(rows)

    return jax.jit(digests)


# ---------------------------------------------------------------------------
# the sentinel
# ---------------------------------------------------------------------------


@dataclass
class DivergenceReport:
    """One confirmed divergence, fully localized."""

    step: int
    component: str               # "bucket<i>" | "opt_state" | "prng"
    bucket: Optional[int]        # set when the component is a bucket
    leaf_index: Optional[int]    # flatten-order leaf position
    leaf_name: Optional[str]
    minority_ranks: Tuple[int, ...] = ()
    majority_ranks: Tuple[int, ...] = ()
    detail: str = field(default="", compare=False)

    def describe(self) -> str:
        where = self.component
        if self.leaf_name is not None:
            where += f" (leaf {self.leaf_name})"
        ranks = ",".join(str(r) for r in self.minority_ranks)
        return (f"rank(s) {ranks} diverged from the majority at step "
                f"{self.step} in {where}")


def _default_exchange(vec: np.ndarray, name: str) -> np.ndarray:
    """Allgather over the eager engine.  The engine's dtype table has
    no uint32 entry, so the digest words ride as int32 bit patterns —
    a pure reinterpretation, gathered bytes come back untouched."""
    from ..ops import eager  # noqa: PLC0415

    wire = np.ascontiguousarray(vec).view(np.int32)
    return np.asarray(eager.allgather(wire, name=name)).view(np.uint32)


class DivergenceSentinel:
    """Periodic cross-rank digest compare over a bucket layout.

    ``exchange(vec, name) -> (world * len(vec),)`` is injectable so the
    decision logic is testable without an engine; the default is the
    eager ``hvd.allgather``.  Every rank must call :meth:`maybe_check`
    at the same steps with the same component set — the check is itself
    a collective, and the HVD001 rule applies to it like any other.
    """

    def __init__(
        self,
        layout,
        *,
        rank: int,
        check_steps: int = 100,
        action: str = "warn",
        exchange: Optional[Callable[[np.ndarray, str], np.ndarray]] = None,
        leaf_names: Optional[Sequence[str]] = None,
        registry=None,
    ):
        if action not in ACTIONS:
            raise ValueError(
                f"divergence action must be one of {ACTIONS}, got "
                f"{action!r}"
            )
        if check_steps < 1:
            raise ValueError(f"check_steps must be >= 1, got {check_steps}")
        self.layout = layout
        self.rank = int(rank)
        self.check_steps = int(check_steps)
        self.action = action
        self.exchange = exchange or _default_exchange
        self.leaf_names = list(leaf_names) if leaf_names else None
        if registry is None:
            from .registry import get_registry  # noqa: PLC0415

            registry = get_registry()
        self._reg = registry
        self.checks = 0
        self.detections = 0

    # ----------------------------------------------------------- checks

    def maybe_check(self, step: int, leaves: Sequence, *,
                    opt_leaves: Optional[Sequence] = None,
                    prng_key=None) -> Optional[DivergenceReport]:
        """Run :meth:`check` when ``step`` lands on the cadence.  All
        ranks share the cadence arithmetic, so either every rank
        exchanges or none does."""
        if step % self.check_steps != 0:
            return None
        return self.check(step, leaves, opt_leaves=opt_leaves,
                          prng_key=prng_key)

    def check(self, step: int, leaves: Sequence, *,
              opt_leaves: Optional[Sequence] = None,
              prng_key=None) -> Optional[DivergenceReport]:
        extras: List[Tuple[str, Sequence]] = []
        if opt_leaves is not None:
            extras.append(("opt_state", list(opt_leaves)))
        if prng_key is not None:
            extras.append(("prng", [np.asarray(prng_key)]))
        vec, components = tree_digest_vector(leaves, self.layout,
                                             extras=extras)
        mat = self._gather(vec, f"health.digest.s{step}")
        self.checks += 1
        self._reg.counter("health.divergence.checks").inc()
        self._reg.gauge("health.divergence.last_check_step").set(step)
        if bool((mat == mat[0]).all()):
            self._reg.gauge("health.divergence.alert").set(0)
            return None
        report = self._localize(step, mat, components, leaves)
        self._record(report)
        self._act(report)
        return report

    def _gather(self, vec: np.ndarray, name: str) -> np.ndarray:
        flat = np.asarray(self.exchange(vec, name), dtype=np.uint32)
        world = flat.size // vec.size
        if world * vec.size != flat.size:
            raise ValueError(
                f"digest exchange returned {flat.size} words for a "
                f"{vec.size}-word vector — ragged gather?"
            )
        return flat.reshape(world, vec.size)

    # ------------------------------------------------------ localization

    def _localize(self, step: int, mat: np.ndarray,
                  components: List[str],
                  leaves: Sequence) -> DivergenceReport:
        minority, majority = _partition(mat)
        bad_cols = np.nonzero((mat != mat[majority[0]]).any(axis=0))[0]
        comp_index = int(bad_cols[0]) // DIGEST_WIDTH
        component = components[comp_index]
        bucket = leaf_index = None
        leaf_name = None
        if component.startswith("bucket"):
            bucket = int(component[len("bucket"):])
            leaf_index, leaf_name = self._descend(step, bucket, leaves)
        return DivergenceReport(
            step=int(step),
            component=component,
            bucket=bucket,
            leaf_index=leaf_index,
            leaf_name=leaf_name,
            minority_ranks=tuple(minority),
            majority_ranks=tuple(majority),
        )

    def _descend(self, step: int, bucket_index: int, leaves: Sequence):
        """Second-phase exchange: the divergent bucket's per-leaf
        digests.  Deterministic on every rank (all ranks saw the same
        gathered matrix, so all reach this call or none do)."""
        bucket = self.layout.buckets[bucket_index]
        local = leaf_digest_matrix(leaves, bucket).ravel()
        mat = self._gather(local,
                           f"health.digest.b{bucket_index}.s{step}")
        _, majority = _partition(mat)
        bad = np.nonzero((mat != mat[majority[0]]).any(axis=0))[0]
        if not bad.size:  # raced a repair; keep the bucket verdict
            return None, None
        pos = int(bad[0]) // DIGEST_WIDTH
        leaf_index = bucket.leaf_indices[pos]
        name = (self.leaf_names[leaf_index]
                if self.leaf_names and leaf_index < len(self.leaf_names)
                else f"leaf{leaf_index}")
        return leaf_index, name

    # ----------------------------------------------------------- verdict

    def _record(self, report: DivergenceReport) -> None:
        self.detections += 1
        minority = ",".join(str(r) for r in report.minority_ranks)
        detail = (f"step={report.step} minority={minority} "
                  f"component={report.component}")
        if report.bucket is not None:
            detail += f" bucket={report.bucket}"
        if report.leaf_name is not None:
            detail += f" leaf={report.leaf_name}"
        report.detail = detail
        tags = {"component": report.component}
        if report.leaf_name is not None:
            tags["leaf"] = report.leaf_name
        self._reg.counter("health.divergence.detected", **tags).inc()
        self._reg.gauge("health.divergence.alert").set(1)
        from . import flightrec  # noqa: PLC0415

        flightrec.record("health.divergence", name=report.component,
                         cycle=report.step, detail=detail)
        LOG.error("HVD001 runtime violation: %s", report.describe())

    def _act(self, report: DivergenceReport) -> None:
        if self.action == "warn":
            return
        if self.action == "dump":
            # Leave the evidence NOW: the poisoned state may kill the
            # job (or worse, checkpoint) before any death-path dump.
            from . import flightrec  # noqa: PLC0415
            from .registry import dump_metrics  # noqa: PLC0415

            try:
                flightrec.dump_flight_recorder(trigger="health.divergence")
            except Exception:  # pragma: no cover - defensive
                pass
            try:
                dump_metrics()
            except Exception:  # pragma: no cover - defensive
                pass
            return
        raise DivergenceHalt(
            f"divergence sentinel: {report.describe()} "
            f"(--divergence-action halt)"
        )


def _partition(mat: np.ndarray) -> Tuple[List[int], List[int]]:
    """Split ranks into (minority, majority) by digest-row pattern.
    Majority = the most common row; ties break toward the pattern of
    the lowest rank holding it, so every rank (and every rerun) names
    the same culprit."""
    rows = [tuple(int(x) for x in mat[r]) for r in range(mat.shape[0])]
    counts: dict = {}
    for row in rows:
        counts[row] = counts.get(row, 0) + 1
    best = max(counts.items(),
               key=lambda kv: (kv[1], -rows.index(kv[0])))[0]
    majority = [r for r, row in enumerate(rows) if row == best]
    minority = [r for r, row in enumerate(rows) if row != best]
    return minority, majority


# ---------------------------------------------------------------------------
# serving twin
# ---------------------------------------------------------------------------


def page_state_digest(paged) -> np.ndarray:
    """Digest of a :class:`~..serve.paged.PagedKV` pool's observable
    state: every slot's block-table row + position, plus the free list
    (sorted — the heap's internal order is arrival-dependent, the SET
    of free pages is the invariant)."""
    if paged is None:
        return digest_words(np.empty(0, dtype=np.uint32))
    rows: List[List[int]] = [list(paged.table(s)) + [paged.position(s)]
                             for s in range(paged.num_slots)]
    flat = [x for row in rows for x in row] + sorted(paged._free)
    return digest_array(np.asarray(flat, dtype=np.int32))


def serve_state_digest(sdoc_raw: bytes, paged) -> np.ndarray:
    """The serving twin's per-check digest: broadcast schedule doc
    bytes + page-table state, concatenated.  Replicated ranks of a
    width group must produce identical values every step — the serving
    form of HVD001."""
    return np.concatenate([blob_digest(sdoc_raw),
                           page_state_digest(paged)])
