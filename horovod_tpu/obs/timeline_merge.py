"""Merge per-rank Chrome-trace timelines into one all-rank trace.

Every rank now records its own timeline (runtime/timeline.py; the
reference records rank 0 only, timeline.cc).  The per-rank writers use
the streaming-tolerant trace format — ``[`` then one comma-terminated
event per line, no required ``]`` — so a rank killed mid-job (elastic
respawn, OOM) still leaves a loadable trace.  This module repairs and
merges those files into a single *valid-JSON* Chrome trace with one
``pid`` lane per rank, which is where cross-rank negotiation skew first
becomes visible: the same tensor's NEGOTIATE bar on every lane, start
offsets = straggler ranks.

Used by the launcher at job end (run/runner.py) and directly::

    python -m horovod_tpu.obs.timeline_merge out.json rank0.json rank1.json
"""

from __future__ import annotations

import glob
import json
import os
from typing import List, Optional, Sequence, Tuple

from . import pathspec
from .pathspec import epoch_of_path, rank_of_path

__all__ = ["load_events", "merge", "merge_glob", "rank_of_path"]

# Lane id for incarnation (rank, epoch): epochs beyond the first get
# their own pid lane — two processes' perf_counter-relative timestamps
# both start near 0, so sharing a lane would garble the bars.
_EPOCH_LANE_STRIDE = 100000


def load_events(path: str) -> List[dict]:
    """Load one timeline file, tolerating truncation.

    Accepts well-formed arrays (the native engine still closes its
    ``]``), the streaming format (trailing comma, no terminator), and a
    file cut mid-event by a kill — the trailing partial line is dropped,
    everything before it survives.  Non-dict and empty entries are
    filtered out.
    """
    with open(path) as f:
        text = f.read().strip()
    if not text:
        return []
    events: Optional[list] = None
    try:
        events = json.loads(text)
    except ValueError:
        body = text.lstrip("[").rstrip().rstrip(",")
        while body:
            try:
                events = json.loads(f"[{body}]")
                break
            except ValueError:
                # drop the last (possibly half-written) event line and
                # retry; bounded by the number of newlines in the file
                cut = body.rfind("\n")
                if cut < 0:
                    events = []
                    break
                body = body[:cut].rstrip().rstrip(",")
    if not isinstance(events, list):
        return []
    return [e for e in events if isinstance(e, dict) and e]


def merge(paths: Sequence[str], out_path: str) -> int:
    """Merge per-rank trace files into one valid Chrome trace at
    ``out_path``; returns the number of events written.

    Each incarnation gets its own ``pid`` lane — ``rank`` for the first
    epoch, a distinct id for later (elastic respawn) incarnations, since
    every process's timestamps restart near zero and sharing a lane
    would overlay the two lifetimes.  ``process_name`` metadata events
    label the lanes.  Ordering is preserved per file; Chrome/Perfetto
    sort by ``ts`` internally.
    """
    merged: List[dict] = []
    lanes: List[Tuple[int, str]] = []
    for path in sorted(paths):
        events = load_events(path)
        if not events:
            continue
        path_rank = rank_of_path(path)
        epoch = epoch_of_path(path) or 0
        lane = (path_rank if path_rank is not None else 0)
        lane += epoch * _EPOCH_LANE_STRIDE
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "trace_complete":
                continue  # writer terminator, not a lane event
            if path_rank is not None:
                ev["pid"] = lane
            merged.append(ev)
        label = f"rank {path_rank if path_rank is not None else 0}"
        if epoch:
            label += f" (epoch {epoch})"
        lanes.append((lane, label))
    meta = [
        {"ph": "M", "name": "process_name", "pid": lane, "tid": 0,
         "args": {"name": label}}
        for lane, label in sorted(set(lanes))
    ]
    pathspec.write_json_atomic(out_path, meta + merged, indent=None)
    return len(merged)


def per_rank_glob(raw: str) -> str:
    """The glob matching every per-rank file the writers derive from a
    ``HVDTPU_TIMELINE`` value (same rules module as resolve_path)."""
    return pathspec.glob_pattern(raw, "trace")


def merged_output_path(raw: str) -> str:
    """Where the launcher writes the merged trace: the raw path itself
    for the plain-file form (so ``--timeline-filename t.json`` still
    ends with ``t.json``, now holding every rank), ``merged.json``
    inside the directory form, and ``<template>.merged.json`` for
    templates."""
    if "{rank}" in raw:
        base, ext = os.path.splitext(raw.replace("{rank}", "merged"))
        return f"{base}{ext or '.json'}"
    if raw.endswith(os.sep) or os.path.isdir(raw):
        return os.path.join(raw, "merged.json")
    return raw


def merge_glob(raw: str, out_path: Optional[str] = None) -> Optional[str]:
    """Merge every per-rank file derived from the ``HVDTPU_TIMELINE``
    value ``raw``; returns the merged path, or None when no per-rank
    files exist (e.g. remote-only ranks)."""
    out = out_path or merged_output_path(raw)
    paths = [p for p in glob.glob(per_rank_glob(raw))
             if os.path.abspath(p) != os.path.abspath(out)]
    if not paths:
        return None
    merge(paths, out)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 2:
        print("usage: python -m horovod_tpu.obs.timeline_merge "
              "OUT.json RANK_FILE [RANK_FILE ...]", file=sys.stderr)
        return 2
    n = merge(argv[1:], argv[0])
    print(f"merged {n} events from {len(argv) - 1} files into {argv[0]}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
