"""HBM memory plane: per-owner device-memory accounting, compiled
per-program breakdowns, and the OOM black box.

The observability stack explains where every millisecond (obs/trace.py)
and every FLOP (obs/profile.py) goes — this module is the missing
*byte* axis, with three legs:

* **Static accounting** — :func:`parse_memory_analysis` reads XLA's own
  post-compile memory breakdown (``compiled.memory_analysis()``:
  argument / output / temp / alias bytes) version-tolerantly, the way
  ``shard_map_compat`` tolerates interpreter drift: the attribute-object
  form (jax 0.4.x), a dict form, a single-element-list form, and an
  interpreter that exposes nothing at all (``source: unavailable`` —
  never a crash).  :func:`register_program` publishes one breakdown per
  compiled program as ``mem.compiled.*{program=…}`` gauges; the compile
  sites (engine fused allreduce, the overlap train step per mode, the
  slot engine's decode/assign) call it with the executable they just
  built, so per-program memory is a property of the artifact — the
  GSPMD argument: memory scaling is *why* sharding exists, so it must
  be measured per program.
* **Dynamic census** — :func:`census` buckets ``jax.live_arrays()`` by
  logical owner through a lightweight tagging registry
  (:func:`register_owner`: params / optimizer_state / grad_buckets /
  kv_cache suppliers; everything unclaimed is ``other``) and reads the
  backend's ``memory_stats()`` (bytes_in_use / peak / limit —
  None-tolerant: CPU reports nothing and the census says so instead of
  inventing an HBM).  Published as ``mem.{hbm_bytes_in_use,
  hbm_peak_bytes,hbm_limit_bytes,headroom_bytes,live_bytes}`` +
  ``mem.owner_bytes{owner=…}`` gauges; :func:`install_census` arms it
  as a registry collector so every snapshot (the live stream, the exit
  dump, a BENCH record) refreshes the numbers for free.  The census is
  host-triggered: it sees the arrays alive *between* dispatches, not
  XLA's transient peak (docs/observability.md states this honestly).
* **OOM black box** — :func:`maybe_record_oom` (hooked into
  ``flightrec.record_exception``, so it fires on every death path that
  records its exception) detects a RESOURCE_EXHAUSTED and drops a
  ``mem.oom`` event carrying the last census and the dominant owner
  into the flight-recorder ring — the PyTorch-flight-recorder idea
  applied to memory: always-on bounded evidence that survives the
  crash, so the post-mortem can say "rank 3 died allocating in
  decode_step; kv_cache held 82% of tagged memory" instead of "OOM
  somewhere".  :func:`alloc_guard` is the ``mem_alloc`` fault point's
  consumer (``action=oom`` raises a backend-shaped RESOURCE_EXHAUSTED)
  so the whole path is deterministically chaos-testable.

KV occupancy (:func:`kv_occupancy`) is the pure math behind
``serve.kv.{allocated_bytes,live_bytes,waste_ratio}``: what the
contiguous fixed-row slot pool reserves for its busy slots vs the
positions actually written — the exact number ROADMAP item 1's paged
attention will attack, measured before it lands so its win is provable.

No jax import at module scope: the launcher imports obs eagerly and
must not pay (or hang on) a backend handshake for it.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "OWNERS",
    "parse_memory_analysis",
    "register_program",
    "program_report",
    "reset_programs",
    "register_owner",
    "reset_owners",
    "census",
    "last_census",
    "install_census",
    "device_memory_stats",
    "dominant_owner",
    "kv_occupancy",
    "memory_record",
    "is_resource_exhausted",
    "resource_exhausted_error",
    "alloc_guard",
    "maybe_record_oom",
    "record_oom",
]

# The owner taxonomy.  Free-form owners are accepted (a future subsystem
# can tag itself without touching this module) but the canonical five
# are what the docs, the digest and the post-mortem verdict talk about.
OWNERS = ("params", "optimizer_state", "grad_buckets", "kv_cache", "other")

# -- module state ------------------------------------------------------------
# REENTRANT locks: record_oom() runs from flightrec.record_exception,
# which excepthook/fatal-signal handlers call — a signal landing while
# the owning thread is mid-census must not self-deadlock the dying rank
# (hvdtpu-lint HVDC103, the PR-4 flush-deadlock class).
_lock = threading.RLock()
_owners: Dict[str, List[Callable]] = {}
_programs: Dict[str, dict] = {}
_last_census: Optional[dict] = None
_census_installed = False


# ---------------------------------------------------------------------------
# static accounting: compiled.memory_analysis()
# ---------------------------------------------------------------------------

# (breakdown key, memory_analysis attribute/dict key) pairs.
_MA_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("temp_bytes", "temp_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
)


def parse_memory_analysis(compiled) -> dict:
    """Version-tolerant read of ``compiled.memory_analysis()``.

    Returns ``{"source": "memory_analysis", "argument_bytes": …,
    "output_bytes": …, "temp_bytes": …, "alias_bytes": …,
    "generated_code_bytes": …, "total_bytes": …}`` where
    ``total_bytes`` is the per-device footprint XLA accounts for one
    execution: arguments + outputs + temporaries, minus the aliased
    (donated) bytes that are counted on both sides.

    Tolerates every per-version shape: the ``CompiledMemoryStats``
    attribute object (jax 0.4.x), a plain dict, a single-element list
    of either, and an executable that exposes no analysis at all —
    those degrade to ``{"source": "unavailable"}``, never an exception
    (the ``flops_from_compiled`` contract, applied to bytes).
    """
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {"source": "unavailable"}
    if isinstance(ma, (list, tuple)):
        ma = ma[0] if ma else None
    if ma is None:
        return {"source": "unavailable"}
    out = {"source": "memory_analysis"}
    seen_any = False
    for key, field in _MA_FIELDS:
        if isinstance(ma, dict):
            v = ma.get(field)
        else:
            v = getattr(ma, field, None)
        try:
            v = int(v)
        except (TypeError, ValueError):
            v = None
        if v is not None:
            seen_any = True
            out[key] = v
        else:
            out[key] = 0
    if not seen_any:
        return {"source": "unavailable"}
    out["total_bytes"] = max(
        out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
        - out["alias_bytes"], 0,
    )
    return out


def register_program(name: str, compiled=None, *, stats: Optional[dict] = None,
                     registry=None) -> dict:
    """Record one compiled program's memory breakdown and publish it as
    ``mem.compiled.*{program=name}`` gauges.  Call with the executable
    at the compile site (``stats=`` accepts a pre-parsed breakdown — the
    mem gate reuses it).  Re-registration overwrites: a recompile's
    numbers are the current truth.  Never raises — accounting is
    observability, not correctness."""
    try:
        if stats is None:
            stats = parse_memory_analysis(compiled)
        with _lock:
            _programs[name] = dict(stats)
        if stats.get("source") != "memory_analysis":
            return stats
        from .registry import get_registry  # noqa: PLC0415

        reg = registry if registry is not None else get_registry()
        for key, _ in _MA_FIELDS:
            reg.gauge(f"mem.compiled.{key}", program=name).set(
                stats.get(key, 0)
            )
        reg.gauge("mem.compiled.total_bytes", program=name).set(
            stats.get("total_bytes", 0)
        )
        return stats
    except Exception:
        return stats if isinstance(stats, dict) else {"source": "unavailable"}


def program_report() -> Dict[str, dict]:
    """``{program name -> breakdown}`` of everything registered so far
    (what BENCH records embed)."""
    with _lock:
        return {k: dict(v) for k, v in _programs.items()}


def reset_programs() -> None:
    """Drop registered program breakdowns (tests)."""
    with _lock:
        _programs.clear()


# ---------------------------------------------------------------------------
# dynamic census: owner tagging + jax.live_arrays + backend memory_stats
# ---------------------------------------------------------------------------


def register_owner(owner: str, supplier: Callable) -> None:
    """Tag a logical owner of device memory.  ``supplier`` is called at
    census time and returns the owner's CURRENT pytree (or None when
    the owner is gone — dead suppliers are pruned, so register through
    a weakref when the owner's lifetime is shorter than the process:
    ``register_owner("kv_cache", lambda r=weakref.ref(e): (r() or
    _G).cache)``-style).  Suppliers must be cheap: they run on every
    registry snapshot once :func:`install_census` armed the plane."""
    with _lock:
        _owners.setdefault(owner, []).append(supplier)


def reset_owners() -> None:
    """Drop every owner supplier (tests, or a full plane re-arm)."""
    with _lock:
        _owners.clear()


def _device_nbytes(leaf) -> Optional[int]:
    """Bytes this PROCESS's devices hold for one array leaf, computed
    from sharding METADATA only (``sharding.shard_shape`` x addressable
    device count) — a globally-sharded ZeRO buffer counts its local
    1/world, a replicated array counts one logical copy.  Deliberately
    never touches ``addressable_shards[...].data``: reading it mints a
    NEW live jax.Array view over the same buffer, which would make the
    census itself inflate the very ``jax.live_arrays()`` population it
    measures.  None for non-array leaves."""
    n = getattr(leaf, "nbytes", None)
    if n is None:
        return None
    try:
        n = int(n)
    except (TypeError, ValueError):
        return None
    sharding = getattr(leaf, "sharding", None)
    try:
        if sharding is not None and not getattr(
                leaf, "is_fully_replicated", True):
            shard_shape = sharding.shard_shape(leaf.shape)
            count = 1
            for dim in shard_shape:
                count *= int(dim)
            return count * leaf.dtype.itemsize \
                * max(len(sharding.addressable_devices), 1)
    except Exception:
        pass
    return n


def _buffer_key(arr):
    """Identity of an array's underlying device buffer: two jax.Array
    OBJECTS can wrap one buffer (``addressable_shards[...].data`` views,
    ``device_plane._local`` extraction), and counting both would
    double-book the bytes.  Falls back to object identity where the
    pointer is unavailable (multi-device sharded arrays)."""
    try:
        return ("ptr", arr.unsafe_buffer_pointer())
    except Exception:
        return ("id", id(arr))


def device_memory_stats() -> dict:
    """Backend memory stats summed over this process's local devices.
    ``{"source": "memory_stats", "bytes_in_use", "peak_bytes",
    "limit_bytes", "headroom_bytes"}`` — or ``{"source":
    "unavailable"}`` when no device reports (CPU returns None: there is
    no HBM, and pretending host RAM were one would poison every budget
    downstream)."""
    try:
        import jax  # noqa: PLC0415

        devices = jax.local_devices()
    except Exception:
        return {"source": "unavailable"}
    in_use = peak = limit = 0
    seen = False
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        seen = True
        in_use += int(ms.get("bytes_in_use", 0) or 0)
        peak += int(ms.get("peak_bytes_in_use", 0) or 0)
        limit += int(ms.get("bytes_limit", 0) or 0)
    if not seen:
        return {"source": "unavailable"}
    out = {
        "source": "memory_stats",
        "bytes_in_use": in_use,
        "peak_bytes": peak,
        "limit_bytes": limit or None,
    }
    out["headroom_bytes"] = (limit - in_use) if limit else None
    return out


def census(*, publish: bool = True, registry=None) -> dict:
    """One owner-bucketed pass over the live device arrays plus the
    backend stats.  Returns (and caches as :func:`last_census`)::

        {"source": "live_arrays" | "unavailable",
         "total_bytes": <sum of live array bytes on this process>,
         "owners": {"params": …, "kv_cache": …, …, "other": …},
         "device": <device_memory_stats()>}

    ``publish=True`` additionally sets the ``mem.*`` gauges.  Owner
    attribution is by object identity: a supplier's leaves ARE the live
    arrays (same Python objects), so no bytes are double-counted and
    everything untagged lands in ``other``."""
    global _last_census
    with _lock:
        suppliers = [(owner, list(fns)) for owner, fns in _owners.items()]
    owners: Dict[str, int] = {}
    claimed: Dict[Tuple, str] = {}
    dead: List[Tuple[str, Callable]] = []
    for owner, fns in suppliers:
        total = 0
        for fn in fns:
            try:
                tree = fn()
            except Exception:
                tree = None
            if tree is None:
                dead.append((owner, fn))
                continue
            try:
                import jax  # noqa: PLC0415

                leaves = jax.tree_util.tree_leaves(tree)
            except Exception:
                leaves = []
            for leaf in leaves:
                b = _device_nbytes(leaf)
                if b is None:
                    continue
                key = _buffer_key(leaf)
                if key in claimed:
                    continue
                claimed[key] = owner
                total += b
        owners[owner] = owners.get(owner, 0) + total
    if dead:
        with _lock:
            for owner, fn in dead:
                fns = _owners.get(owner)
                if fns and fn in fns:
                    fns.remove(fn)
    source = "unavailable"
    total_live = sum(owners.values())
    other = 0
    try:
        import jax  # noqa: PLC0415

        live = jax.live_arrays()
        source = "live_arrays"
    except Exception:
        live = None
    if live is not None:
        total_live = 0
        seen: set = set()
        for arr in live:
            b = _device_nbytes(arr)
            if b is None:
                continue
            key = _buffer_key(arr)
            if key in seen:
                continue  # a second view of a buffer already counted
            seen.add(key)
            total_live += b
            if key not in claimed:
                other += b
    # ADD to (not overwrite) any explicitly-registered "other" supplier:
    # free-form owners are legal, and their claimed bytes must not
    # vanish from every bucket just because they chose this name.
    owners["other"] = owners.get("other", 0) + other
    doc = {
        "source": source,
        "total_bytes": int(total_live),
        "owners": {k: int(v) for k, v in owners.items()},
        "device": device_memory_stats(),
    }
    with _lock:
        _last_census = doc
    if publish:
        _publish_census(doc, registry=registry)
    return doc


def _publish_census(doc: dict, registry=None) -> None:
    try:
        from .registry import get_registry  # noqa: PLC0415

        reg = registry if registry is not None else get_registry()
        reg.gauge("mem.live_bytes").set(doc.get("total_bytes", 0))
        for owner, b in (doc.get("owners") or {}).items():
            reg.gauge("mem.owner_bytes", owner=owner).set(b)
        dev = doc.get("device") or {}
        if dev.get("source") == "memory_stats":
            reg.gauge("mem.hbm_bytes_in_use").set(dev.get("bytes_in_use", 0))
            reg.gauge("mem.hbm_peak_bytes").set(dev.get("peak_bytes", 0))
            if dev.get("limit_bytes"):
                reg.gauge("mem.hbm_limit_bytes").set(dev["limit_bytes"])
                reg.gauge("mem.headroom_bytes").set(
                    dev.get("headroom_bytes") or 0
                )
    except Exception:
        pass  # gauges are observability, not correctness


def last_census() -> Optional[dict]:
    """The most recent :func:`census` result (what the OOM event
    falls back to when a fresh census cannot run inside the handler)."""
    with _lock:
        return dict(_last_census) if _last_census else None


def install_census(registry=None) -> None:
    """Arm the census as a registry collector: every snapshot (the live
    stream's publish round, the exit dump, ``collect_engine_gauges``)
    refreshes the ``mem.*`` gauges.  Idempotent."""
    global _census_installed
    with _lock:
        if _census_installed:
            return
        _census_installed = True
    from .registry import get_registry  # noqa: PLC0415

    reg = registry if registry is not None else get_registry()

    def _collect(r) -> None:
        census(publish=True, registry=r)

    reg.register_collector(_collect)


def reset_census() -> None:
    """Forget the cached census + installed-collector latch (tests;
    the collector itself dies with its registry)."""
    global _last_census, _census_installed
    with _lock:
        _last_census = None
        _census_installed = False


def dominant_owner(doc: Optional[dict] = None) -> Tuple[Optional[str], float]:
    """``(owner, share)`` of the biggest tagged-or-other bucket in a
    census (share of the census total).  ``(None, 0.0)`` on an empty
    census."""
    doc = doc or last_census()
    owners = (doc or {}).get("owners") or {}
    total = sum(owners.values())
    if not total:
        return None, 0.0
    owner = max(sorted(owners), key=lambda k: owners[k])
    return owner, owners[owner] / total


def memory_record() -> dict:
    """The record-embeddable view: one fresh census + every registered
    per-program breakdown.  Safe anywhere (a degraded BENCH record may
    write before jax ever initialized — the census then reports
    ``source: unavailable`` and the programs dict is empty)."""
    try:
        c = census(publish=False)
    except Exception:
        c = last_census() or {"source": "unavailable"}
    return {"census": c, "programs": program_report()}


# ---------------------------------------------------------------------------
# KV occupancy: allocated vs live bytes of a contiguous slot pool
# ---------------------------------------------------------------------------


def kv_occupancy(positions: Sequence[int], active_slots: Sequence[int],
                 cache_len: int, bytes_per_position: float,
                 pool_bytes: Optional[int] = None) -> dict:
    """Occupancy of a fixed-row KV slot pool.

    * ``allocated_bytes`` — what the contiguous design reserves for the
      busy slots: slots-in-use x worst-case ``cache_len`` rows.
    * ``live_bytes`` — positions those slots actually wrote:
      ``sum(pos[slot])`` x bytes-per-position.
    * ``waste_ratio`` — ``1 - live/allocated`` (0.0 when idle): the
      tail a short request wastes in a long-cache pool, i.e. the bytes
      paged attention (ROADMAP item 1) reclaims.
    * ``pool_bytes`` — the whole pool's resident footprint (free slots
      included), when the caller knows it.
    """
    slots = sorted(set(int(s) for s in active_slots))
    allocated = len(slots) * int(cache_len) * float(bytes_per_position)
    live = 0.0
    for s in slots:
        pos = int(positions[s]) if 0 <= s < len(positions) else 0
        live += min(max(pos, 0), int(cache_len)) * float(bytes_per_position)
    out = {
        "slots_in_use": len(slots),
        "allocated_bytes": int(allocated),
        "live_bytes": int(live),
        "waste_ratio": (1.0 - live / allocated) if allocated else 0.0,
    }
    if pool_bytes is not None:
        out["pool_bytes"] = int(pool_bytes)
    return out


# ---------------------------------------------------------------------------
# OOM black box
# ---------------------------------------------------------------------------


class ResourceExhaustedError(RuntimeError):
    """Stand-in for the backend's RESOURCE_EXHAUSTED when jaxlib's
    XlaRuntimeError cannot be constructed (stripped environments)."""


def resource_exhausted_error(message: str) -> BaseException:
    """A backend-shaped RESOURCE_EXHAUSTED: the real
    ``jaxlib.xla_extension.XlaRuntimeError`` when available (so
    ``except XlaRuntimeError`` handlers and the OOM detector both treat
    the injected death exactly like a real allocator failure), else the
    local stand-in."""
    if not message.startswith("RESOURCE_EXHAUSTED"):
        message = "RESOURCE_EXHAUSTED: " + message
    try:
        from jaxlib.xla_extension import XlaRuntimeError  # noqa: PLC0415

        return XlaRuntimeError(message)
    except Exception:
        return ResourceExhaustedError(message)


def is_resource_exhausted(exc: BaseException) -> bool:
    """Whether an exception is the backend's out-of-device-memory
    signature: XLA surfaces allocator failures as RuntimeErrors whose
    message leads with RESOURCE_EXHAUSTED (plus jaxlib's
    XlaRuntimeError type), and the injected fault is built to match."""
    if isinstance(exc, ResourceExhaustedError):
        return True
    try:
        return "RESOURCE_EXHAUSTED" in str(exc)
    except Exception:
        return False


def record_oom(where: str = "", exc: Optional[BaseException] = None) -> dict:
    """Drop a ``mem.oom`` event (last census + dominant owner) into the
    flight-recorder ring — the memory half of the black box.  Returns
    the event's parsed fields (tests assert on them)."""
    try:
        doc = census(publish=False)
    except Exception:
        doc = last_census() or {}
    owner, share = dominant_owner(doc)
    owners = (doc or {}).get("owners") or {}
    dev = (doc or {}).get("device") or {}
    fields = {
        "where": where or "?",
        "owner": owner or "?",
        "share": round(share, 4),
        "owner_bytes": owners.get(owner, 0) if owner else 0,
        "total_bytes": (doc or {}).get("total_bytes", 0),
        "in_use": dev.get("bytes_in_use"),
        "limit": dev.get("limit_bytes"),
    }
    detail = " ".join(
        f"{k}={v}" for k, v in fields.items() if v is not None
    )
    try:
        from . import flightrec  # noqa: PLC0415

        flightrec.record("mem.oom", name=where or (owner or ""),
                         detail=detail)
    except Exception:
        pass
    return fields


def maybe_record_oom(exc: BaseException, where: str = "") -> bool:
    """Record the OOM black-box event iff ``exc`` is a
    RESOURCE_EXHAUSTED.  Hooked into ``flightrec.record_exception`` so
    every death path that records its exception gets the memory story
    for free; safe to call redundantly (each call appends one ring
    event — the post-mortem reads the newest)."""
    if not is_resource_exhausted(exc):
        return False
    if getattr(exc, "_hvdtpu_oom_recorded", False):
        # Already black-boxed at the allocation site (alloc_guard) with
        # the PRECISE program name — the generic death-path hook must
        # not append a newer, vaguer event (the post-mortem reads the
        # newest).
        return True
    record_oom(where=where, exc=exc)
    try:
        exc._hvdtpu_oom_recorded = True
    except Exception:
        pass
    return True


def alloc_guard(where: str, *, rank: Optional[int] = None) -> None:
    """The ``mem_alloc`` fault point's consumer: call on an
    allocation-heavy path (the serve decode/prefill steps) so
    ``HVDTPU_FAULT_SPEC=mem_alloc:action=oom`` deterministically raises
    a backend-shaped RESOURCE_EXHAUSTED there — the chaos input the
    whole OOM black-box path (event, post-mortem verdict) is tested
    against.  Near-free when no fault spec is loaded."""
    from ..testing import faults  # noqa: PLC0415

    if not faults.active():
        return
    action = faults.maybe_fail("mem_alloc", rank=rank, name=where)
    if action == "oom":
        err = resource_exhausted_error(
            f"RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            f"in {where} (injected by HVDTPU_FAULT_SPEC mem_alloc)"
        )
        # Black-box NOW, at the allocation site, with the precise
        # program name — the death-path hook sees the marker and keeps
        # this event as the newest memory story.
        record_oom(where=where, exc=err)
        try:
            err._hvdtpu_oom_recorded = True
        except Exception:
            pass
        raise err


# Optional env knob: arming the census at init time for any worker
# (serve_worker and bench arm it explicitly; a training job can opt in
# without code changes).
CENSUS_ENV = "HVDTPU_MEM_CENSUS"


def maybe_install_from_env() -> None:
    """Arm the census collector when ``HVDTPU_MEM_CENSUS=1`` (called
    from worker init paths that already import the obs plane)."""
    if os.environ.get(CENSUS_ENV, "") in ("1", "true", "on", "yes"):
        install_census()


def accounting_armed() -> bool:
    """Whether the memory plane is armed in this process (census
    collector installed, or ``HVDTPU_MEM_CENSUS=1``).  Compile sites
    whose registration costs a real extra compile (the engine's fused
    allreduce AOT probe) consult this so the cost lands only on jobs
    that asked for the plane — bench and the serving worker arm it;
    a bare unit-test engine spin-up stays exactly as cheap as before.
    Sites where the artifact is already in hand (slot engine, overlap,
    bench) register unconditionally: their registration is free."""
    if _census_installed:
        return True
    return os.environ.get(CENSUS_ENV, "") in ("1", "true", "on", "yes")
