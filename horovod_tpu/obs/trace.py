"""Request-level distributed tracing: Dapper-style causal spans over
the existing observability planes.

The metrics plane (PR 2/3) answers "how slow is ttft" as one opaque
histogram sample; the flight recorder (PR 4) answers "what died".
Neither answers *where a specific request's time went*.  This module is
the missing causal layer (Sigelman et al. 2010, PAPERS.md): every
serve request's rid doubles as its **trace id**, and each stage of its
life — launcher-side ingest, schedule broadcast, admission, prefill,
per-N-token decode windows, finish, result fetch — is recorded as a
**span** ``(trace, name, t0, dur, epoch, args)`` into a bounded
per-process ring.  Training gets the same treatment at step
granularity: engine cycles emit negotiate/execute spans and the
overlap plane annotates its bucket layout, so negotiation vs wire vs
compute per step lands in the same merged view.

Design rules, inherited from the planes this rides on:

* **Deterministic sampling** (:func:`sampled`) — the decision is a pure
  function of the trace id (sha1, not ``hash()``: PYTHONHASHSEED must
  not change the sampled set), so every rank and the launcher reach the
  SAME verdict with no coordination.  A rank-divergent span set would
  make trace-merge blame a healthy rank for "missing" spans — the
  HVD001 invariant applies to sampling decisions.
* **Bounded memory** — a fixed-capacity ring per process
  (``HVDTPU_TRACE_CAPACITY``, default 8192 spans), overwrite-counted
  like the flight recorder: a week-long serving job records forever
  without growing.
* **Zero cost when off** — every producer call site gates on
  :func:`enabled` (one env read, cached); unset ``HVDTPU_TRACE`` means
  no ring, no locks, no span dicts.
* **Per-span epoch** — a span records the elastic epoch it happened
  in, not the env at dump time: a survivor rank's single dump carries
  spans from every epoch it lived through, which is how a replayed
  request's waterfall shows both incarnations (and the recovery gap
  between them) explicitly.
* **Death-path flush** — the ring dumps through the shared flush
  (obs/flightrec.py ``on_death``), over the shared pathspec rules
  (stem ``spans``), so a crashed rank's spans survive it exactly like
  its metrics and its black box.

The launcher-side consumer is ``obs/trace_merge.py``: it globs every
rank's span file (the launcher's own, tagged ``launcher``, included),
merges them into a Chrome-trace waterfall with one lane per request,
and derives the latency-decomposition report (ttft/tpot components,
p50/p99 each).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..utils import env as envmod

SCHEMA = "hvdtpu-trace-v1"
DEFAULT_CAPACITY = 8192
MIN_CAPACITY = 64

# Injection point consumed in :func:`flush` — `trace_flush:action=
# trace_drop` suppresses one rank's span dump, the deterministic chaos
# input trace-merge's missing-rank handling is tested against
# (mirroring the PR-7 replica_push/drop_replica pattern).
FAULT_POINT = "trace_flush"

__all__ = [
    "SCHEMA",
    "TraceBuffer",
    "enabled",
    "sample_rate",
    "sampled",
    "get_buffer",
    "reset_buffer",
    "add_span",
    "span",
    "resolve_dump_path",
    "flush",
]


def enabled() -> bool:
    """True when a span dump target is armed (``HVDTPU_TRACE``).  The
    one gate every producer call site checks before paying for a span."""
    return bool(os.environ.get(envmod.TRACE))


def sample_rate() -> float:
    return envmod.env_float(envmod.TRACE_SAMPLE_RATE, 1.0)


# hvdtpu: deterministic
def sampled(trace_id: str, rate: Optional[float] = None) -> bool:
    """Deterministic sampling verdict for one trace id.

    Pure function of (trace_id, rate): sha1 of the id mapped onto
    [0, 1) and compared to the rate.  Every process holding the same id
    and rate — every serving rank, the launcher's ingest pump, the
    client — derives the identical verdict, so a sampled request's
    spans exist on ALL ranks or NONE, never a rank-divergent subset.
    """
    if rate is None:
        rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = int(hashlib.sha1(trace_id.encode()).hexdigest()[:8], 16)
    return (h / float(0x100000000)) < rate


def _current_epoch() -> int:
    return envmod.env_int("HVDTPU_ELASTIC_EPOCH", 0)


class TraceBuffer:
    """Fixed-capacity ring of span dicts.

    Spans are appended until capacity, then overwritten oldest-first
    (``dropped`` counts the casualties — the dump is honest about what
    the ring forgot).  The lock is REENTRANT for the same reason as
    every other obs-plane lock: the death-path flush may interrupt the
    owning thread mid-:meth:`add` from a signal handler (hvdtpu-lint
    HVDC103)."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = envmod.env_int(
                envmod.TRACE_CAPACITY, DEFAULT_CAPACITY
            )
        self.capacity = max(int(capacity), MIN_CAPACITY)
        self._slots: List[Optional[dict]] = [None] * self.capacity
        self._seq = 0
        self._lock = threading.RLock()

    def add(self, span_doc: dict) -> None:
        with self._lock:
            self._slots[self._seq % self.capacity] = span_doc
            self._seq += 1

    @property
    def recorded(self) -> int:
        return self._seq

    @property
    def dropped(self) -> int:
        return max(0, self._seq - self.capacity)

    def snapshot(self) -> List[dict]:
        """Chronological copy of the surviving window (oldest first)."""
        with self._lock:
            n = min(self._seq, self.capacity)
            start = self._seq % self.capacity if self._seq > self.capacity \
                else 0
            out = []
            for i in range(n):
                slot = self._slots[(start + i) % self.capacity]
                if slot is not None:
                    out.append(slot)
            return out

    def dump(self, path: str, *, rank) -> dict:
        """Write the dump-schema JSON document atomically; returns it."""
        doc = {
            "schema": SCHEMA,
            "rank": rank,
            "pid": os.getpid(),
            "wall_time": time.time(),
            "capacity": self.capacity,
            "recorded": self.recorded,
            "dropped": self.dropped,
            "sample_rate": sample_rate(),
            "spans": self.snapshot(),
        }
        from . import pathspec  # noqa: PLC0415

        pathspec.write_json_atomic(path, doc)
        return doc


# -- process-global buffer ---------------------------------------------------

_buffer: Optional[TraceBuffer] = None
# Reentrant: flush() runs on the fatal-signal death path and the
# interrupted thread may hold this very lock (hvdtpu-lint HVDC103).
_buffer_lock = threading.RLock()
_flush_armed = False


def get_buffer() -> TraceBuffer:
    """The process-global span ring.  First use arms the death-path
    flush (a no-op unless ``HVDTPU_TRACE`` is set at flush time), so a
    crashed rank's spans land next to its flight-recorder ring."""
    global _buffer, _flush_armed
    if _buffer is None:
        with _buffer_lock:
            if _buffer is None:
                _buffer = TraceBuffer()
                if not _flush_armed:
                    from .flightrec import on_death  # noqa: PLC0415

                    on_death(_death_flush)
                    _flush_armed = True
    return _buffer


def reset_buffer() -> None:
    """Drop the global buffer (tests)."""
    global _buffer
    with _buffer_lock:
        _buffer = None


def add_span(trace: str, name: str, t0: float, t1: float,
             epoch: Optional[int] = None, **args) -> None:
    """Record one completed span: ``[t0, t1]`` wall-clock seconds
    (``time.time()`` — spans from different processes on one host align
    without clock negotiation).  ``epoch=None`` stamps the current
    elastic epoch; serving code passes its rendezvous epoch explicitly
    because a survivor's env still names the epoch it was SPAWNED in.
    ``args`` must be JSON-serializable scalars/lists."""
    doc = {
        "trace": trace,
        "name": name,
        "t0": t0,
        "dur": max(t1 - t0, 0.0),
        "epoch": _current_epoch() if epoch is None else int(epoch),
    }
    if args:
        doc["args"] = args
    get_buffer().add(doc)


@contextmanager
def span(trace: str, name: str, epoch: Optional[int] = None, **args):
    """Context-manager form of :func:`add_span` for call sites that
    wrap one straight-line block."""
    t0 = time.time()
    try:
        yield
    finally:
        add_span(trace, name, t0, time.time(), epoch=epoch, **args)


def _resolve_rank() -> str:
    return envmod.artifact_rank()


def resolve_dump_path(raw: str, rank: Optional[str] = None) -> str:
    """``HVDTPU_TRACE`` value -> this rank's span file, via the shared
    pathspec rules (dir / {rank} template / plain path, epoch tag) —
    the merge CLI globs with the same module, so they cannot drift."""
    from . import pathspec  # noqa: PLC0415

    return pathspec.resolve(
        raw, "spans", _resolve_rank() if rank is None else rank
    )


def flush(path: Optional[str] = None) -> Optional[str]:
    """Dump the global span ring; ``path=None`` resolves from the env.
    Returns the written path, or None when tracing is not armed (or a
    ``trace_flush:action=trace_drop`` chaos fault suppressed this
    flush — the deterministic missing-rank input trace-merge is tested
    against; the suppression itself is black-boxed)."""
    raw = path or os.environ.get(envmod.TRACE)
    if not raw:
        return None
    from ..testing.faults import maybe_fail  # noqa: PLC0415

    if maybe_fail(FAULT_POINT) == "trace_drop":
        return None
    resolved = resolve_dump_path(raw) if path is None else path
    get_buffer().dump(resolved, rank=_resolve_rank())
    return resolved


def _death_flush() -> None:
    try:
        flush()
    except Exception:
        pass  # a span dump must never break the death path
