"""Step-time anatomy: where a benchmark's mean step wall time went.

A BENCH record that ships a number without its explanation invites the
r03-r05 failure mode in analysis form: the next reader cannot tell a
comms regression from a host-input stall.  This module decomposes the
measured mean step time into three components that tile it:

* **compute** — the ideal matmul time of the step: model FLOPs (XLA's
  post-fusion ``cost_analysis()``, via obs/profile.py) over the chip's
  peak.  By construction ``compute_ms = MFU x step_ms``, so the
  anatomy and the PR-11 MFU gauge can never disagree.
* **collective_wait** — engine collective overhead per step, from the
  ``engine.cycle_time_ms`` histogram the cycle loop already feeds
  (zero on the world==1 jit path, which never starts the engine).
* **host_gap** — the residual: dispatch gaps, input pipeline, python
  overhead.  Defined as ``step - compute - collective`` (clamped at
  zero), which is what makes the three components tile the step time
  exactly; the raw residual is preserved in ``residual_ms`` so an
  over-estimated compute term is visible rather than papered over.

Beside the split ride a top-K HLO op table (parsed from the compiled
artifact's text) and a **roofline verdict** — compute-/memory-/comms-
bound, judged from the collective fraction, the MFU gauge and the
arithmetic intensity vs the chip's ridge point, with the PR-8 dcn/ici
byte counters printed next to it so a comms verdict names its fabric.

Stdlib-only, no jax import at module scope; :func:`attach_anatomy` is
best-effort by contract — anatomy must never sink the measurement it
explains.
"""

from __future__ import annotations

import re
from typing import List, Optional

from .profile import peak_flops

__all__ = ["step_anatomy", "attach_anatomy", "top_ops_from_compiled",
           "roofline_verdict", "HBM_BANDWIDTH", "CPU_BW_ESTIMATE",
           "COMMS_BOUND_FRAC", "COMPUTE_BOUND_MFU"]

# Peak HBM bandwidth, bytes/sec (public TPU spec sheets) — only used
# for the ridge point of the roofline verdict, so order-of-magnitude
# accuracy is enough.  Keys match obs/profile.py's PEAK_FLOPS table.
HBM_BANDWIDTH = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v5": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}
# A few DDR channels; estimate-flagged wherever it flows, like
# profile.CPU_PEAK_ESTIMATE.
CPU_BW_ESTIMATE = 5e10

# Verdict thresholds: a step spending over a third of itself waiting on
# collectives is comms-bound whatever the MFU says; an MFU at or above
# 0.4 means the MXUs are the constraint.
COMMS_BOUND_FRAC = 0.35
COMPUTE_BOUND_MFU = 0.4

# opcode right before its '(' operand list, after the '=' — tolerant of
# the shape/layout noise HLO text puts between them.
_OPCODE_RE = re.compile(r"=\s+[^=(]*?([a-z][\w-]*)\(")
# Structural opcodes that say nothing about where time went.
_BORING_OPS = {"parameter", "constant", "tuple", "get-tuple-element",
               "bitcast", "copy", "after-all"}


def _bytes_from_compiled(compiled) -> Optional[float]:
    """``bytes accessed`` from cost_analysis(), with the same
    list-vs-dict shape tolerance as profile.flops_from_compiled."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    try:
        v = float(ca.get("bytes accessed", 0.0))
    except (AttributeError, TypeError, ValueError):
        return None
    return v if v > 0 else None


def top_ops_from_compiled(compiled, k: int = 8) -> List[dict]:
    """Top-K HLO opcodes by instruction count from the compiled
    artifact's text — which op families dominate the module (fusion
    kinds, collectives, convolutions), not a per-op timing profile.
    Returns [] when the artifact exposes no text."""
    try:
        text = compiled.as_text()
    except Exception:
        return []
    if not isinstance(text, str) or not text:
        return []
    counts: dict = {}
    for line in text.splitlines():
        if "=" not in line:
            continue
        m = _OPCODE_RE.search(line)
        if not m:
            continue
        op = m.group(1)
        if op in _BORING_OPS:
            continue
        counts[op] = counts.get(op, 0) + 1
    top = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
    return [{"op": op, "count": n} for op, n in top]


def roofline_verdict(*, mfu: Optional[float],
                     collective_frac: float,
                     flops_per_step: Optional[float],
                     bytes_per_step: Optional[float],
                     device_kind: Optional[str],
                     dtype: str = "bf16") -> dict:
    """compute- / memory- / comms-bound, with the evidence beside the
    word.  Comms wins first (a stalled fabric caps everything else);
    then MFU or arithmetic intensity vs the ridge point decides between
    the MXUs and HBM."""
    peak, peak_estimate = peak_flops(device_kind or "", dtype)
    bw = HBM_BANDWIDTH.get(device_kind or "")
    bw_estimate = bw is None
    if bw is None:
        bw = CPU_BW_ESTIMATE
    ridge = peak / bw  # FLOPs/byte at which HBM stops being the limit
    intensity = None
    if flops_per_step and bytes_per_step:
        intensity = flops_per_step / bytes_per_step
    if collective_frac > COMMS_BOUND_FRAC:
        verdict = "comms-bound"
        basis = (f"collective wait is {collective_frac:.0%} of the step "
                 f"(> {COMMS_BOUND_FRAC:.0%})")
    elif (mfu is not None and mfu >= COMPUTE_BOUND_MFU) or (
            intensity is not None and intensity >= ridge):
        verdict = "compute-bound"
        if mfu is not None and mfu >= COMPUTE_BOUND_MFU:
            basis = f"MFU {mfu:.2f} >= {COMPUTE_BOUND_MFU}"
        else:
            basis = (f"arithmetic intensity {intensity:.1f} FLOPs/B >= "
                     f"ridge {ridge:.1f}")
    else:
        verdict = "memory-bound"
        basis = ("low MFU with low collective wait"
                 if intensity is None else
                 f"arithmetic intensity {intensity:.1f} FLOPs/B < "
                 f"ridge {ridge:.1f}")
    out = {
        "verdict": verdict,
        "basis": basis,
        "mfu": mfu,
        "collective_frac": round(collective_frac, 4),
        "ridge_flops_per_byte": round(ridge, 2),
        "estimate": bool(peak_estimate or bw_estimate),
    }
    if intensity is not None:
        out["arithmetic_intensity"] = round(intensity, 2)
    return out


def _engine_collective_ms(steps_observed: Optional[int]) -> tuple:
    """(per-step collective-wait ms, source string).  Total engine cycle
    time (the ``engine.cycle_time_ms`` histogram's sum — negotiation +
    wire time for every bucket) amortized over the steps that ran.
    Zero with an explaining source when the engine never started."""
    try:
        from .registry import get_registry  # noqa: PLC0415

        total = 0.0
        count = 0
        for m in get_registry().snapshot():
            if m.get("name") in ("engine.cycle_time_ms",
                                 "engine.negotiation_ms"):
                total += float(m.get("sum") or 0.0)
                count += int(m.get("count") or 0)
        if count == 0:
            return 0.0, "no engine cycles (jit path or world=1)"
        if steps_observed and steps_observed > 0:
            return total / steps_observed, "engine.cycle_time_ms histogram"
        return total, "engine.cycle_time_ms histogram (unamortized)"
    except Exception:
        return 0.0, "registry unavailable"


def step_anatomy(step_ms: float, *,
                 mfu: Optional[float] = None,
                 flops_per_step: Optional[float] = None,
                 device_kind: Optional[str] = None,
                 dtype: str = "bf16",
                 compiled=None,
                 steps_observed: Optional[int] = None,
                 gauges: Optional[dict] = None) -> Optional[dict]:
    """Decompose ``step_ms`` into compute / collective_wait / host_gap
    (which tile it by construction) plus the op table and roofline
    verdict.  Returns None only when ``step_ms`` is unusable."""
    if not isinstance(step_ms, (int, float)) or not step_ms > 0:
        return None
    peak, peak_estimate = peak_flops(device_kind or "", dtype)
    compute_ms = None
    compute_source = None
    if isinstance(mfu, (int, float)) and mfu >= 0:
        # MFU = achieved/peak, so ideal compute time = MFU x wall time:
        # the anatomy reuses the record's own MFU rather than rederiving
        # a number that could disagree with it.
        compute_ms = float(mfu) * step_ms
        compute_source = "mfu x step"
    elif isinstance(flops_per_step, (int, float)) and flops_per_step > 0:
        compute_ms = flops_per_step / peak * 1e3
        compute_source = "flops / peak"
    if compute_ms is None:
        compute_ms = 0.0
        compute_source = "unknown (no MFU, no FLOPs)"
    compute_ms = min(compute_ms, step_ms)
    collective_ms, collective_source = _engine_collective_ms(steps_observed)
    collective_ms = min(collective_ms, step_ms - compute_ms)
    residual_ms = step_ms - compute_ms - collective_ms
    host_gap_ms = max(residual_ms, 0.0)
    components = {
        "compute_ms": round(compute_ms, 4),
        "collective_wait_ms": round(collective_ms, 4),
        "host_gap_ms": round(host_gap_ms, 4),
    }
    tile_pct = (compute_ms + collective_ms + host_gap_ms) / step_ms * 100.0
    out = {
        "step_ms": round(float(step_ms), 4),
        "components_ms": components,
        "components_pct": {
            k.replace("_ms", "_pct"): round(v / step_ms * 100.0, 2)
            for k, v in components.items()
        },
        "tile_pct": round(tile_pct, 2),
        "residual_ms": round(residual_ms, 4),
        "method": {
            "compute": compute_source,
            "collective_wait": collective_source,
            "host_gap": "residual (step - compute - collective)",
            "peak_flops_estimate": bool(peak_estimate),
        },
    }
    bytes_per_step = _bytes_from_compiled(compiled) if compiled else None
    if bytes_per_step is not None:
        out["bytes_per_step"] = bytes_per_step
    roofline = roofline_verdict(
        mfu=float(mfu) if isinstance(mfu, (int, float)) else None,
        collective_frac=collective_ms / step_ms,
        flops_per_step=(float(flops_per_step)
                        if isinstance(flops_per_step, (int, float))
                        else None),
        bytes_per_step=bytes_per_step,
        device_kind=device_kind, dtype=dtype,
    )
    # The PR-8 two-fabric byte counters beside the verdict: a
    # comms-bound verdict should name which fabric carried the bytes.
    for key in ("engine.dcn_bytes", "engine.ici_bytes"):
        v = (gauges or {}).get(key)
        if isinstance(v, (int, float)):
            roofline[key.split(".", 1)[1]] = v
    out["roofline"] = roofline
    if compiled is not None:
        top = top_ops_from_compiled(compiled)
        if top:
            out["top_ops"] = top
    return out


def attach_anatomy(out: dict, **kwargs) -> dict:
    """Embed ``anatomy.*`` into a result payload, best-effort: anatomy
    explains a measurement and must never sink one."""
    try:
        anatomy = step_anatomy(**kwargs)
        if anatomy is not None:
            out["anatomy"] = anatomy
    except Exception:
        pass
    return out
