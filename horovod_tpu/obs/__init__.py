"""horovod_tpu.obs — the per-rank observability plane.

One package for the three things a distributed job must be able to tell
you after the fact (PAPER.md §5's debuggability pillars, made
quantitative):

* **metrics** (obs/registry.py) — Counter/Gauge/Histogram instruments
  updated from the engine cycle loop, the stall inspector, checkpoint
  save/restore and every elastic event; dumped per rank as JSON via
  ``HVDTPU_METRICS_DUMP`` and aggregated by the launcher's
  ``--stats-summary`` table (obs/summary.py).
* **progress beat** (obs/progress.py) — a monotonic collectives-
  completed counter piggybacked on the elastic KV heartbeat, plus the
  launcher-side workload-aware staleness policy that kills a rank whose
  beat thread lives but whose training thread is deadlocked.
* **all-rank timeline merge** (obs/timeline_merge.py) — repairs and
  merges the per-rank Chrome traces (runtime/timeline.py) into one
  valid trace with a lane per rank.
* **live telemetry** (obs/stream.py worker side, obs/live.py launcher
  side) — per-rank snapshot deltas streamed over the signed KV path
  while the job runs: console digests, ``live_history.jsonl``, and a
  Prometheus ``GET /metrics`` scrape endpoint on the launcher.
* **straggler attribution** (obs/straggler.py) — which rank arrives
  last at collectives, accumulated as ``engine.straggler.*`` metrics
  from both collective paths, surfaced in the live digest and the
  ``--stats-summary`` straggler section.
* **flight recorder** (obs/flightrec.py) + **post-mortem**
  (obs/postmortem.py) — an always-on bounded per-rank event ring
  flushed on every death path (signals, excepthooks, exit), and the
  launcher-side analyzer that correlates all ranks' rings into a
  root-cause verdict when the job dies.
* **request-level tracing** (obs/trace.py worker+launcher side,
  obs/trace_merge.py consumer) — Dapper-style spans keyed by request
  id (and by step for training), deterministically sampled, dumped per
  rank over the shared pathspec rules and merged into a per-request
  Chrome-trace waterfall plus a ttft/tpot latency-decomposition
  report.
* **MFU profiler** (obs/profile.py) — model-FLOPs accounting
  (compiled ``cost_analysis()`` with analytic fallbacks) over measured
  step time, published live as ``perf.mfu`` / ``perf.model_tflops`` /
  ``perf.step_ms`` gauges.
* **goodput ledger** (obs/goodput.py) — the wall-clock axis: every
  per-rank second classified (init / compile / productive_step /
  collective_wait / checkpoint / recovery / idle / degraded) off the
  events the flight recorder already emits, published as
  ``goodput.*`` gauges with per-elastic-epoch lost-time attribution
  (rendezvous / respawn / stall), plus the serving-side token-goodput
  variant (``serve.goodput.*``).
* **tenant SLO burn-rate plane** (obs/slo.py) — per-tenant /
  per-SLO-class sliding-window ttft/tpot digests judged against
  ``--slo-ttft-ms``-style targets, with two-window error-budget
  burn-rate alerting (fast window pages on cliffs, slow window warns
  on slow burns), published as ``serve.slo.*``.
* **training-health plane** (obs/health.py + obs/divergence.py) — the
  *numbers* axis: an in-graph per-step numerics bundle (loss, grad
  norms per overlap bucket, update/param ratio, nonfinite counts)
  riding the step's existing host sync, judged by a pure EWMA+MAD
  anomaly table (``health.*`` gauges, rising-edge alerts), plus the
  cross-rank divergence sentinel — periodic bitwise digests of
  params/optimizer state/PRNG key exchanged over the engine, the
  runtime verifier of the HVD001 bitwise-replication invariant, with
  minority-rank + bucket + leaf localization and a serving twin over
  the broadcast schedule doc + KV page tables.
* **memory plane** (obs/memplane.py) — the byte axis: compiled
  per-program breakdowns (``memory_analysis()``, version-tolerant),
  an owner-tagged ``jax.live_arrays()`` census with backend
  ``memory_stats()`` (``mem.*`` gauges, KV-cache occupancy math), and
  the OOM black box (``mem.oom`` flight-recorder events feeding the
  post-mortem's memory verdict).

See docs/observability.md and docs/postmortem.md.
"""

from . import divergence  # noqa: F401
from . import flightrec  # noqa: F401
from . import goodput  # noqa: F401
from . import health  # noqa: F401
from . import memplane  # noqa: F401
from . import slo  # noqa: F401
from . import profile  # noqa: F401
from . import progress  # noqa: F401
from . import straggler  # noqa: F401
from . import stream  # noqa: F401
from . import trace  # noqa: F401
from .registry import (  # noqa: F401
    METRICS_DUMP_ENV,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    dump_metrics,
    get_registry,
    reset_registry,
)

set_phase = progress.set_phase
dump_flight_recorder = flightrec.dump_flight_recorder
install_death_hooks = flightrec.install_death_hooks

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "METRICS_DUMP_ENV",
    "get_registry",
    "reset_registry",
    "dump_metrics",
    "dump_flight_recorder",
    "install_death_hooks",
    "divergence",
    "flightrec",
    "goodput",
    "health",
    "profile",
    "progress",
    "slo",
    "straggler",
    "stream",
    "trace",
    "set_phase",
]
