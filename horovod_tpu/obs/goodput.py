"""Per-rank wall-clock goodput ledger: where every second went.

Sixteen PRs of machinery emit *events* — flight-recorder phase
transitions and rendezvous records (obs/flightrec.py), progress beats
(obs/progress.py), decode/step spans (obs/trace.py) — but nothing adds
them up: after a chaos run nobody can say what fraction of the job's
wall-clock was productive work versus compile, collective waits,
checkpoint stalls or elastic recovery.  This module is the accountant.

* :class:`GoodputLedger` — an exhaustive interval ledger over the
  caller's clock.  Exactly one of the eight classes is "open" at any
  instant; :meth:`enter` closes the open interval and opens the next,
  so the per-class totals tile ``[start, now]`` with no gap and no
  overlap and the fractions sum to 1.0 by construction.  Pure function
  of the timestamps the caller supplies — decision-table tests drive a
  fake clock, production passes ``time.time()``.
* **Per-epoch lost-time attribution** — every second spent in
  ``recovery`` is additionally charged to its *cause* (``rendezvous``,
  ``respawn``, ``stall``) under the elastic epoch it happened in, so
  "epoch 3 cost 12s, all rendezvous" is a statement the ledger can
  make, not a grep over logs.
* :func:`classify_event` / :func:`ledger_from_events` — the mapping
  from the event vocabulary flightrec already records (``phase``,
  ``rendezvous``, ``ckpt.begin``/``ckpt.commit``, restores, signals)
  to ledger transitions, so a post-hoc ledger can be rebuilt from any
  rank's black box.
* :func:`install` — live wiring: subscribes to the flight recorder's
  event tap and registers a metrics collector, so ``goodput.fraction``
  and ``goodput.secs{class=…}`` gauges appear in every dump and live
  stream without any hot-path cost beyond the events already recorded.
* :class:`TokenGoodput` — the serving-side variant: tokens actually
  generated over slot-step capacity (a fleet decoding 3 tokens/step on
  a 4-slot pool has token goodput 0.75), published beside the PR-14
  KV-occupancy gauges by the serving loop.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "CLASSES",
    "LOST_CAUSES",
    "GoodputLedger",
    "TokenGoodput",
    "classify_event",
    "ledger_from_events",
    "install",
    "uninstall",
    "get_ledger",
    "publish",
]

# The exhaustive wall-clock partition.  `productive_step` is the only
# class that counts toward goodput.fraction; everything else is the
# overhead taxonomy the roadmap's hardware campaign needs itemized.
CLASSES: Tuple[str, ...] = (
    "init",
    "compile",
    "productive_step",
    "collective_wait",
    "checkpoint",
    "recovery",
    "idle",
    "degraded",
)

# What recovery seconds are attributed to, per elastic epoch:
# rendezvous (world re-forming), respawn (a fresh incarnation replaying
# state), stall (a wedged peer burning everyone's budget).
LOST_CAUSES: Tuple[str, ...] = ("rendezvous", "respawn", "stall")

# Classes that are excursions FROM productive time: leaving one via
# resume() returns to the class that was open when it began.
_EXCURSIONS = ("checkpoint", "collective_wait")


class GoodputLedger:
    """Exhaustive interval ledger over a caller-supplied clock.

    Thread-safe (the live tap records from whatever thread hits the
    flight recorder), but all time arithmetic is pure: no call reads a
    clock.  Non-monotonic timestamps are clamped — a backwards wall
    clock yields a zero-length interval, never a negative one."""

    def __init__(self, start: float, epoch: int = 0,
                 cls: str = "init"):
        if cls not in CLASSES:
            raise ValueError(f"unknown goodput class {cls!r}")
        self._lock = threading.RLock()
        self._start = float(start)
        self._now = float(start)
        self._cls = cls
        self._cause: Optional[str] = None
        self._epoch = int(epoch)
        self._resume_to = "productive_step"
        self._secs: Dict[str, float] = {c: 0.0 for c in CLASSES}
        # epoch -> class -> secs (the per-incarnation breakdown)
        self._by_epoch: Dict[int, Dict[str, float]] = {}
        # epoch -> cause -> secs (recovery attribution only)
        self._lost: Dict[int, Dict[str, float]] = {}

    # ------------------------------------------------------------ state

    @property
    def current(self) -> str:
        return self._cls

    @property
    def epoch(self) -> int:
        return self._epoch

    def _close(self, now: float) -> None:
        dt = max(float(now) - self._now, 0.0)
        self._now = max(float(now), self._now)
        if dt <= 0.0:
            return
        self._secs[self._cls] += dt
        per = self._by_epoch.setdefault(self._epoch, {})
        per[self._cls] = per.get(self._cls, 0.0) + dt
        if self._cls == "recovery":
            cause = self._cause or "rendezvous"
            lost = self._lost.setdefault(self._epoch, {})
            lost[cause] = lost.get(cause, 0.0) + dt

    # ------------------------------------------------------- transitions

    def enter(self, cls: str, now: float,
              cause: Optional[str] = None) -> None:
        """Close the open interval at ``now`` and open ``cls``.
        ``cause`` tags recovery time for the lost-time attribution
        (ignored for other classes)."""
        if cls not in CLASSES:
            raise ValueError(f"unknown goodput class {cls!r}")
        with self._lock:
            if cls in _EXCURSIONS and self._cls not in _EXCURSIONS:
                self._resume_to = self._cls
            self._close(now)
            self._cls = cls
            self._cause = cause if cls == "recovery" else None

    def resume(self, now: float) -> None:
        """Return from a checkpoint / collective-wait excursion to the
        class that was open when it began."""
        with self._lock:
            self.enter(self._resume_to, now)

    def epoch_start(self, epoch: int, now: float,
                    cause: str = "rendezvous") -> None:
        """An elastic epoch boundary: everything from here until the
        next class transition is recovery, charged to ``cause`` under
        the NEW epoch — the epoch that paid for it."""
        with self._lock:
            self._close(now)
            self._epoch = int(epoch)
            self._cls = "recovery"
            self._cause = cause if cause in LOST_CAUSES else "rendezvous"

    # ---------------------------------------------------------- reading

    def secs(self, now: Optional[float] = None) -> Dict[str, float]:
        """Per-class totals including the open interval (closed at
        ``now`` when given, at the last transition otherwise)."""
        with self._lock:
            out = dict(self._secs)
            if now is not None:
                dt = max(float(now) - self._now, 0.0)
                out[self._cls] += dt
            return out

    def fractions(self, now: Optional[float] = None) -> Dict[str, float]:
        """Per-class share of total wall-clock; sums to 1.0 (±fp) by
        construction whenever any time has elapsed."""
        secs = self.secs(now)
        total = sum(secs.values())
        if total <= 0.0:
            return {c: 0.0 for c in CLASSES}
        return {c: secs[c] / total for c in CLASSES}

    def by_epoch(self, now: Optional[float] = None
                 ) -> Dict[int, Dict[str, float]]:
        with self._lock:
            out = {e: dict(per) for e, per in self._by_epoch.items()}
            if now is not None:
                dt = max(float(now) - self._now, 0.0)
                if dt > 0.0:
                    per = out.setdefault(self._epoch, {})
                    per[self._cls] = per.get(self._cls, 0.0) + dt
            return out

    def lost(self, now: Optional[float] = None
             ) -> Dict[int, Dict[str, float]]:
        """Recovery seconds by (epoch, cause) — the lost-time bill."""
        with self._lock:
            out = {e: dict(c) for e, c in self._lost.items()}
            if now is not None and self._cls == "recovery":
                dt = max(float(now) - self._now, 0.0)
                if dt > 0.0:
                    cause = self._cause or "rendezvous"
                    per = out.setdefault(self._epoch, {})
                    per[cause] = per.get(cause, 0.0) + dt
            return out

    # -------------------------------------------------------- publishing

    def publish(self, reg, now: float) -> None:
        """Land the ledger in a metrics registry: ``goodput.fraction``
        (the productive share), ``goodput.secs{class=…}`` per class,
        and ``goodput.lost_secs{cause=…}`` for the recovery bill."""
        fr = self.fractions(now)
        secs = self.secs(now)
        reg.gauge("goodput.fraction").set(
            round(fr.get("productive_step", 0.0), 6))
        for cls in CLASSES:
            reg.gauge("goodput.secs", **{"class": cls}).set(
                round(secs[cls], 3))
        totals: Dict[str, float] = {}
        for per in self.lost(now).values():
            for cause, s in per.items():
                totals[cause] = totals.get(cause, 0.0) + s
        for cause, s in totals.items():
            reg.gauge("goodput.lost_secs", cause=cause).set(round(s, 3))

    def summary(self, now: Optional[float] = None) -> dict:
        """The drain/stats-summary document: fractions, seconds, and
        the per-epoch lost-time attribution."""
        return {
            "fraction": round(
                self.fractions(now).get("productive_step", 0.0), 6),
            "secs": {c: round(s, 3)
                     for c, s in self.secs(now).items() if s > 0.0},
            "lost": {
                str(e): {c: round(s, 3) for c, s in per.items()}
                for e, per in sorted(self.lost(now).items())
            },
        }


# -- event classification ----------------------------------------------------

# phase events (obs/progress.py) name the workload phase directly.
_PHASE_CLASS = {
    "init": "init",
    "compile": "compile",
    "steady": "productive_step",
}


def classify_event(kind: str, name: str = ""
                   ) -> Optional[Tuple[str, Optional[str]]]:
    """Map one flight-recorder event to a ledger transition.

    Returns ``(class, cause)`` to enter, ``("resume", None)`` for an
    excursion end (checkpoint commit), or None for events that carry no
    wall-clock meaning (collective enqueue/complete and friends tick
    too often to be transitions — the phase events already bracket
    them)."""
    if kind == "phase":
        cls = _PHASE_CLASS.get(name)
        return (cls, None) if cls else None
    if kind == "rendezvous":
        return ("recovery", "rendezvous")
    if kind == "ckpt.begin":
        return ("checkpoint", None)
    if kind in ("ckpt.commit", "ckpt.error"):
        return ("resume", None)
    if kind.startswith("ckpt.restore"):
        return ("recovery", "respawn")
    if kind == "init" and name in ("serve_replay",):
        return ("recovery", "respawn")
    if kind == "stall":
        return ("recovery", "stall")
    if kind in ("signal", "exception"):
        # Post-fault time until the process dies (or re-rendezvouses)
        # is not productive and not yet attributed: degraded.
        return ("degraded", None)
    return None


def ledger_from_events(events: List[dict], start: Optional[float] = None,
                       end: Optional[float] = None,
                       epoch: int = 0) -> GoodputLedger:
    """Fold a flight-recorder event list (dump schema: dicts with
    ``t``/``kind``/``name``/``cycle``) into a ledger — the post-hoc
    accountant over any rank's black box."""
    events = sorted(
        (e for e in events if isinstance(e.get("t"), (int, float))),
        key=lambda e: e["t"],
    )
    if start is None:
        start = events[0]["t"] if events else 0.0
    ledger = GoodputLedger(start, epoch=epoch)
    for e in events:
        verdict = classify_event(str(e.get("kind", "")),
                                 str(e.get("name", "")))
        if verdict is None:
            continue
        cls, cause = verdict
        t = max(float(e["t"]), start)
        if cls == "resume":
            ledger.resume(t)
        elif str(e.get("kind")) == "rendezvous":
            cycle = e.get("cycle")
            ledger.epoch_start(
                int(cycle) if isinstance(cycle, int) and cycle >= 0
                else ledger.epoch + 1, t, cause=cause or "rendezvous")
        else:
            ledger.enter(cls, t, cause=cause)
    if end is not None:
        # Close the trailing interval so fractions cover [start, end].
        ledger.enter(ledger.current, end)
    return ledger


# -- serving token goodput ---------------------------------------------------


class TokenGoodput:
    """Decode-capacity utilization: tokens actually generated over the
    slot-step capacity that elapsed — ``tokens ÷ (steps × slots)``, and
    per wall-clock, ``tokens ÷ (slot-seconds)`` against the pool.  A
    4-slot pool decoding 3 tokens per step has token goodput 0.75; an
    idle pool decays toward 0.  Pure function of the caller's clock,
    like the ledger."""

    def __init__(self, slots: int, start: float):
        self.slots = max(int(slots), 1)
        self._start = float(start)
        self._tokens = 0
        self._steps = 0

    def observe_step(self, tokens: int) -> None:
        """One decode step completed, emitting ``tokens`` (0 on an idle
        step — idle capacity is exactly what the fraction must see)."""
        self._steps += 1
        self._tokens += max(int(tokens), 0)

    @property
    def tokens(self) -> int:
        return self._tokens

    def fraction(self) -> float:
        """Share of slot-step capacity converted into tokens."""
        if self._steps <= 0:
            return 0.0
        return self._tokens / float(self._steps * self.slots)

    def per_slot_second(self, now: float) -> float:
        """Tokens per slot-second of pool existence."""
        elapsed = max(float(now) - self._start, 1e-9)
        return self._tokens / (elapsed * self.slots)

    def publish(self, reg, now: float) -> None:
        reg.gauge("serve.goodput.token_fraction").set(
            round(self.fraction(), 6))
        reg.gauge("serve.goodput.tokens_per_slot_sec").set(
            round(self.per_slot_second(now), 4))


# -- live wiring -------------------------------------------------------------

_ledger: Optional[GoodputLedger] = None
_lock = threading.RLock()
_tap_installed = False


def get_ledger() -> Optional[GoodputLedger]:
    return _ledger


def _on_event(kind: str, name: str, cycle: int, t: float) -> None:
    ledger = _ledger
    if ledger is None:
        return
    verdict = classify_event(kind, name)
    if verdict is None:
        return
    cls, cause = verdict
    if cls == "resume":
        ledger.resume(t)
    elif kind == "rendezvous":
        ledger.epoch_start(
            cycle if isinstance(cycle, int) and cycle >= 0
            else ledger.epoch + 1, t, cause=cause or "rendezvous")
    else:
        ledger.enter(cls, t, cause=cause)


def _collect(reg) -> None:
    # A pre-snapshot hook, not a retiring collector: the ledger may be
    # re-armed after a reset and the hook must keep working.
    ledger = _ledger
    if ledger is not None:
        ledger.publish(reg, time.time())


_collector_reg = None  # the registry instance _collect is registered on


def install(now: Optional[float] = None, epoch: int = 0) -> GoodputLedger:
    """Arm the live ledger: one module-global :class:`GoodputLedger`
    fed by the flight recorder's event tap (every phase / rendezvous /
    ckpt event already being recorded becomes a transition), published
    into the process registry by a pre-snapshot collector.  Idempotent
    per process; re-installing resets the ledger (a fresh incarnation
    starts a fresh book — its flight-recorder rendezvous event charges
    the recovery to the new epoch)."""
    global _ledger, _tap_installed, _collector_reg
    from . import flightrec  # noqa: PLC0415
    from .registry import get_registry  # noqa: PLC0415

    with _lock:
        _ledger = GoodputLedger(
            time.time() if now is None else now, epoch=epoch)
        if not _tap_installed:
            flightrec.add_observer(_on_event)
            _tap_installed = True
        # reset_registry() mints a fresh registry without our hook, so
        # registration is per registry INSTANCE, not per process.
        reg = get_registry()
        if _collector_reg is not reg:
            reg.register_collector(_collect)
            _collector_reg = reg
    return _ledger


def uninstall() -> None:
    """Drop the live ledger (tests).  The tap stays registered but
    becomes a no-op; the collector retires itself on next snapshot."""
    global _ledger
    with _lock:
        _ledger = None


def publish(reg, now: Optional[float] = None) -> None:
    """Publish the live ledger into ``reg`` (no-op when not armed)."""
    ledger = _ledger
    if ledger is not None:
        ledger.publish(reg, time.time() if now is None else now)
