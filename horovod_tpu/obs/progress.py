"""Collective-path progress beat: counter, phase, and the launcher policy.

The elastic KV heartbeat (elastic/context.py) proves a *process* is
alive; it deliberately cannot see a deadlocked *training thread* — the
beat thread keeps beating through one, and the hang is only surfaced by
peers' collective timeouts, burning their retry budget (the ROADMAP open
item, and what BENCH_r03–r05's never-diagnosed hangs cost).

This module closes that gap with three pieces:

* **Worker side** — a process-global monotonic counter ticked from the
  collective path itself (the eager engine after every performed
  response; the elastic context after every KV collective).  If the
  training thread wedges, the counter freezes even though the beat
  thread lives.
* **Phase** — ``init`` until the first tick, ``steady`` after it, and an
  explicit ``compile`` that user code (or frameworks) can set around
  legitimately long non-collective phases (XLA compiles, data loading).
  The next tick returns the phase to ``steady``.
* **Waiting flag** — a rank *blocked inside* an elastic wait (it has
  contributed to a collective, or is parked in rendezvous waiting for
  the world to form) reports ``waiting``.  Its counter is frozen too,
  but it is frozen *because of someone else*: killing it would shoot
  every innocent peer of one hung rank.  The culpable rank — the one
  wedged in user code or before contributing — is the one frozen while
  NOT waiting, and that is the only one the policy kills.
* **Launcher side** — :class:`ProgressPolicy`, the workload-aware
  staleness rule: the beat payload piggybacks
  ``(counter, phase, waiting)`` on the existing heartbeat, and the
  policy applies *separate budgets* to steady-state (no collective
  completed in ``steady_timeout`` while not waiting → the thread is
  declared dead, the rank killed and respawned directly) and
  init/compile (``grace_timeout``; 0 = never kill, because "has not
  issued a collective yet" is indistinguishable from "legitimately
  computing").  Like the exit/heartbeat rules, windows are measured
  entirely on the launcher's clock from when it *observes* a change —
  immune to cross-host skew.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = [
    "tick",
    "value",
    "phase",
    "set_phase",
    "reset",
    "waiting",
    "in_wait",
    "beat_payload",
    "beat_epoch",
    "parse_beat",
    "ProgressPolicy",
    "PHASE_INIT",
    "PHASE_COMPILE",
    "PHASE_STEADY",
]

PHASE_INIT = "init"
PHASE_COMPILE = "compile"
PHASE_STEADY = "steady"

_lock = threading.Lock()
_count = 0
_phase = PHASE_INIT
_waiting_depth = 0


def tick(n: int = 1, *, to_steady: bool = True) -> int:
    """Record ``n`` completed collectives; returns the new count.

    A USER-level collective proves the workload reached steady state,
    so the phase snaps there.  Framework-internal collectives (the
    epoch-start state sync) pass ``to_steady=False``: they advance the
    counter — the launcher sees liveness — but must not end the
    init/compile grace before the user's first step (whose jit compile
    may legitimately outlast the steady budget) has even started."""
    global _count, _phase
    changed = None
    with _lock:
        _count += n
        if to_steady and _phase != PHASE_STEADY:
            changed = _phase
            _phase = PHASE_STEADY
        count = _count
    if changed is not None:
        # Phase transitions are rare (once per phase), so the flight-
        # recorder event costs nothing on the per-tick hot path.
        from . import flightrec  # noqa: PLC0415

        flightrec.record("phase", name=PHASE_STEADY, detail=changed)
    return count


def value() -> int:
    return _count


def phase() -> str:
    return _phase


def set_phase(name: str) -> None:
    """Declare a workload phase.  ``compile`` buys the grace budget for
    a legitimately long non-collective stretch (mid-training recompile,
    giant data shuffle); the next completed collective returns the phase
    to ``steady`` automatically."""
    global _phase
    if name not in (PHASE_INIT, PHASE_COMPILE, PHASE_STEADY):
        raise ValueError(
            f"unknown phase {name!r}; expected one of "
            f"{(PHASE_INIT, PHASE_COMPILE, PHASE_STEADY)}"
        )
    with _lock:
        prev, _phase = _phase, name
    if prev != name:
        from . import flightrec  # noqa: PLC0415

        flightrec.record("phase", name=name, detail=prev)


def reset() -> None:
    """Zero the counter and phase (tests, or re-launch in-process)."""
    global _count, _phase, _waiting_depth
    with _lock:
        _count = 0
        _phase = PHASE_INIT
        _waiting_depth = 0


@contextlib.contextmanager
def waiting():
    """Mark the calling thread as blocked in an elastic wait — it has
    done its part (contributed / checked in) and is parked on peers or
    the launcher.  The beat reports it, and the progress policy exempts
    it: its freeze is someone else's fault."""
    global _waiting_depth
    with _lock:
        _waiting_depth += 1
    try:
        yield
    finally:
        with _lock:
            _waiting_depth -= 1


def in_wait() -> bool:
    return _waiting_depth > 0


def beat_payload(epoch: Optional[int] = None) -> bytes:
    """The heartbeat body: wall clock (legacy liveness field) plus the
    progress counter, phase and waiting flag, one JSON object per beat.
    ``epoch`` stamps the sender's rendezvous epoch so the launcher can
    discard a dead incarnation's stale beat instead of attributing it to
    the respawned successor."""
    doc = {"t": time.time(), "p": _count, "ph": _phase,
           "w": _waiting_depth > 0}
    if epoch is not None:
        doc["e"] = int(epoch)
    return json.dumps(doc).encode()


def beat_epoch(raw: bytes) -> Optional[int]:
    """The sender's epoch stamp, or None for legacy/unstamped beats."""
    try:
        e = json.loads(raw.decode()).get("e")
        return int(e) if e is not None else None
    except Exception:
        return None


def parse_beat(
    raw: bytes,
) -> Tuple[Optional[int], Optional[str], bool]:
    """Extract ``(progress, phase, waiting)`` from a beat body.  Legacy
    beats (bare ``repr(time.time())``) and garbage parse to
    ``(None, None, False)``: process liveness still works, the progress
    policy just has no data."""
    try:
        doc = json.loads(raw.decode())
        return (int(doc["p"]), str(doc.get("ph") or PHASE_STEADY),
                bool(doc.get("w", False)))
    except Exception:
        return None, None, False


class ProgressPolicy:
    """Launcher-side staleness judge for progress beats.

    ``observe(rank, raw_beat, now)`` returns a human-readable reason
    string when the rank should be declared dead, else None.  State is
    per-rank; call :meth:`forget` when a rank exits or is respawned so
    the successor incarnation gets fresh windows.

    Budgets:

    * ``steady_timeout`` — seconds without a new collective completing
      while the worker reports steady-state.  0 disables the policy.
    * ``grace_timeout`` — the same window while the worker reports
      init/compile.  0 (the default) never kills during those phases:
      the process heartbeat still covers frozen processes, and a worker
      that simply does not use collectives must not be shot for it.
    """

    def __init__(self, steady_timeout: float = 0.0,
                 grace_timeout: float = 0.0):
        self.steady_timeout = float(steady_timeout or 0.0)
        self.grace_timeout = float(grace_timeout or 0.0)
        # rank -> (progress, phase, waiting, launcher time last changed)
        self._seen: Dict[int, Tuple] = {}

    @property
    def enabled(self) -> bool:
        return self.steady_timeout > 0 or self.grace_timeout > 0

    def forget(self, rank: int) -> None:
        self._seen.pop(rank, None)

    def observe(self, rank: int, raw: bytes, now: float) -> Optional[str]:
        if not self.enabled:
            return None
        progress, ph, is_waiting = parse_beat(raw)
        if progress is None:
            return None  # legacy/garbled beat: no progress visibility
        seen = self._seen.get(rank)
        state = (progress, ph, is_waiting)
        if seen is None or seen[:3] != state:
            # Window (re)starts when the launcher OBSERVES a change in
            # the counter, the declared phase, or the waiting flag — a
            # worker that drops into `compile` or unblocks from a wait
            # gets a fresh window.
            self._seen[rank] = state + (now,)
            return None
        if is_waiting:
            # Blocked inside an elastic wait: it contributed / checked
            # in and is parked on peers.  Frozen, but not at fault —
            # the culpable rank is the one frozen while NOT waiting.
            return None
        budget = (
            self.steady_timeout if ph == PHASE_STEADY else self.grace_timeout
        )
        if budget <= 0:
            return None
        age = now - seen[3]
        if age <= budget:
            return None
        return (
            f"no collective completed in {age:.0f}s outside any "
            f"collective wait (phase {ph!r}, budget {budget:.0f}s, "
            f"progress counter stuck at {progress})"
        )
