"""Cross-rank post-mortem: load every rank's flight-recorder dump and
name the root cause.

The flight recorder (obs/flightrec.py) guarantees each rank leaves a
bounded ring of structured events on any catchable death path.  This
module is the launcher-side half: correlate those rings — plus
``live_history.jsonl`` and the merged timeline when present — into one
report that answers the questions a 3 a.m. pager actually asks:

* **Which rank failed first**, and what was its last event / last
  completed collective?
* **What was the last collective every rank agreed on** (rings aligned
  on (cycle, op))?
* **Where was every other rank at the time of death** — running,
  waiting (and on which op), or already exited?
* **Did the collective schedules diverge** — did some rank submit a
  different op sequence than its peers (the classic desync hang)?

Library use::

    report = postmortem.analyze(postmortem.load_dumps(spec))
    print(postmortem.verdict(report))

CLI::

    python -m horovod_tpu.obs.postmortem <dump-dir-or-spec> \
        [--live-history live_history.jsonl] [--timeline merged.json] \
        [--expected-ranks N] [--output postmortem.json]

Both launchers (``launch_job`` / ``launch_elastic_job``) run this
automatically on abnormal job end: the per-rank dumps are collected,
``postmortem.json`` lands next to them, and the verdict paragraph is
printed.  A rank killed with SIGKILL (or a lost host) leaves no dump;
it is reported as ``no black box`` rather than silently skipped.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
from typing import Dict, List, Optional

from ..utils.logging import get_logger
from . import flightrec, pathspec

LOG = get_logger("obs.postmortem")

REPORT_SCHEMA = "hvdtpu-postmortem-v1"

# Event kinds that mean "this rank had submitted work and was parked on
# peers (or the engine) when the dump was taken".
_WAIT_KINDS = ("enqueue", "negotiate", "execute", "wait")
# Dump triggers that mean the process was dying (vs. a routine exit or
# an operator-requested dump).  "exception" is the flush the elastic
# worker's error path issues after catching a user exception itself.
_DEATH_TRIGGERS = ("excepthook", "threading.excepthook", "exception")

__all__ = [
    "REPORT_SCHEMA",
    "load_dumps",
    "analyze",
    "verdict",
    "write_report",
    "generate",
    "main",
]


def load_dumps(spec: str) -> List[dict]:
    """Load every flight-recorder dump reachable from ``spec`` — the
    same dir / ``{rank}`` template / plain-path forms the writers used
    (shared rules in obs/pathspec.py), or a direct glob.  Unreadable or
    wrong-schema files are skipped with a warning, not fatal: a half-
    written dump must not cost the analysis of the intact ones."""
    patterns = [pathspec.glob_pattern(spec, "flightrec")]
    if os.path.isdir(spec):
        # Direct dumps (explicit path= calls in tests/tools) may not
        # carry a rank tag; accept any flightrec*.json in the dir too.
        patterns.append(os.path.join(spec, "flightrec*.json"))
    paths = sorted({p for pat in patterns for p in _glob.glob(pat)})
    dumps: List[dict] = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            LOG.warning("skipping unreadable flightrec dump %s: %s",
                        path, exc)
            continue
        if doc.get("schema") != flightrec.SCHEMA:
            LOG.warning("skipping %s: schema %r is not %r",
                        path, doc.get("schema"), flightrec.SCHEMA)
            continue
        doc["_path"] = path
        dumps.append(doc)
    return dumps


def _latest_per_rank(dumps: List[dict]) -> Dict[int, dict]:
    """One dump per rank: the latest incarnation's last word (elastic
    respawns leave one epoch-tagged file per incarnation; the newest
    epoch — then the newest wall time — is the story of how the job
    ended)."""
    best: Dict[int, dict] = {}
    for doc in dumps:
        try:
            rank = int(doc.get("rank"))
        except (TypeError, ValueError):
            continue
        key = (doc.get("epoch") or 0, doc.get("wall_time") or 0.0)
        cur = best.get(rank)
        if cur is None or key > ((cur.get("epoch") or 0),
                                 (cur.get("wall_time") or 0.0)):
            best[rank] = doc
    return best


def _rank_summary(doc: dict) -> dict:
    events = doc.get("events") or []
    trigger = doc.get("trigger") or "unknown"
    completes = [e for e in events if e.get("kind") == "complete"]
    last_complete = completes[-1] if completes else None
    # The last OPERATIONAL event: the death-path bookkeeping the flush
    # itself appends ("signal", "exception") restates the trigger — the
    # question a post-mortem answers is what the rank was DOING.
    ops = [e for e in events if e.get("kind") not in ("signal", "exception")]
    last_event = ops[-1] if ops else (events[-1] if events else None)
    died = trigger in _DEATH_TRIGGERS or trigger.startswith("signal:")
    if trigger == f"signal:{flightrec._DUMP_SIGNAL}":
        died = False  # dump-only signal: an operator snapshot, not a death
    if trigger == "atexit" and doc.get("last_exception") is None:
        position = "exited"
        waiting_on = None
    elif last_event is not None and last_event.get("kind") in _WAIT_KINDS:
        position = "waiting"
        waiting_on = last_event.get("name") or None
    else:
        position = "running"
        waiting_on = None
    # Cross-rank stream alignment starts at each rank's LAST rendezvous
    # event: a survivor's ring spans earlier epochs a respawned peer
    # never lived through, and comparing from ring-start would convict
    # every recovered elastic job of "divergence".  Non-elastic rings
    # have no rendezvous events and align whole.
    aligned = events
    for i in range(len(events) - 1, -1, -1):
        if events[i].get("kind") == "rendezvous":
            aligned = events[i + 1:]
            break
    # Restore provenance: the checkpoint tier records every recovery
    # as a ``ckpt.restore`` event with a ``source=peer|disk|none``
    # detail.  The NEWEST one is this incarnation's recovery story —
    # the analyzer's proof of where a respawned rank's state came from.
    restores = [e for e in events if e.get("kind") == "ckpt.restore"]
    last_restore = None
    if restores:
        ev = restores[-1]
        fields = dict(
            kv.split("=", 1) for kv in (ev.get("detail") or "").split()
            if "=" in kv
        )
        last_restore = {
            "source": fields.get("source"),
            "replica_adopted": fields.get("replica") == "True",
            "ms": float(fields["ms"]) if "ms" in fields else None,
            "commits": ev.get("cycle"),
        }
    # OOM black box: the memory plane drops a ``mem.oom`` event (last
    # census + dominant owner) on every RESOURCE_EXHAUSTED death path.
    # The NEWEST one is this incarnation's memory story — the proof of
    # WHAT was resident when the allocator gave up.
    # Training-health black box: the divergence sentinel drops a
    # ``health.divergence`` event the moment a rank's state digest
    # splits from the majority, and the health monitor a
    # ``health.nonfinite`` on the FIRST nonfinite gradient.  The NEWEST
    # of each is this incarnation's numerics story — often the real
    # root cause steps before the crash the other planes see.
    divs = [e for e in events if e.get("kind") == "health.divergence"]
    last_divergence = None
    if divs:
        ev = divs[-1]
        fields = dict(
            kv.split("=", 1) for kv in (ev.get("detail") or "").split()
            if "=" in kv
        )
        last_divergence = {
            "step": ev.get("cycle"),
            "component": fields.get("component") or ev.get("name"),
            "bucket": fields.get("bucket"),
            "leaf": fields.get("leaf"),
            "minority": fields.get("minority"),
        }
    nonfinites = [e for e in events if e.get("kind") == "health.nonfinite"]
    first_nonfinite = None
    if nonfinites:
        ev = nonfinites[0]
        fields = dict(
            kv.split("=", 1) for kv in (ev.get("detail") or "").split()
            if "=" in kv
        )
        first_nonfinite = {
            "step": ev.get("cycle"),
            "bucket": fields.get("bucket"),
            "leaf": fields.get("leaf"),
            "count": fields.get("count"),
        }
    health_alerts = sorted({
        e.get("name") for e in events
        if e.get("kind") == "health.alert" and e.get("name")
    })
    ooms = [e for e in events if e.get("kind") == "mem.oom"]
    last_oom = None
    if ooms:
        ev = ooms[-1]
        fields = dict(
            kv.split("=", 1) for kv in (ev.get("detail") or "").split()
            if "=" in kv
        )

        def _num(key, cast):
            try:
                return cast(fields[key])
            except (KeyError, TypeError, ValueError):
                return None

        last_oom = {
            "where": fields.get("where"),
            "owner": fields.get("owner"),
            "share": _num("share", float),
            "owner_bytes": _num("owner_bytes", int),
            "total_bytes": _num("total_bytes", int),
            "in_use": _num("in_use", int),
            "limit": _num("limit", int),
        }
    return {
        "rank": int(doc.get("rank")),
        "epoch": doc.get("epoch") or 0,
        "trigger": trigger,
        "died": died,
        "wall_time": doc.get("wall_time"),
        "recorded": doc.get("recorded", len(events)),
        "overwritten": doc.get("overwritten", 0),
        "position": position,
        "waiting_on": waiting_on,
        "last_event": last_event,
        "last_collective": (last_complete or {}).get("name") or None,
        "last_exception": doc.get("last_exception"),
        "last_restore": last_restore,
        "last_oom": last_oom,
        "last_divergence": last_divergence,
        "first_nonfinite": first_nonfinite,
        "health_alerts": health_alerts,
        "submitted": [e.get("name") for e in aligned
                      if e.get("kind") == "enqueue"],
        "completed": [e.get("name") for e in aligned
                      if e.get("kind") == "complete"],
        "dump_path": doc.get("_path"),
    }


def _counted(seq: List[str]) -> List[tuple]:
    """Stream of (op, k-th occurrence): real training loops reuse the
    same tensor names every step, so bare names cannot identify WHICH
    instance of a collective two ranks have in common."""
    counts: Dict[str, int] = {}
    out = []
    for op in seq:
        counts[op] = counts.get(op, 0) + 1
        out.append((op, counts[op]))
    return out


def _last_common_collective(ranks: List[dict]) -> Optional[dict]:
    """The last collective instance every rank completed.  Streams are
    already rendezvous-aligned (see :func:`_rank_summary`) and
    negotiation is deterministic, so each rank's completion stream is a
    prefix of the same global sequence; occurrence-counting makes
    repeated names (``grad_w`` completed every step) identify distinct
    instances instead of matching a 100-step-old completion."""
    if any(not r["completed"] for r in ranks) or not ranks:
        return None
    if any(r["overwritten"] for r in ranks):
        # A wrapped ring's surviving window starts at an unknown true
        # instance, so occurrence labels no longer align across ranks
        # — a confidently wrong "all ranks completed X" would mask the
        # very lag the post-mortem exists to expose.  (Elastic rings
        # are re-anchored at each rendezvous, so this bites only
        # long static epochs; raise HVDTPU_FLIGHTREC_CAPACITY to
        # widen the window.)
        LOG.warning(
            "flight-recorder ring(s) overwrote events; skipping "
            "last-common-collective alignment (window starts unknown)"
        )
        return None
    streams = [_counted(r["completed"]) for r in ranks]
    common = set(streams[0])
    for s in streams[1:]:
        common &= set(s)
    if not common:
        return None
    for op, k in reversed(streams[0]):
        if (op, k) in common:
            return {"op": op, "occurrence": k}
    return None


def _schedule_divergence(ranks: List[dict]) -> Optional[dict]:
    """The classic desync: ranks submitted *different* op sequences.
    Compare the per-rank enqueue streams position by position; the
    first index where two ranks disagree (both having submitted that
    many ops — a rank that merely died earlier is not divergent) is the
    divergence point."""
    seqs = {r["rank"]: r["submitted"] for r in ranks if r["submitted"]}
    if len(seqs) < 2:
        return None
    # Suffix-align overwritten rings the cheap, honest way: divergence
    # detection is only exact while no ring overwrote its head.
    if any(r["overwritten"] for r in ranks):
        LOG.warning(
            "flight-recorder ring(s) overwrote events; schedule-"
            "divergence detection covers only the surviving window"
        )
    depth = min(len(s) for s in seqs.values())
    for i in range(depth):
        ops = {rank: s[i] for rank, s in seqs.items()}
        if len(set(ops.values())) > 1:
            return {"index": i, "ops": ops}
    return None


def _read_live_history(path: Optional[str]) -> Optional[dict]:
    if not path or not os.path.exists(path):
        return None
    last = None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        last = json.loads(line)
                    except ValueError:
                        continue  # crash-torn final row
    except OSError:
        return None
    return last


def analyze(
    dumps: List[dict],
    *,
    expected_ranks: Optional[int] = None,
    live_history: Optional[str] = None,
    timeline_path: Optional[str] = None,
) -> dict:
    """Correlate per-rank flight-recorder dumps into the report dict
    (schema ``hvdtpu-postmortem-v1``)."""
    per_rank = _latest_per_rank(dumps)
    ranks = sorted(
        (_rank_summary(doc) for doc in per_rank.values()),
        key=lambda r: r["rank"],
    )
    present = {r["rank"] for r in ranks}
    missing = (
        sorted(set(range(expected_ranks)) - present)
        if expected_ranks else []
    )

    dead = [r for r in ranks if r["died"]]
    # First-failure ordering: a SELF-inflicted death (SIGABRT, an
    # uncaught exception) outranks a SIGTERM — the launcher's failure
    # propagation SIGTERMs the survivors, so in a cascade the SIGTERM
    # dumps are consequences, not causes.  Wall time is only the
    # tiebreak WITHIN a class: the cascade gap is routinely sub-second,
    # inside ordinary cross-host clock skew, so raw wall-clock
    # comparison across hosts would blame whichever host's clock ran
    # behind (the same reason heartbeat staleness is judged on the
    # launcher's clock only).
    first = min(
        dead,
        key=lambda r: (r["trigger"] == "signal:SIGTERM",
                       r["wall_time"] or 0.0),
    ) if dead else None
    first_failure: Optional[dict] = None
    if first is not None:
        first_failure = {
            "rank": first["rank"],
            "trigger": first["trigger"],
            "wall_time": first["wall_time"],
            "last_event": first["last_event"],
            "last_collective": first["last_collective"],
            "exception": (first["last_exception"] or {}).get("type"),
        }
    elif missing:
        first_failure = {
            "rank": missing[0],
            "trigger": "no_black_box",
            "wall_time": None,
            "last_event": None,
            "last_collective": None,
            "exception": None,
        }

    report = {
        "schema": REPORT_SCHEMA,
        "expected_ranks": expected_ranks,
        "ranks_with_dumps": sorted(present),
        "ranks_missing_dumps": missing,
        "first_failure": first_failure,
        "last_common_collective": _last_common_collective(ranks),
        "schedule_divergence": _schedule_divergence(ranks),
        "restore_provenance": {
            str(r["rank"]): r["last_restore"]
            for r in ranks if r.get("last_restore")
        },
        "memory": {
            str(r["rank"]): r["last_oom"]
            for r in ranks if r.get("last_oom")
        },
        "health": {
            str(r["rank"]): {
                "divergence": r.get("last_divergence"),
                "first_nonfinite": r.get("first_nonfinite"),
                "alerts": r.get("health_alerts") or [],
            }
            for r in ranks
            if r.get("last_divergence") or r.get("first_nonfinite")
            or r.get("health_alerts")
        },
        "ranks": ranks,
        "live_last_round": _read_live_history(live_history),
    }
    if timeline_path and os.path.exists(timeline_path):
        report["timeline"] = {"path": timeline_path}
    return report


def verdict(report: dict) -> str:
    """The human paragraph: who failed first, in what, and who was
    left waiting on whom."""
    parts: List[str] = []
    first = report.get("first_failure")
    if first is None:
        parts.append(
            "No rank left a death-path dump — every black box records a "
            "routine exit.  If the job still failed, the failure was in "
            "the launcher or outside the instrumented ranks."
        )
    elif first.get("trigger") == "no_black_box":
        parts.append(
            f"Rank {first['rank']} left no black box (SIGKILL, OOM "
            f"kill, lost host, or it never started) and is the most "
            f"likely first failure."
        )
    else:
        last_ev = first.get("last_event") or {}
        desc = f"rank {first['rank']} failed first ({first['trigger']}"
        if first.get("exception"):
            desc += f", {first['exception']}"
        desc += ")"
        if last_ev:
            desc += (
                f"; its last recorded event was {last_ev.get('kind')!r}"
            )
            if last_ev.get("name"):
                desc += f" of {last_ev.get('name')!r}"
            cyc = last_ev.get("cycle")
            if cyc is not None and cyc >= 0:
                desc += f" at cycle {cyc}"
        if first.get("last_collective"):
            desc += (
                f"; the last collective it completed was "
                f"{first['last_collective']!r}"
            )
        parts.append(desc[0].upper() + desc[1:] + ".")
    later_dead = [
        r for r in report.get("ranks", [])
        if r["died"] and first is not None and r["rank"] != first.get("rank")
    ]
    if later_dead:
        parts.append(
            "Subsequently "
            + "; ".join(
                f"rank {r['rank']} died ({r['trigger']}"
                + (f", {r['last_exception']['type']}"
                   if r.get("last_exception") else "")
                + ")"
                for r in later_dead
            )
            + "."
        )
    common = report.get("last_common_collective")
    if common:
        inst = (f" (instance #{common['occurrence']})"
                if common.get("occurrence", 1) > 1 else "")
        parts.append(
            f"The last collective all ranks completed was "
            f"{common['op']!r}{inst}."
        )
    waiters = [
        r for r in report.get("ranks", [])
        if r["position"] == "waiting"
        and (first is None or r["rank"] != first.get("rank"))
    ]
    if waiters:
        parts.append(
            "At the time of death "
            + "; ".join(
                f"rank {r['rank']} was waiting on "
                f"{(r['waiting_on'] or 'an unnamed op')!r}"
                for r in waiters
            )
            + "."
        )
    exited = [r["rank"] for r in report.get("ranks", [])
              if r["position"] == "exited"]
    if exited:
        parts.append(
            f"Rank(s) {exited} had already exited cleanly."
        )
    div = report.get("schedule_divergence")
    if div:
        ops = ", ".join(
            f"rank {rank} submitted {op!r}"
            for rank, op in sorted(div["ops"].items())
        )
        parts.append(
            f"COLLECTIVE SCHEDULE DIVERGENCE at submission #"
            f"{div['index'] + 1}: {ops} — ranks disagreeing on the op "
            f"sequence is the classic desync hang."
        )
    health = report.get("health") or {}
    div_bits = []
    nf_bits = []
    alert_bits = []
    for rank, h in sorted(health.items(), key=lambda kv: int(kv[0])):
        h = h or {}
        d = h.get("divergence")
        if d:
            where = d.get("component") or "?"
            if d.get("leaf"):
                where += f" (leaf {d['leaf']})"
            bit = f"rank {rank} diverged from the majority"
            if d.get("minority") not in (None, "", str(rank)):
                bit = (f"rank(s) {d['minority']} diverged from the "
                       f"majority (seen by rank {rank})")
            if d.get("step") is not None:
                bit += f" at step {d['step']}"
            bit += f" in {where}"
            div_bits.append(bit)
        nf = h.get("first_nonfinite")
        if nf:
            bit = f"rank {rank}'s first nonfinite gradient"
            if nf.get("step") is not None:
                bit += f" appeared at step {nf['step']}"
            if nf.get("leaf"):
                bit += f" in leaf {nf['leaf']!r}"
                if nf.get("bucket") is not None:
                    bit += f" (bucket {nf['bucket']})"
            nf_bits.append(bit)
        alerts = h.get("alerts") or []
        if alerts and not d and not nf:
            alert_bits.append(
                f"rank {rank} raised health alert(s) "
                + ", ".join(repr(a) for a in alerts)
            )
    if div_bits:
        # Dedup: every rank records the identical verdict (the sentinel
        # compares the same gathered matrix everywhere).
        parts.append("TRAINING-STATE DIVERGENCE: "
                     + "; ".join(sorted(set(div_bits))) + " — "
                     "the bitwise-replication invariant broke at "
                     "runtime; do not trust checkpoints taken after "
                     "this step.")
    if nf_bits:
        parts.append("NONFINITE GRADIENTS: " + "; ".join(nf_bits) + ".")
    if alert_bits:
        parts.append("Training-health alerts before death: "
                     + "; ".join(alert_bits) + ".")
    mem = report.get("memory") or {}
    if mem:
        def _gb(b):
            return (f"{b / 2 ** 30:.2f}GB" if b and b >= 2 ** 30
                    else f"{(b or 0) / 2 ** 20:.1f}MB")

        bits = []
        for rank, m in sorted(mem.items(), key=lambda kv: int(kv[0])):
            m = m or {}
            bit = f"rank {rank} died allocating in {m.get('where')!r}"
            if m.get("owner"):
                bit += f"; {m['owner']} held"
                if m.get("share") is not None:
                    bit += f" {m['share']:.0%} of"
                bit += " the tagged device memory"
                if m.get("owner_bytes"):
                    bit += f" ({_gb(m['owner_bytes'])}"
                    if m.get("total_bytes"):
                        bit += f" of {_gb(m['total_bytes'])}"
                    bit += ")"
            if m.get("in_use") is not None and m.get("limit"):
                bit += (f"; HBM {_gb(m['in_use'])} in use of "
                        f"{_gb(m['limit'])}")
            bits.append(bit)
        parts.append("OUT OF DEVICE MEMORY: " + "; ".join(bits) + ".")
    prov = report.get("restore_provenance") or {}
    if prov:
        parts.append(
            "Recovery provenance: "
            + "; ".join(
                f"rank {rank} restored from "
                + {"peer": "a live peer", "disk": "the disk manifest",
                   "none": "nothing (fresh start)"}.get(
                       (p or {}).get("source"), "an unknown source")
                + (f" at commit {p['commits']}"
                   if (p or {}).get("commits") is not None else "")
                for rank, p in sorted(prov.items(),
                                      key=lambda kv: int(kv[0]))
            )
            + "."
        )
    missing = report.get("ranks_missing_dumps") or []
    if missing and (first is None
                    or first.get("trigger") != "no_black_box"):
        parts.append(
            f"Rank(s) {missing} left no black box "
            f"(SIGKILL/OOM/lost host cannot be caught)."
        )
    return " ".join(parts)


def write_report(report: dict, path: str) -> str:
    return pathspec.write_json_atomic(path, report)


def generate(
    spec: str,
    *,
    expected_ranks: Optional[int] = None,
    live_history: Optional[str] = None,
    timeline_path: Optional[str] = None,
    output: Optional[str] = None,
) -> Optional[dict]:
    """The launcher's one-call entry: load, analyze, write
    ``postmortem.json`` (default: next to the dumps when ``spec`` is a
    directory), return the report — or None when no dumps exist.  Never
    raises: a post-mortem failure must not mask the job's real error."""
    try:
        dumps = load_dumps(spec)
        if not dumps:
            LOG.warning("no flight-recorder dumps under %r — "
                        "no post-mortem possible", spec)
            return None
        report = analyze(
            dumps, expected_ranks=expected_ranks,
            live_history=live_history, timeline_path=timeline_path,
        )
        report["verdict"] = verdict(report)
        if output is None and os.path.isdir(spec):
            output = os.path.join(spec, "postmortem.json")
        if output:
            report["report_path"] = write_report(report, output)
        return report
    except Exception as exc:  # pragma: no cover - defensive
        LOG.warning("post-mortem generation failed: %s", exc)
        return None


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.obs.postmortem",
        description=(
            "Correlate per-rank flight-recorder dumps into a root-cause "
            "report for a dead job."
        ),
    )
    parser.add_argument(
        "dumps",
        help="The HVDTPU_FLIGHTREC_DUMP value the job used: a "
             "directory, a {rank} template, or a plain path.",
    )
    parser.add_argument("--live-history", default=None,
                        help="live_history.jsonl from the live plane.")
    parser.add_argument("--timeline", default=None,
                        help="Merged all-rank Chrome trace, if present.")
    parser.add_argument("--expected-ranks", type=int, default=None,
                        help="Job world size (flags ranks with no dump).")
    parser.add_argument("--output", default=None,
                        help="Where to write postmortem.json "
                             "(default: next to the dumps).")
    args = parser.parse_args(argv)
    report = generate(
        args.dumps,
        expected_ranks=args.expected_ranks,
        live_history=args.live_history,
        timeline_path=args.timeline,
        output=args.output,
    )
    if report is None:
        print(f"postmortem: no flight-recorder dumps under "
              f"{args.dumps!r}", file=sys.stderr)
        return 2
    print(report["verdict"])
    if report.get("report_path"):
        print(f"postmortem report: {report['report_path']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
