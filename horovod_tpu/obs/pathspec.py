"""Shared per-rank artifact path resolution.

Both observability artifacts — metrics dumps (``HVDTPU_METRICS_DUMP``)
and timelines (``HVDTPU_TIMELINE``) — accept the same value forms and
must agree between the writers (one file per rank) and the launcher-side
aggregators (glob them all back).  One implementation, parameterized by
the filename stem, so the rules can never desynchronize:

* ``{rank}`` template — substituted verbatim;
* a directory (existing, or trailing separator) — ``<stem>.<tag>.json``
  inside it;
* plain path — the tag is inserted before the extension.

The tag is ``rank.<k>``, epoch-qualified to ``e<E>.rank.<k>`` under the
elastic launcher (``HVDTPU_ELASTIC_EPOCH``): a respawned incarnation
must never overwrite the file its dead predecessor left — that file is
the evidence of why it died.
"""

from __future__ import annotations

import itertools
import json
import os
import re
from typing import Optional

__all__ = ["resolve", "glob_pattern", "rank_of_path", "epoch_of_path",
           "epoch_tag", "write_json_atomic", "write_bytes_atomic"]

# Per-call uniquifier for tmp names: pid alone is not enough — a
# signal-handler flush may reentrantly interrupt an in-progress dump on
# the SAME thread (the flight recorder's death path is built for
# exactly that), and two writers sharing one tmp path would tear the
# final document.  itertools.count().__next__ is atomic under the GIL.
_tmp_seq = itertools.count()


def write_bytes_atomic(path: str, data: bytes) -> str:
    """The one atomic byte write every durable artifact uses (checkpoint
    Store payloads, checkpoint shards, and — via
    :func:`write_json_atomic` — every obs JSON document): per-call-unique
    tmp file + ``os.replace`` so a reader, a crash mid-write, or a
    reentrant second writer can never leave a torn or half-visible file.
    A failed write removes its own tmp so clean directories stay clean.
    Returns ``path``."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{next(_tmp_seq)}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    return path


def write_json_atomic(path: str, doc, *, indent: int = 1) -> str:
    """The one atomic JSON write every obs artifact uses (metrics dump,
    flight-recorder dump, post-mortem report, merged timeline):
    tmp-file + ``os.replace`` so a reader — or a crash mid-write —
    never sees a torn document.  Returns ``path``."""
    return write_bytes_atomic(
        path, json.dumps(doc, indent=indent).encode()
    )

_RANK_RE = re.compile(r"(?:^|[^0-9a-zA-Z])rank[._]?(\d+)")
_EPOCH_RE = re.compile(r"\.e(\d+)\.")


def resolve(raw: str, stem: str, rank, epoch: Optional[str] = None) -> str:
    """This rank's file for the env value ``raw``.  ``epoch=None`` reads
    ``HVDTPU_ELASTIC_EPOCH`` from the environment."""
    rank = str(rank)
    if epoch is None:
        epoch = os.environ.get("HVDTPU_ELASTIC_EPOCH")
    tag = (f"e{epoch}.rank.{rank}" if epoch not in (None, "")
           else f"rank.{rank}")
    if "{rank}" in raw:
        # Template form keeps the user's exact shape; the epoch tag is
        # still inserted (before the extension) — the
        # never-overwrite-the-predecessor invariant holds for every form.
        path = raw.replace("{rank}", rank)
        if epoch not in (None, ""):
            base, ext = os.path.splitext(path)
            path = f"{base}.e{epoch}{ext}"
        return path
    if raw.endswith(os.sep) or os.path.isdir(raw):
        return os.path.join(raw, f"{stem}.{tag}.json")
    base, ext = os.path.splitext(raw)
    return f"{base}.{tag}{ext or '.json'}"


def epoch_tag(path: str, epoch: Optional[str] = None) -> str:
    """Insert the elastic-epoch tag (``.e<E>`` before the extension)
    into ``path`` when an epoch is active; identity otherwise.  For
    artifacts that are per-job rather than per-rank (the autotune CSV —
    only rank 0 tunes): a respawned incarnation must append to its own
    epoch's file, never clobber its dead predecessor's history."""
    if epoch is None:
        epoch = os.environ.get("HVDTPU_ELASTIC_EPOCH")
    if epoch in (None, ""):
        return path
    base, ext = os.path.splitext(path)
    return f"{base}.e{epoch}{ext}"


def glob_pattern(raw: str, stem: str) -> str:
    """The glob matching every per-rank file :func:`resolve` can derive
    from ``raw`` (all ranks, all epochs) — what the launcher aggregates.
    Never matches the merged/summary output path itself."""
    if "{rank}" in raw:
        return raw.replace("{rank}", "*")
    if raw.endswith(os.sep) or os.path.isdir(raw):
        return os.path.join(raw, f"{stem}.*rank*.json")
    base, ext = os.path.splitext(raw)
    return f"{base}.*rank*{ext or '.json'}"


def rank_of_path(path: str) -> Optional[int]:
    """Best-effort rank extraction from a per-rank filename
    (``trace.rank.3.json``, ``trace.e1.rank.3.json``, ``rank_3`` ...)."""
    m = None
    for m in _RANK_RE.finditer(os.path.basename(path)):
        pass  # keep the last match: epoch tags come before the rank tag
    return int(m.group(1)) if m else None


def epoch_of_path(path: str) -> Optional[int]:
    m = _EPOCH_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None
