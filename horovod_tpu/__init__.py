"""horovod_tpu — a TPU-native distributed deep-learning training framework.

A ground-up rebuild of the capabilities of Horovod 0.19.1 (reference:
yangw1234/horovod, see SURVEY.md) designed for TPU hardware: XLA collectives
(`psum`/`all_gather`/`ppermute`) compiled over the ICI/DCN device mesh
replace MPI/NCCL/Gloo; `jax.distributed` + an HTTP rendezvous replace the
MPI/Gloo controller bootstrap; a native (C++) background engine provides the
reference's asynchronous named-tensor eager path (negotiation, tensor
fusion, response cache, timeline, stall inspection).

Typical use (the reference's four-line recipe, README.rst "Usage")::

    import horovod_tpu as hvd

    hvd.init()
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))          # grads psum'd
    params = hvd.broadcast_parameters(params, root_rank=0)     # state sync
    step = hvd.distribute(train_step)                          # shard_map'd
"""

from .basics import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    num_devices,
    device_rank,
    is_homogeneous,
    slice_id,
    num_slices,
    slice_size,
    slice_of_rank,
    xla_collectives_built,
    native_engine_built,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    gloo_built,
    gloo_enabled,
    nccl_built,
    ccl_built,
    ddl_built,
    mesh,
    global_topology,
    DP_AXIS,
    CROSS_AXIS,
    LOCAL_AXIS,
    SLICE_AXIS,
)
from .ops.collectives import (  # noqa: F401
    ReduceOp,
    Average,
    Sum,
    Adasum,
    Min,
    Max,
    allreduce,
    allreduce_,
    grouped_allreduce,
    allgather,
    broadcast,
    broadcast_,
    alltoall,
    reducescatter,
)

__version__ = "0.1.0"


def __getattr__(name):
    # Heavier layers load lazily so `import horovod_tpu` stays cheap and the
    # jit-only path never starts the eager engine.
    if name in (
        "DistributedOptimizer",
        "DistributedGradientTransform",
        "distribute",
        "broadcast_parameters",
        "broadcast_optimizer_state",
        "broadcast_object",
        "sync_gradients",
        "OverlapPlan",
        "overlap",
    ):
        from . import optim  # noqa: PLC0415

        return getattr(optim, name)
    if name == "Compression":
        from .ops.compression import Compression  # noqa: PLC0415

        return Compression
    if name in ("Estimator", "Model"):
        from . import estimator as _est  # noqa: PLC0415

        return getattr(_est, name)
    if name in (
        "Store",
        "LocalStore",
        "save_checkpoint",
        "save_checkpoint_async",
        "restore_checkpoint",
        "latest_checkpoint_step",
    ):
        from . import checkpoint as _ckpt  # noqa: PLC0415

        return getattr(_ckpt, name)
    if name in ("IndexedSlices", "allreduce_sparse", "sparse_to_dense"):
        from .ops import sparse as _sparse  # noqa: PLC0415

        return {
            "IndexedSlices": _sparse.IndexedSlices,
            "allreduce_sparse": _sparse.allreduce_sparse,
            "sparse_to_dense": _sparse.to_dense,
        }[name]
    if name in (
        "allreduce_async",
        "allreduce_async_",
        "allgather_async",
        "broadcast_async",
        "broadcast_async_",
        "synchronize",
        "poll",
        "join",
    ):
        from .ops import eager  # noqa: PLC0415

        return getattr(eager, name)
    if name == "SyncBatchNorm":
        from .parallel.sync_batch_norm import SyncBatchNorm  # noqa: PLC0415

        return SyncBatchNorm
    if name == "callbacks":
        import importlib  # noqa: PLC0415

        # `from . import callbacks` would re-enter this __getattr__ while
        # the submodule is mid-import (fromlist probing) and recurse.
        return importlib.import_module("horovod_tpu.callbacks")
    if name == "obs":
        import importlib  # noqa: PLC0415

        # Observability plane (metrics registry, progress beat, timeline
        # merge) — see docs/observability.md.
        return importlib.import_module("horovod_tpu.obs")
    raise AttributeError(f"module 'horovod_tpu' has no attribute {name!r}")
