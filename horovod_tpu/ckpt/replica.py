"""Peer-replica tier: committed state kept live, restore without disk.

The elastic launcher can respawn a dead rank in seconds (PR 1), but the
respawned incarnation still has to get its state from somewhere.  The
durable floor is the sharded manifest on disk (ckpt/sharded.py); this
tier keeps a *hot* copy so the common case — one preempted rank in an
otherwise healthy job — never touches cold storage (Ray's
lineage/supervision pattern, PAPERS.md, specialized to SPMD):

* **Push on commit** — after every commit, each rank pushes its shard
  (chunked at ``HVDTPU_CKPT_REPLICA_CHUNK_KB``, SHA-256-checksummed) to
  its ring neighbor's replica key over the launcher's HMAC-signed KV
  path — the same authenticated transport heartbeats and rendezvous
  already trust.  The meta record is written LAST and chunk keys are
  step-namespaced, so a rank dying mid-push leaves the *previous*
  replica intact and readable, never a torn one.
* **Fetch on respawn** — a respawned incarnation asks for the replica
  its predecessor pushed.  Checksum or chunk-count mismatch, or a
  replica from a different job generation, makes :meth:`fetch` return
  ``None`` — the caller falls back to disk.  Old-step chunks are
  garbage-collected (authenticated DELETE) after each successful push.
* **Honest limits** — replicas live in the launcher-resident KV store's
  memory: they survive any number of *rank* deaths but die with the
  launcher/job.  Disk is still the durability floor; this tier is the
  fast path above it, not a replacement.

The ``drop_replica`` fault action (``HVDTPU_FAULT_SPEC=
"replica_push:rank=1:action=drop_replica"``) deterministically
suppresses a push, so stale-replica recovery is testable.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import List, Optional, Tuple

from ..obs import flightrec as _flightrec
from ..obs import get_registry
from ..testing.faults import maybe_fail
from ..utils import env as envmod
from ..utils.logging import get_logger

LOG = get_logger("ckpt")

SCOPE = "ckptrep"

__all__ = ["SCOPE", "ReplicaTier", "tier_from_env", "job_fingerprint"]


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def job_fingerprint(kv) -> str:
    """Job identity derived from the per-job HMAC secret — the guard
    every KV-resident artifact that must not outlive its job stamps
    itself with.  A *different* secret already fails the transport MAC;
    this closes the same-secret-endpoint-reuse case.  Shared by the
    replica tier below and the weight hot-swap announce channel
    (serve/hotswap.py), so both planes reject a recycled endpoint the
    same way."""
    secret = getattr(kv, "_secret", "") or ""
    return hashlib.sha256(
        b"hvdtpu-ckpt-job:" + secret.encode()
    ).hexdigest()[:16]


class ReplicaTier:
    """One rank's handle on the replica plane.

    ``kv`` is a :class:`~..run.rendezvous.KVStoreClient` (HMAC-signed);
    ``world`` is the current membership list, used only to pick the
    ring neighbor recorded as the replica's holder — the key space is
    per-owner, so membership changes never orphan a replica."""

    def __init__(self, kv, rank: int, world: Optional[List[int]] = None,
                 *, chunk_bytes: Optional[int] = None):
        self.kv = kv
        self.rank = int(rank)
        self.world = sorted(world) if world else [self.rank]
        if chunk_bytes is None:
            chunk_bytes = envmod.env_int(
                envmod.CKPT_REPLICA_CHUNK_KB,
                envmod.DEFAULT_REPLICA_CHUNK_KB,
            ) * 1024
        self.chunk_bytes = max(int(chunk_bytes), 1)
        # Job fingerprint: a long-lived/reused KV endpoint must never
        # serve one job's replica to the next job's 0-commit respawn as
        # its own predecessor's state (see job_fingerprint above).
        self.job_id = job_fingerprint(kv)

    # ------------------------------------------------------------ topology

    def holder(self, owner: Optional[int] = None) -> int:
        """The ring neighbor that nominally holds ``owner``'s replica
        (next member in sorted world order).  Observability only: the
        replica bytes live in the KV store either way."""
        owner = self.rank if owner is None else int(owner)
        world = self.world if owner in self.world else sorted(
            set(self.world) | {owner}
        )
        i = world.index(owner)
        return world[(i + 1) % len(world)]

    # ---------------------------------------------------------------- push

    def push(self, payload: bytes, *, step: int,
             commits: Optional[int] = None) -> bool:
        """Push this rank's committed shard.  Chunks first, meta LAST —
        the meta rename is the replica's commit point, so a mid-push
        death leaves the previous version valid.  Returns False when a
        ``drop_replica`` fault suppressed the push (chaos) or the KV
        store is unreachable (launcher going down — never fatal: the
        commit itself already succeeded)."""
        if maybe_fail("replica_push", step=step,
                      rank=self.rank) == "drop_replica":
            get_registry().counter("ckpt.replica_dropped").inc()
            LOG.warning("replica push for step %d suppressed by "
                        "drop_replica fault", step)
            return False
        t0 = time.monotonic()
        checksum = _sha256(payload)
        chunks = [payload[i:i + self.chunk_bytes]
                  for i in range(0, len(payload), self.chunk_bytes)] or [b""]
        meta = {
            "step": int(step),
            "commits": int(step if commits is None else commits),
            "chunks": len(chunks),
            "bytes": len(payload),
            "checksum": checksum,
            "holder": self.holder(),
            "job": self.job_id,
            "pushed_at": time.time(),
        }
        written = 0
        try:
            for i, chunk in enumerate(chunks):
                self.kv.put(SCOPE, f"o{self.rank}.s{step}.c{i}", chunk)
                written = i + 1
            old = self._meta()
            self.kv.put(SCOPE, f"owner_{self.rank}",
                        json.dumps(meta).encode())
            if old is not None and old.get("step") != meta["step"]:
                self._gc(old)
        except Exception as exc:
            # The KV store going away mid-push (launcher teardown) must
            # not fail the commit that triggered the push — and the
            # chunks this attempt DID land are unreachable (the meta
            # still names the previous step), so sweep them rather
            # than leak a snapshot's worth of store memory per failure.
            LOG.warning("replica push for step %d failed: %s", step, exc)
            get_registry().counter("ckpt.replica_push_errors").inc()
            self._gc({"step": step, "chunks": written})
            return False
        metrics = get_registry()
        metrics.histogram("ckpt.replica_push_ms").observe(
            (time.monotonic() - t0) * 1e3
        )
        metrics.counter("ckpt.replica_pushes").inc()
        metrics.counter("ckpt.replica_push_bytes").inc(len(payload))
        _flightrec.record(
            "ckpt.replica_push", name=f"step{step}", cycle=int(step),
            detail=f"bytes={len(payload)} chunks={len(chunks)} "
                   f"holder={meta['holder']}",
        )
        return True

    def _meta(self, owner: Optional[int] = None) -> Optional[dict]:
        owner = self.rank if owner is None else int(owner)
        try:
            raw = self.kv.get(SCOPE, f"owner_{owner}")
        except Exception as exc:
            # Transport/auth failure reads as "no replica" — the
            # recovery path must degrade to disk, never crash in sync.
            LOG.warning("replica meta fetch for rank %d failed: %s",
                        owner, exc)
            return None
        if raw is None:
            return None
        try:
            return json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return None

    def _gc(self, old_meta: dict, owner: Optional[int] = None) -> None:
        """Best-effort delete of a superseded replica's chunks."""
        owner = self.rank if owner is None else int(owner)
        step = old_meta.get("step")
        for i in range(int(old_meta.get("chunks") or 0)):
            try:
                self.kv.delete(SCOPE, f"o{owner}.s{step}.c{i}")
            except Exception:
                return  # launcher going down; leak is bounded anyway

    # --------------------------------------------------------------- fetch

    def fetch(self, owner: Optional[int] = None
              ) -> Optional[Tuple[bytes, dict]]:
        """The newest valid replica pushed for ``owner`` (default: this
        rank — the respawn path asks for its predecessor's).  Returns
        ``(payload, meta)``, or None when no replica exists, a chunk is
        missing (push died before its meta landed... then meta is old
        and chunks exist; a *gc race* can still lose one), or the
        checksum fails — every None means "fall back to disk"."""
        owner = self.rank if owner is None else int(owner)
        meta = self._meta(owner)
        if meta is None:
            return None
        if meta.get("job") != self.job_id:
            # Another job's leftover on a reused KV endpoint: valid
            # bytes, wrong universe — never adopt it.
            get_registry().counter("ckpt.replica_invalid").inc()
            LOG.warning(
                "replica for rank %d belongs to a different job "
                "(fingerprint %s != %s); ignoring it", owner,
                meta.get("job"), self.job_id,
            )
            return None
        step = meta.get("step")
        parts = []
        try:
            for i in range(int(meta.get("chunks") or 0)):
                raw = self.kv.get(SCOPE, f"o{owner}.s{step}.c{i}")
                if raw is None:
                    get_registry().counter("ckpt.replica_invalid").inc()
                    return None
                parts.append(raw)
        except Exception as exc:
            LOG.warning("replica fetch for rank %d failed: %s", owner, exc)
            return None
        payload = b"".join(parts)
        if _sha256(payload) != meta.get("checksum"):
            get_registry().counter("ckpt.replica_invalid").inc()
            LOG.warning(
                "replica for rank %d (step %s) failed checksum "
                "validation; ignoring it", owner, step,
            )
            return None
        return payload, meta


def tier_from_env(ctx=None) -> Optional[ReplicaTier]:
    """Build the ambient tier when ``HVDTPU_CKPT_REPLICA`` is on.

    Under the elastic launcher the tier rides the rendezvous store (the
    worker's :class:`ElasticContext` supplies client, rank, and world);
    outside it, ``HVDTPU_ELASTIC_KV``/``HVDTPU_LIVE_KV`` name the
    endpoint directly.  None when the knob is off or no KV endpoint
    exists — callers degrade to disk."""
    import os  # noqa: PLC0415

    if not envmod.env_bool(envmod.CKPT_REPLICA):
        return None
    if ctx is not None and getattr(ctx, "kv", None) is not None:
        return ReplicaTier(ctx.kv, ctx.rank, list(ctx.world))
    addr = (os.environ.get("HVDTPU_ELASTIC_KV")
            or os.environ.get(envmod.LIVE_KV))
    if not addr:
        return None
    from ..run.rendezvous import KVStoreClient  # noqa: PLC0415
    from ..utils.env import resolve_rank  # noqa: PLC0415

    return ReplicaTier(KVStoreClient(addr), resolve_rank(0))
