"""Sharded checkpoints + peer-replica recovery (ISSUE 7).

Two coupled tiers above the rank-0 orbax path in ``checkpoint.py``:

* :mod:`~horovod_tpu.ckpt.sharded` — every rank writes only its own
  shard (atomic, checksummed), rank 0 commits a manifest LAST, restore
  reassembles and reshards across world-size changes (N -> M).
* :mod:`~horovod_tpu.ckpt.replica` — each rank mirrors its committed
  shard to its ring neighbor's key over the HMAC-signed KV path, so a
  respawned rank restores from a live peer replica in seconds and
  touches disk only when no peer holds a valid copy.

``elastic.State`` routes commit/restore/sync through both tiers; the
restore *provenance* (``peer`` / ``disk`` / ``none``) is recorded in
the metrics registry and the flight recorder and surfaced by the
post-mortem analyzer.  See docs/checkpoint.md.
"""

from .replica import ReplicaTier, tier_from_env  # noqa: F401
from .sharded import (  # noqa: F401
    MANIFEST,
    SCHEMA,
    ShardCorruptError,
    ShardedSave,
    latest_step,
    list_steps,
    load_manifest,
    restore_sharded,
    save_sharded,
    save_sharded_async,
    shard_assignment,
)

__all__ = [
    "MANIFEST",
    "SCHEMA",
    "ShardCorruptError",
    "ShardedSave",
    "ReplicaTier",
    "tier_from_env",
    "latest_step",
    "list_steps",
    "load_manifest",
    "restore_sharded",
    "save_sharded",
    "save_sharded_async",
    "shard_assignment",
]
