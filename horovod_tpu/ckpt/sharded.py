"""Sharded checkpoint format: every rank writes only its own shard.

The rank-0 ``save_checkpoint`` discipline (checkpoint.py) funnels the
whole pytree through one writer — fine for a workstation, minutes of
serialized I/O at production world sizes.  This format splits the work:

* **Leaf-partitioned shards** — the state pytree is flattened and its
  leaves are assigned round-robin to ranks (leaf ``i`` -> shard
  ``i % world_size``).  Each rank pickles only its own leaves into
  ``shard_<rank>_of_<world>.bin`` plus a tiny ``*.meta.json`` sidecar
  carrying the payload's SHA-256, both written through the shared
  atomic tmp+rename helper (obs/pathspec.py) — a crash mid-save can
  never leave a torn shard that a later restore then selects.
* **Manifest committed LAST by rank 0** — ``manifest.json`` records the
  schema, step, writer world size, the full leaf table (index, shard,
  shape, dtype), every shard's checksum, and the pickled treedef.  A
  step directory without a valid manifest is *not a checkpoint*:
  :func:`latest_step` never selects it, so the commit point is exactly
  the manifest rename.
* **Reshard on restore (N -> M)** — restore reads the manifest's shard
  table, not the current world: any number of readers can reassemble a
  checkpoint written by any number of writers, so an elastic
  shrink/grow restores the same logical state bit-for-bit.  The *next*
  save re-partitions over the new world.
* **Overlapped save** — :func:`save_sharded_async` snapshots leaves to
  host and hands the write to a background thread (the AsyncSave
  pattern); ``wait()`` is the commit point.  Cross-rank commit status
  rides the filesystem, not a collective: rank 0 polls for every
  sidecar before renaming the manifest, and every other rank polls for
  the manifest — so a failed save surfaces on EVERY rank (the
  all-or-nothing contract AsyncSave's commit-status broadcast
  established), and the path works identically under the engine, the
  elastic KV world, and a single process.

Honest limits: the sidecar/manifest handshake assumes the step
directory is visible to all writers (shared filesystem or single
host).  On non-shared filesystems run one save per host and lean on
the peer-replica tier (ckpt/replica.py) for recovery; disk remains the
durability floor — replicas die with the job.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..obs import flightrec as _flightrec
from ..obs import get_registry
from ..obs.pathspec import write_bytes_atomic, write_json_atomic
from ..testing.faults import corrupt_bytes, maybe_fail
from ..utils import env as envmod
from ..utils.logging import get_logger

LOG = get_logger("ckpt")

SCHEMA = "hvdtpu-sharded-ckpt-v1"
MANIFEST = "manifest.json"

__all__ = [
    "SCHEMA",
    "MANIFEST",
    "ShardCorruptError",
    "ShardedSave",
    "shard_assignment",
    "step_dir",
    "write_shard",
    "write_manifest",
    "load_manifest",
    "latest_step",
    "list_steps",
    "save_sharded_async",
    "save_sharded",
    "restore_sharded",
    "read_shard_payload",
]


class ShardCorruptError(RuntimeError):
    """A shard's bytes do not match the manifest's checksum (torn or
    corrupted write); the restore path treats the whole step as invalid
    and falls back to an older one."""


def step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"shards_{step:010d}")


def _shard_name(rank: int, world: int) -> str:
    return f"shard_{rank:05d}_of_{world:05d}.bin"


def _sidecar_name(rank: int, world: int) -> str:
    return f"shard_{rank:05d}_of_{world:05d}.meta.json"


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def shard_assignment(num_leaves: int, world_size: int) -> List[List[int]]:
    """Leaf indices owned by each shard: round-robin ``i % world_size``.
    Every rank computes the identical table (it is a pure function of
    two integers), so there is nothing to negotiate."""
    if world_size < 1:
        raise ValueError(f"world_size must be >= 1, got {world_size}")
    table: List[List[int]] = [[] for _ in range(world_size)]
    for i in range(num_leaves):
        table[i % world_size].append(i)
    return table


def _flatten(state: Any) -> Tuple[List[np.ndarray], Any]:
    """Flatten + SNAPSHOT: numpy leaves are copied (np.asarray would
    alias the caller's buffer, and the background writer must not race
    an in-place ``w -= lr*g`` into a checksum-valid-but-torn shard);
    jax arrays are immutable, so their host materialization is safe."""

    def snap(x):
        if isinstance(x, np.ndarray):
            return x.copy()
        return np.asarray(x)

    leaves, treedef = jax.tree_util.tree_flatten(state)
    return [snap(leaf) for leaf in leaves], treedef


def write_shard(
    directory: str,
    step: int,
    rank: int,
    world_size: int,
    leaves: Dict[int, np.ndarray],
) -> dict:
    """Write this rank's shard (its assigned leaves, pickled) plus the
    checksum sidecar, both atomically.  Returns the sidecar dict."""
    payload = pickle.dumps(
        {int(i): np.asarray(a) for i, a in leaves.items()},
        protocol=pickle.HIGHEST_PROTOCOL,
    )
    checksum = _sha256(payload)
    # Chaos point "shard_write": an action=corrupt_write spec makes the
    # bytes on disk disagree with the checksum just computed — exactly
    # the torn/bit-flipped write restore-time validation must reject.
    # The rank filter resolves from the PROCESS env (not the shard
    # position passed in as ``rank``): after an elastic shrink the two
    # diverge, and "rank=2" in a spec must keep meaning rank 2.
    if maybe_fail("shard_write", step=step) == "corrupt_write":
        payload = corrupt_bytes(payload)
    d = step_dir(directory, step)
    write_bytes_atomic(os.path.join(d, _shard_name(rank, world_size)),
                       payload)
    meta = {
        "rank": int(rank),
        "world_size": int(world_size),
        "step": int(step),
        "file": _shard_name(rank, world_size),
        "bytes": len(payload),
        "checksum": checksum,
        "leaves": sorted(int(i) for i in leaves),
    }
    write_json_atomic(os.path.join(d, _sidecar_name(rank, world_size)),
                      meta)
    metrics = get_registry()
    metrics.histogram("ckpt.shard_bytes").observe(float(len(payload)))
    metrics.counter("ckpt.shards_written").inc()
    _flightrec.record("ckpt.shard", name=f"step{step}", cycle=step,
                      detail=f"rank={rank} bytes={len(payload)}")
    return meta


def _leaf_specs(leaves: List[np.ndarray], world_size: int) -> List[dict]:
    table = shard_assignment(len(leaves), world_size)
    shard_of = {}
    for shard, owned in enumerate(table):
        for i in owned:
            shard_of[i] = shard
    return [
        {
            "index": i,
            "shard": shard_of[i],
            "shape": list(np.shape(a)),
            "dtype": str(np.asarray(a).dtype),
        }
        for i, a in enumerate(leaves)
    ]


def write_manifest(
    directory: str,
    step: int,
    world_size: int,
    leaf_specs: List[dict],
    treedef,
    *,
    extra: Optional[dict] = None,
    sidecar_timeout: float = 30.0,
) -> str:
    """Rank 0's commit: wait for every writer's sidecar, then rename the
    manifest into place LAST.  Raises if any sidecar never appears —
    the step stays invisible to :func:`latest_step` in that case."""
    d = step_dir(directory, step)
    deadline = time.monotonic() + sidecar_timeout
    shards = []
    for rank in range(world_size):
        path = os.path.join(d, _sidecar_name(rank, world_size))
        while True:
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        shards.append(json.load(f))
                    break
                except ValueError:
                    pass  # racing the atomic rename; retry
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"sharded save step {step}: shard sidecar for rank "
                    f"{rank}/{world_size} never appeared under {d!r} — "
                    f"a writer died before its shard landed; the step "
                    f"is NOT committed"
                )
            time.sleep(0.02)
    try:
        treedef_b64 = base64.b64encode(pickle.dumps(treedef)).decode()
    except Exception:  # jax-version drift: treedefs not picklable
        treedef_b64 = None
    doc = {
        "schema": SCHEMA,
        "step": int(step),
        "world_size": int(world_size),
        "created": time.time(),
        "num_leaves": len(leaf_specs),
        "leaves": leaf_specs,
        "shards": sorted(shards, key=lambda s: s["rank"]),
        "treedef": treedef_b64,
        "treedef_repr": str(treedef),
        "extra": dict(extra or {}),
    }
    return write_json_atomic(os.path.join(d, MANIFEST), doc)


def load_manifest(directory: str, step: int) -> Optional[dict]:
    path = os.path.join(step_dir(directory, step), MANIFEST)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("schema") != SCHEMA:
        return None
    return doc


def list_steps(directory: str) -> List[int]:
    """Steps with a schema-valid manifest — an uncommitted step
    directory (writer died before the manifest rename) is invisible."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        if not name.startswith("shards_"):
            continue
        try:
            step = int(name[len("shards_"):])
        except ValueError:
            continue
        if load_manifest(directory, step) is not None:
            steps.append(step)
    return sorted(steps)


def latest_step(directory: str,
                newer_than: Optional[int] = None) -> Optional[int]:
    """Newest committed step, or None.  ``newer_than`` makes this a
    cheap incremental poll (the weight hot-swap watcher calls it every
    few decode steps, forever): step directories are scanned by NAME
    descending and the manifest — the expensive validation — is only
    loaded for candidates above the floor, so a long-lived serving
    fleet pays O(1) manifest reads per poll instead of O(published
    versions)."""
    if newer_than is None:
        steps = list_steps(directory)
        return steps[-1] if steps else None
    if not os.path.isdir(directory):
        return None
    candidates = []
    for name in os.listdir(directory):
        if not name.startswith("shards_"):
            continue
        try:
            step = int(name[len("shards_"):])
        except ValueError:
            continue
        if step > newer_than:
            candidates.append(step)
    for step in sorted(candidates, reverse=True):
        if load_manifest(directory, step) is not None:
            return step
    return None


def read_shard_payload(directory: str, step: int, shard: dict) -> Dict[int, np.ndarray]:
    """Read + checksum-validate one shard named by the manifest."""
    path = os.path.join(step_dir(directory, step), shard["file"])
    try:
        with open(path, "rb") as f:
            payload = f.read()
    except OSError as exc:
        raise ShardCorruptError(
            f"shard {shard['file']} of step {step} unreadable: {exc}"
        ) from exc
    if _sha256(payload) != shard["checksum"]:
        raise ShardCorruptError(
            f"shard {shard['file']} of step {step} failed checksum "
            f"validation (torn or corrupted write)"
        )
    return pickle.loads(payload)


class ShardedSave:
    """Handle for an in-flight :func:`save_sharded_async`.

    The writer thread does all I/O: this rank's shard, then (rank 0)
    the sidecar wait + manifest rename, then (every rank) the
    manifest-commit poll.  ``wait()`` joins the thread and raises the
    deferred error, so a failed save surfaces on every rank and repeat
    ``wait()`` never silently blesses it."""

    def __init__(self, directory: str, step: int, rank: int):
        self.directory = directory
        self.step = step
        self.rank = rank
        self.path = step_dir(directory, step)
        self.manifest: Optional[dict] = None
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    def wait(self) -> str:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
            metrics = get_registry()
            if self._error is not None:
                metrics.counter("ckpt.save_errors").inc()
                _flightrec.record(
                    "ckpt.error", name=f"step{self.step}",
                    cycle=self.step, detail=str(self._error)[:200],
                )
            else:
                metrics.counter("ckpt.saves_committed").inc()
                _flightrec.record("ckpt.commit", name=f"step{self.step}",
                                  cycle=self.step, detail="sharded")
        if self._error is not None:
            raise self._error
        return self.path


def save_sharded_async(
    directory: str,
    state: Any,
    step: int,
    *,
    rank: Optional[int] = None,
    world_size: Optional[int] = None,
    extra: Optional[dict] = None,
    commit_timeout: Optional[float] = None,
) -> ShardedSave:
    """Start this rank's shard write in the background; ``wait()`` is
    the commit point.  Leaves are snapshotted to host arrays BEFORE
    returning, so the training loop may mutate ``state`` immediately.

    ``rank``/``world_size`` default to the engine world
    (``hvd.rank()``/``size()``) and may be passed explicitly to ride a
    different world (the elastic context supplies world *positions*)
    or to simulate many writers in one process (tests).
    """
    if rank is None or world_size is None:
        from ..basics import rank as _rank, size as _size  # noqa: PLC0415

        rank = _rank() if rank is None else rank
        world_size = _size() if world_size is None else world_size
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside world of {world_size}")
    if commit_timeout is None:
        commit_timeout = envmod.env_float(
            envmod.CKPT_COMMIT_TIMEOUT, envmod.DEFAULT_CKPT_COMMIT_TIMEOUT
        )
    leaves, treedef = _flatten(state)
    specs = _leaf_specs(leaves, world_size)
    owned = {i: leaves[i]
             for i in shard_assignment(len(leaves), world_size)[rank]}
    handle = ShardedSave(directory, step, rank)
    get_registry().counter("ckpt.saves_started").inc()
    _flightrec.record("ckpt.begin", name=f"step{step}", cycle=step,
                      detail=f"sharded rank={rank}/{world_size}")

    def _write():
        try:
            my_meta = write_shard(directory, step, rank, world_size,
                                  owned)
            if rank == 0:
                # A pre-existing manifest (an earlier attempt at this
                # step) is NOT removed: it stays restorable until the
                # atomic rename replaces it — a crash mid-re-save must
                # never destroy a checkpoint that was already durable.
                # Peers can't be confused by it because their commit
                # poll below accepts only a manifest carrying THEIR
                # attempt's checksum.
                write_manifest(
                    directory, step, world_size, specs, treedef,
                    extra=extra, sidecar_timeout=commit_timeout,
                )
                manifest = load_manifest(directory, step)
            else:
                # Commit = a manifest that names THIS attempt's shard
                # checksum for this rank.  A stale manifest from an
                # earlier attempt keeps the poll waiting (not failing:
                # rank 0 may simply not have re-committed yet); only
                # the deadline turns a mismatch into an error.
                deadline = time.monotonic() + commit_timeout
                manifest = None
                while True:
                    doc = load_manifest(directory, step)
                    if doc is not None:
                        mine = next(
                            (s for s in doc.get("shards", [])
                             if s.get("rank") == rank), None)
                        if mine is not None and \
                                mine.get("checksum") == \
                                my_meta["checksum"]:
                            manifest = doc
                            break
                    if time.monotonic() > deadline:
                        if doc is not None:
                            raise RuntimeError(
                                f"sharded save step {step}: the "
                                f"committed manifest never carried "
                                f"this rank's shard checksum (a stale "
                                f"attempt's sidecar was committed "
                                f"instead) — this save is NOT valid "
                                f"on rank {rank}"
                            )
                        raise TimeoutError(
                            f"sharded save step {step}: manifest never "
                            f"committed by rank 0 within "
                            f"{commit_timeout}s — no rank may treat "
                            f"this step as committed"
                        )
                    time.sleep(0.02)
            handle.manifest = manifest
        except BaseException as exc:  # surfaces at wait()
            handle._error = exc

    handle._thread = threading.Thread(
        target=_write, name=f"hvdtpu_ckpt_shard_w{rank}", daemon=True
    )
    handle._thread.start()
    return handle


def save_sharded(directory: str, state: Any, step: int, **kwargs) -> str:
    """Synchronous :func:`save_sharded_async` (write + commit)."""
    return save_sharded_async(directory, state, step, **kwargs).wait()


def restore_sharded(
    directory: str,
    target: Any = None,
    step: Optional[int] = None,
    *,
    with_manifest: bool = False,
):
    """Reassemble a sharded checkpoint into one pytree (any reader
    world size — the manifest, not the current world, names the
    shards; this is what makes N->M elastic reshard work).

    ``target`` supplies the tree structure (validated against the
    manifest's leaf count); ``target=None`` unflattens with the
    manifest's pickled treedef.  ``step=None`` restores the newest
    valid step, **falling back to older steps** when a shard fails
    checksum validation — a corrupt newest checkpoint degrades to the
    previous commit instead of killing recovery.  An explicitly
    requested step never falls back.  ``with_manifest=True`` returns
    ``(state, manifest)``.
    """
    t0 = time.monotonic()
    metrics = get_registry()
    explicit = step is not None
    candidates = [step] if explicit else list(reversed(list_steps(directory)))
    if not candidates:
        raise FileNotFoundError(
            f"no committed sharded checkpoint under {directory!r}"
        )
    last_exc: Optional[Exception] = None
    for s in candidates:
        manifest = load_manifest(directory, s)
        if manifest is None:
            last_exc = FileNotFoundError(
                f"step {s} has no valid manifest under {directory!r}"
            )
            if explicit:
                raise last_exc
            continue
        try:
            state = _reassemble(directory, manifest, target)
        except ShardCorruptError as exc:
            metrics.counter("ckpt.restore_corrupt_shards").inc()
            LOG.warning("sharded restore: step %d rejected (%s)%s",
                        s, exc,
                        "" if explicit else "; falling back to an "
                        "older committed step")
            last_exc = exc
            if explicit:
                raise
            continue
        # Disk-reassembly time specifically; the end-to-end recovery
        # time (ckpt.restore_ms) is observed by State.sync, which may
        # not touch disk at all.
        metrics.histogram("ckpt.restore_disk_ms").observe(
            (time.monotonic() - t0) * 1e3
        )
        metrics.counter("ckpt.restores_disk").inc()
        _flightrec.record(
            "ckpt.restore_disk", name=f"step{manifest['step']}",
            cycle=manifest["step"],
            detail=f"world={manifest['world_size']}",
        )
        return (state, manifest) if with_manifest else state
    raise last_exc if last_exc is not None else FileNotFoundError(
        f"no restorable sharded checkpoint under {directory!r}"
    )


def _leaf_sig(x) -> Tuple[list, str]:
    """(shape, dtype) of a target leaf — concrete arrays, python
    scalars, and abstract ShapeDtypeStructs alike."""
    shape = getattr(x, "shape", None)
    if shape is None:
        shape = np.shape(x)
    dtype = getattr(x, "dtype", None)
    if dtype is None:
        dtype = np.asarray(x).dtype
    return list(shape), str(dtype)


def _reassemble(directory: str, manifest: dict, target: Any):
    step = manifest["step"]
    flat: Dict[int, np.ndarray] = {}
    for shard in manifest["shards"]:
        flat.update(read_shard_payload(directory, step, shard))
    n = manifest["num_leaves"]
    missing = [i for i in range(n) if i not in flat]
    if missing:
        raise ShardCorruptError(
            f"step {step}: leaves {missing[:5]} missing from every shard"
        )
    leaves = [flat[i] for i in range(n)]
    if target is not None:
        t_leaves, treedef = jax.tree_util.tree_flatten(target)
        if treedef.num_leaves != n:
            raise ValueError(
                f"target has {treedef.num_leaves} leaves but the "
                f"manifest records {n} — structure mismatch "
                f"(manifest treedef: {manifest.get('treedef_repr')})"
            )
        # Leaf count alone would let a same-arity checkpoint from a
        # DIFFERENT model restore silently into the wrong fields; the
        # manifest's per-leaf shape/dtype table rejects that here, at
        # the restore site, instead of as wrong weights later.
        for spec, tl in zip(manifest.get("leaves") or [], t_leaves):
            shape, dtype = _leaf_sig(tl)
            if spec.get("shape") is not None and spec["shape"] != shape:
                raise ValueError(
                    f"leaf {spec['index']}: target shape {shape} != "
                    f"manifest shape {spec['shape']} — this checkpoint "
                    f"belongs to a different state structure"
                )
            if spec.get("dtype") is not None and spec["dtype"] != dtype:
                raise ValueError(
                    f"leaf {spec['index']}: target dtype {dtype} != "
                    f"manifest dtype {spec['dtype']} — this checkpoint "
                    f"belongs to a different state structure"
                )
    else:
        raw = manifest.get("treedef")
        if raw is None:
            raise ValueError(
                "manifest carries no pickled treedef (writer's jax "
                "could not serialize it); pass a target pytree"
            )
        treedef = pickle.loads(base64.b64decode(raw))
    return jax.tree_util.tree_unflatten(treedef, leaves)
