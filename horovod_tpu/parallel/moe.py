"""Mixture-of-experts with expert parallelism (EP).

Beyond reference parity (Horovod 0.19.1 is data-parallel only,
SURVEY.md §2.9): a GShard-style MoE MLP for the transformer family,
TPU-first —

* **static shapes everywhere**: top-k routing becomes one-hot
  dispatch/combine tensors with a fixed per-expert capacity, so the
  whole layer is einsums the MXU eats (no gather/scatter, no dynamic
  sizes);
* capacity overflow DROPS tokens (they ride the residual), the standard
  Switch/GShard behavior;
* an auxiliary load-balancing loss (Switch formulation: E * sum over
  experts of fraction-of-tokens x mean-gate) keeps routing spread;
* **expert parallelism**: experts shard over a mesh axis; tokens reach
  their expert's owner through one ``lax.all_to_all`` each way — the
  EP result is EXACTLY the dense formulation's (same math, different
  layout), pinned by tests/test_moe.py.

Layout contract for :func:`moe_mlp_ep` — call inside ``shard_map`` with
tokens sharded over the axis and the expert weights sharded on their
leading (expert) dim; every rank must carry the same token count.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["init_moe_params", "moe_mlp", "moe_mlp_ep", "MoEParams"]

# Initialization scheme, shared by the raw-NamedTuple and flax paths so
# the two can never drift: small-normal router, fan-in-scaled FFN.
ROUTER_STD = 0.02


def _ffn_scales(d: int, ff: int):
    return (2.0 / d) ** 0.5, (2.0 / ff) ** 0.5


# Routing group size: tokens route within fixed-size groups (GShard
# grouping), so dispatch/combine stay O(n * group) instead of O(n^2) —
# at group 4096 and cf=2, a layer's routing tensors are bounded at
# ~n * 16k floats regardless of sequence length.
DEFAULT_GROUP_SIZE = 4096


class MoEParams(NamedTuple):
    """Weights of one MoE MLP: router + E experts' FFNs."""

    router: jax.Array  # [d, E]
    w1: jax.Array      # [E, d, ff]
    b1: jax.Array      # [E, ff]
    w2: jax.Array      # [E, ff, d]
    b2: jax.Array      # [E, d]


def init_moe_params(key, d: int, ff: int, num_experts: int,
                    dtype=jnp.float32) -> MoEParams:
    kr, k1, k2 = jax.random.split(key, 3)
    s1, s2 = _ffn_scales(d, ff)
    return MoEParams(
        router=(jax.random.normal(kr, (d, num_experts)) * ROUTER_STD
                ).astype(dtype),
        w1=(jax.random.normal(k1, (num_experts, d, ff)) * s1).astype(dtype),
        b1=jnp.zeros((num_experts, ff), dtype),
        w2=(jax.random.normal(k2, (num_experts, ff, d)) * s2).astype(dtype),
        b2=jnp.zeros((num_experts, d), dtype),
    )


def _routing(x2, router, num_experts: int, top_k: int, capacity: int,
             valid=None):
    """Shared routing math on flat tokens ``x2 [n, d]``.

    Returns ``(dispatch [n, E, C], combine [n, E, C], aux_loss)`` —
    the GShard one-hot formulation: ``dispatch`` says which (expert,
    capacity-slot) each token occupies; ``combine`` carries the gate
    weight on the same slot.  ``valid [n]`` (optional bool) marks real
    tokens: padding rows claim no capacity slots and are excluded from
    the aux statistics.
    """
    n = x2.shape[0]
    if valid is None:
        valid = jnp.ones((n,), jnp.float32)
    else:
        valid = valid.astype(jnp.float32)
    n_valid = jnp.maximum(valid.sum(), 1.0)
    logits = (x2.astype(jnp.float32) @ router.astype(jnp.float32))
    gates = jax.nn.softmax(logits, axis=-1)  # [n, E]

    # Switch/GShard aux loss on the FULL distribution (before top-k):
    # E * sum_e mean_tokens_to_e * mean_gate_e ; == 1 when uniform.
    # importance = fraction of (valid) tokens whose top-1 is e
    top1 = jnp.argmax(gates, axis=-1)
    me = (jax.nn.one_hot(top1, num_experts) * valid[:, None]
          ).sum(0) / n_valid
    ce = (gates * valid[:, None]).sum(0) / n_valid
    aux_loss = num_experts * jnp.sum(me * ce)

    dispatch = jnp.zeros((n, num_experts, capacity), jnp.float32)
    combine = jnp.zeros((n, num_experts, capacity), jnp.float32)
    remaining = gates
    # fill[e] = next free capacity slot of expert e, advanced per k-round
    fill = jnp.zeros((num_experts,), jnp.int32)
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)            # [n]
        gate_k = jnp.take_along_axis(
            remaining, idx[:, None], axis=-1
        )[:, 0]
        onehot = jax.nn.one_hot(idx, num_experts) * valid[:, None]
        # position of each token within its expert's queue this round
        pos_in_e = (jnp.cumsum(onehot, axis=0) - 1.0)   # [n, E]
        slot = (pos_in_e * onehot).sum(-1).astype(jnp.int32) \
            + jnp.take(fill, idx)                       # [n]
        keep = slot < capacity                          # overflow drops
        slot_oh = jax.nn.one_hot(
            jnp.where(keep, slot, capacity), capacity + 1
        )[:, :capacity]                                 # [n, C]
        d_k = onehot[:, :, None] * slot_oh[:, None, :]  # [n, E, C]
        dispatch = dispatch + d_k
        combine = combine + d_k * gate_k[:, None, None]
        fill = fill + jnp.sum(
            onehot * keep[:, None], axis=0
        ).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)          # mask chosen expert
    # normalize combine weights over the selected experts per token
    denom = combine.sum(axis=(1, 2), keepdims=True)
    combine = jnp.where(denom > 0, combine / jnp.maximum(denom, 1e-9), 0.0)
    return dispatch, combine, aux_loss


def _expert_ffn(buf, w1, b1, w2, b2, dtype, act_store_dtype=None):
    """Batched expert FFN on ``buf [E_local, C, d]``.  When
    ``act_store_dtype`` is set, the gelu intermediate (the 4x-wide
    saved activation) materializes at that dtype — the MoE leg of the
    transformer's opt-in fp8 activation storage
    (models/transformer.py act_store)."""
    h = jnp.einsum("ecd,edf->ecf", buf.astype(dtype), w1.astype(dtype))
    h = jax.nn.gelu(h + b1[:, None, :].astype(dtype))
    if act_store_dtype is not None:
        h = jnp.asarray(jnp.asarray(h, act_store_dtype), dtype)
    out = jnp.einsum("ecf,efd->ecd", h, w2.astype(dtype))
    return out + b2[:, None, :].astype(dtype)


def _grouped_routing(x2, router, num_experts, top_k, capacity_factor,
                     group_size):
    """Route within fixed-size token groups (vmapped _routing): returns
    ``(xg [G,g,d], dispatch [G,g,E,C], combine [G,g,E,C], capacity,
    aux, n)`` with per-group capacity, keeping routing memory linear in
    n.  Token counts that don't divide by the group PAD with invalid
    rows (they claim no capacity and skew no statistics) rather than
    shrinking the group — a tiny divisor would make per-group capacity
    ~1 and silently drop most tokens."""
    n, d = x2.shape
    g = min(group_size, n)
    pad = (-n) % g
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    valid = (jnp.arange(n + pad) < n)
    xg = x2.reshape((n + pad) // g, g, d)
    vg = valid.reshape((n + pad) // g, g)
    capacity = max(1, int(-(-capacity_factor * g * top_k // num_experts)))
    dispatch, combine, aux = jax.vmap(
        lambda xx, vv: _routing(xx, router, num_experts, top_k, capacity,
                                valid=vv)
    )(xg, vg)
    return xg, dispatch, combine, capacity, aux.mean(), n


def moe_mlp(x, params: MoEParams, *, top_k: int = 2,
            capacity_factor: float = 2.0,
            group_size: int = DEFAULT_GROUP_SIZE,
            dtype=jnp.float32, act_store_dtype=None):
    """Dense (single-device / data-parallel) MoE MLP.

    ``x [b, s, d]`` -> ``(y [b, s, d], aux_loss)``.  Tokens route within
    groups of <= ``group_size``; capacity =
    ``ceil(capacity_factor * group * top_k / E)`` slots per expert per
    group; overflow tokens pass through with zero MLP contribution
    (residual-only).
    """
    b, s, d = x.shape
    num_experts = params.router.shape[1]
    n = b * s
    x2 = x.reshape(n, d)
    xg, dispatch, combine, capacity, aux, n = _grouped_routing(
        x2, params.router, num_experts, top_k, capacity_factor, group_size
    )
    G = xg.shape[0]
    buf = jnp.einsum("gnec,gnd->gecd", dispatch, xg.astype(jnp.float32))
    buf = buf.transpose(1, 0, 2, 3).reshape(num_experts, G * capacity, d)
    out = _expert_ffn(buf, params.w1, params.b1, params.w2, params.b2,
                      dtype, act_store_dtype)
    out = out.reshape(num_experts, G, capacity, d).transpose(1, 0, 2, 3)
    y = jnp.einsum("gnec,gecd->gnd", combine, out.astype(jnp.float32))
    y = y.reshape(-1, d)[:n]  # drop padding rows
    return y.reshape(b, s, d).astype(x.dtype), aux


def moe_mlp_ep(x, params: MoEParams, ep_axis: str, *, top_k: int = 2,
               capacity_factor: float = 2.0,
               group_size: int = DEFAULT_GROUP_SIZE, dtype=jnp.float32,
               act_store_dtype=None):
    """Expert-parallel MoE MLP: call inside ``shard_map``.

    Sharding: ``x [b_local, s, d]`` tokens sharded over ``ep_axis``;
    ``params.w1/b1/w2/b2`` sharded on the leading expert dim
    (``E_local = E / P`` per rank); ``params.router`` replicated.
    Per-expert capacity counts LOCAL tokens, so global capacity per
    expert is identical to the dense formulation run per shard.

    Two ``lax.all_to_all`` (tokens to expert owners and back); result is
    numerically identical to :func:`moe_mlp` applied shard-wise with the
    full expert set.
    """
    p = lax.axis_size(ep_axis)
    b, s, d = x.shape
    e_local = params.w1.shape[0]
    num_experts = e_local * p
    if params.router.shape[1] != num_experts:
        # without this, out-of-range expert indices one-hot to zero and
        # tokens silently ride the residual
        raise ValueError(
            f"router has {params.router.shape[1]} experts but the sharded "
            f"weights imply {e_local} x {p} ranks = {num_experts}"
        )
    n = b * s
    x2 = x.reshape(n, d)
    xg, dispatch, combine, capacity, aux, n = _grouped_routing(
        x2, params.router, num_experts, top_k, capacity_factor, group_size
    )
    G = xg.shape[0]
    cap_total = G * capacity
    # local per-expert buffers for ALL experts, then ship each expert
    # group to its owner: [E, G*C, d] -> a2a over the expert dim ->
    # [P * E_local tiles] == this rank's experts' tokens from every rank
    buf = jnp.einsum("gnec,gnd->gecd", dispatch, xg.astype(jnp.float32))
    buf = buf.transpose(1, 0, 2, 3).reshape(num_experts, cap_total, d)
    buf = buf.reshape(p, e_local, cap_total, d)
    buf = lax.all_to_all(buf, ep_axis, split_axis=0, concat_axis=0,
                         tiled=False)          # [P, e_local, G*C, d]
    buf = buf.transpose(1, 0, 2, 3).reshape(e_local, p * cap_total, d)
    out = _expert_ffn(buf, params.w1, params.b1, params.w2, params.b2,
                      dtype, act_store_dtype)
    out = out.reshape(e_local, p, cap_total, d).transpose(1, 0, 2, 3)
    out = lax.all_to_all(out, ep_axis, split_axis=0, concat_axis=0,
                         tiled=False)          # [P, e_local, G*C, d] home
    out = out.reshape(num_experts, G, capacity, d).transpose(1, 0, 2, 3)
    y = jnp.einsum("gnec,gecd->gnd", combine, out.astype(jnp.float32))
    y = y.reshape(-1, d)[:n]  # drop padding rows
    # aux is a per-shard statistic; average it so every rank agrees
    aux = lax.pmean(aux, ep_axis)
    return y.reshape(b, s, d).astype(x.dtype), aux


# --------------------------------------------------------------------- flax

def moe_flax_params(module, d: int, ff: int, num_experts: int) -> MoEParams:
    """Declare the MoE weights on a flax module (fp32 params, like the
    rest of the model family; compute casts per call)."""
    import flax.linen as nn  # noqa: PLC0415

    s1, s2 = _ffn_scales(d, ff)
    return MoEParams(
        router=module.param(
            "router", nn.initializers.normal(ROUTER_STD), (d, num_experts),
            jnp.float32,
        ),
        w1=module.param(
            "w1", nn.initializers.normal(s1), (num_experts, d, ff),
            jnp.float32,
        ),
        b1=module.param(
            "b1", nn.initializers.zeros, (num_experts, ff), jnp.float32
        ),
        w2=module.param(
            "w2", nn.initializers.normal(s2), (num_experts, ff, d),
            jnp.float32,
        ),
        b2=module.param(
            "b2", nn.initializers.zeros, (num_experts, d), jnp.float32
        ),
    )
