"""GPipe-style pipeline parallelism for the transformer family.

Beyond reference parity (Horovod 0.19.1 is data-parallel only,
SURVEY.md §2.9): the GPT block stack splits into P contiguous stages
over a ``pp`` mesh axis; microbatches stream through the pipeline with
activations handed to the next stage by ``lax.ppermute`` each tick —
the TPU-idiomatic SPMD pipeline (every rank runs the SAME program; stage
identity comes from ``axis_index``), with a ``lax.scan`` over
``M + P - 1`` ticks so the schedule is one compiled loop, no
data-dependent control flow.

Embeddings and the LM head stay replicated and run outside the
pipelined region (they are marginal at these widths); each stage holds
only its ``num_layers / P`` blocks' weights.  Equivalence with the
unsharded model — forward and gradients — is pinned by
tests/test_pipeline.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["stack_pp_params", "pp_gpt_apply"]


def stack_pp_params(params, cfg, pp: int):
    """Split a GPT parameter pytree into ``(staged, replicated)``.

    ``staged``: the block weights restacked as a pytree whose leaves have
    leading dims ``[pp, layers_per_stage, ...]`` — shard over the mesh
    with ``in_specs=P(pp_axis)``.  ``replicated``: embeddings, final LN,
    head — ``in_specs=P()`` (truly replicated; see
    tensor_parallel.stack_tp_params for why that distinction is
    load-bearing under autodiff).
    """
    if cfg.num_layers % pp:
        raise ValueError(
            f"pp={pp} must divide num_layers={cfg.num_layers}"
        )
    if set(params.keys()) == {"params"}:
        params = params["params"]
    p = jax.tree_util.tree_map(np.asarray, params)
    per = cfg.num_layers // pp
    blocks = [p[f"block{i}"] for i in range(cfg.num_layers)]
    if any("fc1" not in b for b in blocks):
        raise ValueError(
            "stack_pp_params supports dense blocks only (MoE blocks "
            "shard over the ep axis; see docs/moe.md)"
        )
    # stack homogenous block trees: leaf -> [pp, per, ...]
    staged = jax.tree_util.tree_map(
        lambda *leaves: jnp.asarray(np.stack(leaves).reshape(
            (pp, per) + np.asarray(leaves[0]).shape
        )),
        *blocks,
    )
    replicated = {
        k: jax.tree_util.tree_map(jnp.asarray, v)
        for k, v in p.items() if not k.startswith("block")
    }
    return staged, replicated


def _dense_block(cfg, p, x, positions, rope_tabs):
    """One transformer block from raw weights — the shared
    ``models.transformer.block_math`` wiring via its raw-weights
    entry point (single source of truth for the block forward)."""
    from ..models.transformer import raw_block_forward  # noqa: PLC0415

    return raw_block_forward(cfg, p, x, positions, rope_tabs)


def pp_gpt_apply(staged_params, replicated_params, cfg, tokens,
                 pp_axis: str, *, microbatches: int,
                 pos_offset=0, positions=None):
    """``GPT.apply`` with the block stack pipelined over ``pp_axis``.

    ``tokens [batch, seq]`` must be replicated over the axis and have
    ``batch % microbatches == 0``.  The schedule is GPipe forward:
    ``M + P - 1`` ticks, one microbatch entering stage 0 per tick,
    activations ppermuted stage-to-stage.  Returns fp32 logits.
    """
    from .tensor_parallel import _gpt_embed, _gpt_head  # noqa: PLC0415

    pp = lax.axis_size(pp_axis)
    stage = lax.axis_index(pp_axis)
    rep = replicated_params
    b, s = tokens.shape
    if b % microbatches:
        raise ValueError(
            f"batch {b} must divide into microbatches={microbatches}"
        )
    # embed (replicated, outside the pipeline) — shared GPT scaffold
    x, positions, rope_tabs = _gpt_embed(rep, cfg, tokens, pos_offset,
                                         positions)

    mb = b // microbatches
    mbs = x.reshape(microbatches, mb, s, cfg.emb_dim)
    local = jax.tree_util.tree_map(lambda a: a[0], staged_params)
    layers_per_stage = jax.tree_util.tree_leaves(local)[0].shape[0]

    def run_stage(x):
        for j in range(layers_per_stage):
            p_j = jax.tree_util.tree_map(lambda a: a[j], local)
            x = _dense_block(cfg, p_j, x, positions, rope_tabs)
        return x

    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
    n_ticks = microbatches + pp - 1

    # The scan carry must have the same varying-axes set as the tick
    # outputs: pp_axis (the ppermute), every axis the activations vary
    # over (e.g. a dp axis in a composed dp x pp mesh — tokens sharded
    # over dp make every stage output dp-varying), and every axis the
    # stage weights vary over.
    _carry_axes = {pp_axis}
    for ref_val in (mbs, *jax.tree_util.tree_leaves(local)[:1]):
        try:
            _carry_axes |= set(jax.typeof(ref_val).vma)
        except (AttributeError, TypeError):
            pass
    _carry_axes = tuple(sorted(_carry_axes))

    def _varying(v):
        """Mark a replicated value device-varying over the carry's axes
        so the scan carry's type matches the tick outputs under
        replication tracking (check_vma=True) — a no-op without it."""
        try:
            return lax.pcast(v, _carry_axes, to="varying")
        except (AttributeError, TypeError):  # older jax: pvary spelling
            try:
                return lax.pvary(v, _carry_axes)
            except (AttributeError, TypeError):
                return v  # very old jax: no vma tracking to satisfy

    zero = _varying(jnp.zeros((mb, s, cfg.emb_dim), cfg.dtype))

    def tick(carry, t):
        incoming, outputs = carry
        # stage 0 ingests microbatch t (while t < M); others take the
        # activation handed over by the previous stage
        feed_idx = jnp.clip(t, 0, microbatches - 1)
        fresh = lax.dynamic_index_in_dim(mbs, feed_idx, axis=0,
                                         keepdims=False)
        x_in = jnp.where(stage == 0, fresh, incoming)
        y = run_stage(x_in)
        # last stage finished microbatch t - (pp - 1) this tick
        out_idx = jnp.clip(t - (pp - 1), 0, microbatches - 1)
        take = jnp.logical_and(stage == pp - 1, t >= pp - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(take,
                      y,
                      lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                               keepdims=False)),
            out_idx, axis=0,
        )
        incoming = lax.ppermute(y, pp_axis, fwd_perm)
        return (incoming, outputs), None

    outputs0 = _varying(jnp.zeros(
        (microbatches, mb, s, cfg.emb_dim), cfg.dtype
    ))
    (_, outputs), _ = lax.scan(
        tick, (zero, outputs0), jnp.arange(n_ticks)
    )
    # only the last stage holds real outputs; broadcast them to all
    # ranks so the (replicated) head runs everywhere and the caller gets
    # replicated logits — one psum of a masked contribution
    outputs = lax.psum(
        jnp.where(stage == pp - 1, outputs, jnp.zeros_like(outputs)),
        pp_axis,
    )
    x = outputs.reshape(b, s, cfg.emb_dim)
    return _gpt_head(rep, cfg, x)
