"""GPipe-style pipeline parallelism for the transformer family.

Beyond reference parity (Horovod 0.19.1 is data-parallel only,
SURVEY.md §2.9): the GPT block stack splits into P contiguous stages
over a ``pp`` mesh axis; microbatches stream through the pipeline with
activations handed to the next stage by ``lax.ppermute`` each tick —
the TPU-idiomatic SPMD pipeline (every rank runs the SAME program; stage
identity comes from ``axis_index``), with a ``lax.scan`` over
``M + P - 1`` ticks so the schedule is one compiled loop, no
data-dependent control flow.

Embeddings and the LM head stay replicated and run outside the
pipelined region (they are marginal at these widths); each stage holds
only its ``num_layers / P`` blocks' weights.  Equivalence with the
unsharded model — forward and gradients — is pinned by
tests/test_pipeline.py.

The schedule family (docs/pipeline.md):

* :func:`pp_gpt_apply` — GPipe forward, full logits on every rank
  (inference/eval, equivalence tests).
* :func:`pp_gpt_loss` — training: stage-local head + token loss inside
  the tick, ONE scalar psum rejoin, per-tick remat.
* :func:`pp_gpt_loss_circular` — circular/interleaved groups: each
  device holds ``circles`` non-contiguous layer groups and the stream
  wraps the ring, shrinking the bubble ~``circles``x with no
  masked-branch waste (the SPMD answer to 1F1B — see docs/pipeline.md).
* :func:`pp_tp_gpt_loss` — TP-sharded blocks inside stages: the 3-axis
  ``dp x pp x tp`` deployment shape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["stack_pp_params", "stack_pp_params_circular",
           "stack_tp_pp_params", "unstack_pp_params",
           "unstack_pp_params_circular", "unstack_tp_pp_params",
           "pp_gpt_apply", "pp_gpt_loss", "pp_gpt_loss_circular",
           "pp_tp_gpt_loss"]


def stack_pp_params(params, cfg, pp: int):
    """Split a GPT parameter pytree into ``(staged, replicated)``.

    ``staged``: the block weights restacked as a pytree whose leaves have
    leading dims ``[pp, layers_per_stage, ...]`` — shard over the mesh
    with ``in_specs=P(pp_axis)``.  ``replicated``: embeddings, final LN,
    head — ``in_specs=P()`` (truly replicated; see
    tensor_parallel.stack_tp_params for why that distinction is
    load-bearing under autodiff).
    """
    if cfg.num_layers % pp:
        raise ValueError(
            f"pp={pp} must divide num_layers={cfg.num_layers}"
        )
    if set(params.keys()) == {"params"}:
        params = params["params"]
    p = jax.tree_util.tree_map(np.asarray, params)
    per = cfg.num_layers // pp
    blocks = [p[f"block{i}"] for i in range(cfg.num_layers)]
    if any("fc1" not in b for b in blocks):
        raise ValueError(
            "stack_pp_params supports dense blocks only (MoE blocks "
            "shard over the ep axis; see docs/moe.md)"
        )
    # stack homogenous block trees: leaf -> [pp, per, ...]
    staged = jax.tree_util.tree_map(
        lambda *leaves: jnp.asarray(np.stack(leaves).reshape(
            (pp, per) + np.asarray(leaves[0]).shape
        )),
        *blocks,
    )
    replicated = {
        k: jax.tree_util.tree_map(jnp.asarray, v)
        for k, v in p.items() if not k.startswith("block")
    }
    return staged, replicated


def stack_pp_params_circular(params, cfg, pp: int, circles: int):
    """Restack for the circular schedule: device ``s`` holds the
    ``circles`` non-contiguous layer groups ``{s, s+pp, ..}`` —
    ``staged`` leaves get leading dims ``[pp, circles, layers_per_group,
    ...]`` (group ``v*pp + s`` at ``staged[s, v]``), so the microbatch
    stream can wrap through every device ``circles`` times
    (:func:`pp_gpt_loss_circular`).  ``replicated`` as in
    :func:`stack_pp_params`."""
    if circles < 1:
        raise ValueError(f"circles={circles} must be >= 1")
    if cfg.num_layers % (pp * circles):
        raise ValueError(
            f"pp*circles={pp}*{circles} must divide "
            f"num_layers={cfg.num_layers}"
        )
    staged, replicated = stack_pp_params(params, cfg, pp)
    per_group = cfg.num_layers // (pp * circles)
    # stack_pp_params laid blocks contiguously: [pp, per_stage, ...] with
    # per_stage = circles*per_group and stage s holding layers
    # [s*per_stage, (s+1)*per_stage).  The circular layout instead puts
    # layer (v*pp + s)*per_group + j at [s, v, j]; restack from the flat
    # block order via [circles, pp, per_group] -> transpose.
    def _restack(leaf):
        flat = jnp.reshape(leaf, (cfg.num_layers,) + leaf.shape[2:])
        grouped = jnp.reshape(
            flat, (circles, pp, per_group) + leaf.shape[2:]
        )
        return jnp.transpose(
            grouped, (1, 0, 2) + tuple(range(3, grouped.ndim))
        )

    return jax.tree_util.tree_map(_restack, staged), replicated


def _check_staged_lead(staged, want: tuple, what: str):
    """Loud mismatch guard for the unstack inverses: JAX index clamping
    would otherwise turn a wrong pp/circles/tp into a silently
    corrupted (correct-shaped!) checkpoint."""
    got = jax.tree_util.tree_leaves(staged)[0].shape[:len(want)]
    if tuple(got) != want:
        raise ValueError(
            f"staged leaves have leading dims {tuple(got)}, expected "
            f"{want} ({what}) — unstacking with different factors than "
            "the tree was stacked with"
        )


def unstack_pp_params(staged, replicated, cfg, pp: int):
    """Inverse of :func:`stack_pp_params`: reassemble the canonical GPT
    parameter pytree (``block{i}`` entries + embeddings/head) from the
    staged tree — docs/inference.md's "unstack the leading dims"
    instruction as code (round-trip pinned by tests/test_pipeline.py).
    """
    per = cfg.num_layers // pp
    _check_staged_lead(staged, (pp, per), "pp, layers_per_stage")
    out = dict(replicated)
    for i in range(cfg.num_layers):
        s, j = divmod(i, per)
        out[f"block{i}"] = jax.tree_util.tree_map(
            lambda a: a[s, j], staged
        )
    return out


def unstack_pp_params_circular(staged, replicated, cfg, pp: int,
                               circles: int):
    """Inverse of :func:`stack_pp_params_circular` (layer
    ``(v*pp + s)*per_group + j`` lives at ``staged[s, v, j]``)."""
    per_group = cfg.num_layers // (pp * circles)
    _check_staged_lead(staged, (pp, circles, per_group),
                       "pp, circles, layers_per_group")
    out = dict(replicated)
    for i in range(cfg.num_layers):
        g, j = divmod(i, per_group)
        v, s = divmod(g, pp)
        out[f"block{i}"] = jax.tree_util.tree_map(
            lambda a: a[s, v, j], staged
        )
    return out


def unstack_tp_pp_params(staged_sharded, staged_replicated, replicated,
                         cfg, pp: int, tp: int):
    """Inverse of :func:`stack_tp_pp_params`: per-block per-rank shards
    are re-formed and handed to ``unstack_tp_params`` — a TP-in-PP
    training state round-trips to the canonical checkpoint format."""
    from .tensor_parallel import unstack_tp_params  # noqa: PLC0415

    per = cfg.num_layers // pp
    _check_staged_lead(staged_sharded, (pp, tp, per),
                       "pp, tp, layers_per_stage")
    _check_staged_lead(staged_replicated, (pp, per),
                       "pp, layers_per_stage")
    sharded, rep = {}, dict(replicated)
    for i in range(cfg.num_layers):
        s, j = divmod(i, per)
        sharded[f"block{i}"] = jax.tree_util.tree_map(
            lambda a: a[s, :, j], staged_sharded
        )
        rep[f"block{i}"] = jax.tree_util.tree_map(
            lambda a: a[s, j], staged_replicated
        )
    return unstack_tp_params(sharded, rep, cfg, tp)


def _dense_block(cfg, p, x, positions, rope_tabs):
    """One transformer block from raw weights — the shared
    ``models.transformer.block_math`` wiring via its raw-weights
    entry point (single source of truth for the block forward)."""
    from ..models.transformer import raw_block_forward  # noqa: PLC0415

    return raw_block_forward(cfg, p, x, positions, rope_tabs)


def _head_loss(replicated_params, cfg, y, tgt):
    """Per-microbatch token loss from a stage's final activation — the
    one definition both the contiguous and circular training schedules
    mask into their ticks."""
    from .tensor_parallel import _gpt_head  # noqa: PLC0415

    logits = _gpt_head(replicated_params, cfg, y)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.take_along_axis(logp, tgt[..., None], -1).mean()


def _vma_axes(refs, base):
    """The varying-axes set a scan carry must declare: ``base`` plus
    every axis any of ``refs`` (activations, stage weights) varies
    over — e.g. a dp axis in a composed dp x pp mesh."""
    axes = set(base)
    for r in refs:
        try:
            axes |= set(jax.typeof(r).vma)
        except (AttributeError, TypeError):
            pass
    return tuple(sorted(axes))


def _mark_varying(v, axes):
    """Mark a replicated value device-varying over ``axes`` so a scan
    carry's type matches the tick outputs under replication tracking
    (check_vma=True) — a no-op without it."""
    try:
        return lax.pcast(v, axes, to="varying")
    except (AttributeError, TypeError):  # older jax: pvary spelling
        try:
            return lax.pvary(v, axes)
        except (AttributeError, TypeError):
            return v  # very old jax: no vma tracking to satisfy


class _Schedule:
    """Everything the GPipe tick loop shares between the logits and the
    stage-local-loss entry points: the embedded microbatch stream, the
    (optionally remat'd) stage body, the permutation, and the vma
    plumbing for the scan carry."""

    def __init__(self, staged_params, replicated_params, cfg, tokens,
                 pp_axis, microbatches, pos_offset, positions, remat,
                 contiguous=True, local=None, layer_fn=None,
                 extra_axes=()):
        from .tensor_parallel import _gpt_embed  # noqa: PLC0415

        self.pp_axis = pp_axis
        self.pp = lax.axis_size(pp_axis)
        self.stage = lax.axis_index(pp_axis)
        self.cfg = cfg
        b, s = tokens.shape
        if b % microbatches:
            raise ValueError(
                f"batch {b} must divide into microbatches={microbatches}"
            )
        # embed (replicated, outside the pipeline) — shared GPT scaffold
        x, positions, rope_tabs = _gpt_embed(
            replicated_params, cfg, tokens, pos_offset, positions
        )
        self.b, self.s = b, s
        self.mb = b // microbatches
        self.microbatches = microbatches
        self.mbs = x.reshape(microbatches, self.mb, s, cfg.emb_dim)
        self.positions, self.rope_tabs = positions, rope_tabs
        default_local = local is None
        if default_local:
            local = jax.tree_util.tree_map(lambda a: a[0], staged_params)
        self.local = local
        layers_per_stage = jax.tree_util.tree_leaves(local)[0].shape[0]
        per_stage = cfg.num_layers // self.pp
        if contiguous:
            # Guard against mis-stacked params reaching a contiguous
            # entry point — circular-stacked trees (extra [circles] dim
            # broadcasting through the matmuls) or a stack built for a
            # different pp (stages silently dropped): finite-looking
            # but wrong loss, no error.  (The converse mistake is
            # caught in pp_gpt_loss_circular.)
            if default_local:
                qkv = local["qkv"]["kernel"]
                if qkv.ndim != 3 or layers_per_stage != per_stage:
                    raise ValueError(
                        f"staged qkv kernel has shape {qkv.shape}, "
                        f"expected [{per_stage}, emb, qkv_dim] "
                        "(num_layers/pp contiguous layers per device) — "
                        "params stacked with stack_pp_params_circular "
                        "must go through pp_gpt_loss_circular"
                    )
            elif layers_per_stage != per_stage:
                raise ValueError(
                    f"staged params carry {layers_per_stage} "
                    f"layers/stage but num_layers/pp = {per_stage} — "
                    "stacked for a different pp than this mesh axis?"
                )

        if layer_fn is None:
            def layer_fn(p_j, x, positions, rope_tabs):
                return _dense_block(cfg, p_j, x, positions, rope_tabs)

        def run_stage(x):
            for j in range(layers_per_stage):
                p_j = jax.tree_util.tree_map(lambda a: a[j], local)
                x = layer_fn(p_j, x, positions, rope_tabs)
            return x

        if remat:
            # Backward then stores one (mb, s, emb) input per tick and
            # recomputes the blocks' internals, instead of saving every
            # attention/MLP intermediate of every tick — the per-stage
            # activation-memory fix for pipelined training.
            run_stage = jax.checkpoint(run_stage)
        self.run_stage = run_stage

        self.fwd_perm = [(i, (i + 1) % self.pp) for i in range(self.pp)]
        self.n_ticks = microbatches + self.pp - 1

        # The scan carry must have the same varying-axes set as the tick
        # outputs: pp_axis (the ppermute), every declared extra axis
        # (e.g. tp in TP-in-PP), every axis the activations vary over
        # (e.g. a dp axis in a composed dp x pp mesh — tokens sharded
        # over dp make every stage output dp-varying), and every axis
        # the stage weights vary over.
        self._carry_axes = _vma_axes(
            (self.mbs, *jax.tree_util.tree_leaves(local)[:1]),
            {pp_axis, *extra_axes},
        )

    def varying(self, v):
        """:func:`_mark_varying` over this schedule's carry axes."""
        return _mark_varying(v, self._carry_axes)

    def stage_io(self, incoming, t):
        """The per-tick stage input/output shared by every schedule:
        stage 0 ingests microbatch t (clipped), other stages take the
        handed-over activation; returns the stage output and its
        ppermuted hand-off."""
        feed_idx = jnp.clip(t, 0, self.microbatches - 1)
        fresh = lax.dynamic_index_in_dim(self.mbs, feed_idx, axis=0,
                                         keepdims=False)
        x_in = jnp.where(self.stage == 0, fresh, incoming)
        y = self.run_stage(x_in)
        return y, lax.ppermute(y, self.pp_axis, self.fwd_perm)


def pp_gpt_apply(staged_params, replicated_params, cfg, tokens,
                 pp_axis: str, *, microbatches: int,
                 pos_offset=0, positions=None, remat: bool = False):
    """``GPT.apply`` with the block stack pipelined over ``pp_axis``.

    ``tokens [batch, seq]`` must be replicated over the axis and have
    ``batch % microbatches == 0``.  The schedule is GPipe forward:
    ``M + P - 1`` ticks, one microbatch entering stage 0 per tick,
    activations ppermuted stage-to-stage.  Returns fp32 logits.

    This entry point materializes every microbatch's final activation
    and broadcasts them over the axis so every rank returns full logits
    — right for inference/eval and the equivalence tests.  For training
    use :func:`pp_gpt_loss`, whose rejoin is one scalar.
    """
    from .tensor_parallel import _gpt_head  # noqa: PLC0415

    sched = _Schedule(staged_params, replicated_params, cfg, tokens,
                      pp_axis, microbatches, pos_offset, positions, remat)
    pp, stage, mb, s = sched.pp, sched.stage, sched.mb, sched.s
    zero = sched.varying(jnp.zeros((mb, s, cfg.emb_dim), cfg.dtype))

    def tick(carry, t):
        incoming, outputs = carry
        y, handoff = sched.stage_io(incoming, t)
        # last stage finished microbatch t - (pp - 1) this tick
        out_idx = jnp.clip(t - (pp - 1), 0, microbatches - 1)
        take = jnp.logical_and(stage == pp - 1, t >= pp - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(take,
                      y,
                      lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                               keepdims=False)),
            out_idx, axis=0,
        )
        return (handoff, outputs), None

    outputs0 = sched.varying(jnp.zeros(
        (microbatches, mb, s, cfg.emb_dim), cfg.dtype
    ))
    (_, outputs), _ = lax.scan(
        tick, (zero, outputs0), jnp.arange(sched.n_ticks)
    )
    # only the last stage holds real outputs; broadcast them to all
    # ranks so the (replicated) head runs everywhere and the caller gets
    # replicated logits — one psum of a masked contribution
    outputs = lax.psum(
        jnp.where(stage == pp - 1, outputs, jnp.zeros_like(outputs)),
        pp_axis,
    )
    x = outputs.reshape(sched.b, s, cfg.emb_dim)
    return _gpt_head(replicated_params, cfg, x)


def pp_gpt_loss(staged_params, replicated_params, cfg, tokens, targets,
                pp_axis: str, *, microbatches: int,
                pos_offset=0, positions=None, remat: bool = True):
    """Pipelined causal-LM training loss with a stage-local head.

    The GPipe schedule of :func:`pp_gpt_apply`, but built for training
    (VERDICT r4 weak #5): the LM head and the token cross-entropy run
    per-microbatch inside the tick — only the last stage's contribution
    is kept — and the cross-stage rejoin is ONE scalar ``psum`` instead
    of broadcasting an ``(M, mb, seq, emb)`` activation buffer over the
    axis.  With ``remat=True`` (the default: this entry point exists for
    training) backward stores one stage input per tick rather than every
    block intermediate, so per-stage activation memory is
    O(ticks x mb x seq x emb) flat instead of O(M x layer internals).

    ``targets [batch, seq]`` are the next-token labels aligned with
    ``tokens``.  Returns the mean token loss, replicated over the axis.
    """
    sched = _Schedule(staged_params, replicated_params, cfg, tokens,
                      pp_axis, microbatches, pos_offset, positions, remat)
    return _gpipe_loss(sched, replicated_params, cfg, targets, remat)


def _gpipe_loss(sched, replicated_params, cfg, targets, remat):
    """The GPipe loss tick loop shared by the contiguous and TP-in-PP
    entry points: last stage finishes microbatch ``t - (pp-1)`` each
    tick, runs head+loss on it there (SPMD: every stage computes them,
    only the last stage's masked contribution survives — no
    microbatch's final activation ever outlives its tick), and the
    rejoin is one scalar psum."""
    pp, stage, mb, s = sched.pp, sched.stage, sched.mb, sched.s
    microbatches = sched.microbatches
    tgt_mbs = targets.reshape(microbatches, mb, s)
    zero = sched.varying(jnp.zeros((mb, s, cfg.emb_dim), cfg.dtype))

    def head_loss(y, tgt):
        return _head_loss(replicated_params, cfg, y, tgt)

    if remat:
        head_loss = jax.checkpoint(head_loss)

    def tick(carry, t):
        incoming, loss_sum = carry
        y, handoff = sched.stage_io(incoming, t)
        out_idx = jnp.clip(t - (pp - 1), 0, microbatches - 1)
        tgt = lax.dynamic_index_in_dim(tgt_mbs, out_idx, axis=0,
                                       keepdims=False)
        take = jnp.logical_and(stage == pp - 1, t >= pp - 1)
        loss_sum = loss_sum + jnp.where(take, head_loss(y, tgt), 0.0)
        return (handoff, loss_sum), None

    loss0 = sched.varying(jnp.zeros((), jnp.float32))
    (_, loss_sum), _ = lax.scan(
        tick, (zero, loss0), jnp.arange(sched.n_ticks)
    )
    # every microbatch is the same size, so the mean of per-microbatch
    # means is the global token mean; the psum is the whole rejoin
    return lax.psum(loss_sum, sched.pp_axis) / microbatches


def pp_gpt_loss_circular(staged_params, replicated_params, cfg, tokens,
                         targets, pp_axis: str, *, microbatches: int,
                         circles: int, pos_offset=0, positions=None,
                         remat: bool = True):
    """:func:`pp_gpt_loss` on the circular (interleaved-group) schedule.

    Each device holds ``circles`` non-contiguous layer groups
    (:func:`stack_pp_params_circular`) and the microbatch stream wraps
    through the ring ``circles`` times: device ``s`` at tick ``t`` works
    stream position ``k = t - s`` — circle ``v = k // M``, microbatch
    ``m = k % M`` — always exactly ONE group-forward per tick, so unlike
    a 1F1B schedule there is no masked-branch compute waste (see
    docs/pipeline.md).  Bubble shrinks from ``(P-1)/(M+P-1)`` to
    ``(P-1)/(circles*M + P-1)`` — the praxis-style circular pipeline —
    at the price of ``circles``x the ppermute hand-off traffic.

    A circle-boundary activation (device P-1's output for circle
    ``v < circles-1``) re-enters device 0 ``M - P + 1`` ticks after it
    arrives, banked in an M-slot ring buffer: slot ``h % M`` is written
    at tick ``h + P`` and read at tick ``h + M``, collision-free for
    ``microbatches >= pp`` (enforced).  Loss/head/rejoin semantics are
    exactly :func:`pp_gpt_loss` (stage-local head on the final circle,
    one scalar psum).
    """
    sched = _Schedule(staged_params, replicated_params, cfg, tokens,
                      pp_axis, microbatches, pos_offset, positions,
                      remat=False,       # applied to run_group below
                      contiguous=False)  # leaves are [circles, group, ..]
    pp, stage, mb, s = sched.pp, sched.stage, sched.mb, sched.s
    M = microbatches
    if M < pp:
        raise ValueError(
            f"circular schedule needs microbatches >= pp ({M} < {pp}): "
            "the ring buffer re-feeds device 0 M-P+1 ticks after arrival"
        )
    leaves = jax.tree_util.tree_leaves(sched.local)
    if leaves[0].shape[0] != circles:
        raise ValueError(
            f"staged params carry {leaves[0].shape[0]} groups/device, "
            f"expected circles={circles} — restack with "
            "stack_pp_params_circular(params, cfg, pp, circles)"
        )
    per_group = leaves[0].shape[1]
    tgt_mbs = targets.reshape(M, mb, s)

    def run_group(v, x):
        p_v = jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, v, 0, keepdims=False),
            sched.local,
        )
        for j in range(per_group):
            p_j = jax.tree_util.tree_map(lambda a: a[j], p_v)
            x = _dense_block(cfg, p_j, x, sched.positions,
                             sched.rope_tabs)
        return x

    def head_loss(y, tgt):
        return _head_loss(replicated_params, cfg, y, tgt)

    if remat:
        run_group = jax.checkpoint(run_group)
        head_loss = jax.checkpoint(head_loss)

    n_ticks = circles * M + pp - 1
    zero = sched.varying(jnp.zeros((mb, s, cfg.emb_dim), cfg.dtype))
    queue0 = sched.varying(jnp.zeros((M, mb, s, cfg.emb_dim), cfg.dtype))
    loss0 = sched.varying(jnp.zeros((), jnp.float32))

    def tick(carry, t):
        incoming, queue, loss_sum = carry
        # (1) bank the arrival FIRST: device 0's incoming this tick is
        # stream position h = t - pp (device P-1's output last tick);
        # write-then-read makes the M == pp edge (write and read of the
        # same slot in one tick) correct.
        h = t - pp
        slot = jnp.mod(h, M)  # non-negative for any h
        queue = lax.dynamic_update_index_in_dim(
            queue,
            jnp.where(h >= 0, incoming,
                      lax.dynamic_index_in_dim(queue, slot, 0,
                                               keepdims=False)),
            slot, axis=0,
        )
        # (2) this device's stream position
        k = jnp.clip(t - stage, 0, circles * M - 1)
        k_valid = jnp.logical_and(t - stage >= 0,
                                  t - stage < circles * M)
        v = k // M
        m = jnp.mod(k, M)
        fresh = lax.dynamic_index_in_dim(sched.mbs, m, 0, keepdims=False)
        banked = lax.dynamic_index_in_dim(queue, m, 0, keepdims=False)
        x0 = jnp.where(v == 0, fresh, banked)
        x_in = jnp.where(stage == 0, x0, incoming)
        y = run_group(v, x_in)
        # (3) final-circle outputs of the last device carry the loss
        tgt = lax.dynamic_index_in_dim(tgt_mbs, m, 0, keepdims=False)
        take = jnp.logical_and(
            jnp.logical_and(stage == pp - 1, v == circles - 1), k_valid
        )
        loss_sum = loss_sum + jnp.where(take, head_loss(y, tgt), 0.0)
        handoff = lax.ppermute(y, pp_axis, sched.fwd_perm)
        return (handoff, queue, loss_sum), None

    (_, _, loss_sum), _ = lax.scan(
        tick, (zero, queue0, loss0), jnp.arange(n_ticks)
    )
    return lax.psum(loss_sum, pp_axis) / M


def stack_tp_pp_params(params, cfg, pp: int, tp: int):
    """Restack for TP-inside-PP: pipeline stages whose blocks are
    Megatron-sharded over a second mesh axis — the 3-axis
    (dp x pp x tp) deployment shape.

    Returns ``(staged_sharded, staged_replicated, replicated)``:

    * ``staged_sharded`` — block matmul shards, leaves
      ``[pp, tp, layers_per_stage, ...]``: ``in_specs=P(pp_axis,
      tp_axis)``.
    * ``staged_replicated`` — per-block LNs and post-psum biases
      (tp-replicated but stage-local), leaves ``[pp, layers_per_stage,
      ...]``: ``in_specs=P(pp_axis)``.
    * ``replicated`` — embeddings, final LN, head: ``in_specs=P()``.
    """
    from .tensor_parallel import stack_tp_params  # noqa: PLC0415

    if cfg.num_layers % pp:
        raise ValueError(
            f"pp={pp} must divide num_layers={cfg.num_layers}"
        )
    sharded, replicated = stack_tp_params(params, cfg, tp)
    per = cfg.num_layers // pp

    def _stack_blocks(tree_of_blocks, tp_leading):
        blocks = [tree_of_blocks[f"block{i}"]
                  for i in range(cfg.num_layers)]

        def _leaf(*leaves):
            stacked = jnp.stack([jnp.asarray(x) for x in leaves])
            # [L, (tp,) ...] -> [pp, per, (tp,) ...]
            stacked = jnp.reshape(
                stacked, (pp, per) + stacked.shape[1:]
            )
            if tp_leading:  # -> [pp, tp, per, ...]
                stacked = jnp.moveaxis(stacked, 2, 1)
            return stacked

        return jax.tree_util.tree_map(_leaf, *blocks)

    staged_sharded = _stack_blocks(sharded, tp_leading=True)
    staged_replicated = _stack_blocks(
        {k: v for k, v in replicated.items() if k.startswith("block")},
        tp_leading=False,
    )
    true_replicated = {
        k: jax.tree_util.tree_map(jnp.asarray, v)
        for k, v in replicated.items() if not k.startswith("block")
    }
    return staged_sharded, staged_replicated, true_replicated


def pp_tp_gpt_loss(staged_sharded, staged_replicated, replicated_params,
                   cfg, tokens, targets, pp_axis: str, tp_axis: str, *,
                   microbatches: int, pos_offset=0, positions=None,
                   remat: bool = True):
    """:func:`pp_gpt_loss` with each stage's blocks Megatron-sharded
    over ``tp_axis`` — TP inside PP, the composition a real multi-pod
    deployment runs (dp x pp x tp; the dp axis comes from the caller's
    mesh and gradient pmean as in ``tests/test_composed.py``).

    Per tick each rank runs its stage's layers on its head/width shard
    (two psums per block over ``tp_axis`` — parallel/tensor_parallel.py)
    and hands the full activation to the next stage over ``pp_axis``;
    head/loss/rejoin semantics are exactly :func:`pp_gpt_loss`.  Trees
    from :func:`stack_tp_pp_params`.
    """
    from .tensor_parallel import _tp_block  # noqa: PLC0415

    tp = lax.axis_size(tp_axis)
    # slice off both sharded leading dims ([pp, tp, ...] / [pp, ...]);
    # the tuple is one pytree so _Schedule's per-layer slicing and the
    # layers-per-stage guard see both trees together
    local = (
        jax.tree_util.tree_map(lambda a: a[0][0], staged_sharded),
        jax.tree_util.tree_map(lambda a: a[0], staged_replicated),
    )

    def layer_fn(p_j, x, positions, rope_tabs):
        sh_j, rep_j = p_j
        return _tp_block(cfg, sh_j, rep_j, x, positions, rope_tabs,
                         tp_axis, tp)

    sched = _Schedule(None, replicated_params, cfg, tokens, pp_axis,
                      microbatches, pos_offset, positions, remat,
                      local=local, layer_fn=layer_fn,
                      extra_axes=(tp_axis,))
    loss = _gpipe_loss(sched, replicated_params, cfg, targets, remat)
    # value-identical on every tp rank (post-psum activations): the
    # pmean collapses the tp axis for a replicated scalar return
    return lax.pmean(loss, tp_axis)
