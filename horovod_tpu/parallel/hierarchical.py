"""Hierarchical (2-level) allreduce over a cross x local mesh.

Reference: NCCLHierarchicalAllreduce (horovod/common/ops/nccl_operations.cc:162-300,
strategy comment :218-229): NCCL ReduceScatter within the node, MPI
allreduce across nodes on the scattered shards, NCCL Allgather back.  The
point is to put the bisection-heavy phase on the fast local fabric and send
only 1/local_size of the bytes over the slow cross fabric.

TPU mapping: LOCAL_AXIS rides ICI (fast, within a slice) and CROSS_AXIS
rides DCN (across slices), so the same 3-phase schedule applies verbatim:

    psum_scatter(LOCAL) -> psum(CROSS) -> all_gather(LOCAL)

For single-slice jobs a flat psum is both simpler and optimal; XLA already
picks torus-optimal ring/tree schedules within ICI.  This op exists for the
multi-slice (DCN-connected) topology, where the reference's reasoning
about heterogeneous fabrics carries over unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..basics import CROSS_AXIS, LOCAL_AXIS
from ..ops.collectives import Average, ReduceOp, Sum

__all__ = ["hierarchical_allreduce"]


def hierarchical_allreduce(
    tensor,
    op: ReduceOp = Average,
    *,
    local_axis: str = LOCAL_AXIS,
    cross_axis: str = CROSS_AXIS,
):
    """Allreduce across both mesh axes, scattering over the local axis so
    the cross-fabric phase moves 1/local_size of the bytes.

    Call inside shard_map over the 2D ``mesh("hierarchical")``.
    """
    if op not in (Average, Sum):
        raise ValueError(f"hierarchical_allreduce supports Average/Sum, got {op!r}")

    def one(x):
        x = jnp.asarray(x)
        shape = x.shape
        local_n = lax.axis_size(local_axis)
        flat = jnp.ravel(x)
        pad = (-flat.size) % local_n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        # Phase 1 (ICI): reduce-scatter so each local rank owns a shard.
        shard = lax.psum_scatter(flat, local_axis, scatter_dimension=0, tiled=True)
        # Phase 2 (DCN): allreduce only the shard across slices.
        shard = lax.psum(shard, cross_axis)
        # Phase 3 (ICI): gather the fully-reduced shards back.
        full = lax.all_gather(shard, local_axis, axis=0, tiled=True)
        if pad:
            full = full[:-pad]
        out = full.reshape(shape)
        if op == Average:
            out = out / (local_n * lax.axis_size(cross_axis))
        return out

    return jax.tree_util.tree_map(one, tensor)
