"""Hierarchical (2-level) allreduce over a cross x local mesh.

Reference: NCCLHierarchicalAllreduce (horovod/common/ops/nccl_operations.cc:162-300,
strategy comment :218-229): NCCL ReduceScatter within the node, MPI
allreduce across nodes on the scattered shards, NCCL Allgather back.  The
point is to put the bisection-heavy phase on the fast local fabric and send
only 1/local_size of the bytes over the slow cross fabric.

TPU mapping: LOCAL_AXIS rides ICI (fast, within a slice) and CROSS_AXIS
rides DCN (across slices), so the same 3-phase schedule applies verbatim:

    psum_scatter(LOCAL) -> psum(CROSS) -> all_gather(LOCAL)

For single-slice jobs a flat psum is both simpler and optimal; XLA already
picks torus-optimal ring/tree schedules within ICI.  This op exists for the
multi-slice (DCN-connected) topology, where the reference's reasoning
about heterogeneous fabrics carries over unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..basics import CROSS_AXIS, LOCAL_AXIS
from ..ops.collectives import Average, ReduceOp, Sum, axis_size

__all__ = [
    "hierarchical_allreduce",
    "hierarchical_adasum",
    "hierarchical_reduce_scatter",
    "hierarchical_all_gather",
]


def _resolve_compressor(compression):
    """``None``/``"none"``/name/Compressor -> Compressor class or None.
    String names resolve through ops.compression.Compression so the CLI
    knob (``--dcn-compression bf16``) and the API accept the same
    vocabulary."""
    if compression in (None, "none"):
        return None
    if isinstance(compression, str):
        from ..ops.compression import Compression  # noqa: PLC0415

        # Explicit whitelist, NOT getattr over the namespace: only pure
        # cast compressors can live inside the jitted schedule (the
        # stateful error-feedback wrapper would leak tracers), so names
        # like "ef_bf16" must fail HERE with a clear message, not
        # mid-trace.
        comp = {"bf16": Compression.bf16, "fp16": Compression.fp16}.get(
            compression
        )
        if comp is None:
            raise ValueError(
                f"unknown dcn compression {compression!r}; choices: "
                f"none, bf16, fp16"
            )
        return comp
    return compression


def hierarchical_reduce_scatter(
    flat,
    op: ReduceOp = Sum,
    *,
    local_axis: str = LOCAL_AXIS,
    cross_axis: str = CROSS_AXIS,
    compression=None,
):
    """Reduce a 1-D buffer over BOTH fabrics, keep this rank's
    1/(local*cross) shard: psum_scatter on ICI, then psum_scatter of the
    slice-partial shard on DCN — so the cross-slice leg moves only
    1/local_size of the bytes, and on a compressed wire when one is
    configured.  ``flat.size`` must divide local*cross (pad first).

    This is the scatter half of the ZeRO-1 schedule composed with the
    two-fabric plane: the element-wise result equals the matching slice
    of :func:`hierarchical_allreduce` exactly (uncompressed)."""
    if op not in (Average, Sum):
        raise ValueError(
            f"hierarchical_reduce_scatter supports Average/Sum, got {op!r}"
        )
    comp = _resolve_compressor(compression)
    x = jnp.asarray(flat)
    shard = lax.psum_scatter(x, local_axis, scatter_dimension=0, tiled=True)
    if comp is not None:
        wire, ctx = comp.compress(shard)
        shard = comp.decompress(
            lax.psum_scatter(wire, cross_axis, scatter_dimension=0,
                             tiled=True),
            ctx,
        )
    else:
        shard = lax.psum_scatter(shard, cross_axis, scatter_dimension=0,
                                 tiled=True)
    if op == Average:
        shard = shard / (axis_size(local_axis) * axis_size(cross_axis))
    return shard


def hierarchical_all_gather(
    shard,
    *,
    local_axis: str = LOCAL_AXIS,
    cross_axis: str = CROSS_AXIS,
):
    """Inverse of :func:`hierarchical_reduce_scatter`'s slicing: gather
    the cross-fabric chunks back into the slice-local shard (1/local of
    the bytes on DCN), then gather the local shards on ICI."""
    x = jnp.asarray(shard)
    x = lax.all_gather(x, cross_axis, axis=0, tiled=True)
    return lax.all_gather(x, local_axis, axis=0, tiled=True)


def hierarchical_allreduce(
    tensor,
    op: ReduceOp = Average,
    *,
    local_axis: str = LOCAL_AXIS,
    cross_axis: str = CROSS_AXIS,
    compression=None,
):
    """Allreduce across both mesh axes, scattering over the local axis so
    the cross-fabric phase moves 1/local_size of the bytes.

    Call inside shard_map over the 2D ``mesh("hierarchical")`` (or the
    outer two axes of ``mesh("slice")``).  ``compression`` (None/"bf16"/
    "fp16"/a Compressor) casts ONLY the cross-fabric shard down before
    the DCN psum and widens right after — the ICI phases stay exact, so
    total error is bounded by one cast round-trip on slice-partial sums.
    """
    if op not in (Average, Sum):
        raise ValueError(f"hierarchical_allreduce supports Average/Sum, got {op!r}")
    comp = _resolve_compressor(compression)

    def one(x):
        x = jnp.asarray(x)
        shape = x.shape
        local_n = axis_size(local_axis)
        flat = jnp.ravel(x)
        pad = (-flat.size) % local_n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        # Phase 1 (ICI): reduce-scatter so each local rank owns a shard.
        shard = lax.psum_scatter(flat, local_axis, scatter_dimension=0, tiled=True)
        # Phase 2 (DCN): allreduce only the shard across slices — on the
        # compressed wire when one is configured.
        if comp is not None:
            wire, ctx = comp.compress(shard)
            shard = comp.decompress(lax.psum(wire, cross_axis), ctx)
        else:
            shard = lax.psum(shard, cross_axis)
        # Phase 3 (ICI): gather the fully-reduced shards back.
        full = lax.all_gather(shard, local_axis, axis=0, tiled=True)
        if pad:
            full = full[:-pad]
        out = full.reshape(shape)
        if op == Average:
            out = out / (local_n * axis_size(cross_axis))
        return out

    return jax.tree_util.tree_map(one, tensor)


def hierarchical_adasum(
    tensor,
    *,
    local_axis: str = LOCAL_AXIS,
    cross_axis: str = CROSS_AXIS,
):
    """Two-level Adasum (reference AdasumGpuAllreduceOp,
    horovod/common/ops/adasum_gpu_operations.cc: NCCL ReduceScatter
    intra-node -> Adasum-MPI VHDD across nodes -> NCCL Allgather).

    Local ranks hold correlated gradients (same data distribution), so a
    plain sum intra-slice is the right estimator; the Adasum projection is
    applied only across slices, exactly the reference's hierarchy.  Call
    inside shard_map over ``mesh("hierarchical")``; the cross axis must be
    a power of two (VHDD pairing).
    """
    from ..ops.adasum import adasum_allreduce  # noqa: PLC0415

    def one(x):
        x = jnp.asarray(x)
        shape = x.shape
        local_n = axis_size(local_axis)
        flat = jnp.ravel(x)
        pad = (-flat.size) % local_n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        # Phase 1 (ICI): reduce-scatter, averaging within the slice (the
        # reference scales by 1/local_size before the cross-node VHDD —
        # adasum_gpu_operations.cc ScaleBuffer path).
        shard = (
            lax.psum_scatter(flat, local_axis, scatter_dimension=0, tiled=True)
            / local_n
        )
        # Phase 2 (DCN): Adasum projection on the shards across slices.
        shard = adasum_allreduce(shard, axis_name=cross_axis)
        # Phase 3 (ICI): gather the combined shards back.
        full = lax.all_gather(shard, local_axis, axis=0, tiled=True)
        if pad:
            full = full[:-pad]
        return full.reshape(shape)

    return jax.tree_util.tree_map(one, tensor)
