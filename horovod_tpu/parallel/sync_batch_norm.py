"""Cross-replica batch normalization.

Reference: horovod/torch/sync_batch_norm.py (194 LoC) — computes global
batch statistics by allreducing per-GPU sums/counts and allgathering counts
for the backward pass.  The TPU build gets the same semantics from two
pieces:

* :func:`sync_batch_stats` — the functional core: global mean/var across the
  DP axis via two fused psums (sum and sum-of-squares), weighted by local
  batch size so uneven local batches are handled like the reference's
  count allgather.
* :class:`SyncBatchNorm` — a flax ``nn.Module`` drop-in that normalizes with
  the global stats.  Autodiff through the psums gives exactly the gradient
  the reference hand-writes in its backward (sum_dy / sum_dy_xmu terms),
  because those terms *are* the VJPs of the stat psums.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..basics import DP_AXIS

try:
    import flax.linen as nn

    _HAVE_FLAX = True
except Exception:  # pragma: no cover
    _HAVE_FLAX = False

__all__ = ["sync_batch_stats", "SyncBatchNorm"]


def sync_batch_stats(x, *, axis_name: str = DP_AXIS, reduce_axes=None):
    """Global (mean, var, count) of ``x`` across local reduce axes and the
    mesh axis.  ``reduce_axes`` defaults to all but the last (feature) dim.
    """
    from ..ops.collectives import allreduce, Sum  # noqa: PLC0415

    x = jnp.asarray(x)
    if reduce_axes is None:
        reduce_axes = tuple(range(x.ndim - 1))
    local_count = 1
    for a in reduce_axes:
        local_count *= x.shape[a]
    local_sum = jnp.sum(x, axis=reduce_axes)
    local_sq = jnp.sum(jnp.square(x), axis=reduce_axes)
    # One fused wire round for [sum, sumsq, count] — the reference issues
    # a single allreduce of the stacked stats too (sync_batch_norm.py).
    total_sum, total_sq, total_count = allreduce(
        (local_sum, local_sq, jnp.asarray(local_count, x.dtype)),
        op=Sum,
        axis_name=axis_name,
    )
    mean = total_sum / total_count
    var = total_sq / total_count - jnp.square(mean)
    return mean, var, total_count


if _HAVE_FLAX:

    class SyncBatchNorm(nn.Module):
        """Drop-in for ``flax.linen.BatchNorm`` with cross-replica stats
        (reference: hvd.SyncBatchNorm, torch/sync_batch_norm.py).

        Use inside a shard_map'd/pjit'd model; ``axis_name`` must match the
        mesh axis the step runs over."""

        axis_name: str = DP_AXIS
        use_running_average: Optional[bool] = None
        momentum: float = 0.99
        epsilon: float = 1e-5
        dtype: Optional[jnp.dtype] = None
        use_bias: bool = True
        use_scale: bool = True

        @nn.compact
        def __call__(self, x, use_running_average: Optional[bool] = None):
            use_ra = nn.merge_param(
                "use_running_average",
                self.use_running_average,
                use_running_average,
            )
            features = x.shape[-1]
            ra_mean = self.variable(
                "batch_stats", "mean", lambda: jnp.zeros(features, jnp.float32)
            )
            ra_var = self.variable(
                "batch_stats", "var", lambda: jnp.ones(features, jnp.float32)
            )
            if use_ra:
                mean, var = ra_mean.value, ra_var.value
            else:
                mean, var, _ = sync_batch_stats(x, axis_name=self.axis_name)
                if not self.is_initializing():
                    ra_mean.value = (
                        self.momentum * ra_mean.value + (1 - self.momentum) * mean
                    )
                    ra_var.value = (
                        self.momentum * ra_var.value + (1 - self.momentum) * var
                    )
            y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
            if self.use_scale:
                y = y * self.param("scale", nn.initializers.ones, (features,))
            if self.use_bias:
                y = y + self.param("bias", nn.initializers.zeros, (features,))
            return jnp.asarray(y, self.dtype or x.dtype)

else:  # pragma: no cover

    class SyncBatchNorm:  # type: ignore[no-redef]
        def __init__(self, *a, **k):
            raise ImportError("SyncBatchNorm requires flax")
