"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference framework is data-parallel only (SURVEY.md §2.9/§5.7 — no
sequence parallelism exists in Horovod 0.19.1), but long-context scaling is
first-class in the TPU build: sequences longer than one chip's HBM are
sharded over a mesh axis and attention runs distributed.

Two schedules, both called inside ``shard_map`` over a sequence axis:

* :func:`ring_attention` — blockwise attention with an online softmax;
  K/V blocks rotate around the ring via ``lax.ppermute`` while each device
  keeps its Q shard.  Communication per step is one K/V block over ICI
  (neighbor exchange), overlapping with the block matmul — the TPU-native
  analog of Ring Attention (Liu et al.; see PAPERS.md), built on the same
  collective the Adasum VHDD uses.  Memory per device is O(S/P), enabling
  contexts P× longer than a single chip.

* :func:`ulysses_attention` — all-to-all resharding (DeepSpeed-Ulysses
  style): q/k/v flip from sequence-sharded to head-sharded with one
  ``lax.all_to_all``, attention runs *unpartitioned* per head, and the
  output flips back.  Two all-to-alls total; preferable when
  num_heads >= axis size and ICI all-to-all bandwidth is plentiful.

Both are reverse-mode differentiable (scan + ppermute/all_to_all have
transpose rules), so they drop into a training step directly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "ulysses_attention", "local_attention"]


def local_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
) -> jax.Array:
    """Plain softmax attention on local (unpartitioned) q/k/v.

    Shapes ``[batch, seq, heads, head_dim]``.  ``q_offset``/``kv_offset``
    are the global positions of the first local row — the causal mask is
    computed in *global* coordinates so sharded callers get the right
    triangle.  The single-device reference that the distributed schedules
    must reproduce bit-for-bit (up to fp associativity).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        kv_pos = kv_offset + jnp.arange(k.shape[1])
        s = jnp.where(kv_pos[None, :] > q_pos[:, None], -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention over a sequence-sharded mesh axis.

    Call inside ``shard_map`` with q/k/v sharded along dim 1 (sequence)
    over ``axis_name``; shapes ``[batch, seq_local, heads, head_dim]``.
    Each of the P ring steps attends the local Q shard against one K/V
    block, folds the result into an online-softmax accumulator, and
    rotates the K/V block to the next neighbor with ``ppermute`` — the
    classic flash-attention recurrence, distributed.

    The causal mask is evaluated in global coordinates: at step t this
    rank holds the block originally owned by rank ``(me - t) % P``, so a
    whole block from a later rank masks to zero contribution and earlier
    blocks pass through unmasked.
    """
    size = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale_ = scale if scale is not None else d ** -0.5
    perm = [(j, (j + 1) % size) for j in range(size)]

    qf = q.astype(jnp.float32)
    q_pos = me * s_local + jnp.arange(s_local)

    def step(carry, t):
        k_blk, v_blk, o, m, l = carry
        src = (me - t) % size  # original owner of the block in hand
        scores = (
            jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
            * scale_
        )
        if causal:
            kv_pos = src * s_local + jnp.arange(s_local)
            scores = jnp.where(
                kv_pos[None, :] > q_pos[:, None], -jnp.inf, scores
            )
        m_new = jnp.maximum(m, scores.max(-1))
        # exp(-inf - -inf) can only arise for a row with no unmasked key in
        # ANY block so far; causal rings always see the self-block at t=0
        # (the diagonal is unmasked), so m_new is finite from step 0 on.
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)  # [b,h,q]
        l = l * corr + p.sum(-1)
        o = (
            o * corr.transpose(0, 2, 1)[..., None]
            + jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        )
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, o, m_new, l), None

    o0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    m0 = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    (k_, v_, o, m, l), _ = lax.scan(
        step, (k, v, o0, m0, l0), jnp.arange(size)
    )
    del k_, v_, m
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ulysses-style sequence parallelism: reshard seq→heads, attend, flip
    back.

    Call inside ``shard_map`` with q/k/v sharded along dim 1 (sequence);
    shapes ``[batch, seq_local, heads, head_dim]`` with
    ``heads % axis_size == 0``.  One all-to-all turns the layout into
    full-sequence × heads/P, attention runs unpartitioned per head (the
    causal triangle needs no coordinate bookkeeping), and a second
    all-to-all restores sequence sharding.
    """
    size = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % size != 0:
        raise ValueError(
            f"ulysses_attention requires heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({size}); use ring_attention for "
            f"head counts smaller than the mesh axis."
        )

    def seq_to_heads(x):
        # [b, s/P, h, d] -> [b, s, h/P, d]
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    out = local_attention(
        seq_to_heads(q),
        seq_to_heads(k),
        seq_to_heads(v),
        causal=causal,
        scale=scale,
    )
    return heads_to_seq(out)
