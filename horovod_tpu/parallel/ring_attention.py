"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference framework is data-parallel only (SURVEY.md §2.9/§5.7 — no
sequence parallelism exists in Horovod 0.19.1), but long-context scaling is
first-class in the TPU build: sequences longer than one chip's HBM are
sharded over a mesh axis and attention runs distributed.

Two schedules, both called inside ``shard_map`` over a sequence axis:

* :func:`ring_attention` — blockwise attention with an online softmax;
  K/V blocks rotate around the ring via ``lax.ppermute`` while each device
  keeps its Q shard.  Communication per step is one K/V block over ICI
  (neighbor exchange), overlapping with the block matmul — the TPU-native
  analog of Ring Attention (Liu et al.; see PAPERS.md), built on the same
  collective the Adasum VHDD uses.  Memory per device is O(S/P), enabling
  contexts P× longer than a single chip.

* :func:`ulysses_attention` — all-to-all resharding (DeepSpeed-Ulysses
  style): q/k/v flip from sequence-sharded to head-sharded with one
  ``lax.all_to_all``, attention runs *unpartitioned* per head, and the
  output flips back.  Two all-to-alls total; preferable when
  num_heads >= axis size and ICI all-to-all bandwidth is plentiful.

* :func:`ring_attention_zigzag` — the load-balanced causal ring.  A
  contiguous causal ring is latency-bound by its last rank (it attends at
  every step even though earlier ranks skip masked blocks); zigzag
  placement (rank i holds sequence chunks i and 2P-1-i) balances the
  triangle so EVERY rank computes exactly two half-size quadrant attends
  per ring step — ~2x less critical-path attention compute than the
  contiguous causal ring, with no masking inside the steady-state loop at
  all (the only masked compute is the self-chunk diagonal, handled once
  before the ring turns).

All are reverse-mode differentiable (scan + ppermute/all_to_all have
transpose rules), so they drop into a training step directly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "ring_attention",
    "ring_attention_zigzag",
    "ulysses_attention",
    "local_attention",
    "zigzag_positions",
    "zigzag_shard",
    "zigzag_unshard",
]


def _online_softmax_update(state, q_sub, k_sub, v_sub, scale, mask=None):
    """One online-softmax accumulation of ``q_sub`` (fp32) against a K/V
    block — the single definition of the m/l/o recurrence shared by the
    contiguous ring and the zigzag ring.  ``state`` is ``(o [b,sq,h,d],
    m [b,h,sq], l [b,h,sq])`` in fp32; ``mask`` is a bool ``[sq, sk]``
    (True = masked) used only for diagonal/partial blocks."""
    o, m, l = state
    if q_sub.shape[2] != k_sub.shape[2]:
        # GQA/MQA: k/v arrive at kv_heads and broadcast HERE — after any
        # ppermute — so ring interconnect traffic stays at kv width
        rep = q_sub.shape[2] // k_sub.shape[2]
        k_sub = jnp.repeat(k_sub, rep, axis=2)
        v_sub = jnp.repeat(v_sub, rep, axis=2)
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q_sub, k_sub.astype(jnp.float32))
        * scale
    )
    if mask is not None:
        scores = jnp.where(mask[None, None], -jnp.inf, scores)
    m_new = jnp.maximum(m, scores.max(-1))
    # exp(-inf - -inf) can only arise for a q row with no unmasked key in
    # ANY block folded so far; both ring schedules fold the (diagonal-
    # masked) self block first, so m is finite from the first update on.
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)  # [b,h,q]
    l = l * corr + p.sum(-1)
    o = (
        o * corr.transpose(0, 2, 1)[..., None]
        + jnp.einsum("bhqk,bkhd->bqhd", p, v_sub.astype(jnp.float32))
    )
    return o, m_new, l


def local_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
) -> jax.Array:
    """Plain softmax attention on local (unpartitioned) q/k/v.

    Shapes ``[batch, seq, heads, head_dim]``.  ``q_offset``/``kv_offset``
    are the global positions of the first local row — the causal mask is
    computed in *global* coordinates so sharded callers get the right
    triangle.  The single-device reference that the distributed schedules
    must reproduce bit-for-bit (up to fp associativity).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        kv_pos = kv_offset + jnp.arange(k.shape[1])
        s = jnp.where(kv_pos[None, :] > q_pos[:, None], -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention over a sequence-sharded mesh axis.

    Call inside ``shard_map`` with q/k/v sharded along dim 1 (sequence)
    over ``axis_name``; shapes ``[batch, seq_local, heads, head_dim]``.
    Each of the P ring steps attends the local Q shard against one K/V
    block, folds the result into an online-softmax accumulator, and
    rotates the K/V block to the next neighbor with ``ppermute`` — the
    classic flash-attention recurrence, distributed.

    The causal mask is evaluated in global coordinates: at step t this
    rank holds the block originally owned by rank ``(me - t) % P``, so a
    whole block from a later rank masks to zero contribution and earlier
    blocks pass through unmasked.
    """
    size = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale_ = scale if scale is not None else d ** -0.5
    perm = [(j, (j + 1) % size) for j in range(size)]

    qf = q.astype(jnp.float32)
    q_pos = me * s_local + jnp.arange(s_local)

    def attend_block(operands):
        k_blk, v_blk, o, m, l, src = operands
        mask = None
        if causal:
            kv_pos = src * s_local + jnp.arange(s_local)
            mask = kv_pos[None, :] > q_pos[:, None]
        return _online_softmax_update(
            (o, m, l), qf, k_blk, v_blk, scale_, mask=mask
        )

    def step(carry, t):
        k_blk, v_blk, o, m, l = carry
        src = (me - t) % size  # original owner of the block in hand
        if causal:
            # A block from a later rank is ENTIRELY above the diagonal:
            # skip its einsums outright.  In this bulk-synchronous ring
            # the saving is FLOPs/energy, not wall-clock — every step
            # ends at the ppermute, and some rank (always the last)
            # attends at every step, so step latency is unchanged.  The
            # latency fix is load-balanced sequence placement:
            # ring_attention_zigzag, which gives every rank the same
            # per-step compute.  The diagonal-only mask refinement
            # (src == me) is deliberately not special-cased: the where
            # costs ~1/d of the einsum.
            o, m, l = lax.cond(
                src > me,
                lambda ops: (ops[2], ops[3], ops[4]),
                attend_block,
                (k_blk, v_blk, o, m, l, src),
            )
        else:
            o, m, l = attend_block((k_blk, v_blk, o, m, l, src))
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, o, m, l), None

    o0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    m0 = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    (k_, v_, o, m, l), _ = lax.scan(
        step, (k, v, o0, m0, l0), jnp.arange(size)
    )
    del k_, v_, m
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _zigzag_order(size: int, seq: int):
    """Chunk permutation of the zigzag layout: [0, 2P-1, 1, 2P-2, ...]."""
    if seq % (2 * size):
        raise ValueError(f"sequence {seq} not divisible by 2*size={2 * size}")
    return [c for i in range(size) for c in (i, 2 * size - 1 - i)]


def _apply_chunk_order(x, order, axis):
    chunks = jnp.split(x, len(order), axis)
    return jnp.concatenate([chunks[c] for c in order], axis)


def zigzag_shard(x: jax.Array, size: int, axis: int = 0) -> jax.Array:
    """Reorder a GLOBAL sequence so a contiguous equal split over ``size``
    ranks gives each rank i the zigzag pair (chunk i, chunk 2*size-1-i).

    Feed the result through your normal sequence sharding (shard_map
    in_specs along ``axis``); pair with :func:`zigzag_unshard` on gathered
    outputs.  Sequence length must divide by 2*size."""
    return _apply_chunk_order(x, _zigzag_order(size, x.shape[axis]), axis)


def zigzag_unshard(x: jax.Array, size: int, axis: int = 0) -> jax.Array:
    """Inverse of :func:`zigzag_shard` on the same global view."""
    order = _zigzag_order(size, x.shape[axis])
    import numpy as _np

    return _apply_chunk_order(x, list(_np.argsort(order)), axis)


def zigzag_positions(axis_index, size: int, s_local: int) -> jax.Array:
    """Global token positions of rank ``axis_index``'s local rows under
    the zigzag layout (first half = chunk i, second half = chunk
    2*size-1-i)."""
    half = s_local // 2
    lo = axis_index * half + jnp.arange(half)
    hi = (2 * size - 1 - axis_index) * half + jnp.arange(half)
    return jnp.concatenate([lo, hi])


def ring_attention_zigzag(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    """Load-balanced CAUSAL ring attention over zigzag-placed sequences.

    Layout contract: the global sequence was passed through
    :func:`zigzag_shard` before sharding, so this rank's local rows are
    ``concat(chunk_me, chunk_{2P-1-me})`` in global order (positions from
    :func:`zigzag_positions`).  Outputs are in the same local layout;
    gather + :func:`zigzag_unshard` recovers global order.

    Why it balances: with contiguous placement the causal triangle gives
    rank P-1 work at every ring step while rank 0 idles after step 0.
    With the zigzag pair, quadrant (q-half x kv-half) visibility at step
    t (kv block originally from ``src = (me-t) % P``) is STATIC:

    - early-q vs late-kv: never visible (skipped by construction),
    - late-q  vs early-kv: always fully visible,
    - early-q vs early-kv: fully visible iff src < me,
    - late-q  vs late-kv:  fully visible iff src > me,

    so after the t=0 self-block (the only masked compute), every rank
    runs exactly TWO unmasked half-size attends per step.  Critical-path
    attention FLOPs are ~half the contiguous causal ring's and uniform
    across ranks.
    """
    size = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    if s_local % 2:
        raise ValueError("zigzag requires an even local sequence length")
    half = s_local // 2
    scale_ = scale if scale is not None else d ** -0.5
    perm = [(j, (j + 1) % size) for j in range(size)]
    qf = q.astype(jnp.float32)

    def accum(state, q_sub, k_sub, v_sub, mask=None):
        return _online_softmax_update(state, q_sub, k_sub, v_sub, scale_,
                                      mask=mask)

    def init_state():
        return (
            jnp.zeros((b, half, h, d), jnp.float32),
            jnp.full((b, h, half), -jnp.inf, jnp.float32),
            jnp.zeros((b, h, half), jnp.float32),
        )

    q_lo, q_hi = qf[:, :half], qf[:, half:]

    # t = 0: the self block — the ONLY masked compute in the schedule.
    tri = jnp.arange(half)[None, :] > jnp.arange(half)[:, None]  # k > q
    st_lo = accum(init_state(), q_lo, k[:, :half], v[:, :half], mask=tri)
    st_hi = accum(init_state(), q_hi, k[:, half:], v[:, half:], mask=tri)
    # late-q sees ALL of its own early chunk (me < 2P-1-me always)
    st_hi = accum(st_hi, q_hi, k[:, :half], v[:, :half])

    def step(carry, t):
        k_blk, v_blk, st_lo, st_hi = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = (me - t) % size  # original owner of the block now in hand
        kc, vc = k_blk[:, :half], v_blk[:, :half]   # src's early chunk
        kd, vd = k_blk[:, half:], v_blk[:, half:]   # src's late chunk
        # exactly one of the two conds fires per step (src != me here)
        st_lo = lax.cond(
            src < me,
            lambda st: accum(st, q_lo, kc, vc),
            lambda st: st,
            st_lo,
        )
        st_hi = lax.cond(
            src > me,
            lambda st: accum(st, q_hi, kd, vd),
            lambda st: st,
            st_hi,
        )
        st_hi = accum(st_hi, q_hi, kc, vc)
        return (k_blk, v_blk, st_lo, st_hi), None

    (k_, v_, st_lo, st_hi), _ = lax.scan(
        step, (k, v, st_lo, st_hi), jnp.arange(1, size)
    )
    del k_, v_

    def finish(state):
        o, _, l = state
        return o / l.transpose(0, 2, 1)[..., None]

    out = jnp.concatenate([finish(st_lo), finish(st_hi)], axis=1)
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ulysses-style sequence parallelism: reshard seq→heads, attend, flip
    back.

    Call inside ``shard_map`` with q/k/v sharded along dim 1 (sequence);
    shapes ``[batch, seq_local, heads, head_dim]`` with
    ``heads % axis_size == 0``.  One all-to-all turns the layout into
    full-sequence × heads/P, attention runs unpartitioned per head (the
    causal triangle needs no coordinate bookkeeping), and a second
    all-to-all restores sequence sharding.
    """
    size = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % size != 0:
        raise ValueError(
            f"ulysses_attention requires heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({size}); use ring_attention for "
            f"head counts smaller than the mesh axis."
        )

    def seq_to_heads(x):
        # [b, s/P, h, d] -> [b, s, h/P, d]
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    out = local_attention(
        seq_to_heads(q),
        seq_to_heads(k),
        seq_to_heads(v),
        causal=causal,
        scale=scale,
    )
    return heads_to_seq(out)
