"""Parallelism utilities: hierarchical (2-level) collectives over the
cross x local mesh, cross-replica batch norm, sequence/context parallelism
(ring attention, Ulysses all-to-all), and sharding helpers."""

from .hierarchical import (  # noqa: F401
    hierarchical_adasum,
    hierarchical_allreduce,
)
from .ring_attention import (  # noqa: F401
    local_attention,
    ring_attention,
    ring_attention_zigzag,
    ulysses_attention,
    zigzag_positions,
    zigzag_shard,
    zigzag_unshard,
)
from .moe import (  # noqa: F401
    MoEParams,
    init_moe_params,
    moe_mlp,
    moe_mlp_ep,
)
from .pipeline import (  # noqa: F401
    pp_gpt_apply, pp_gpt_loss, pp_gpt_loss_circular, pp_tp_gpt_loss,
    stack_pp_params, stack_pp_params_circular, stack_tp_pp_params,
    unstack_pp_params, unstack_pp_params_circular, unstack_tp_pp_params,
)
from .sync_batch_norm import SyncBatchNorm, sync_batch_stats  # noqa: F401
from .tensor_parallel import (  # noqa: F401
    stack_tp_params, tp_gpt_apply, unstack_tp_params,
)
