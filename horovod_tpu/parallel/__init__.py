"""Parallelism utilities: hierarchical (2-level) collectives over the
cross x local mesh, cross-replica batch norm, and sharding helpers."""

from .hierarchical import hierarchical_allreduce  # noqa: F401
from .sync_batch_norm import SyncBatchNorm, sync_batch_stats  # noqa: F401
