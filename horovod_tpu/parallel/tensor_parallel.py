"""Megatron-style tensor parallelism for the transformer family.

Beyond reference parity (Horovod 0.19.1 is data-parallel only,
SURVEY.md §2.9 — TP listed as optional stretch): the GPT block's weights
shard across a mesh axis the Megatron way —

* qkv projection **column-parallel** (whole attention heads per rank:
  attention is embarrassingly parallel over heads, zero comms),
* output projection **row-parallel** (one ``psum`` rejoins the residual),
* MLP fc1 column-parallel, fc2 row-parallel (one ``psum``),

so a block costs exactly TWO psums over the tp axis, and every matmul
stays MXU-large.  LayerNorms, embeddings, and the LM head stay
replicated (their cost is marginal at these widths).

The implementation operates on the EXISTING `GPT` parameter pytree:
:func:`stack_tp_params` reshapes a trained/initialized checkpoint into
per-rank shards with a leading ``tp`` dim (shard it over the axis with
``in_specs=P("tp")``), and :func:`tp_gpt_apply` reproduces
``GPT.apply`` bit-for-bit (up to fp associativity) inside ``shard_map``.
Equivalence is pinned by tests/test_tensor_parallel.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["stack_tp_params", "unstack_tp_params", "tp_gpt_apply"]


def _split_qkv_columns(kernel, bias, cfg, tp: int):
    """Split the fused qkv projection so rank r holds whole head groups:
    q columns [r*h/tp head blocks], k and v columns likewise at kv_heads.
    Returns per-rank (kernel, bias) lists."""
    emb = cfg.emb_dim
    hd = cfg.head_dim
    kv_dim = cfg.kv_heads * hd
    q_w, k_w, v_w = (
        kernel[:, :emb], kernel[:, emb:emb + kv_dim],
        kernel[:, emb + kv_dim:],
    )
    q_b, k_b, v_b = bias[:emb], bias[emb:emb + kv_dim], bias[emb + kv_dim:]
    qs = np.split(np.asarray(q_w), tp, axis=1)
    ks = np.split(np.asarray(k_w), tp, axis=1)
    vs = np.split(np.asarray(v_w), tp, axis=1)
    qbs = np.split(np.asarray(q_b), tp)
    kbs = np.split(np.asarray(k_b), tp)
    vbs = np.split(np.asarray(v_b), tp)
    kernels = [
        np.concatenate([qs[r], ks[r], vs[r]], axis=1) for r in range(tp)
    ]
    biases = [
        np.concatenate([qbs[r], kbs[r], vbs[r]]) for r in range(tp)
    ]
    return kernels, biases


def stack_tp_params(params, cfg, tp: int):
    """Split a GPT parameter pytree into ``(sharded, replicated)`` trees.

    ``sharded`` carries the block matmul weights with a leading ``tp``
    dimension (rank r's shard at index r) — pass it through ``shard_map``
    with ``in_specs=P(tp_axis)``.  ``replicated`` carries embeddings,
    layer norms, post-psum biases, and the LM head — pass it with
    ``in_specs=P()``.  The separation is LOAD-BEARING for training, not
    just memory hygiene: stacking replicated weights per rank and
    sharding them makes every downstream value device-varying, and the
    psum transpose then sums the per-rank cotangents — sharded-weight
    gradients come out scaled by tp (pinned by
    tests/test_tensor_parallel.py).

    Requires ``num_heads % tp == 0`` and ``kv_heads % tp == 0`` (whole
    heads per rank) and ``mlp_ratio * emb_dim % tp == 0``.
    """
    if cfg.num_heads % tp or cfg.kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_heads={cfg.num_heads} and "
            f"kv_heads={cfg.kv_heads}"
        )
    if (cfg.mlp_ratio * cfg.emb_dim) % tp:
        raise ValueError(f"tp={tp} must divide the MLP width")
    if set(params.keys()) == {"params"}:  # accept the flax variables dict
        params = params["params"]
    p = jax.tree_util.tree_map(np.asarray, params)
    sharded, replicated = {}, {}
    for name, sub in p.items():
        if not name.startswith("block"):
            replicated[name] = sub  # embeddings / final LN / head
            continue
        blk = dict(sub)
        if "fc1" not in blk:
            raise ValueError(
                "stack_tp_params supports dense blocks only; MoE blocks "
                "(cfg.moe_experts > 0) shard over the ep axis instead "
                "(parallel/moe.py moe_mlp_ep)"
            )
        qk, qb = _split_qkv_columns(
            blk["qkv"]["kernel"], blk["qkv"]["bias"], cfg, tp
        )
        sharded[name] = {
            "qkv": {"kernel": np.stack(qk), "bias": np.stack(qb)},
            # proj/fc2 row-parallel; their biases apply once after the
            # psum, so they live on the replicated tree
            "proj": {
                "kernel": np.stack(
                    np.split(blk["proj"]["kernel"], tp, axis=0)
                ),
            },
            "fc1": {
                "kernel": np.stack(np.split(blk["fc1"]["kernel"], tp,
                                            axis=1)),
                "bias": np.stack(np.split(blk["fc1"]["bias"], tp)),
            },
            "fc2": {
                "kernel": np.stack(np.split(blk["fc2"]["kernel"], tp,
                                            axis=0)),
            },
        }
        replicated[name] = {
            "ln1": blk["ln1"],
            "ln2": blk["ln2"],
            "proj_bias": blk["proj"]["bias"],
            "fc2_bias": blk["fc2"]["bias"],
        }
    to_jnp = lambda t: jax.tree_util.tree_map(jnp.asarray, t)
    return to_jnp(sharded), to_jnp(replicated)




def unstack_tp_params(sharded, replicated, cfg, tp: int):
    """Inverse of :func:`stack_tp_params`: reassemble the canonical GPT
    parameter pytree from the per-rank shards — the code behind
    docs/inference.md's "invert the column/row splits" instruction, so a
    TP-trained state round-trips to the single-device checkpoint format
    (pinned by tests/test_tensor_parallel.py)."""
    emb = cfg.emb_dim
    kv_dim = cfg.kv_heads * cfg.head_dim
    qw, kw = emb // tp, kv_dim // tp
    out = {k: v for k, v in replicated.items()
           if not k.startswith("block")}
    for name, blk in sharded.items():
        lead = np.asarray(blk["qkv"]["kernel"]).shape[0]
        if lead != tp:
            # numpy slicing never goes out of bounds, so a wrong tp
            # would reassemble a CORRECT-SHAPED but scrambled qkv
            # kernel — fail loudly instead
            raise ValueError(
                f"{name} shards carry leading dim {lead}, expected "
                f"tp={tp} — unstacking with a different tp than the "
                "tree was stacked with"
            )
        rep_blk = replicated[name]
        kern = np.asarray(blk["qkv"]["kernel"])  # [tp, emb, qw+2kw]
        bias = np.asarray(blk["qkv"]["bias"])    # [tp, qw+2kw]
        qkv_kernel = np.concatenate(
            [np.concatenate(list(part), axis=1)
             for part in (kern[:, :, :qw], kern[:, :, qw:qw + kw],
                          kern[:, :, qw + kw:])],
            axis=1,
        )
        qkv_bias = np.concatenate(
            [np.concatenate(list(part))
             for part in (bias[:, :qw], bias[:, qw:qw + kw],
                          bias[:, qw + kw:])]
        )
        out[name] = {
            "ln1": rep_blk["ln1"],
            "ln2": rep_blk["ln2"],
            "qkv": {"kernel": jnp.asarray(qkv_kernel),
                    "bias": jnp.asarray(qkv_bias)},
            "proj": {
                # row-parallel: shards concatenate back on the input dim
                "kernel": jnp.concatenate(
                    list(blk["proj"]["kernel"]), axis=0
                ),
                "bias": rep_blk["proj_bias"],
            },
            "fc1": {
                "kernel": jnp.concatenate(
                    list(blk["fc1"]["kernel"]), axis=1
                ),
                "bias": jnp.concatenate(list(blk["fc1"]["bias"])),
            },
            "fc2": {
                "kernel": jnp.concatenate(
                    list(blk["fc2"]["kernel"]), axis=0
                ),
                "bias": rep_blk["fc2_bias"],
            },
        }
    return out


def _gpt_embed(rep, cfg, tokens, pos_offset, positions):
    """Shared replicated preamble of the TP/PP reimplementations of
    GPT.apply — ONE copy of its trace-time guards and embedding contract
    (max_len check, zigzag-positions requirement, learned-table gather
    with loud NaN fill, rope tables).  Returns (x, positions, rope_tabs).
    """
    s = tokens.shape[1]
    if s > cfg.max_len:
        raise ValueError(f"sequence length {s} exceeds max_len={cfg.max_len}")
    if positions is None:
        if cfg.attention_impl == "zigzag":
            raise ValueError(
                "attention_impl='zigzag' requires explicit positions "
                "(zigzag_positions(axis_index, P, s_local))"
            )
        positions = pos_offset + jnp.arange(s)
    x = jnp.take(rep["wte"]["embedding"], tokens, axis=0).astype(cfg.dtype)
    if cfg.pos_embedding == "learned":
        pos = jnp.take(rep["wpe"], positions, axis=0,
                       mode="fill", fill_value=jnp.nan)
        x = x + pos.astype(cfg.dtype)[None]
    rope_tabs = None
    if cfg.pos_embedding == "rope":
        from ..ops.rope import rope_tables  # noqa: PLC0415

        rope_tabs = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    return x, positions, rope_tabs


def _gpt_head(rep, cfg, x):
    """Shared replicated epilogue: final LN + LM head, fp32 logits."""
    from ..models.transformer import raw_layer_norm  # noqa: PLC0415

    x = raw_layer_norm(x, rep["lnf"]["scale"], rep["lnf"]["bias"])
    logits = x.astype(cfg.dtype) @ rep["head"]["kernel"].astype(cfg.dtype)
    return logits.astype(jnp.float32)


def _tp_block(cfg, p, rep, x, positions, rope_tabs, tp_axis, tp,
              attend=None):
    """One transformer block on this rank's head/width shard: the shared
    ``block_math`` wiring with column-parallel qkv/fc1 and row-parallel
    proj/fc2 closures — each row-parallel matmul rejoined by one psum,
    its bias applied once after (the bias lives on the replicated
    tree).  ``attend`` overrides the attention schedule exactly as in
    ``block_math`` — the width-sharded paged decode path
    (models/decode.py) supplies one that appends to its per-shard KV
    pages and attends its own heads."""
    from ..models.transformer import (  # noqa: PLC0415
        block_math, raw_dense, raw_layer_norm,
    )

    dt = cfg.dtype

    def row(kernel, bias):  # row-parallel: psum rejoin, then the bias
        return lambda h: lax.psum(
            h.astype(dt) @ kernel.astype(dt), tp_axis
        ) + bias.astype(dt)

    def mlp(h):
        from ..models.transformer import act_store  # noqa: PLC0415

        return row(p["fc2"]["kernel"], rep["fc2_bias"])(
            act_store(jax.nn.gelu(raw_dense(p["fc1"], dt)(h)), cfg)
        )

    return block_math(
        cfg, x, positions, rope_tabs,
        ln1=lambda h: raw_layer_norm(h, rep["ln1"]["scale"],
                                     rep["ln1"]["bias"]),
        qkv=raw_dense(p["qkv"], dt),
        proj=row(p["proj"]["kernel"], rep["proj_bias"]),
        ln2=lambda h: raw_layer_norm(h, rep["ln2"]["scale"],
                                     rep["ln2"]["bias"]),
        mlp=mlp,
        num_heads=cfg.num_heads // tp,
        num_kv_heads=cfg.kv_heads // tp,
        attend=attend,
    )


def tp_gpt_apply(sharded_params, replicated_params, cfg, tokens,
                 tp_axis: str, pos_offset=0, positions=None):
    """``GPT.apply`` with block weights tensor-sharded over ``tp_axis``.

    Call inside ``shard_map`` with the two trees from
    :func:`stack_tp_params`: ``sharded_params`` with ``in_specs=
    P(tp_axis)``, ``replicated_params`` with ``in_specs=P()``, tokens
    replicated.  Returns fp32 logits, identical (up to fp associativity)
    to the unsharded model's.  Use ``check_vma=True`` (replication
    tracking) when differentiating — see ``stack_tp_params``.
    """
    from ..ops.collectives import axis_size  # noqa: PLC0415

    tp = axis_size(tp_axis)
    p = jax.tree_util.tree_map(lambda a: a[0], sharded_params)
    rep = replicated_params
    x, positions, rope_tabs = _gpt_embed(rep, cfg, tokens, pos_offset,
                                         positions)
    for i in range(cfg.num_layers):
        x = _tp_block(cfg, p[f"block{i}"], rep[f"block{i}"], x, positions,
                      rope_tabs, tp_axis, tp)
    return _gpt_head(rep, cfg, x)
