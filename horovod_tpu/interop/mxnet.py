"""``horovod.mxnet``-compatible API on host MXNet NDArrays.

The migration surface of the reference's MXNet frontend
(horovod/mxnet/__init__.py:40-154, horovod/mxnet/mpi_ops.py:52-199):
``init/rank/size``, ``allreduce[_]``/``allgather``/``broadcast[_]`` with the
reference's ``average=``/``name=``/``priority=`` signature, a
``DistributedOptimizer`` that allreduces gradients inside ``update()`` and
folds the average into ``rescale_grad``, a gluon ``DistributedTrainer``
whose ``_allreduce_grads`` rides our engine, and ``broadcast_parameters``
with the deferred-init broadcast hook.

Like the torch frontend (interop/torch.py), MXNet here is the *host*
framework — NDArrays are staged through numpy into the eager engine (whose
data plane is device-resident when enabled); the TPU compute path remains
JAX.  Upstream MXNet is EOL (docs/migration.md has the porting table), so
``mxnet`` is imported lazily: every entry point works the moment an
``mxnet``-shaped module is importable and raises a clear error otherwise.
The wrapper logic itself is exercised in CI against a duck-typed stand-in
(tests/test_mxnet_interop.py) — the same logic-vs-integration split the
reference gets from crossing its CI images.

Differences from the reference, by design:
* ``priority`` is accepted and ignored: the reference forwards it to the
  MXNet engine's dependency scheduler; our engine's negotiation order is
  the deterministic cross-rank order, which priorities must not perturb.
* ``DistributedOptimizer``/``DistributedTrainer`` are factories returning
  instances of dynamically-created subclasses (``mx.optimizer.Optimizer``
  is only subclassable once mxnet imports).
"""

from __future__ import annotations

import types
from typing import Optional

import numpy as np

from ..basics import (  # noqa: F401  (re-exported API surface)
    cross_rank,
    cross_size,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    shutdown,
    size,
)
from ..ops import eager
from ..ops.collectives import Average, Sum  # noqa: F401

__all__ = [
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size", "cross_rank", "cross_size",
    "is_homogeneous",
    "mpi_built", "mpi_enabled", "mpi_threads_supported",
    "gloo_built", "gloo_enabled", "nccl_built",
    "allreduce", "allreduce_", "allgather", "broadcast", "broadcast_",
    "DistributedOptimizer", "DistributedTrainer", "broadcast_parameters",
]


def _mx():
    try:
        import mxnet  # noqa: PLC0415
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.interop.mxnet needs an importable `mxnet` module. "
            "Upstream MXNet is EOL; see docs/migration.md for the "
            "MXNet -> JAX porting table."
        ) from e
    return mxnet


def _to_np(tensor) -> np.ndarray:
    return np.asarray(tensor.asnumpy())


def _write_back(tensor, value: np.ndarray):
    # NDArray in-place assignment; reshape covers the engine's 0-d -> (1,)
    # scalar flattening.
    tensor[:] = value.reshape(tensor.shape)
    return tensor


def _new_like(tensor, value: np.ndarray):
    # Keep the source NDArray's context (reference mxnet/mpi_ops.py
    # allocates outputs with ctx=tensor.context): without it, GPU-array
    # collectives would silently return default-context (CPU) outputs.
    mx = _mx()
    ctx = getattr(tensor, "context", None)
    if ctx is not None:
        return mx.nd.array(value, dtype=value.dtype, ctx=ctx)
    return mx.nd.array(value, dtype=value.dtype)


# ---------------------------------------------------------------------------
# collectives (reference mxnet/mpi_ops.py:52-199 signatures)
# ---------------------------------------------------------------------------


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              priority: int = 0):
    """Out-of-place allreduce of an NDArray (reference mpi_ops.py:52-91)."""
    del priority  # see module docstring
    out = eager.allreduce(
        _to_np(tensor), Average if average else Sum, name
    )
    return _new_like(tensor, np.asarray(out))


def allreduce_(tensor, average: bool = True, name: Optional[str] = None,
               priority: int = 0):
    """In-place allreduce (reference mpi_ops.py:94-129)."""
    del priority
    out = eager.allreduce(
        _to_np(tensor), Average if average else Sum, name
    )
    return _write_back(tensor, np.asarray(out))


def allgather(tensor, name: Optional[str] = None, priority: int = 0):
    """Concatenate every rank's NDArray along dim 0
    (reference mpi_ops.py:132-152)."""
    del priority
    out = eager.allgather(_to_np(tensor), name)
    return _new_like(tensor, np.asarray(out))


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              priority: int = 0):
    """Out-of-place broadcast (reference mpi_ops.py:155-176)."""
    del priority
    out = eager.broadcast(_to_np(tensor), root_rank, name)
    return _new_like(tensor, np.asarray(out))


def broadcast_(tensor, root_rank: int, name: Optional[str] = None,
               priority: int = 0):
    """In-place broadcast (reference mpi_ops.py:179-199)."""
    del priority
    out = eager.broadcast(_to_np(tensor), root_rank, name)
    return _write_back(tensor, np.asarray(out))


# ---------------------------------------------------------------------------
# optimizer / trainer wrappers (reference mxnet/__init__.py:40-108)
# ---------------------------------------------------------------------------


def _do_allreduce(index, grad):
    """Sum-allreduce one update's gradient(s); the average lives in the
    optimizer's rescale_grad /= size() (reference mxnet/__init__.py:43-61)."""
    if size() == 1:
        return
    if isinstance(index, (tuple, list)):
        for i in range(len(index)):
            allreduce_(grad[i], average=False, name=str(index[i]),
                       priority=-i)
    else:
        allreduce_(grad, average=False, name=str(index))


def DistributedOptimizer(optimizer):
    """Wrap an ``mx.optimizer.Optimizer``: every ``update`` first
    sum-allreduces the gradient, and ``rescale_grad`` is divided by world
    size so the reduction averages (reference mxnet/__init__.py:40-78)."""
    mx = _mx()

    class _DistributedOptimizer(mx.optimizer.Optimizer):
        def __init__(self, wrapped):
            # No super().__init__: state lives in (and every attribute
            # delegates to) the wrapped optimizer, reference-style.
            self._optimizer = wrapped
            self._optimizer.rescale_grad /= size()

        def __getattr__(self, item):
            return getattr(self._optimizer, item)

        def create_state_multi_precision(self, index, weight):
            return self._optimizer.create_state_multi_precision(index, weight)

        def update(self, index, weight, grad, state):
            _do_allreduce(index, grad)
            self._optimizer.update(index, weight, grad, state)

        def update_multi_precision(self, index, weight, grad, state):
            _do_allreduce(index, grad)
            self._optimizer.update_multi_precision(index, weight, grad, state)

        def set_learning_rate(self, lr):
            self._optimizer.set_learning_rate(lr)

        def set_lr_mult(self, args_lr_mult):
            self._optimizer.set_lr_mult(args_lr_mult)

        def set_wd_mult(self, args_wd_mult):
            self._optimizer.set_wd_mult(args_wd_mult)

    return _DistributedOptimizer(optimizer)


def DistributedTrainer(params, optimizer, optimizer_params=None):
    """gluon Trainer whose ``_allreduce_grads`` uses our engine instead of
    kvstore push/pull, with the average folded into ``_scale``
    (reference mxnet/__init__.py:86-108)."""
    mx = _mx()

    if type(optimizer).__name__ == "_DistributedOptimizer":
        optimizer = optimizer._optimizer
        import warnings  # noqa: PLC0415

        warnings.warn(
            "DistributedTrainer does not take DistributedOptimizer as its "
            "optimizer. We have unwrapped it for you."
        )

    class _DistributedTrainer(mx.gluon.Trainer):
        def __init__(self, params, optimizer, optimizer_params):
            super().__init__(
                params, optimizer, optimizer_params=optimizer_params,
                kvstore=None,
            )
            self._scale /= size()

        def _allreduce_grads(self):
            if size() == 1:
                return
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    allreduce_(param.list_grad()[0], average=False,
                               name=param.name, priority=-i)

    return _DistributedTrainer(params, optimizer, optimizer_params)


# ---------------------------------------------------------------------------
# parameter broadcast (reference mxnet/__init__.py:111-154)
# ---------------------------------------------------------------------------


def _append_broadcast_init(param, root_rank):
    init_impl = param._init_impl

    def wrapped_init_impl(self, *args, **kwargs):
        init_impl(*args, **kwargs)
        broadcast_(self.data(), root_rank=root_rank, name=self.name)

    return wrapped_init_impl


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast ``Module.get_params()`` dicts or gluon ``ParameterDict``s
    from root_rank; deferred-init gluon parameters get a post-init
    broadcast hook (reference mxnet/__init__.py:111-154)."""
    if size() == 1:
        return
    tensors, names = [], []
    if isinstance(params, dict):
        names, tensors = zip(*sorted(params.items())) if params else ((), ())
    elif hasattr(params, "items"):  # gluon ParameterDict (duck-typed)
        mx = _mx()
        deferred_error = mx.gluon.parameter.DeferredInitializationError
        for name, p in sorted(params.items()):
            try:
                tensors.append(p.data())
                names.append(name)
            except deferred_error:
                p._init_impl = types.MethodType(
                    _append_broadcast_init(p, root_rank), p
                )
    else:
        raise ValueError(f"invalid params of type: {type(params)}")
    for tensor, name in zip(tensors, names):
        broadcast_(tensor, root_rank, name=str(name))
