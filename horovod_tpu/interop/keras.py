"""``horovod_tpu.interop.keras`` — alias of :mod:`.tf_keras`.

The reference exposes the same Keras bindings twice (``horovod.keras`` and
``horovod.tensorflow.keras``, both delegating to the shared ``horovod._keras``
impl); scripts migrate from either spelling.
"""

from .tf_keras import *  # noqa: F401,F403
from .tf_keras import callbacks, load_model  # noqa: F401
