"""Framework interop frontends.

The reference ships one binding per host framework (horovod/{torch,
tensorflow,mxnet,keras}); the TPU build's native surface is JAX, and this
package provides the migration-path bindings for users arriving from those
frameworks.  ``horovod_tpu.interop.torch`` mirrors the ``horovod.torch``
API on host (CPU) torch tensors, riding the same eager engine.
"""
