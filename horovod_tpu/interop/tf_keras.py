"""Keras migration frontend — the analog of ``horovod.tensorflow.keras``.

Reference surface (horovod/tensorflow/keras/__init__.py + the shared
_keras impl): ``hvd.DistributedOptimizer`` for ``model.compile``,
``hvd.callbacks.BroadcastGlobalVariablesCallback`` /
``MetricAverageCallback`` / ``LearningRateScheduleCallback`` /
``LearningRateWarmupCallback`` for ``model.fit(callbacks=[...])``
(_keras/callbacks.py:20-185), and ``hvd.load_model`` which restores a
saved model with its optimizer re-wrapped (_keras/__init__.py:113-128).

A migrating user changes ``import horovod.tensorflow.keras as hvd`` to
``import horovod_tpu.interop.tf_keras as hvd`` and keeps the rest.
Collectives execute on this package's eager engine (negotiated, fused,
dtype-native wire) instead of MPI/NCCL.
"""

from __future__ import annotations

import numpy as np
import tensorflow as tf

from ..basics import (  # noqa: F401  (re-exported API surface)
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from . import tf as _hvd_tf
from .tf import (  # noqa: F401
    Adasum,
    Average,
    Compression,
    DistributedOptimizer,
    Sum,
    allgather,
    allreduce,
    broadcast,
    broadcast_object,
    broadcast_variables,
)

__all__ = [
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size",
    "DistributedOptimizer", "Compression",
    "allreduce", "allgather", "broadcast",
    "broadcast_object", "broadcast_variables",
    "load_model", "callbacks",
    "Average", "Sum", "Adasum",
]


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a saved Keras model with its optimizer wrapped in
    :func:`DistributedOptimizer` (reference _keras/__init__.py:113-128 —
    there via ``custom_objects`` class substitution at deserialization
    time; here by re-wrapping the restored optimizer instance, the one
    stable seam across Keras generations).

    ``custom_optimizers``/``custom_objects`` pass through to Keras
    deserialization for models using custom classes.
    """
    objs = dict(custom_objects or {})
    # A model saved with a wrapped optimizer serializes as
    # "Distributed<Base>"; register deserializers for the stock optimizers
    # and any user-provided ones (reference passes exactly such a
    # custom_objects map, _keras/__init__.py:113-128).
    bases = [
        getattr(tf.keras.optimizers, n)
        for n in ("SGD", "Adam", "AdamW", "RMSprop", "Adagrad", "Adadelta",
                  "Adamax", "Nadam", "Ftrl", "Lion")
        if hasattr(tf.keras.optimizers, n)
    ] + list(custom_optimizers or [])
    for base in bases:
        objs.setdefault(
            f"Distributed{base.__name__}",
            _hvd_tf._make_distributed_keras_class(base, compression),
        )
        # op=Adasum wraps serialize as "Adasum<Base>"
        objs.setdefault(
            f"Adasum{base.__name__}",
            _hvd_tf._make_adasum_keras_class(base, compression),
        )
    for opt_cls in custom_optimizers or []:
        objs.setdefault(opt_cls.__name__, opt_cls)
    model = tf.keras.models.load_model(filepath, custom_objects=objs)
    opt = getattr(model, "optimizer", None)
    if opt is not None and not getattr(opt, "_hvd_wrapped", False):
        # saved with a PLAIN optimizer: wrap the restored instance
        wrapped = DistributedOptimizer(opt, compression=compression)
        try:
            model.optimizer = wrapped
        except AttributeError:  # older Keras: optimizer set via compile only
            # Recompile with the FULL restored compile config (metrics,
            # loss_weights, ...) — not just the loss.
            try:
                cfg = model.get_compile_config()
                cfg["optimizer"] = wrapped
                model.compile_from_config(cfg)
            except Exception:
                model.compile(optimizer=wrapped, loss=model.loss)
    return model


# ---------------------------------------------------------------------------
# model.fit callbacks (reference _keras/callbacks.py:20-185)
# ---------------------------------------------------------------------------


class _CallbacksNamespace:
    """Holder so ``hvd.callbacks.X`` reads like the reference module."""


def _get_lr_var(optimizer):
    lr = getattr(optimizer, "learning_rate", None)
    if lr is None:
        lr = getattr(optimizer, "lr", None)
    return lr


def _set_lr(optimizer, value) -> None:
    lr = _get_lr_var(optimizer)
    if hasattr(lr, "assign"):
        lr.assign(value)
    else:  # plain float attribute
        optimizer.learning_rate = value


def _lr_value(optimizer) -> float:
    lr = _get_lr_var(optimizer)
    try:
        return float(lr.numpy())
    except AttributeError:
        return float(lr)


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast model + optimizer state from ``root_rank`` after the first
    batch (reference _keras/callbacks.py:20-44: on_batch_end once, so
    deferred-build variables exist)."""

    def __init__(self, root_rank: int = 0, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False
        del device

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        variables = list(self.model.variables)
        opt = getattr(self.model, "optimizer", None)
        if opt is not None:
            ov = getattr(opt, "variables", None)
            if callable(ov):  # legacy Keras: a method
                ov = ov()
            variables += list(ov or [])
        broadcast_variables(variables, root_rank=self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch metrics over all ranks before other callbacks (model
    checkpointing, early stopping, LR schedules) read them (reference
    _keras/callbacks.py:46-72)."""

    def __init__(self, device: str = ""):
        super().__init__()
        del device

    def _average_metrics_in_place(self, logs):
        if not logs:
            return
        # Sorted keys => identical call order on every rank, so the
        # engine's sequence names pair the metric allreduces correctly.
        for k in sorted(logs.keys()):
            v = logs[k]
            if isinstance(v, (int, float, np.floating, np.integer)):
                avg = allreduce(
                    tf.constant(float(v), tf.float32), op=Average
                )
                logs[k] = float(avg.numpy())

    def on_epoch_end(self, epoch, logs=None):
        self._average_metrics_in_place(logs)


class LearningRateScheduleCallback(tf.keras.callbacks.Callback):
    """Multiply the initial LR by ``multiplier(epoch)`` — constant within
    an epoch (staircase) or smoothly per batch (reference
    _keras/callbacks.py:74-132)."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch=None, staircase: bool = True,
                 momentum_correction: bool = True, steps_per_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        self._restore_momentum = None
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def on_train_begin(self, logs=None):
        # Auto-fill the per-batch resolution from Keras's own params, like
        # the reference does (_keras/callbacks.py on_train_begin reads
        # self.params['steps']) — without it a non-staircase schedule
        # would silently never adjust the LR.
        if not self.staircase and not self.steps_per_epoch:
            self.steps_per_epoch = (self.params or {}).get("steps")
            if not self.steps_per_epoch:
                raise ValueError(
                    "LearningRateScheduleCallback(staircase=False) could "
                    "not infer steps_per_epoch from model.fit; pass "
                    "steps_per_epoch= explicitly"
                )

    def _in_range(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _adjust(self, epoch) -> None:
        if not self._in_range(int(epoch)):
            return
        opt = self.model.optimizer
        old_lr = _lr_value(opt)
        new_lr = self.initial_lr * self.multiplier(epoch)
        _set_lr(opt, new_lr)
        # Momentum correction (reference _keras/callbacks.py, after Goyal
        # et al. 2017): Keras folds lr into the velocity update, so an LR
        # change perturbs the effective velocity unless momentum is scaled
        # by new_lr/old_lr for the next update, then restored.
        if self.momentum_correction and old_lr > 0 and new_lr != old_lr:
            m = getattr(opt, "momentum", None)
            if isinstance(m, (int, float)) and m:
                self._restore_momentum = float(m)
                opt.momentum = float(m) * new_lr / old_lr

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase:
            self._adjust(epoch)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase and self.steps_per_epoch:
            self._adjust(self.current_epoch + batch / self.steps_per_epoch)

    def on_batch_end(self, batch, logs=None):
        if self._restore_momentum is not None:
            self.model.optimizer.momentum = self._restore_momentum
            self._restore_momentum = None

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = _lr_value(self.model.optimizer)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Ramp the LR from ``initial_lr / size()`` to ``initial_lr`` over the
    first ``warmup_epochs`` (the large-batch warmup recipe the reference
    implements at _keras/callbacks.py:135-185, after Goyal et al. 2017)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True, steps_per_epoch=None,
                 verbose: int = 0):
        world = max(size(), 1)

        def multiplier(epoch):
            if warmup_epochs <= 0:
                return 1.0
            # epoch is fractional (per-batch); linear 1/world -> 1
            frac = min(float(epoch) / warmup_epochs, 1.0)
            return 1.0 / world + (1.0 - 1.0 / world) * frac

        super().__init__(
            initial_lr, multiplier, start_epoch=0, end_epoch=warmup_epochs,
            staircase=False, momentum_correction=momentum_correction,
            steps_per_epoch=steps_per_epoch,
        )
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose and rank() == 0:
            print(
                f"Epoch {epoch + 1}: finished gradual learning rate warmup "
                f"to {_lr_value(self.model.optimizer):g}."
            )


callbacks = _CallbacksNamespace()
callbacks.BroadcastGlobalVariablesCallback = BroadcastGlobalVariablesCallback
callbacks.MetricAverageCallback = MetricAverageCallback
callbacks.LearningRateScheduleCallback = LearningRateScheduleCallback
callbacks.LearningRateWarmupCallback = LearningRateWarmupCallback

# The reference exposes the same callbacks from horovod.tensorflow.keras
# AND horovod.keras; mirror on the tf module for discoverability.
_hvd_tf.callbacks = callbacks
